"""Fused multi-cycle admission bursts: K scheduling cycles in ONE dispatch.

Round 3 measured why the accelerator never ran a production cycle: one
dispatch through this environment's tunnel costs ~112 ms flat, more than
an entire XLA-CPU cycle at the north-star shape, so the calibrated
per-cycle router correctly starved the chip.  The fix is architectural,
not a tuning knob: keep the WHOLE pending set on the device (not just the
cycle heads) and fuse K successive cycles — head selection + classify +
admit scan + usage release + re-heads — into one jitted program, so the
dispatch cost is paid once per K cycles (verdict r3 item 1; reference hot
loop scheduler.go:176-302).

Semantics reproduced per fused cycle, bit-matching the host scheduler:

1. **Heads** (queue/manager.go:586 Heads): the top of every CQ's heap —
   here an argmin over a dense per-CQ rank matrix.  Ranks are
   host-precomputed with the exact heap comparator (priority desc,
   queue-order timestamp asc, key asc — cluster_queue.go:408); they are
   static within a burst because priorities/timestamps never change
   without an external event, and external events end the burst.
2. **Classify** (flavorassigner.go:499): the vectorized nominate of
   ops.cycle.classify_np, evaluated dense over [C, S, R].
3. **Cycle order** (scheduler.go:567 entryOrdering): borrows asc, then a
   host-precomputed (priority desc, timestamp asc, heads-position) rank.
4. **Admit scan** (scheduler.go:211-284): forest-parallel — one head per
   cohort forest per step, fits re-checked chain-locally, usage charged
   up the ancestor chain (the ops.cycle.admit_scan_forests discipline).
5. **Requeue semantics** (cluster_queue.go:225): a NoFit head parks in
   the inadmissible lot (BestEffortFIFO) or stays eligible (StrictFIFO);
   a fit head that lost capacity in-scan requeues immediately (stays
   eligible) — FAILED_AFTER_NOMINATION is immediate on both strategies.
6. **Finish + unpark** (driver.finish_workload → manager.go:490
   QueueInadmissibleWorkloads): quota released at end-of-cycle unparks
   every CQ in the affected cohort forest.  Releases come from two
   sources: workloads admitted IN the burst finishing ``runtime`` cycles
   later (the perf harness's fake execution — reference
   runner/controller/controller.go:113), and an external release
   schedule for workloads admitted before the burst.

Anything the fused math can't decide bit-identically makes the cycle
**dirty**: a preempt-capable head outside the modeled envelope (the
walk neither policy-stopped on the preempt slot nor left it as the only
preempt-capable choice — the host's pick then depends on the reclaim
oracle), or a head outside the vectorized classify's coverage (multi-RG
/ multi-PodSet / taints / TAS / partial admission — ``vec_ok`` False).
FlavorFungibility itself runs in-kernel: the classify step walks each
head's flavor list from its carried resume start slot with the
whenCanBorrow/whenCanPreempt stop rules and records the next start slot
exactly as the host records last_tried_flavor_idx.  The kernel reports
the first dirty cycle and
the host applies only the clean prefix, running the normal per-cycle
path from there.  Decisions are additionally validated on application:
the driver compares each cycle's modeled heads against the live queues
and truncates on any divergence, so burst mode can never corrupt state
even under unmodeled events.

Usage invariant that makes device-resident state exact: for every cohort
node, ``usage[node] == Σ_children max(0, usage[child] - guaranteed
[child])`` (resource_node.go:123-144 add/remove bubbling preserves it, by
induction).  The kernel therefore keeps only CQ-level usage as ground
truth and rebuilds cohort rows level-by-level each cycle — releases need
no sequential remove-chain walks.
"""

from __future__ import annotations

import itertools
import os
import sys

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .quota_kernel import available_all, available_at
from .cycle import add_usage_chain_batched
from ..chaos import injector as _chaos
from ..features import env_value

INF_I32 = np.int32(2**31 - 1)
I32_MAX = 2**31 - 1
# composite in-forest ordering key: borrows (entryOrdering's primary) in
# bit 30, the host-precomputed (priority, timestamp, position) rank below
_BORROW_BIT = np.int32(1 << 30)


# ----------------------------------------------------------------------
# The fused kernel
# ----------------------------------------------------------------------

# kind codes in the per-cycle decision output
KIND_NONE = 0
KIND_ADMIT = 1
KIND_SKIP = 2          # fit at nominate, lost capacity in-scan
KIND_PARK = 3          # NoFit (BestEffortFIFO parks; reserve parks too)
KIND_PREEMPT = 4       # preempting entry: issue evictions for targets
KIND_RESERVE = 5       # preempt-classified, no targets: reserve + requeue
KIND_OVERLAP_SKIP = 6  # overlapping preemption targets (scheduler.go:235)
KIND_PRE_NOFIT = 7     # preempt entry no longer fits in-scan

# dirty-reason bits (per burst cycle)
DIRTY_PREEMPT = 1      # preempt head outside the modeled envelope
DIRTY_SCALAR = 2       # head outside vectorized-classify coverage
DIRTY_RESUME = 4       # head with fungibility resume state


def _burst_cycles(
    # dense workload state [C, M, ...] — pending AND admitted rows
    wl_req,          # [C, M, R] int32 scaled requests
    wl_rank,         # [C, M] int32 heap rank (INF_I32 = empty slot)
    wl_cycle_rank,   # [C, M] int32 global (priority, ts, pos) rank
    wl_prio,         # [C, M] int32 priority
    wl_uidrank,      # [C, M] int32 global uid rank (candidate tiebreak)
    vec_ok,          # [C, M] bool  vectorized-classify coverage
    elig0,           # [C, M] bool  in the heap at burst start
    parked0,         # [C, M] bool  in the inadmissible lot at burst start
    resume0,         # [C, M] int32 flavor-walk start slot (fungibility
                     #              resume state; 0 = full walk)
    # admitted-row state (rows holding quota at burst start)
    adm0,            # [C, M] bool
    adm_seq0,        # [C, M] int32 reservation-time dense rank (ties ==)
    adm_usage0,      # [C, M, F] int32 admitted usage vectors
    adm_uses0,       # [C, M, F] bool  flavor-resource PRESENCE in usage
    death0,          # [C, M] int32 cycle offset of finish (INF_I32 none)
    seq_base,        # scalar int32: first seq for in-burst admissions
    # quota plane
    u_cq0,           # [C, F] int32 CQ-level usage at burst start
    potential0,      # [N, F] int32 available() at zero usage (static)
    # structure (PackedStructure tensors)
    subtree, guaranteed, borrow_cap, has_blim,   # [N, F]
    parent,          # [N] int32
    node_level,      # [N] int32 (roots = 0)
    nominal_cq,      # [C, F]
    npb_cq,          # [C, F] nominal+borrowingLimit (reserve cap)
    slot_fr,         # [C, S, R] int32 F-index or -1
    slot_valid,      # [C, S] bool
    cq_can_preempt_borrow,                       # [C] bool
    cq_wcb,          # [C] bool whenCanBorrow == Borrow
    cq_wcp,          # [C] bool whenCanPreempt == Preempt
    forest_of_cq,    # [C] int32
    strict_cq,       # [C] bool StrictFIFO
    # preemption policy + modeling envelope (static per structure)
    wcq_lower,       # [C] bool withinClusterQueue == LowerPriority
    rwc_enabled,     # [C] bool reclaimWithinCohort != Never
    rwc_only_lower,  # [C] bool reclaimWithinCohort == LowerPriority
    preempt_ok,      # [C] bool CQ inside the in-kernel preempt envelope
    members,         # [G, L] int32 CQ indices per forest (-1 pad, static)
    cand_rows,       # [G, KC] int32 flattened (cq*M+m) candidate row ids
    cand_lmem,       # [G, KC] int32 member slot of each candidate's CQ
    self_lmem,       # [C] int32 member slot of the CQ itself
    # event schedule
    ext_release,     # [K, C, F] int32 non-row usage released at END of k
    ext_unpark,      # [K, G] bool forest unpark events at END of cycle k
    *, K: int, depth: int, L: int, S: int, KC: int,
    n_levels: int, G: int, runtime: int, axis_name=None,
):
    """Run K fused admission cycles with in-kernel preemption.

    Returns per-cycle (head_row[K,C], kind[K,C], slot[K,C], borrows[K,C],
    tgt_words[K,C,KC//32] uint32, dirty[K], dirty_reason[K]) plus the
    final u_cq.  ``slot`` is the fit slot for admit/skip kinds and the
    preempt slot for preempt kinds.  ``tgt_words`` is the bit-packed
    candidate-slot mask of each preempting head's targets (indices into
    cand_rows[forest_of_cq[c]]).

    Preemption is decided bit-identically to the host path
    (preemption.go:127-342) inside the modeled envelope: candidate
    discovery (same-CQ lower-priority + cohort borrowers), candidate
    ordering (other-CQ first, priority asc, newest reservation first,
    uid), plan_searches' staged specs with borrowWithinCohort == Never,
    greedy removal with live borrowing re-check + fill-back minimization,
    and the scan-time overlap/fits discipline of admit_scan_preempt.
    Anything outside the envelope makes the cycle dirty and the host
    per-cycle path decides it instead.

    The sequential greedy/fill-back walks run as ``lax.while_loop``s
    that exit as soon as every searching lane either fitted or ran out
    of quota-holding candidates (candidates sort admitted-first), so
    their cost tracks the candidates actually walked — not the KC = L*M
    table capacity — with no extra compilation shapes."""
    # dtype-tightened planes (ops/packing.py tighten_arrays) cross the
    # host boundary narrow and upcast here; already-int32 inputs make
    # these no-ops that XLA elides.  The kernel body below is unchanged.
    wl_req = wl_req.astype(jnp.int32)
    wl_cycle_rank = wl_cycle_rank.astype(jnp.int32)
    wl_prio = wl_prio.astype(jnp.int32)
    wl_uidrank = wl_uidrank.astype(jnp.int32)
    parent = parent.astype(jnp.int32)
    node_level = node_level.astype(jnp.int32)
    nominal_cq = nominal_cq.astype(jnp.int32)
    slot_fr = slot_fr.astype(jnp.int32)
    forest_of_cq = forest_of_cq.astype(jnp.int32)
    members = members.astype(jnp.int32)
    cand_rows = cand_rows.astype(jnp.int32)
    cand_lmem = cand_lmem.astype(jnp.int32)
    self_lmem = self_lmem.astype(jnp.int32)
    C, M, R = wl_req.shape
    N, F = subtree.shape
    CM = C * M
    KCW = KC // 32
    cidx = jnp.arange(C, dtype=jnp.int32)
    has_parent_cq = parent[:C] >= 0
    sq_cq = subtree[:C]                      # [C,F] borrowing_with base
    g_cq = guaranteed[:C]
    root_of_cq = jnp.maximum(parent[:C], 0)  # depth<=2 inside envelope
    sq_root = subtree[root_of_cq]            # [C, F]
    bit_w = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    # per-CQ flavor-list length: vector-ok CQs have every rg flavor
    # materialized as a valid slot (solver._cq_vector_ok), so the valid
    # count IS len(rg.flavors) — the host walk's n_slots
    slot_cnt = jnp.sum(slot_valid, axis=1).astype(jnp.int32)   # [C]

    # per-CQ static candidate tables (gathered per forest)
    crows = cand_rows[forest_of_cq]                    # [C, KC]
    cvalid = crows >= 0
    crs = jnp.maximum(crows, 0)
    cci = (crs // M).astype(jnp.int32)                 # [C, KC]
    cmi = (crs % M).astype(jnp.int32)
    clm = cand_lmem[forest_of_cq]                      # [C, KC]
    same_cq = cvalid & (cci == cidx[:, None])
    c_prio = wl_prio[cci, cmi]
    c_uid = wl_uidrank[cci, cmi]
    memC = members[forest_of_cq]                       # [C, L]
    mem_valid = memC >= 0
    memCs = jnp.maximum(memC, 0)
    g_mem = jnp.where(mem_valid[:, :, None], guaranteed[memCs], 0)
    lane_oh = (jnp.arange(L, dtype=jnp.int32)[None, :]
               == self_lmem[:, None])                  # [C, L] own slot

    def rebuild_usage(u_cq):
        """CQ usage → full node usage via the subtree invariant."""
        usage = jnp.zeros((N, F), dtype=jnp.int32).at[:C].set(u_cq)
        parent_safe = jnp.maximum(parent, 0)
        for lvl in range(n_levels - 1, 0, -1):
            is_l = (node_level == lvl) & (parent >= 0)
            contrib = jnp.where(is_l[:, None],
                                jnp.maximum(0, usage - guaranteed), 0)
            usage = usage.at[parent_safe].add(contrib)
        return usage

    def avail_from_members(u_mem):
        """available() at every CQ from its forest's member usage rows.

        ``u_mem``: [..., C, L, F] member-CQ usage planes.  Depth<=2 twin
        of available_at (resource_node.go:89): local headroom plus the
        root's remaining subtree quota, borrow-limit clamped."""
        over = jnp.where(mem_valid[..., None],
                         jnp.maximum(0, u_mem - g_mem), 0)
        root_use = over.sum(axis=-2)                    # [..., C, F]
        root_avail = sq_root - root_use
        u_self = jnp.sum(u_mem * lane_oh[..., None], axis=-2)
        local = jnp.maximum(0, g_cq - u_self)
        blim_cap = borrow_cap[:C] - jnp.maximum(0, u_self - g_cq)
        par_avail = jnp.where(has_blim[:C],
                              jnp.minimum(blim_cap, root_avail),
                              root_avail)
        return (jnp.where(has_parent_cq[:, None], local + par_avail,
                          sq_cq - u_self), u_self)

    def cycle(carry, k):
        (elig, parked, resume, adm, adm_seq, adm_usage, adm_uses, death,
         u_cq) = carry
        usage = rebuild_usage(u_cq)
        avail = available_all(usage, subtree, guaranteed, borrow_cap,
                              has_blim, parent, depth)

        # -- heads: argmin heap rank per CQ ---------------------------
        key = jnp.where(elig, wl_rank, INF_I32)
        row = jnp.argmin(key, axis=1).astype(jnp.int32)        # [C]
        has_head = key[cidx, row] < INF_I32
        req = wl_req[cidx, row]                                # [C, R]
        prio_head = wl_prio[cidx, row]

        # -- classify (classify_np dense twin) ------------------------
        frs = slot_fr                                          # [C,S,R]
        frs_safe = jnp.maximum(frs, 0)
        covered = frs >= 0
        needed = req[:, None, :] > 0
        missing = jnp.any(needed & ~covered, axis=2)           # [C,S]
        av = avail[:C][cidx[:, None, None], frs_safe]          # [C,S,R]
        pot = potential0[:C][cidx[:, None, None], frs_safe]
        nom = nominal_cq[cidx[:, None, None], frs_safe]
        use = usage[:C][cidx[:, None, None], frs_safe]
        sq = subtree[:C][cidx[:, None, None], frs_safe]

        relevant = covered & needed
        fit_r = req[:, None, :] <= av
        nofit_r = req[:, None, :] > pot
        preempt_capable_r = ((req[:, None, :] <= nom)
                             | cq_can_preempt_borrow[:, None, None])
        res_nofit = relevant & (nofit_r | (~fit_r & ~preempt_capable_r))
        fit_s = (jnp.all(jnp.where(relevant, fit_r, True), axis=2)
                 & ~missing & slot_valid)                      # [C,S]
        nofit_s = jnp.any(res_nofit, axis=2) | missing | ~slot_valid
        preempt_s = ~fit_s & ~nofit_s
        borrow_r = jnp.where(relevant, use + req[:, None, :] > sq, False)
        borrows_s = jnp.any(borrow_r, axis=2) & has_parent_cq[:, None]

        # -- fungibility walk (flavorassigner.go:326-391 dense twin) --
        # scan the flavor list from the carried resume start; STOP on a
        # slot per whenCanBorrow/whenCanPreempt, else keep the best mode
        # (first occurrence of max: FIT=2 > PREEMPT=1 > NO_FIT=0)
        start = resume[cidx, row]                              # [C]
        active_s = (jnp.arange(S, dtype=jnp.int32)[None, :]
                    >= start[:, None])                         # [C,S]
        stop_s = (active_s & (fit_s | (preempt_s & cq_wcp[:, None]))
                  & (~borrows_s | cq_wcb[:, None]))
        has_stop = jnp.any(stop_s, axis=1)
        act_mode = jnp.where(active_s,
                             jnp.where(fit_s, 2,
                                       jnp.where(preempt_s, 1, 0)), 0)
        best_mode = act_mode.max(axis=1)
        best_idx = jnp.argmax((act_mode == best_mode[:, None]) & active_s,
                              axis=1).astype(jnp.int32)
        chosen = jnp.where(has_stop,
                           jnp.argmax(stop_s, axis=1).astype(jnp.int32),
                           best_idx)
        chosen_mode = act_mode[cidx, chosen]
        has_fit = (chosen_mode == 2) & has_head
        fit_slot = jnp.where(has_fit, chosen, -1)
        borrows = borrows_s[cidx, chosen] & has_fit
        has_preempt = (chosen_mode == 1) & has_head
        # the resume state the host records for this walk: the stop slot
        # when it stopped mid-list, else -1 (whole list attempted)
        tried_c = jnp.where(has_stop & (chosen < slot_cnt - 1),
                            chosen, -1)
        pending_c = tried_c >= 0

        # -- preempt head facts on the chosen preempt slot ------------
        p_idx = chosen
        p_count = (preempt_s & active_s).sum(axis=1)
        p_borrows = borrows_s[cidx, p_idx] & has_preempt
        pfrs = slot_fr[cidx, p_idx]                            # [C, R]
        prel = (pfrs >= 0) & (req > 0)
        pfrs_s = jnp.maximum(pfrs, 0)
        pfit_r = fit_r[cidx, p_idx]                            # [C, R]
        frs_need = jnp.zeros((C, F), dtype=bool).at[
            cidx[:, None], pfrs_s].max(prel & ~pfit_r)         # [C, F]
        wu = jnp.zeros((C, F), dtype=jnp.int32).at[
            cidx[:, None], pfrs_s].add(jnp.where(prel, req, 0))
        # the modeled envelope: the preempt choice must not depend on
        # the reclaim oracle (cycle.py:122-126) — a policy-stopped walk
        # is final, and a single preempt-capable slot leaves the
        # best-mode pick no freedom either
        pre_model = (has_preempt & preempt_ok
                     & (has_stop | (p_count == 1)))

        dirty_c = has_head & ((has_preempt & ~pre_model)
                              | ~vec_ok[cidx, row])
        # dirty/dirty_reason are the kernel's ONLY cross-forest
        # quantities (everything else is forest-local), and nothing in
        # the scan's state transitions reads the GLOBAL flags (park_new
        # gates on the forest-local dirty_c) — so each cycle emits its
        # local reduction and the cross-shard psum is hoisted out of
        # the scan: one collective per WINDOW instead of one per cycle,
        # which removes K sync barriers from every sharded dispatch
        dflags = jnp.stack([
            jnp.any(dirty_c).astype(jnp.int32),
            jnp.any(has_preempt & ~pre_model).astype(jnp.int32),
            jnp.any(has_head & ~vec_ok[cidx, row]).astype(jnp.int32),
            # fungibility resume runs in-kernel now; the DIRTY_RESUME
            # lane stays for flag-layout compatibility and is always 0
            jnp.zeros((), dtype=jnp.int32)])

        # -- nominate-time preemption searches (preemption.go:127-342) -
        def run_searches(_):
            c_adm = adm[cci, cmi] & cvalid
            c_seq = adm_seq[cci, cmi]
            c_usage = adm_usage[cci, cmi]                  # [C, KC, F]
            c_uses = adm_uses[cci, cmi]
            uses_needed = jnp.any(c_uses & frs_need[:, None, :], axis=2)
            borrow_cq0 = u_cq > sq_cq                      # [C, F]
            b0 = (jnp.any(frs_need[:, None, :] & borrow_cq0[cci], axis=2)
                  & has_parent_cq[cci])
            elig_same = (same_cq & wcq_lower[:, None]
                         & (c_prio < prio_head[:, None]))
            elig_cross = (cvalid & ~same_cq & rwc_enabled[:, None] & b0
                          & (~rwc_only_lower[:, None]
                             | (c_prio < prio_head[:, None])))
            e_base = c_adm & uses_needed & (elig_same | elig_cross)
            has_cross = jnp.any(e_base & ~same_cq, axis=1)
            under_nom = jnp.all(
                jnp.where(frs_need, u_cq < nominal_cq, True), axis=1)
            e_same = e_base & same_cq
            # plan_searches (preemption.go:144-191, bwc == Never):
            #   no cross → (all=same, borrow); cross+under-nominal →
            #   staged (all, no-borrow) then (same, borrow); else
            #   (same, borrow)
            staged = has_cross & under_nom
            m0 = jnp.where(staged[:, None], e_base, e_same)
            ab0 = ~staged
            m1 = jnp.where(staged[:, None], e_same, False)
            msk = jnp.stack([m0, m1])                      # [2, C, KC]
            ab = jnp.stack([ab0, jnp.ones_like(ab0)])      # [2, C]

            # candidatesOrdering (preemption.go:591): other-CQ first,
            # priority asc, newest reservation first, uid asc; one total
            # order — spec masks filter during the walk like the host's
            # pre-filtered lists.  Two int32 composite keys (field
            # ranges gated at pack time: |prio| < 2^20, seq < 2^20,
            # uid rank < 2^19) replace a 5-key lexsort — this sort runs
            # per preempt cycle over [C, KC].  (int64 keys are
            # unavailable without jax_enable_x64.)
            # ineligible candidates sort LAST (the host sorts its
            # pre-filtered eligible list; relative order among eligible
            # is unchanged) — so the greedy walk never wades through
            # dead positions and exhaustion is the eligible count
            elig_any = msk[0] | msk[1]                     # [C, KC]
            B20 = jnp.int32(1 << 20)
            inv_seq = (B20 - 1) - c_seq                    # 20 bits
            key_hi = (((~elig_any).astype(jnp.int32) << 30)
                      | (same_cq.astype(jnp.int32) << 29)
                      | ((c_prio + B20) << 8)
                      | (inv_seq >> 12))
            key_lo = ((inv_seq & 0xFFF) << 19) | c_uid
            order = jax.vmap(lambda lo, hi: jnp.lexsort((lo, hi)))(
                key_lo, key_hi).astype(jnp.int32)

            u_mem0 = jnp.where(mem_valid[:, :, None], u_cq[memCs], 0)
            u_mem0 = jnp.broadcast_to(u_mem0, (2, C, L, F))

            def fits_of(u_mem, allow_b):
                availC, u_self = avail_from_members(u_mem)  # [2, C, F]
                need = wu > 0
                ok = jnp.all(jnp.where(need[None], wu[None] <= availC,
                                       True), axis=-1)
                bblock = (~allow_b) & jnp.any(
                    need[None] & (u_self + wu[None] > sq_cq[None]),
                    axis=-1)
                return ok & ~bblock                         # [2, C]

            # candidates sort eligible-first, so every walkable position
            # for lane c lies below its eligible-candidate count — the
            # while loops exit once every searching lane fitted or
            # exhausted (typical walks are tens of steps, not KC)
            n_elig_c = jnp.sum(elig_any, axis=1).astype(jnp.int32)  # [C]
            # spec 1 exists only for staged searches; an always-empty
            # spec-1 mask must not keep the walk alive to exhaustion
            spec_active = jnp.stack([pre_model, pre_model & staged])

            def gstep(t, u_mem, fitted):
                j = order[cidx, t]                          # [C]
                e_t = msk[:, cidx, j]                       # [2, C]
                usage_t = c_usage[cidx, j]                  # [C, F]
                lm_t = clm[cidx, j]
                cross_t = ~same_cq[cidx, j]
                sq_cand = sq_cq[cci[cidx, j]]               # [C, F]
                oh = (jnp.arange(L, dtype=jnp.int32)[None, :]
                      == lm_t[:, None])                     # [C, L]
                u_cand = jnp.sum(u_mem * oh[None, :, :, None], axis=-2)
                # live borrowing re-check for cross-CQ candidates
                # (preemption.go:309 within the greedy walk)
                live_b = jnp.any(frs_need[None] & (u_cand > sq_cand[None]),
                                 axis=-1)                   # [2, C]
                take = e_t & ~fitted & jnp.where(cross_t[None], live_b,
                                                 True)
                u_mem = u_mem - (take[:, :, None, None]
                                 * oh[None, :, :, None]
                                 * usage_t[None, :, None, :])
                fitted = fitted | (take & fits_of(u_mem, ab))
                return u_mem, fitted, take

            # per-iteration carries are bit-packed [2, C, KC//32] words:
            # a boolean [2, C, KC] carry costs a multi-MB copy per
            # dynamic update at production shapes
            def unpack_bits(wrds):
                bits = (wrds[..., None]
                        >> jnp.arange(32, dtype=jnp.uint32)) & 1
                return bits.reshape(*wrds.shape[:-1], KC).astype(bool)

            def g_cond(state):
                t, u_mem, fitted, take_w = state
                alive = spec_active & ~fitted & (t < n_elig_c)[None, :]
                return (t < KC) & jnp.any(alive)

            def g_body(state):
                t, u_mem, fitted, take_w = state
                u_mem, fitted, take = gstep(t, u_mem, fitted)
                w = t >> 5
                bit = (t & 31).astype(jnp.uint32)
                word = take_w[:, :, w] | (take.astype(jnp.uint32) << bit)
                return (t + 1, u_mem, fitted,
                        take_w.at[:, :, w].set(word))

            t0 = jnp.int32(0)
            _, u_mem, fitted, take_w = jax.lax.while_loop(
                g_cond, g_body,
                (t0, u_mem0, jnp.zeros((2, C), dtype=bool),
                 jnp.zeros((2, C, KCW), dtype=jnp.uint32)))
            take_t = unpack_bits(take_w) & fitted[:, :, None]  # [2,C,KC]
            pos = jnp.arange(KC, dtype=jnp.int32)
            lastpos = jnp.max(jnp.where(take_t, pos, -1), axis=-1)
            keep_w0 = jnp.sum(
                take_t.reshape(2, C, KCW, 32).astype(jnp.uint32)
                * bit_w[None, None, None, :], axis=-1)

            def f_cond(state):
                t, u_mem, keep_w = state
                return t >= 0

            def f_body(state):
                t, u_mem, keep_w = state
                j = order[cidx, t]
                usage_t = c_usage[cidx, j]
                lm_t = clm[cidx, j]
                oh = (jnp.arange(L, dtype=jnp.int32)[None, :]
                      == lm_t[:, None])
                w = t >> 5
                bit = (t & 31).astype(jnp.uint32)
                word = keep_w[:, :, w]
                kt = ((word >> bit) & 1).astype(bool)
                cond = kt & (lastpos != t)                  # [2, C]
                u_try = u_mem + (cond[:, :, None, None]
                                 * oh[None, :, :, None]
                                 * usage_t[None, :, None, :])
                drop = cond & fits_of(u_try, ab)            # fillBack
                u_mem = u_mem + (drop[:, :, None, None]
                                 * oh[None, :, :, None]
                                 * usage_t[None, :, None, :])
                word = word & ~(drop.astype(jnp.uint32) << bit)
                return t - 1, u_mem, keep_w.at[:, :, w].set(word)

            # fill-back only visits positions below the last taken one
            tf0 = jnp.max(lastpos) - 1
            _, _, keep_w = jax.lax.while_loop(
                f_cond, f_body, (tf0, u_mem, keep_w0))
            keep = unpack_bits(keep_w)
            # sorted positions → candidate slots
            inv = jnp.zeros((C, KC), dtype=jnp.int32).at[
                cidx[:, None], order].set(
                jnp.broadcast_to(pos[None, :], (C, KC)))
            take_j = jnp.take_along_axis(keep, inv[None], axis=-1)
            use1 = ~fitted[0] & fitted[1]
            preempting = pre_model & (fitted[0] | fitted[1])
            tgt = jnp.where(use1[:, None], take_j[1], take_j[0])
            tgt = tgt & preempting[:, None]
            return preempting, tgt

        preempting0, tgt0 = jax.lax.cond(
            jnp.any(pre_model), run_searches,
            lambda _: (jnp.zeros(C, dtype=bool),
                       jnp.zeros((C, KC), dtype=bool)),
            operand=None)
        reserve_c = pre_model & ~preempting0

        # -- cycle order + forest schedule ----------------------------
        # entryOrdering (scheduler.go:567) within each forest: borrows
        # asc then the static (priority desc, ts asc, position) rank.
        # Fit heads AND modeled preempt heads participate.
        head_crank = wl_cycle_rank[cidx, row]
        entry_borrows = jnp.where(has_fit, borrows, p_borrows)
        in_scan = has_fit | preempting0 | reserve_c
        fit_key = jnp.where(
            in_scan,
            head_crank + jnp.where(entry_borrows, _BORROW_BIT, 0),
            INF_I32)                                           # [C]
        mem_safe = jnp.maximum(members, 0)
        keys_gl = jnp.where(members >= 0, fit_key[mem_safe],
                            INF_I32)                           # [G, L]
        ord_gl = jnp.argsort(keys_gl, axis=1)
        keys_sorted = jnp.take_along_axis(keys_gl, ord_gl, axis=1)
        mat = jnp.where(keys_sorted < INF_I32,
                        jnp.take_along_axis(mem_safe, ord_gl, axis=1),
                        -1)                                    # [G, L]

        # -- admit scan: one entry per forest per step ----------------
        # Carries CQ-level scan/check usage (admit_scan_preempt's
        # usage / usage_check split, scheduler.go:372 fits under
        # PreemptedWorkloads) + the used-target marks; upper tree levels
        # are rebuilt from the subtree invariant each step.  The target
        # gather/scatter machinery is KC-sized per step, so cycles with
        # no preempting entry run a light scan without it.
        def make_step(with_targets: bool):
            def step(scan_carry, col):
                u_scan, u_check, used = scan_carry
                cqs = mat[:, col]                              # [G]
                valid_l = cqs >= 0
                cs = jnp.maximum(cqs, 0)
                lane_pre = preempting0[cs] & valid_l           # [G]
                if with_targets:
                    lane_tgt = tgt0[cs] & lane_pre[:, None]    # [G, KC]
                    rows_l = jnp.maximum(crows[cs], 0)         # [G, KC]
                    tci = (rows_l // M).astype(jnp.int32)
                    tmi = (rows_l % M).astype(jnp.int32)
                    overlap = jnp.any(used[tci * M + tmi] & lane_tgt,
                                      axis=1)
                    act = lane_pre & ~overlap
                    tgt_act = lane_tgt & act[:, None]
                    tdelta = adm_usage[tci, tmi]               # [G,KC,F]
                    rem = jnp.zeros((C, F), dtype=jnp.int32).at[tci].add(
                        jnp.where(tgt_act[:, :, None], tdelta, 0))
                    plane_check2 = rebuild_usage(u_check - rem)
                else:
                    overlap = jnp.zeros(G, dtype=bool)
                    act = lane_pre
                    plane_check2 = rebuild_usage(u_check)
                plane_scan = rebuild_usage(u_scan)

                def lane(cq, is_act):
                    cq_s = jnp.maximum(cq, 0)
                    avail_row = available_at(plane_check2, subtree,
                                             guaranteed, borrow_cap,
                                             has_blim, parent, cq_s,
                                             depth)
                    # fit entry: fixed-slot re-check
                    slot = jnp.maximum(fit_slot[cq_s], 0)
                    frs_l = slot_fr[cq_s, slot]                # [R]
                    amt_l = req[cq_s]
                    frs_ls = jnp.maximum(frs_l, 0)
                    rel_l = (frs_l >= 0) & (amt_l > 0)
                    fit_ok = jnp.all(jnp.where(
                        rel_l, amt_l <= avail_row[frs_ls], True))
                    admit = (cq >= 0) & has_fit[cq_s] & fit_ok
                    delta = jnp.zeros(F, dtype=jnp.int32).at[frs_ls].add(
                        jnp.where(rel_l & admit, amt_l, 0))
                    # preempting entry: fits after its targets removed
                    wuc = wu[cq_s]
                    pre_ok = jnp.all(jnp.where(wuc > 0,
                                               wuc <= avail_row, True))
                    pre_now = is_act & pre_ok
                    delta = delta + jnp.where(pre_now, wuc, 0)
                    # reserve entry (resourcesToReserve, scheduler:383)
                    is_res = (cq >= 0) & reserve_c[cq_s]
                    cur = plane_scan[cq_s]                     # [F]
                    res_b = jnp.minimum(wuc, npb_cq[cq_s] - cur)
                    res_n = jnp.maximum(0, jnp.minimum(
                        wuc, nominal_cq[cq_s] - cur))
                    rdelta = jnp.where(p_borrows[cq_s], res_b, res_n)
                    delta = delta + jnp.where(is_res & (wuc > 0),
                                              rdelta, 0)
                    charged = admit | pre_now | is_res
                    return (admit, pre_now, is_act & ~pre_ok, delta,
                            charged)

                admit_l, pre_l, nofit_l, deltas, charged_l = (
                    jax.vmap(lane)(cqs, act))
                add = jnp.where((charged_l & valid_l)[:, None],
                                deltas, 0)
                u_scan = u_scan.at[cs].add(add)
                if with_targets:
                    rem_commit = jnp.zeros(
                        (C, F), dtype=jnp.int32).at[tci].add(
                        jnp.where((tgt_act & pre_l[:, None])[:, :, None],
                                  tdelta, 0))
                    u_check = u_check - rem_commit
                    used = used.at[(tci * M + tmi).reshape(-1)].max(
                        (tgt_act & pre_l[:, None]).reshape(-1))
                u_check = u_check.at[cs].add(add)
                return (u_scan, u_check, used), (admit_l, pre_l,
                                                 nofit_l,
                                                 overlap & lane_pre)
            return step

        used0 = jnp.zeros(CM, dtype=bool)
        cols = jnp.arange(L)

        def scan_heavy(_):
            return jax.lax.scan(make_step(True), (u_cq, u_cq, used0),
                                cols)

        def scan_light(_):
            return jax.lax.scan(make_step(False), (u_cq, u_cq, used0),
                                cols)

        (u_scan, _, used), (admit_cols, pre_cols, nofit_cols,
                            ovl_cols) = jax.lax.cond(
            jnp.any(preempting0), scan_heavy, scan_light, operand=None)
        # scatter scan lanes back to per-CQ flags
        flat_cq = mat.T.reshape(-1)                            # [L*G]
        fv = flat_cq >= 0
        fs_ = jnp.maximum(flat_cq, 0)

        def scatter_flag(cols):
            return jnp.zeros(C, dtype=bool).at[fs_].max(
                cols.reshape(-1) & fv)

        admitted_c = scatter_flag(admit_cols)
        preempting_c = scatter_flag(pre_cols)
        pre_nofit_c = scatter_flag(nofit_cols)
        overlap_c = scatter_flag(ovl_cols)

        # -- end-of-cycle state transitions ---------------------------
        # admit delta per admitted head (committed usage)
        fslot_s = jnp.maximum(fit_slot, 0)
        afrs = slot_fr[cidx, fslot_s]                          # [C, R]
        arel = (afrs >= 0) & (req > 0) & admitted_c[:, None]
        afrs_s = jnp.maximum(afrs, 0)
        adm_delta = jnp.zeros((C, F), dtype=jnp.int32).at[
            cidx[:, None], afrs_s].add(jnp.where(arel, req, 0))
        adm_uses_new = jnp.zeros((C, F), dtype=bool).at[
            cidx[:, None], afrs_s].max(arel)

        skipped = has_fit & ~admitted_c            # stays eligible
        # a reserve head whose walk stopped mid-list keeps pending
        # flavors: the host requeues it immediately (cluster_queue.py
        # _requeue_if_not_present) so it stays eligible, not parked
        park_new = ((has_head & ~has_fit & ~has_preempt & ~dirty_c)
                    | (reserve_c & ~pending_c)) & ~strict_cq
        gone = admitted_c | park_new
        elig = elig.at[cidx, row].set(
            jnp.where(gone, False, elig[cidx, row]))
        parked = parked.at[cidx, row].set(
            park_new | parked[cidx, row])
        # fungibility resume: heads whose walk stopped mid-list and that
        # requeue with the recorded last_state restart at tried+1
        # (skip / pending reserve / overlap-skip / preempt-nofit);
        # everything else — admit (a later eviction requeues a FRESH
        # Info), park, preempt issued, strict NoFit — resets to 0
        keep_resume = (skipped | (reserve_c & pending_c) | overlap_c
                       | pre_nofit_c)
        head_start = jnp.where(keep_resume & pending_c, tried_c + 1, 0)
        resume = resume.at[cidx, row].set(
            jnp.where(has_head, head_start, resume[cidx, row]))
        # admitted rows join the quota-holding table
        adm = adm.at[cidx, row].set(admitted_c | adm[cidx, row])
        adm_seq = adm_seq.at[cidx, row].set(
            jnp.where(admitted_c, seq_base + k, adm_seq[cidx, row]))
        adm_usage = adm_usage.at[cidx, row].set(
            jnp.where(admitted_c[:, None], adm_delta,
                      adm_usage[cidx, row]))
        adm_uses = adm_uses.at[cidx, row].set(
            jnp.where(admitted_c[:, None], adm_uses_new,
                      adm_uses[cidx, row]))
        death_new = (k + runtime) if runtime > 0 else INF_I32
        death = death.at[cidx, row].set(
            jnp.where(admitted_c, death_new, death[cidx, row]))

        # evictions: committed targets leave the table, release usage,
        # and requeue at their original heap rank (queue ordering uses
        # creation time for preemption evictions — workload.py:309)
        used2 = used.reshape(C, M)
        rel_evict = jnp.einsum("cm,cmf->cf", used2.astype(jnp.int32),
                               adm_usage,
                               preferred_element_type=jnp.int32)
        adm = adm & ~used2
        elig = elig | used2
        death = jnp.where(used2, INF_I32, death)

        # modeled finishes: rows whose death is this cycle (eviction
        # wins when both land on the same cycle — the host's admission-
        # identity guard skips the stale finish)
        due = adm & (death == k)
        rel_death = jnp.einsum("cm,cmf->cf", due.astype(jnp.int32),
                               adm_usage,
                               preferred_element_type=jnp.int32)
        adm = adm & ~due

        release = rel_evict + rel_death + ext_release[k]
        u_cq_next = u_cq + adm_delta - release
        released_forest = jnp.zeros(G, dtype=bool).at[forest_of_cq].max(
            jnp.any(release > 0, axis=1))
        unpark_f = ext_unpark[k] | released_forest             # [G]
        do_unpark = unpark_f[forest_of_cq]                     # [C]
        back = parked & do_unpark[:, None]
        elig = elig | back
        parked = parked & ~back

        # -- decision output ------------------------------------------
        kind = jnp.zeros(C, dtype=jnp.int32)
        kind = jnp.where(park_new, KIND_PARK, kind)
        kind = jnp.where(skipped, KIND_SKIP, kind)
        kind = jnp.where(admitted_c, KIND_ADMIT, kind)
        kind = jnp.where(reserve_c, KIND_RESERVE, kind)
        kind = jnp.where(preempting_c, KIND_PREEMPT, kind)
        kind = jnp.where(overlap_c, KIND_OVERLAP_SKIP, kind)
        kind = jnp.where(pre_nofit_c, KIND_PRE_NOFIT, kind)
        slot_out = jnp.where(has_fit, fit_slot,
                             jnp.where(pre_model, p_idx, -1))
        borrows_out = jnp.where(has_fit, borrows, p_borrows)
        tgt_commit = tgt0 & preempting_c[:, None]              # [C, KC]
        tgt_words = jnp.sum(
            tgt_commit.reshape(C, KCW, 32).astype(jnp.uint32)
            * bit_w[None, None, :], axis=-1)                   # [C,KCW]

        out = (jnp.where(has_head, row, -1), kind, slot_out,
               borrows_out, tgt_words, dflags)
        carry = (elig, parked, resume, adm, adm_seq, adm_usage,
                 adm_uses, death, u_cq_next)
        return carry, out

    carry0 = (elig0, parked0, resume0, adm0, adm_seq0, adm_usage0,
              adm_uses0, death0, u_cq0)
    carry, outs = jax.lax.scan(cycle, carry0,
                               jnp.arange(K, dtype=jnp.int32))
    head_row, kind, slot, borrows, tgt_words, dflags = outs
    if axis_name is not None:
        dflags = jax.lax.psum(dflags, axis_name)           # [K, 4]
    dirty = dflags[:, 0] > 0
    dirty_reason = (
        (dflags[:, 1] > 0).astype(jnp.int32) * DIRTY_PREEMPT
        + (dflags[:, 2] > 0).astype(jnp.int32) * DIRTY_SCALAR
        + (dflags[:, 3] > 0).astype(jnp.int32) * DIRTY_RESUME)
    # the full final carry is returned so a pipelined caller can chain
    # the NEXT window's dispatch off the device-resident state (death
    # rebased by -K, seq_base advanced) without a host re-pack
    return (head_row, kind, slot, borrows, tgt_words, dirty,
            dirty_reason, carry)


# the public jitted entrypoint; ``axis_name`` stays None on the serial
# path and names the mesh axis when the raw body runs inside the
# shard_map wrapper (parallel.sharded.sharded_burst_fn)
burst_cycles = partial(
    jax.jit,
    static_argnames=("K", "depth", "L", "S", "KC", "n_levels", "G",
                     "runtime", "axis_name"))(_burst_cycles)


def build_members(forest_of_cq: np.ndarray, n_forests: int,
                  max_per_forest: int) -> np.ndarray:
    """Static [G, L] matrix of CQ indices per forest (-1 pad)."""
    members = np.full((n_forests, max_per_forest), -1, dtype=np.int32)
    fill = np.zeros(n_forests, dtype=np.int64)
    for ci, f in enumerate(forest_of_cq):
        f = int(f)
        if fill[f] < max_per_forest:
            members[f, fill[f]] = ci
            fill[f] += 1
    return members


# ----------------------------------------------------------------------
# Roofline probe (synthetic; used by scripts/accel_roofline.py)
# ----------------------------------------------------------------------

_probe_cache: dict = {}


def burst_probe(C: int, M: int, R: int, K: int, runtime: int = 4):
    """One fused-burst dispatch on synthetic north-star-shaped data.
    Returns the device arrays (caller device_gets them)."""
    key = (C, M, R)
    if key not in _probe_cache:
        rng = np.random.default_rng(0)
        G = max(1, C // 5)
        N = C + G
        F = R
        parent = np.concatenate([
            C + (np.arange(C) % G), np.full(G, -1)]).astype(np.int32)
        node_level = np.concatenate([
            np.ones(C, np.int32), np.zeros(G, np.int32)])
        forest_of_cq = (np.arange(C) % G).astype(np.int32)
        subtree = np.full((N, F), 10**7, np.int32)
        guaranteed = np.full((N, F), 20_000, np.int32)
        guaranteed[C:] = 10**7
        borrow_cap = np.full((N, F), 2**25, np.int32)
        has_blim = np.zeros((N, F), bool)
        nominal_cq = np.full((C, F), 20_000, np.int32)
        slot_fr = np.tile(np.arange(R, dtype=np.int32), (C, 1, 1))
        slot_valid = np.ones((C, 1), bool)
        cpb = np.zeros(C, bool)
        strict = np.zeros(C, bool)
        members = build_members(forest_of_cq, G, 8)
        wl_req = rng.integers(200, 2000, (C, M, R)).astype(np.int32)
        wl_rank = np.argsort(rng.random((C, M))).astype(np.int32)
        wl_cycle_rank = rng.permutation(C * M).reshape(C, M).astype(np.int32)
        ones = np.ones((C, M), bool)
        zeros = np.zeros((C, M), bool)
        u_cq0 = np.zeros((C, F), np.int32)
        from .cycle import available_all_np
        potential0 = available_all_np(
            np.zeros((N, F), np.int64), subtree, guaranteed, borrow_cap,
            has_blim, parent, 2).astype(np.int32)
        _probe_cache[key] = dict(
            wl_req=wl_req, wl_rank=wl_rank, wl_cycle_rank=wl_cycle_rank,
            vec_ok=ones, elig0=ones, parked0=zeros, resume0=zeros,
            u_cq0=u_cq0, potential0=potential0, subtree=subtree,
            guaranteed=guaranteed, borrow_cap=borrow_cap,
            has_blim=has_blim, parent=parent, node_level=node_level,
            nominal_cq=nominal_cq, slot_fr=slot_fr,
            slot_valid=slot_valid, cq_can_preempt_borrow=cpb,
            forest_of_cq=forest_of_cq, strict_cq=strict, members=members,
            G=G)
    d = _probe_cache[key]
    G = d["G"]
    F = R
    ext_release = np.zeros((K, C, R), np.int32)
    ext_unpark = np.zeros((K, G), bool)
    L = 8
    KC = ((L * M + 31) // 32) * 32
    cand_rows, cand_lmem, self_lmem = build_candidate_tables(
        d["forest_of_cq"], d["members"], M, KC)
    zeros_cm = np.zeros((C, M), np.int32)
    return burst_cycles(
        d["wl_req"], d["wl_rank"], d["wl_cycle_rank"],
        zeros_cm, zeros_cm,
        d["vec_ok"], d["elig0"], d["parked0"], zeros_cm,
        np.zeros((C, M), bool), zeros_cm,
        np.zeros((C, M, F), np.int32), np.zeros((C, M, F), bool),
        np.full((C, M), I32_MAX, np.int32), np.int32(1),
        d["u_cq0"],
        d["potential0"], d["subtree"], d["guaranteed"], d["borrow_cap"],
        d["has_blim"], d["parent"], d["node_level"], d["nominal_cq"],
        np.full((C, F), I32_MAX, np.int32),
        d["slot_fr"], d["slot_valid"],
        d["cq_can_preempt_borrow"],
        np.ones(C, bool), np.zeros(C, bool),
        d["forest_of_cq"], d["strict_cq"],
        np.zeros(C, bool), np.zeros(C, bool), np.zeros(C, bool),
        np.zeros(C, bool),
        d["members"], cand_rows, cand_lmem, self_lmem,
        ext_release, ext_unpark,
        K=K, depth=2, L=L, S=1, KC=KC, n_levels=2, G=G,
        runtime=runtime)


# ----------------------------------------------------------------------
# Host side: pack the live queue/cache state into a burst plan
# ----------------------------------------------------------------------

@dataclass
class BurstPlan:
    """Dense device state for one burst + the host maps to apply it."""
    structure: object                 # PackedStructure
    arrays: dict                      # kernel inputs (numpy)
    keys: list                        # [C][M] workload key or None
    C: int
    M: int
    L: int
    G: int
    n_levels: int
    KC: int = 0
    seq_base: int = 1
    row_of_key: dict = None           # key -> (ci, mi)
    max_res_ts: Optional[float] = None  # newest pre-burst reservation
    # shard-resident chaining (pack_burst_cached): the delta-pack state
    # tokens this plan consumed/produced and the CQ indices it re-walked.
    # A resident device copy of the PREVIOUS pack's rows is reusable iff
    # its token matches prev_token — then exactly dirty_cqs rows differ.
    pack_token: Optional[int] = None
    prev_token: Optional[int] = None
    dirty_cqs: Optional[np.ndarray] = None   # None = full walk
    dirty_ranges: Optional[list] = None      # coalesced [lo, hi) rows
    # head-pack accounting: rows charged against the kernel's 2^19
    # composite-key budget vs total rows packed into the [C, M] grid
    # (budget_rows == grid_rows when KUEUE_TPU_HEAD_PACK=0)
    budget_rows: int = 0
    grid_rows: int = 0


def build_candidate_tables(forest_of_cq: np.ndarray, members: np.ndarray,
                           M: int, KC: int):
    """Static preemption-candidate tables: for each forest the flattened
    row ids (cq*M+m) of every member CQ's rows, each row's member slot,
    and each CQ's own member slot."""
    G, L = members.shape
    C = len(forest_of_cq)
    cand_rows = np.full((G, KC), -1, dtype=np.int32)
    cand_lmem = np.zeros((G, KC), dtype=np.int32)
    self_lmem = np.zeros(C, dtype=np.int32)
    for g in range(G):
        j = 0
        for l in range(L):
            cq = int(members[g, l])
            if cq < 0:
                continue
            self_lmem[cq] = l
            n = min(M, KC - j)
            if n > 0:
                cand_rows[g, j:j + n] = cq * M + np.arange(n)
                cand_lmem[g, j:j + n] = l
            j += M
    return cand_rows, cand_lmem, self_lmem


def _static_row(info, st, covers_pods: bool, qts):
    """Per-Info static pack facts: (covers_pods, scaled request vector,
    static vectorized-eligibility, queue-order ts, priority, uid).
    Cached on the Info keyed by the structure generation — requests,
    conditions, and priority are immutable per Info instance (updates
    build a fresh Info — queue/manager.py add_or_update_workload)."""
    R = len(st.resource_names)
    scale = st.resource_scale
    obj = info.obj
    ok = (len(obj.pod_sets) == 1
          and obj.pod_sets[0].topology_request is None
          and not any(ps.min_count is not None and ps.min_count < ps.count
                      for ps in obj.pod_sets))
    exact = True
    acc = np.zeros(R, dtype=np.int64)
    for psr in info.total_requests:
        for r, v in psr.requests.items():
            if r == "pods" and not covers_pods:
                continue
            ri = st.r_index.get(r)
            if ri is None:
                exact = False
                continue
            if v < 0:
                exact = False
                v = 0
            if st.scale_is_one:
                acc[ri] += int(v)
            else:
                s = int(scale[ri])
                q_, rem = divmod(int(v), s)
                if rem:
                    exact = False
                    q_ += 1
                acc[ri] += q_
    if acc.max(initial=0) > I32_MAX:
        exact = False
        np.clip(acc, None, I32_MAX, out=acc)
    return (covers_pods, acc.astype(np.int32), ok and exact,
            qts(obj), obj.priority, obj.uid)


KC_CAP = 4096          # max candidate slots per forest (in-kernel preempt)


def admitted_usage_vec(info, st, scale_of: dict, F: int) -> Optional[tuple]:
    """(usage [F] int32, uses [F] bool) of an admitted Info, scaled into
    the packed structure's flavor-resource axis; None when not exactly
    representable.  Cached on the Info per (structure generation,
    reservation time) — the usage map is stable per admission, and both
    re-packs and the driver's finish-schedule fill walk every admitted
    workload."""
    from ..api.types import WL_QUOTA_RESERVED
    cond = info.obj.conditions.get(WL_QUOTA_RESERVED)
    ts = cond.last_transition_time if cond is not None else -1.0
    gen = st.generation
    hit = getattr(info, "_burst_usage", None)
    if hit is not None and hit[0] == gen and hit[1] == ts:
        return hit[2]
    vec = np.zeros(F, dtype=np.int64)
    uses = np.zeros(F, dtype=bool)
    out = None
    ok = True
    for fr, v in info.usage().items():
        fi = st.fr_index.get(fr)
        s = scale_of.get(fr.resource) if fi is not None else None
        if fi is None or s is None or v % s:
            ok = False
            break
        vec[fi] += v // s
        uses[fi] = True
    if ok and vec.max(initial=0) <= I32_MAX:
        out = (vec.astype(np.int32), uses)
    info._burst_usage = (gen, ts, out)
    return out


_PACK_FAIL = object()   # sentinel: this CQ fails the whole pack


class _CQRows:
    """One CQ's packed rows (pending then admitted) plus the per-CQ
    facts stage B needs.  Records are the unit of delta reuse: a clean
    record re-enters ``_assemble_plan`` untouched while a dirty CQ
    re-walks into a fresh record.  Row order within a record never
    reaches the plan — every stage-B rank comes from a total-order
    lexsort with a unique final tiebreak — so reuse stays bit-identical
    even though a re-walk may enumerate members differently.

    ``n_comp`` / ``comp_max_ts`` account for admitted rows of
    compressible forests (ops/aggregate.py) that were walked but NOT
    packed: their count and max reservation time are all the plan
    needs from them (usage is already in ``u_row``)."""
    __slots__ = ("ci", "pos", "strict", "bad", "truncated",
                 "n_pend", "n_adm", "n_comp", "comp_max_ts",
                 "keys", "uids", "prio", "ts",
                 "res_ts", "parked", "ok", "resume", "adm", "req",
                 "usage", "uses", "u_row", "index_of_key", "infos")

    @property
    def n_rows(self) -> int:
        return self.n_pend + self.n_adm


class _PackStatics:
    """Structure-keyed stage-B tables: tree levels, forest membership,
    preemption-policy flags and the zero-usage potential — all pure
    functions of the packed structure (CQ/cohort spec edits bump the
    structure generation), memoized on the structure object so re-packs
    and delta packs skip the O(N·depth) Python walks."""
    __slots__ = ("forest_of_cq", "node_level", "n_levels", "L",
                 "members", "deep", "wcq_lower", "rwc_enabled",
                 "rwc_only_lower", "modelable_base", "potential0",
                 "comp_cq", "cand_tables")


def _pack_statics(st, cache) -> _PackStatics:
    s = getattr(st, "_burst_statics", None)
    if s is not None:
        return s
    from ..api.types import (BorrowWithinCohortPolicy,
                             ReclaimWithinCohort, WithinClusterQueue)
    from .cycle import available_all_np
    C = len(st.cq_names)
    F = max(1, len(st.fr_index))
    G = st.n_forests
    N = st.node_count
    parent = st.parent
    s = _PackStatics()
    s.cand_tables = {}
    s.forest_of_cq = st.forest_of_node[:C].astype(np.int32)
    node_level = np.zeros(N, dtype=np.int32)
    for ni in range(N):
        lvl, p = 0, parent[ni]
        while p >= 0:
            lvl += 1
            p = parent[p]
        node_level[ni] = lvl
    # node_level[ni] = distance from root (roots = 0); rebuild_usage
    # sweeps deepest levels first via range(n_levels-1, 0, -1)
    s.node_level = node_level
    s.n_levels = int(node_level.max()) + 1
    per_forest = np.bincount(s.forest_of_cq, minlength=G)
    s.L = max(1, int(per_forest.max()))
    s.members = build_members(s.forest_of_cq, G, s.L)
    # forest depth > 2 (nested cohorts) is outside the envelope
    deep = np.zeros(G, dtype=bool)
    np.maximum.at(deep, s.forest_of_cq, node_level[:C] > 1)
    s.deep = deep
    wcq_lower = np.zeros(C, dtype=bool)
    rwc_enabled = np.zeros(C, dtype=bool)
    rwc_only_lower = np.zeros(C, dtype=bool)
    modelable_base = np.zeros(C, dtype=bool)
    for ci, name in enumerate(st.cq_names):
        cq_live = cache.cluster_queue(name)
        if cq_live is None:
            continue
        pol = cq_live.spec.preemption
        wcq_lower[ci] = (pol.within_cluster_queue
                         == WithinClusterQueue.LOWER_PRIORITY)
        rwc_enabled[ci] = (pol.reclaim_within_cohort
                           != ReclaimWithinCohort.NEVER)
        rwc_only_lower[ci] = (pol.reclaim_within_cohort
                              == ReclaimWithinCohort.LOWER_PRIORITY)
        modelable_base[ci] = (
            pol.borrow_within_cohort.policy
            == BorrowWithinCohortPolicy.NEVER
            and pol.within_cluster_queue
            != WithinClusterQueue.LOWER_OR_NEWER_EQUAL_PRIORITY)
    s.wcq_lower = wcq_lower
    s.rwc_enabled = rwc_enabled
    s.rwc_only_lower = rwc_only_lower
    s.modelable_base = modelable_base
    from .aggregate import compressible_cqs
    s.comp_cq = compressible_cqs(s)
    s.potential0 = np.minimum(available_all_np(
        np.zeros((N, F), np.int64), st.subtree_quota, st.guaranteed,
        st.borrow_cap, st.has_borrow_limit, st.parent, st.depth),
        np.int64(I32_MAX)).astype(np.int32)
    st._burst_statics = s
    return s


def _unknown_active_cq(st, queues) -> bool:
    """An active CQ with pending work the structure doesn't know about
    fails the pack (the kernel can't model it at all)."""
    known = st.cq_index
    for name in queues.cluster_queue_names():
        if name in known:
            continue
        q = queues.queue_for(name)
        if q is not None and q.active and q.pending_active():
            return True
    return False


def _pack_cq_rows(st, ci, pos, queues, cache, scheduler, assumed,
                  scale_of, window, compress=False):
    """Stage A for ONE ClusterQueue: walk its heap + parking lot and
    its admitted table into a _CQRows record, or _PACK_FAIL when the CQ
    can't be represented (missing from the cache, inexact usage
    scaling).

    With ``compress`` (CQ in a compressible forest + aggregate planes
    on) the admitted walk runs identically — same bad-detection, same
    usage-vector check, so ``rec.bad`` matches the uncompressed arm
    byte for byte — but representable admitted rows are folded into
    ``n_comp`` / ``comp_max_ts`` aggregates instead of packed rows."""
    from ..api.types import (QueueingStrategy, AdmissionCheckState,
                             WL_EVICTED, WL_QUOTA_RESERVED)
    from .packing import scaled_usage_row
    ordering = scheduler.ordering
    qts = ordering.queue_order_timestamp
    F = max(1, len(st.fr_index))
    R = len(st.resource_names)
    gen = st.generation
    cq_name = st.cq_names[ci]
    cq_live = cache.cluster_queue(cq_name)
    if cq_live is None:
        return _PACK_FAIL
    u_row = scaled_usage_row(st, cq_live)
    if u_row is None:
        return _PACK_FAIL

    rec = _CQRows()
    rec.ci = ci
    rec.pos = pos
    rec.bad = False
    rec.truncated = False
    rec.n_comp = 0
    rec.comp_max_ts = -np.inf

    q = queues.queue_for(cq_name)
    active = q is not None and q.active
    rec.strict = bool(
        active and q.queueing_strategy == QueueingStrategy.STRICT_FIFO)
    members = []
    parked_keys = set()
    if active:
        members.extend(q.heap.items())
        for key, info in q.inadmissible.items():
            rs = info.obj.requeue_state
            if rs is not None and rs.requeue_at is not None:
                # backoff-parked: excluded; a mid-burst expiry diverges
                # the heads and the application validator truncates
                continue
            members.append(info)
            parked_keys.add(info.key)

    if window > 0:
        cap = window + 2
        if len(members) > cap:
            import heapq

            def sel_key(info):
                # the tuple is rebuilt ~rows×windows times at scale;
                # cache it per structure generation alongside the row
                sel = getattr(info, "_burst_sel", None)
                if sel is not None and sel[0] == gen:
                    return sel[1]
                row = getattr(info, "_burst_row", None)
                if row is not None and row[0] == gen:
                    t = (-row[5], row[4], info.key)
                else:
                    obj = info.obj
                    t = (-obj.priority, qts(obj), info.key)
                info._burst_sel = (gen, t)
                return t

            members = heapq.nsmallest(cap, members, key=sel_key)
            rec.truncated = True

    admitted = []
    for key, info in cq_live.workloads.items():
        obj = info.obj
        # assumed-but-applied workloads are normal candidates (the
        # apply hook is synchronous here; a failed apply forgets the
        # assumption before the cycle returns) — only a live evicted
        # condition or a missing reservation breaks the modeled
        # candidate ordering
        if (obj.condition_true(WL_EVICTED)
                or obj.conditions.get(WL_QUOTA_RESERVED) is None):
            rec.bad = True
            continue
        admitted.append(info)

    covers_pods = cq_name in st.cq_covers_pods
    cq_ok = st.cq_vector_ok
    cq_vec = bool(cq_ok[ci]) if cq_ok is not None else False
    if cq_vec and cq_live.spec.namespace_selector:
        cq_vec = False   # selector evaluation stays on the host path
    lr_summaries = scheduler.limit_range_summaries

    n_upper = len(members) + len(admitted)
    prio_l: list[int] = []
    ts_l: list[float] = []
    res_ts_l: list[float] = []
    parked_l: list[bool] = []
    ok_l: list[bool] = []
    resume_l: list[int] = []      # flavor-walk start slot (0 = full)
    key_l: list[str] = []
    uid_l: list[str] = []
    infos: list = []
    req_mat = np.zeros((n_upper, R), dtype=np.int32)
    usage_mat = np.zeros((n_upper, F), dtype=np.int32)
    uses_mat = np.zeros((n_upper, F), dtype=bool)

    i = 0
    for info in members:
        row = getattr(info, "_burst_row", None)
        if row is None or row[0] != gen or row[1] != covers_pods:
            row = (gen, *_static_row(info, st, covers_pods, qts))
            info._burst_row = row
        _, _, req_vec, static_ok, ts, prio, uid = row
        key = info.key
        key_l.append(key)
        uid_l.append(uid)
        prio_l.append(prio)
        ts_l.append(ts)
        res_ts_l.append(0.0)
        parked_l.append(key in parked_keys)
        req_mat[i] = req_vec
        ok = cq_vec and static_ok
        if ok:
            obj = info.obj
            if lr_summaries and lr_summaries.get(obj.namespace):
                ok = False   # LimitRange bounds stay host-side
            elif key in assumed or obj.admission is not None:
                ok = False
            elif obj.admission_check_states and any(
                    stt.state in (AdmissionCheckState.RETRY,
                                  AdmissionCheckState.REJECTED)
                    for stt in obj.admission_check_states.values()):
                ok = False
        ok_l.append(ok)
        from .solver import resume_start
        resume_l.append(resume_start(info, cq_live, covers_pods))
        infos.append(info)
        i += 1
    rec.n_pend = i

    for info in admitted:
        uv = admitted_usage_vec(info, st, scale_of, F)
        if uv is None:
            # not representable as a target/release row: the host
            # handles its cycles (forest out of the envelope) and
            # its finish via the ext_release path
            rec.bad = True
            continue
        if compress:
            # never candidate-gathered (no preempting CQ in this
            # forest): fold into the aggregates; a mid-burst finish
            # reaches the kernel via the ext_release fallback exactly
            # as an unpacked key does today
            rec.n_comp += 1
            ts_r = info.obj.conditions[WL_QUOTA_RESERVED] \
                .last_transition_time
            if ts_r > rec.comp_max_ts:
                rec.comp_max_ts = ts_r
            continue
        row = getattr(info, "_burst_row", None)
        if row is None or row[0] != gen or row[1] != covers_pods:
            row = (gen, *_static_row(info, st, covers_pods, qts))
            info._burst_row = row
        _, _, req_vec, static_ok, ts, prio, uid = row
        key_l.append(info.key)
        uid_l.append(uid)
        prio_l.append(prio)
        ts_l.append(ts)
        parked_l.append(False)
        obj = info.obj
        cond = obj.conditions.get(WL_QUOTA_RESERVED)
        res_ts_l.append(cond.last_transition_time)
        req_mat[i] = req_vec
        usage_mat[i], uses_mat[i] = uv
        # post-eviction afterlife: the same dynamic gates pending
        # rows get (LimitRange bounds, failed admission checks) —
        # an in-burst-evicted row the kernel re-admits must honor
        # everything the host nominate would; gating extra is safe
        # (the cycle goes dirty), gating less diverges decisions
        ok = cq_vec and static_ok
        if ok:
            if lr_summaries and lr_summaries.get(obj.namespace):
                ok = False
            elif obj.admission_check_states and any(
                    stt.state in (AdmissionCheckState.RETRY,
                                  AdmissionCheckState.REJECTED)
                    for stt in obj.admission_check_states.values()):
                ok = False
        ok_l.append(ok)
        resume_l.append(0)
        infos.append(info)
        i += 1
    rec.n_adm = i - rec.n_pend

    rec.keys = (np.asarray(key_l) if key_l
                else np.empty(0, dtype="U1"))
    rec.uids = (np.asarray(uid_l) if uid_l
                else np.empty(0, dtype="U1"))
    rec.prio = np.array(prio_l, dtype=np.int64)
    rec.ts = np.array(ts_l, dtype=np.float64)
    rec.res_ts = np.array(res_ts_l, dtype=np.float64)
    rec.parked = np.array(parked_l, dtype=bool)
    rec.ok = np.array(ok_l, dtype=bool)
    rec.resume = np.array(resume_l, dtype=np.int32)
    adm = np.zeros(i, dtype=bool)
    adm[rec.n_pend:] = True
    rec.adm = adm
    rec.req = req_mat[:i]
    rec.usage = usage_mat[:i]
    rec.uses = uses_mat[:i]
    rec.u_row = u_row
    rec.index_of_key = {k: j for j, k in enumerate(key_l)}
    rec.infos = infos
    return rec


def _walk_records(st, queues, cache, scheduler, window):
    """Stage A over every CQ; None when any CQ fails the pack."""
    C = len(st.cq_names)
    # CQ-position order (the queue manager's heads enumeration order)
    pos_of = {name: i for i, name in
              enumerate(queues.cluster_queue_names())}
    assumed = cache.assumed_workloads
    scale_of = {r: int(st.resource_scale[i])
                for i, r in enumerate(st.resource_names)}
    from .aggregate import agg_planes_enabled
    s = _pack_statics(st, cache)
    comp_cq = s.comp_cq if agg_planes_enabled() else None
    records = []
    for ci in range(C):
        rec = _pack_cq_rows(st, ci, pos_of.get(st.cq_names[ci], C),
                            queues, cache, scheduler, assumed,
                            scale_of, window,
                            compress=(comp_cq is not None
                                      and bool(comp_cq[ci])))
        if rec is _PACK_FAIL:
            return None
        records.append(rec)
    return records


_ROW_ATTRS = ("adm", "prio", "ts", "res_ts", "parked", "ok",
              "resume", "req", "usage", "uses", "keys", "uids")


def _concat_row_fields(records, nz, prev):
    """Concatenate the per-record row arrays into flat stage-B fields.

    ``prev`` (previous record list + its concatenated fields, from the
    delta state) turns the 1000-segment concatenation into a few-chunk
    splice: runs of reused record objects slice the cached flat arrays
    (their rows are unchanged by construction), only re-walked records
    contribute fresh segments.  Returns (fields, bounds) with the same
    values a plain concatenation would produce."""
    chunks = None
    if prev is not None:
        prev_records, prev_fields = prev
        if prev_fields is not None and len(prev_records) == len(records):
            bounds = prev_fields["_bounds"]
            chunks = []          # (0, lo, hi) = prev slice; (1, i, 0)
            run = None
            for i, r in enumerate(records):
                if r is prev_records[i]:
                    if run is None:
                        run = [int(bounds[i]), int(bounds[i + 1])]
                    else:
                        run[1] = int(bounds[i + 1])
                else:
                    if run is not None:
                        chunks.append((0, run[0], run[1]))
                        run = None
                    if r.n_rows:
                        chunks.append((1, i, 0))
            if run is not None:
                chunks.append((0, run[0], run[1]))
    fields = {}
    if chunks is not None:
        for attr in _ROW_ATTRS:
            prev_arr = prev_fields[attr]
            fields[attr] = np.concatenate(
                [prev_arr[lo:hi] if tag == 0
                 else getattr(records[lo], attr)
                 for tag, lo, hi in chunks]) if chunks else prev_arr[:0]
    else:
        for attr in _ROW_ATTRS:
            fields[attr] = np.concatenate(
                [getattr(r, attr) for r in nz])
    n_rows_arr = np.fromiter((r.n_rows for r in records),
                             dtype=np.int64, count=len(records))
    fields["_bounds"] = np.concatenate(
        ([0], np.cumsum(n_rows_arr)))
    return fields


def _assemble_plan(st, records, cache, scheduler, min_m,
                   prev=None, fields_out=None):
    """Stage B: fuse per-CQ row records into the dense [C, M] plan.

    Pure vectorized numpy over the concatenated rows; every rank comes
    from a total-order lexsort (key/uid final tiebreaks), so the output
    is independent of record row order and a plan assembled from
    delta-refreshed records is bit-identical to a full re-walk of the
    same live state.  ``prev``/``fields_out`` carry the flat row arrays
    across windows for the delta path (see ``_concat_row_fields``)."""
    ordering = scheduler.ordering
    C = len(st.cq_names)
    F = max(1, len(st.fr_index))
    R = len(st.resource_names)
    n_pending = sum(r.n_pend for r in records)
    if n_pending == 0:
        return None
    s = _pack_statics(st, cache)
    G = st.n_forests
    forest_of_cq = s.forest_of_cq
    L = s.L
    node_level = s.node_level

    from .packing import _bucket
    # sticky minimum keeps M stable across re-packs as queues drain
    # (every distinct M is a fresh XLA compilation)
    rows_per_cq = max(r.n_rows for r in records)
    M = max(_bucket(rows_per_cq, minimum=4), min_m)

    nz = [r for r in records if r.n_rows > 0]
    fields = _concat_row_fields(records, nz, prev)
    if fields_out is not None:
        fields_out.update(fields)
    n_rows_arr = np.diff(fields["_bounds"])
    ci_a = np.repeat(
        np.fromiter((r.ci for r in records), dtype=np.int32, count=C),
        n_rows_arr)
    pos_a = np.repeat(
        np.fromiter((r.pos for r in records), dtype=np.int32, count=C),
        n_rows_arr)
    adm_a = fields["adm"]
    prio_a = fields["prio"]
    ts_a = fields["ts"]
    parked_a = fields["parked"]
    res_ts_a = fields["res_ts"]
    ok_a = fields["ok"]
    resume_a = fields["resume"]
    req_all = fields["req"]
    usage_all = fields["usage"]
    uses_all = fields["uses"]
    key_arr = fields["keys"]
    uid_arr = fields["uids"]
    n = int(fields["_bounds"][-1])
    strict = np.fromiter((r.strict for r in records), dtype=bool,
                         count=C)

    wl_req = np.zeros((C, M, R), dtype=np.int32)
    wl_rank = np.full((C, M), INF_I32, dtype=np.int32)
    wl_cycle_rank = np.zeros((C, M), dtype=np.int32)
    wl_prio = np.zeros((C, M), dtype=np.int32)
    wl_uidrank = np.zeros((C, M), dtype=np.int32)
    vec_ok = np.zeros((C, M), dtype=bool)
    elig = np.zeros((C, M), dtype=bool)
    parked = np.zeros((C, M), dtype=bool)
    resume = np.zeros((C, M), dtype=np.int32)
    adm = np.zeros((C, M), dtype=bool)
    adm_seq = np.zeros((C, M), dtype=np.int32)
    adm_usage = np.zeros((C, M, F), dtype=np.int32)
    adm_uses = np.zeros((C, M, F), dtype=bool)
    death = np.full((C, M), I32_MAX, dtype=np.int32)

    # heap rank within each CQ: one global lexsort replaces C Python
    # sorts (priority desc, queue-order ts asc, key asc —
    # cluster_queue.go:408).  Admitted rows get ranks too: a preempted
    # target re-enters the heap at exactly this position (preemption
    # evictions keep the creation-time ordering, workload.py:309).
    order = np.lexsort((key_arr, ts_a, -prio_a, ci_a))
    ci_sorted = ci_a[order]
    first = np.ones(n, dtype=bool)
    first[1:] = ci_sorted[1:] != ci_sorted[:-1]
    seg_start = np.maximum.accumulate(
        np.where(first, np.arange(n), 0))
    mi_sorted = (np.arange(n) - seg_start).astype(np.int64)
    mi_a = np.empty(n, dtype=np.int64)
    mi_a[order] = mi_sorted
    # global cycle-order rank (priority desc, ts asc, heads-position);
    # the key tiebreak keeps the rank independent of heap-array order
    # (pops/pushes permute heap.items()), which delta reuse requires
    crank = np.empty(n, dtype=np.int64)
    crank[np.lexsort((key_arr, pos_a, ts_a, -prio_a))] = np.arange(n)
    # uid rank (candidatesOrdering final tiebreak) + reservation-time
    # dense rank (ties share a value; uid breaks them separately).
    # Head-pack mode scopes the rank to budget rows — rows of forests
    # that can preempt (~comp_cq); the rest can never be candidate-
    # gathered (see aggregate.head_pack_enabled), so their uidrank
    # cells are never read and the subset rank preserves the eligible
    # ordering bit for bit while freeing the 19-bit field's range.
    from .aggregate import head_pack_enabled
    head_pack = head_pack_enabled()
    uidrank = np.zeros(n, dtype=np.int64)
    if head_pack:
        bidx = np.nonzero(~s.comp_cq[ci_a])[0]
        uidrank[bidx[np.argsort(uid_arr[bidx], kind="stable")]] = \
            np.arange(len(bidx))
        n_budget = int(len(bidx))
        prio_budget = (int(np.abs(prio_a[bidx]).max()) if n_budget else 0)
    else:
        uidrank[np.argsort(uid_arr, kind="stable")] = np.arange(n)
        n_budget = n
        prio_budget = int(np.abs(prio_a).max(initial=0))
    uniq_ts = np.unique(res_ts_a[adm_a]) if adm_a.any() else np.empty(0)
    seq_a = np.zeros(n, dtype=np.int64)
    if len(uniq_ts):
        seq_a[adm_a] = np.searchsorted(uniq_ts, res_ts_a[adm_a]) + 1
    seq_base = int(len(uniq_ts)) + 2

    wl_rank[ci_a, mi_a] = mi_a
    wl_cycle_rank[ci_a, mi_a] = crank
    wl_prio[ci_a, mi_a] = np.clip(prio_a, -I32_MAX, I32_MAX)
    wl_uidrank[ci_a, mi_a] = uidrank
    parked[ci_a, mi_a] = parked_a
    elig[ci_a, mi_a] = ~parked_a & ~adm_a
    vec_ok[ci_a, mi_a] = ok_a
    resume[ci_a, mi_a] = resume_a
    wl_req[ci_a, mi_a] = req_all
    adm[ci_a, mi_a] = adm_a
    adm_seq[ci_a, mi_a] = seq_a
    adm_usage[ci_a, mi_a] = usage_all
    adm_uses[ci_a, mi_a] = uses_all
    key_list = key_arr.tolist()   # plain str (key_arr is unicode-dtype)
    keys_grid = np.empty((C, M), dtype=object)   # fills with None
    keys_grid[ci_a, mi_a] = np.array(key_list, dtype=object)
    keys: list[list] = keys_grid.tolist()
    row_of_key: dict = dict(zip(
        key_list, zip(ci_a.tolist(), mi_a.tolist())))

    # CQ-level usage, scaled exactly (else no burst) — per-record rows
    if (prev is not None and prev[1] is not None
            and "u_cq" in prev[1] and len(prev[0]) == len(records)):
        u_cq = prev[1]["u_cq"].copy()
        for i, r in enumerate(records):
            if r is not prev[0][i]:
                u_cq[i] = r.u_row
    else:
        u_cq = np.stack([r.u_row for r in records])
    if fields_out is not None:
        fields_out["u_cq"] = u_cq

    # preemption policy flags + the in-kernel modeling envelope
    forest_bad = s.deep.copy()
    for r in records:
        if r.bad:
            forest_bad[int(forest_of_cq[r.ci])] = True
    KC = min(KC_CAP, ((L * M + 31) // 32) * 32)
    if L * M > KC:
        forest_bad[:] = True
    if not ordering.priority_sorting_within_cohort:
        forest_bad[:] = True
    # the kernel's composite candidate-ordering keys pack priority and
    # reservation-seq into 20-bit fields and uid rank into 19; in-burst
    # admissions consume seq_base..seq_base+K-1, so the headroom is the
    # largest window the ladder can dispatch (not a hardcoded constant).
    # Only budget rows (rows the candidate keys can ever encode) are
    # charged against the 2^19/2^20 fields; the seq gate stays global
    # because reservation seqs are dense over distinct admitted
    # timestamps regardless of forest.
    if (prio_budget >= (1 << 20)
            or seq_base + max(K_BURST_LADDER) >= (1 << 20)
            or n_budget >= (1 << 19)):
        forest_bad[:] = True
    preempt_ok = s.modelable_base & ~forest_bad[forest_of_cq]
    # pure function of the structure statics + (M, KC); M is sticky
    # across re-packs, so boundaries after the first reuse the tables
    tables = s.cand_tables.get((M, KC))
    if tables is None:
        tables = build_candidate_tables(forest_of_cq, s.members, M, KC)
        s.cand_tables[(M, KC)] = tables
    cand_rows, cand_lmem, self_lmem = tables

    arrays = dict(
        wl_req=wl_req, wl_rank=wl_rank, wl_cycle_rank=wl_cycle_rank,
        wl_prio=wl_prio, wl_uidrank=wl_uidrank,
        vec_ok=vec_ok, elig0=elig, parked0=parked, resume0=resume,
        adm0=adm, adm_seq0=adm_seq, adm_usage0=adm_usage,
        adm_uses0=adm_uses, death0=death,
        u_cq0=u_cq, potential0=s.potential0,
        subtree=st.subtree_quota, guaranteed=st.guaranteed,
        borrow_cap=st.borrow_cap, has_blim=st.has_borrow_limit,
        parent=st.parent, node_level=node_level,
        nominal_cq=st.nominal_cq, npb_cq=st.nominal_plus_blimit_cq,
        slot_fr=st.slot_fr, slot_valid=st.slot_valid,
        cq_can_preempt_borrow=st.cq_can_preempt_borrow,
        cq_wcb_borrow=st.cq_wcb_borrow, cq_wcp_preempt=st.cq_wcp_preempt,
        forest_of_cq=forest_of_cq, strict_cq=strict,
        wcq_lower=s.wcq_lower, rwc_enabled=s.rwc_enabled,
        rwc_only_lower=s.rwc_only_lower, preempt_ok=preempt_ok,
        members=s.members, cand_rows=cand_rows, cand_lmem=cand_lmem,
        self_lmem=self_lmem)
    # max_res_ts feeds the driver's admission-clock monotonicity check,
    # so it must cover aggregate-compressed admitted rows too (their
    # reservation times are real; only their packed rows are elided)
    max_res_ts = float(res_ts_a[adm_a].max()) if adm_a.any() else None
    comp_max = max((r.comp_max_ts for r in records if r.n_comp),
                   default=None)
    if comp_max is not None:
        max_res_ts = (comp_max if max_res_ts is None
                      else max(max_res_ts, comp_max))
    return BurstPlan(structure=st, arrays=arrays, keys=keys,
                     C=C, M=M, L=L, G=G, n_levels=s.n_levels, KC=KC,
                     seq_base=seq_base, row_of_key=row_of_key,
                     max_res_ts=max_res_ts,
                     budget_rows=n_budget, grid_rows=n)


def pack_burst(structure, queues, cache, scheduler, clock,
               min_m: int = 0, window: int = 0) -> Optional[BurstPlan]:
    """Build the dense [C, M] state from the live queues + cache.

    Rows cover BOTH pending workloads (heap + parking lot) and admitted
    workloads (the quota-holding table preemption selects targets from).
    Returns None when the cluster can't be burst-scheduled at all
    (inexact usage scaling, unknown flavor-resources).  Per-workload
    limitations never fail the pack — they mark the row ``vec_ok=False``
    (pending) or gate the forest out of the in-kernel preemption
    envelope (admitted), so the affected cycles go dirty and run on the
    normal host path instead.

    ``window`` > 0 bounds the dispatch's cycle count: only the
    ``window + 2`` best-ranked pending rows per CQ are packed (plus all
    admitted rows).  Sound because at most one row per CQ leaves the
    eligible set per cycle, so a row below the cutoff cannot become a
    head within the window; any modeling miss is caught by the driver's
    per-cycle heads validation (truncate + repack)."""
    st = structure
    if _unknown_active_cq(st, queues):
        return None   # an active CQ the structure doesn't know
    records = _walk_records(st, queues, cache, scheduler, window)
    if records is None:
        return None
    return _assemble_plan(st, records, cache, scheduler, min_m)


class DeltaPackState:
    """Persistent per-CQ row records carried across burst windows.

    Valid for one (structure generation, resource scale, CQ set,
    window) key; ``pack_burst_cached`` re-walks only journaled-dirty
    CQs against it and re-fuses stage B from the mixed records.
    ``fields`` holds the flat stage-B row concatenation so the next
    window splices only the dirty segments.  ``token`` is a process-wide
    monotone serial: plans record the tokens they consumed/produced so a
    shard-resident device copy can prove it chains from the same state
    (object identity is not enough — ids alias after GC)."""
    __slots__ = ("key", "records", "fields", "token")

    _next_token = itertools.count(1)

    def __init__(self, key, records, fields=None):
        self.key = key
        self.records = records
        self.fields = fields
        self.token = next(DeltaPackState._next_token)


def _roundtrips_clean(rec, q, cq_live, keys, covers_pods) -> bool:
    """Verify that popped-and-requeued heads still match their packed
    rows: same Info object, same parked bit, same flavor-walk start
    slot.  These are the only row facts a pop/requeue roundtrip can
    move without hitting a hard journal touch."""
    from .solver import resume_start
    if q is None or not q.active or cq_live is None:
        return False
    for key in keys:
        parked_now = False
        info = q.heap.get(key)
        if info is None:
            info = q.inadmissible.get(key)
            if info is None:
                return False
            rs = info.obj.requeue_state
            if rs is not None and rs.requeue_at is not None:
                return False   # now backoff-parked: membership changed
            parked_now = True
        idx = rec.index_of_key.get(key)
        if idx is None:
            # below the window cutoff is the only legitimate absence
            if not rec.truncated:
                return False
            continue
        if rec.infos[idx] is not info or idx >= rec.n_pend:
            return False
        if bool(rec.parked[idx]) != parked_now:
            return False
        if int(rec.resume[idx]) != resume_start(info, cq_live,
                                                covers_pods):
            return False
    return True


# above this dirty share a delta walk rebuilds nearly everything anyway
# and the journal bookkeeping makes it slower than a plain full pack
_DELTA_MAX_DIRTY_FRAC = 0.5
_DELTA_MIN_DIRTY_CQS = 8


def pack_burst_cached(structure, queues, cache, scheduler, clock,
                      state=None, min_m: int = 0, window: int = 0,
                      stats=None):
    """Delta-maintained pack_burst; returns ``(plan, state, was_delta)``.

    Routing front door: by default the *streaming* delta pack
    (ops/stream_pack.py) serves the boundary — it patches a persistent
    packed-universe arena in place, O(arrivals + dirty) per window
    instead of the classic path's O(total rows) stage-B reassembly.
    ``KUEUE_TPU_STREAM_PACK=0`` opts back into the classic delta pack,
    ``KUEUE_BURST_DELTA_PACK=0`` forces a full walk every window
    (either path), and a structure the streaming encoder cannot model
    (non-ASCII or oversized workload keys) self-poisons back to the
    classic path.  Both paths share the return contract and produce
    bit-identical plans (test-enforced)."""
    import os
    if (env_value("KUEUE_TPU_STREAM_PACK") != "0"
            and os.environ.get("KUEUE_BURST_DELTA_PACK", "1") != "0"
            and not getattr(structure, "_stream_poison", False)):
        from .stream_pack import pack_burst_streaming
        return pack_burst_streaming(structure, queues, cache, scheduler,
                                    clock, state=state, min_m=min_m,
                                    window=window, stats=stats)
    return _pack_burst_cached_classic(structure, queues, cache,
                                      scheduler, clock, state=state,
                                      min_m=min_m, window=window,
                                      stats=stats)


def _pack_burst_cached_classic(structure, queues, cache, scheduler,
                               clock, state=None, min_m: int = 0,
                               window: int = 0, stats=None):
    """The classic delta pack: re-walk journaled-dirty CQs, re-fuse
    stage B from the mixed records.

    Drains the queue-manager and cache PackJournals; when ``state``
    covers the same (structure generation, resource scale, CQ set,
    window) key and nothing forced a full walk, only journaled-dirty
    CQs are re-walked and the surviving records re-fuse through stage B
    — the boundary pays O(dirty rows) of Python walk instead of O(all
    rows).  Any miss (key change, dirty-all, roundtrip drift, CQ the
    delta path can't model) falls back to a full walk, counted in
    ``stats``.  The returned plan is bit-identical to ``pack_burst`` of
    the same live state (test-enforced by tests/test_delta_pack.py);
    ``KUEUE_BURST_DELTA_PACK=0`` forces the full walk every window."""
    import os
    import time
    st = structure
    dirty: set = set()
    soft: dict = {}
    jranges: list = []
    force_full = False
    for j in (getattr(queues, "pack_journal", None),
              getattr(cache, "pack_journal", None)):
        if j is None:
            force_full = True
        else:
            force_full |= j.drain_into(dirty, soft, row_of=st.cq_index,
                                       ranges_out=jranges)
    enabled = os.environ.get("KUEUE_BURST_DELTA_PACK", "1") != "0"
    from .aggregate import agg_planes_enabled
    key = (st.generation, st.resource_scale.tobytes(),
           tuple(st.cq_names), window, agg_planes_enabled())

    def _full():
        if _unknown_active_cq(st, queues):
            return None, None, False
        records = _walk_records(st, queues, cache, scheduler, window)
        if records is None:
            return None, None, False
        fields: dict = {}
        plan = _assemble_plan(st, records, cache, scheduler, min_m,
                              fields_out=fields if enabled else None)
        if plan is None:
            return None, None, False
        if stats is not None:
            stats["burst_full_packs"] = (
                stats.get("burst_full_packs", 0) + 1)
            stats["rows_repacked"] = (
                stats.get("rows_repacked", 0)
                + sum(r.n_rows for r in records))
        new_state = (DeltaPackState(key, records, fields) if enabled
                     else None)
        # a full walk cannot chain a resident device copy (dirty set is
        # unbounded) but it SEEDS one: the next delta pack may scatter
        plan.pack_token = new_state.token if new_state else None
        return plan, new_state, False

    if not enabled or state is None or state.key != key or force_full:
        return _full()

    t0 = time.perf_counter()
    index_of = st.cq_index
    C = len(st.cq_names)
    # a dirty CQ the structure doesn't know fails the pack exactly when
    # the full walk would (active with pending work); clean unknown CQs
    # were checked at state creation and only change through journaled
    # mutators
    for name in dirty | set(soft):
        if name not in index_of:
            q = queues.queue_for(name)
            if q is not None and q.active and q.pending_active():
                return None, None, False
    # soft-dirty roundtrips: verify the packed dynamic bits still hold;
    # escalate the CQ to a re-walk when they moved
    for name, skeys in soft.items():
        ci = index_of.get(name)
        if ci is None or name in dirty:
            continue
        if not _roundtrips_clean(state.records[ci],
                                 queues.queue_for(name),
                                 cache.cluster_queue(name), skeys,
                                 name in structure.cq_covers_pods):
            dirty.add(name)

    # at full churn the per-CQ delta walk is a near-complete rebuild
    # plus journal/roundtrip overhead — measurably slower than the
    # straight full walk at north-star scale.  The floor keeps small
    # packs on the delta path so its machinery stays exercised.
    if len(dirty) > max(_DELTA_MIN_DIRTY_CQS, _DELTA_MAX_DIRTY_FRAC * C):
        return _full()

    records = list(state.records)
    pos_of = {name: i for i, name in
              enumerate(queues.cluster_queue_names())}
    assumed = cache.assumed_workloads
    scale_of = {r: int(st.resource_scale[i])
                for i, r in enumerate(st.resource_names)}
    repacked = 0
    for name in dirty:
        ci = index_of.get(name)
        if ci is None:
            continue
        rec = _pack_cq_rows(st, ci, pos_of.get(name, C), queues, cache,
                            scheduler, assumed, scale_of, window)
        if rec is _PACK_FAIL:
            return None, None, False
        records[ci] = rec
        repacked += rec.n_rows
    # heads-enumeration positions can shift when CQs leave the queue
    # manager; refresh them on every record (clean ones included)
    for rec in records:
        rec.pos = pos_of.get(st.cq_names[rec.ci], C)
    fields: dict = {}
    plan = _assemble_plan(st, records, cache, scheduler, min_m,
                          prev=(state.records, state.fields),
                          fields_out=fields)
    if plan is None:
        return None, None, False
    new_state = DeltaPackState(key, records, fields)
    # resident chaining facts: which state this plan consumed/produced
    # and exactly which CQ rows differ from the consumed state's plan
    # (post-escalation; clean rows were spliced verbatim, so a device
    # copy of the previous rows needs only these scattered)
    dirty_cis = sorted(index_of[name] for name in dirty
                       if name in index_of)
    plan.pack_token = new_state.token
    plan.prev_token = state.token
    plan.dirty_cqs = np.asarray(dirty_cis, dtype=np.int64)
    from ..utils.journal import PackJournal
    plan.dirty_ranges = PackJournal.coalesce(dirty_cis)
    if stats is not None:
        stats["burst_delta_packs"] = (
            stats.get("burst_delta_packs", 0) + 1)
        stats["rows_repacked"] = (
            stats.get("rows_repacked", 0) + repacked)
        stats["rows_reused"] = (
            stats.get("rows_reused", 0)
            + sum(r.n_rows for r in records) - repacked)
        stats["delta_pack_s"] = (
            stats.get("delta_pack_s", 0.0) + time.perf_counter() - t0)
        stats["burst_journal_dirty_ranges"] = (
            stats.get("burst_journal_dirty_ranges", 0) + len(jranges))
    return plan, new_state, True


# one K rung: every distinct K is a full kernel compilation, and a
# 32-cycle window amortizes the dispatch while deciding a few unused
# cycles at most ~15ms of kernel time when fewer remain
K_BURST_LADDER = (32,)


class _ResidentRows:
    """Device-resident scatter-tier row planes from the last fresh
    sharded dispatch, keyed by the DeltaPackState token that produced
    them.  The next fresh pack reuses them when its ``prev_token``
    matches: the delta pack spliced every clean record verbatim, so
    only its ``dirty_cqs`` rows need to re-cross the host boundary."""
    __slots__ = ("layout", "token", "planes")

    def __init__(self, layout, token, planes):
        self.layout = layout
        self.token = token
        self.planes = planes


@dataclass
class BurstHandle:
    """An in-flight fused-burst dispatch.

    The kernel call has been issued (JAX async dispatch: the device —
    or the XLA-CPU thread pool — executes while the host keeps
    running); ``BurstSolver.fetch`` blocks for the decisions.  ``carry``
    keeps the kernel's final scan state as device arrays after fetch,
    so ``dispatch_next`` can chain the following window's dispatch off
    it without a host re-pack (double-buffered plan, device-resident)."""
    plan: BurstPlan
    K: int
    runtime: int
    seq_base: int                # absolute seq base of THIS window
    dev: object
    pending: object = None       # kernel output tuple, still async
    decisions: tuple = None      # fetched numpy decision arrays
    flags: tuple = None          # (dirty, dirty_reason) via fetch_flags
    carry: tuple = None          # final scan state (jax arrays)
    speculative: bool = False
    t_dispatch: float = 0.0
    sharded: bool = False        # dispatched through the mesh path
    layout: object = None        # BurstShardLayout of a sharded dispatch


class BurstSolver:
    """Dispatch fused bursts and expose the decisions for application.

    ``backend``: "cpu" | "accel" | "auto" (auto = cpu; the roofline
    measurement ROOFLINE_r04.json shows XLA-CPU wins the fused kernel at
    every shape in this environment — the accel's incremental per-cycle
    compute matches the CPU's but each dispatch adds the tunnel RTT)."""

    def __init__(self, backend: str = "auto"):
        from ..compilecache import enable as _enable_compile_cache
        _enable_compile_cache()
        self.backend = backend
        self.stats = {"burst_dispatches": 0, "burst_cycles_decided": 0,
                      "burst_accel_dispatches": 0,
                      "burst_dispatch_s": 0.0,
                      # boundary + fallback visibility (VERDICT r4 item 9)
                      "burst_pack_s": 0.0, "burst_packs": 0,
                      "burst_suppressed_cycles": 0,
                      "burst_dirty_cycles": 0,
                      "burst_dirty_preempt": 0,
                      "burst_dirty_scalar": 0,
                      "burst_dirty_resume": 0,
                      # cycles decided inside bursts by kind
                      "burst_preempt_cycles": 0,
                      # pipelined boundary (speculative next-window
                      # dispatches chained off the kernel's final carry)
                      "burst_spec_dispatches": 0,
                      "burst_overlapped_packs": 0,
                      "burst_spec_cancelled": 0,
                      "burst_serial_windows": 0,
                      "burst_spec_fetch_wait_s": 0.0,
                      # modeled preempt target vanished before apply
                      "burst_target_divergences": 0,
                      # incremental delta-pack boundary (persistent
                      # per-CQ row records; full repack on any miss)
                      "burst_delta_packs": 0, "burst_full_packs": 0,
                      "rows_reused": 0, "rows_repacked": 0,
                      "delta_pack_s": 0.0,
                      # graceful degradation (chaos shard.device_loss or
                      # lose_devices): mesh rebuilt over the survivors,
                      # serial fallback when fewer than two remain
                      "burst_shard_degradations": 0,
                      "burst_shard_serial_fallbacks": 0,
                      # speculative windows discarded by injected faults
                      "burst_chaos_divergences": 0,
                      # shard-resident boundary: fresh packs whose row
                      # planes stayed on the mesh (only dirty rows
                      # scattered from host) vs full re-uploads, and the
                      # host→device bytes actually paid vs what the
                      # upload-everything boundary would have paid
                      "burst_resident_hits": 0,
                      "burst_resident_misses": 0,
                      "burst_resident_scatter_rows": 0,
                      "burst_resident_scatter_ranges": 0,
                      "burst_resident_scatter_s": 0.0,
                      "burst_boundary_bytes_h2d": 0,
                      "burst_boundary_bytes_equiv": 0,
                      # coalesced dirty-row ranges seen by the journal
                      "burst_journal_dirty_ranges": 0,
                      # cost-balanced forest partition (EWMA of decided
                      # heads per forest, fed to BurstShardLayout)
                      "burst_layout_rebuilds": 0,
                      "burst_layout_cost_balanced": 0,
                      "burst_shard_cost_ratio": 0.0}
        # mesh-sharded dispatch (forest partition over a 1-D "cq" axis;
        # parallel.sharded.BurstShardLayout) — off until set_shards(n>1)
        self.n_shards = 1
        self._shard_mesh = None
        self._shard_layouts: dict = {}
        self._sharded_fns: dict = {}
        # shard-resident device copy of the last fresh pack's row planes
        # + the per-forest cycle-cost EWMA feeding the next layout
        self._resident = None
        self._scatter_jit = None
        self._forest_cost: dict | None = None
        # dtype tightening of the serial launch's packed planes (sticky
        # per-plane widths; KUEUE_TPU_PACK_TIGHTEN=0 disables)
        from .packing import TightenState
        self._tighten = TightenState()

    def set_shards(self, n: int):
        """Shard burst dispatches across ``n`` devices: cohort forests
        are partitioned over a 1-D ``("cq",)`` mesh and the fused kernel
        runs under shard_map with the dirty reduction as a psum.
        ``n <= 1`` (or too few devices for a mesh) keeps the serial
        single-device path — graceful degradation, not an error."""
        from ..parallel.sharded import make_burst_mesh
        n = int(n or 0)
        mesh = make_burst_mesh(n) if n > 1 else None
        self.n_shards = mesh.devices.size if mesh is not None else 1
        self._shard_mesh = mesh
        self._shard_layouts = {}
        self._sharded_fns = {}
        self._resident = None
        self._scatter_jit = None
        if mesh is not None:
            self.stats.setdefault("burst_sharded_dispatches", 0)
            # per-shard timing vectors (list-valued stats): how long the
            # host spent building each shard's block of the permuted
            # inputs, and how long each shard's decision slice took to
            # become ready at fetch
            self.stats["burst_shard_pack_s"] = [0.0] * self.n_shards
            self.stats["burst_shard_fetch_s"] = [0.0] * self.n_shards

    def lose_devices(self, n_lost: int = 1) -> int:
        """Graceful shard degradation: ``n_lost`` devices of the burst
        mesh died.  The mesh is rebuilt over the survivors and the next
        ``_layout_for`` re-partitions the cohort forests across them
        (value-remapped exactly like the original layout, so decisions
        stay bit-identical); with fewer than two survivors the window
        re-runs on the serial single-device path.  Returns the new
        shard count."""
        if self.n_shards <= 1:
            return self.n_shards
        from ..parallel.sharded import make_burst_mesh
        survivors = max(1, self.n_shards - max(1, int(n_lost)))
        mesh = make_burst_mesh(survivors) if survivors > 1 else None
        self.n_shards = mesh.devices.size if mesh is not None else 1
        self._shard_mesh = mesh
        self._shard_layouts = {}
        self._sharded_fns = {}
        # the resident copy is laid out for the dead mesh; the next
        # fresh pack re-gathers from host over the survivors
        self._resident = None
        self._scatter_jit = None
        self.stats["burst_shard_degradations"] += 1
        if mesh is None:
            self.stats["burst_shard_serial_fallbacks"] += 1
        else:
            self.stats["burst_shard_pack_s"] = [0.0] * self.n_shards
            self.stats["burst_shard_fetch_s"] = [0.0] * self.n_shards
        return self.n_shards

    @staticmethod
    def _layout_key(plan: BurstPlan):
        st = plan.structure
        return (id(st), st.generation, plan.C, plan.M, plan.G, plan.L,
                plan.KC)

    def _layout_for(self, plan: BurstPlan):
        from ..parallel.sharded import BurstShardLayout
        key = self._layout_key(plan)
        lay = self._shard_layouts.get(key)
        if lay is None:
            # feed the measured per-forest cycle cost when it was
            # sampled under this structure generation — layout rebuilds
            # happen only on structure/mesh change (or an explicit
            # refresh_layouts), so this is where rebalancing lands
            fc = self._forest_cost
            cost = None
            if (fc is not None
                    and fc["generation"] == plan.structure.generation
                    and fc["windows"] > 0 and len(fc["ewma"]) == plan.G):
                cost = fc["ewma"]
            import time as _time
            t0 = _time.perf_counter()
            lay = BurstShardLayout(plan, self.n_shards, forest_cost=cost)
            if os.environ.get("KUEUE_BURST_DEBUG"):
                print(f"layout rebuild: gen={plan.structure.generation} "
                      f"Cs={lay.Cs} Gs={lay.Gs} cost={cost is not None} "
                      f"{(_time.perf_counter() - t0)*1e3:.1f}ms",
                      file=sys.stderr)
            self._shard_layouts = {key: lay}   # one structure at a time
            self.stats["burst_layout_rebuilds"] = (
                self.stats.get("burst_layout_rebuilds", 0) + 1)
            if lay.cost_balanced:
                self.stats["burst_layout_cost_balanced"] = (
                    self.stats.get("burst_layout_cost_balanced", 0) + 1)
            self.stats["burst_shard_cost_ratio"] = lay.cost_ratio
            self.stats["burst_shard_cost"] = list(lay.shard_cost)
        return lay

    def refresh_layouts(self):
        """Drop cached shard layouts so the NEXT fresh pack re-partitions
        the forests with the current cycle-cost EWMA.  Callers must hold
        no in-flight handles (the driver's window boundary, a harness's
        warmup/measure seam): a chained carry is laid out for the old
        partition and dispatch_next refuses to cross layouts."""
        self._shard_layouts = {}
        self._resident = None

    def _note_forest_activity(self, plan: BurstPlan, head_row):
        """Fold one fetched window's decided heads into the per-forest
        cycle-cost EWMA (keyed by structure generation).  head_row is in
        GLOBAL layout ([K, C]; fetch inverse-permutes sharded planes),
        so the sample is identical on the serial and sharded paths."""
        hr = np.asarray(head_row)
        if hr.ndim != 2:
            return
        cols = np.nonzero(hr >= 0)[1]
        sample = np.bincount(
            np.asarray(plan.arrays["forest_of_cq"])[cols],
            minlength=plan.G).astype(np.float64)
        fc = self._forest_cost
        gen = plan.structure.generation
        if (fc is None or fc["generation"] != gen
                or len(fc["ewma"]) != plan.G):
            self._forest_cost = {"generation": gen, "ewma": sample,
                                 "windows": 1}
        else:
            fc["ewma"] = 0.7 * fc["ewma"] + 0.3 * sample
            fc["windows"] += 1

    def _device(self):
        import jax
        try:
            if self.backend == "accel":
                default = jax.devices()[0]
                if default.platform != "cpu":
                    return default
            return jax.devices("cpu")[0]
        except RuntimeError:
            # a registered accelerator plugin that can't initialize must
            # not take the CPU path down with it (solver.py discipline)
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            return jax.devices("cpu")[0]

    def _launch(self, plan: BurstPlan, K: int, runtime: int,
                ext_release, ext_unpark, state, seq_base: int,
                speculative: bool, permuted: bool = False) -> BurstHandle:
        """Issue one fused kernel call without blocking for results.
        ``state`` is the 9-tuple of *0 scan-state arrays (numpy for a
        packed window, jax device arrays for a chained one);
        ``permuted`` marks a chained state already in shard layout."""
        import jax
        import time as _time
        if (_chaos.ACTIVE is not None and self.n_shards > 1
                and not speculative and not permuted):
            # device loss lands at fresh packs only: a chained carry is
            # laid out for the old mesh and dispatch_next already
            # refuses to cross dispatch modes
            f = _chaos.ACTIVE.hit("shard.device_loss")
            if f is not None:
                self.lose_devices(int(f.payload or 1))
        if self.n_shards > 1 and self._shard_mesh is not None:
            return self._launch_sharded(plan, K, runtime, ext_release,
                                        ext_unpark, state, seq_base,
                                        speculative, permuted)
        st = plan.structure
        dev = self._device()
        a = plan.arrays
        if env_value("KUEUE_TPU_PACK_TIGHTEN") != "0":
            # narrow the rank/index/request planes at the serial
            # transfer boundary only — plan.arrays keeps the reference
            # int32 dtypes (parity tests, resident scatter); the kernel
            # upcasts on device.  Scan-state planes are never narrowed
            # (a chained window feeds device outputs straight back in).
            from .packing import tighten_arrays
            a = tighten_arrays(a, self._tighten, self.stats)
        (elig0, parked0, resume0, adm0, adm_seq0, adm_usage0,
         adm_uses0, death0, u_cq0) = state
        self.stats["burst_launch_bytes_h2d"] = (
            self.stats.get("burst_launch_bytes_h2d", 0)
            + sum(v.nbytes for v in a.values()
                  if isinstance(v, np.ndarray))
            + sum(v.nbytes for v in state if isinstance(v, np.ndarray)))
        t0 = _time.perf_counter()
        with jax.default_device(dev):
            out = burst_cycles(
                a["wl_req"], a["wl_rank"], a["wl_cycle_rank"],
                a["wl_prio"], a["wl_uidrank"], a["vec_ok"],
                elig0, parked0, resume0,
                adm0, adm_seq0, adm_usage0,
                adm_uses0, death0, np.int32(seq_base),
                u_cq0,
                a["potential0"], a["subtree"], a["guaranteed"],
                a["borrow_cap"], a["has_blim"], a["parent"],
                a["node_level"], a["nominal_cq"], a["npb_cq"],
                a["slot_fr"], a["slot_valid"], a["cq_can_preempt_borrow"],
                a["cq_wcb_borrow"], a["cq_wcp_preempt"],
                a["forest_of_cq"], a["strict_cq"],
                a["wcq_lower"], a["rwc_enabled"], a["rwc_only_lower"],
                a["preempt_ok"],
                a["members"], a["cand_rows"], a["cand_lmem"],
                a["self_lmem"],
                ext_release, ext_unpark,
                K=K, depth=st.depth, L=plan.L,
                S=int(st.slot_fr.shape[1]), KC=plan.KC,
                n_levels=plan.n_levels, G=plan.G, runtime=max(0, runtime))
        self.stats["burst_dispatches"] += 1
        self.stats["burst_cycles_decided"] += K
        if speculative:
            self.stats["burst_spec_dispatches"] += 1
        else:
            self.stats["burst_serial_windows"] += 1
        if dev.platform != "cpu":
            self.stats["burst_accel_dispatches"] += 1
        return BurstHandle(plan=plan, K=K, runtime=runtime,
                           seq_base=seq_base, dev=dev, pending=out,
                           speculative=speculative, t_dispatch=t0)

    def _sharded_fn(self, plan: BurstPlan, layout, K: int, runtime: int):
        from ..parallel.sharded import sharded_burst_fn
        st = plan.structure
        S = int(st.slot_fr.shape[1])
        key = (K, st.depth, plan.L, S, plan.KC, plan.n_levels,
               layout.Gs, runtime)
        fn = self._sharded_fns.get(key)
        if fn is None:
            if os.environ.get("KUEUE_BURST_DEBUG"):
                print(f"sharded fn miss: K={K} depth={st.depth} "
                      f"L={plan.L} S={S} KC={plan.KC} "
                      f"n_levels={plan.n_levels} Gs={layout.Gs} "
                      f"runtime={runtime} cached={len(self._sharded_fns)}",
                      file=sys.stderr)
            fn = sharded_burst_fn(
                self._shard_mesh, K=K, depth=st.depth, L=plan.L, S=S,
                KC=plan.KC, n_levels=plan.n_levels, G=layout.Gs,
                runtime=max(0, runtime))
            self._sharded_fns[key] = fn
        return fn

    def _row_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self._shard_mesh, P("cq"))

    def _scatter_rows_fn(self):
        # one fused dispatch for ALL planes: per-plane jit calls cost
        # ~7 ms each in SPMD dispatch overhead on a virtual-device mesh,
        # which at 13 planes dwarfs the actual row updates
        if self._scatter_jit is None:
            self._scatter_jit = jax.jit(
                lambda planes, rows, vals: tuple(
                    a.at[rows].set(v) for a, v in zip(planes, vals)))
        return self._scatter_jit

    def _resident_inputs(self, plan: BurstPlan, layout, timers) -> dict:
        """Sharded kernel inputs for a FRESH pack under the
        shard-resident boundary (``KUEUE_TPU_RESIDENT``, default on):

        - STATIC tier: permuted + device_put once per layout lifetime;
        - SCATTER tier (row records + scan-state init planes): reused
          on the mesh when this plan chains the resident copy's pack
          token — only ``plan.dirty_cqs`` rows are scattered from host,
          coalesced (journal.PackJournal.coalesce) and bucketed
          (packing.scatter_pad) into ONE indexed update per plane;
        - GLOBAL tier (dense cross-CQ ranks, preempt envelope):
          re-uploaded every fresh pack.

        ``KUEUE_TPU_RESIDENT_VERIFY=1`` asserts every scattered plane is
        bit-identical to a full host permute (test harness switch).
        Returns the merged name→array dict (device arrays for static +
        scatter tiers, host arrays for the global tier)."""
        import os
        import time as _time
        from ..parallel.sharded import (
            _C_FILLS, _STATE_NAMES, SCATTER_PLANES, GLOBAL_PLANES)
        from ..utils.journal import PackJournal
        from .packing import scatter_pad
        a = plan.arrays
        sh = self._row_sharding()
        stats = self.stats
        dev_static = layout._static_dev
        if dev_static is None:
            t_s = _time.perf_counter()
            host = layout.static_arrays(plan, timers)
            dev_static = {k: jax.device_put(v, sh) for k, v in
                          host.items()}
            layout._static_dev = dev_static
            layout._static_nbytes = sum(v.nbytes for v in host.values())
            stats["burst_boundary_bytes_h2d"] += layout._static_nbytes
            if os.environ.get("KUEUE_BURST_DEBUG"):
                print(f"static tier upload: "
                      f"{layout._static_nbytes/1e6:.1f}MB "
                      f"{(_time.perf_counter() - t_s)*1e3:.1f}ms",
                      file=sys.stderr)
        stats["burst_boundary_bytes_equiv"] += layout._static_nbytes

        res = self._resident
        hit = (res is not None and res.layout is layout
               and plan.prev_token is not None
               and res.token == plan.prev_token
               and plan.dirty_cqs is not None)
        SCs = layout.n_shards * layout.Cs
        full_bytes = sum((a[n].nbytes // max(1, plan.C)) * SCs
                        for n in SCATTER_PLANES)
        t0 = _time.perf_counter()
        if hit:
            planes = dict(res.planes)
            dirty = np.asarray(plan.dirty_cqs)
            D = int(dirty.size)
            if D:
                pos = layout.cq_pos[dirty]
                order = np.argsort(pos, kind="stable")
                cis = dirty[order]
                rows = pos[order].astype(np.int32)
                ranges = PackJournal.coalesce(rows.tolist())
                Dp = scatter_pad(D)
                rows_pad = (np.concatenate(
                    [rows, np.repeat(rows[-1:], Dp - D)])
                    if Dp != D else rows)
                scat = self._scatter_rows_fn()
                nb = 0
                vals_all = []
                for name in SCATTER_PLANES:
                    vals = np.ascontiguousarray(a[name][cis])
                    nb += vals.nbytes
                    if Dp != D:
                        vals = np.concatenate(
                            [vals, np.repeat(vals[-1:], Dp - D, axis=0)])
                    vals_all.append(vals)
                new = scat(tuple(planes[n] for n in SCATTER_PLANES),
                           rows_pad, tuple(vals_all))
                planes.update(zip(SCATTER_PLANES, new))
                stats["burst_resident_scatter_rows"] += D
                stats["burst_resident_scatter_ranges"] += len(ranges)
                stats["burst_boundary_bytes_h2d"] += nb
            stats["burst_resident_hits"] += 1
            stats["burst_resident_scatter_s"] += (
                _time.perf_counter() - t0)
            if env_value("KUEUE_TPU_RESIDENT_VERIFY"):
                for name in SCATTER_PLANES:
                    want = layout.permute_rows(a[name], _C_FILLS[name])
                    if not np.array_equal(np.asarray(planes[name]),
                                          want):
                        raise AssertionError(
                            f"resident scatter drift in {name}")
        else:
            planes = {
                name: jax.device_put(
                    layout.permute_rows(a[name], _C_FILLS[name],
                                        timers), sh)
                for name in SCATTER_PLANES}
            stats["burst_resident_misses"] += 1
            stats["burst_boundary_bytes_h2d"] += full_bytes
            if os.environ.get("KUEUE_BURST_DEBUG"):
                print(f"resident miss: {full_bytes/1e6:.1f}MB "
                      f"{(_time.perf_counter() - t0)*1e3:.1f}ms",
                      file=sys.stderr)
        stats["burst_boundary_bytes_equiv"] += full_bytes

        glob = {}
        for name in GLOBAL_PLANES:
            host = layout.permute_rows(a[name], _C_FILLS[name], timers)
            glob[name] = host
            stats["burst_boundary_bytes_h2d"] += host.nbytes
            stats["burst_boundary_bytes_equiv"] += host.nbytes
        self._resident = (
            _ResidentRows(layout, plan.pack_token, planes)
            if plan.pack_token is not None else None)
        merged = dict(dev_static)
        merged.update(planes)
        merged.update(glob)
        return merged

    def _launch_sharded(self, plan: BurstPlan, K: int, runtime: int,
                        ext_release, ext_unpark, state, seq_base: int,
                        speculative: bool, permuted: bool) -> BurstHandle:
        """Mesh-sharded twin of the serial launch: plan tensors and scan
        state are permuted into per-forest shard blocks (value-remapped
        so every rank/slot the kernel compares is carried verbatim —
        decisions stay bit-identical) and the shard_map-wrapped kernel
        is dispatched once across the whole mesh.  With the resident
        boundary on, the permuted row planes live on the mesh: a fresh
        pack scatters only its dirty rows (``_resident_inputs``) and a
        chained window reuses the cached device dict outright."""
        import os
        import time as _time
        from ..parallel.sharded import _STATE_NAMES
        layout = self._layout_for(plan)
        timers = self.stats.get("burst_shard_pack_s")
        a = None
        if env_value("KUEUE_TPU_RESIDENT") != "0":
            cached = getattr(plan, "_resident_args", None)
            if cached is not None and cached[0] is layout:
                a = cached[1]
            elif not permuted:
                a = self._resident_inputs(plan, layout, timers)
                plan._resident_args = (layout, a)
            if a is not None and not permuted:
                state = tuple(a[n] for n in _STATE_NAMES)
        if a is None:
            a = layout.plan_arrays(plan, timers)
            if not permuted:
                state = layout.permute_state(state, timers)
        (elig0, parked0, resume0, adm0, adm_seq0, adm_usage0,
         adm_uses0, death0, u_cq0) = state
        extr, extu = layout.permute_ext(ext_release, ext_unpark)
        t_fn = _time.perf_counter()
        fn = self._sharded_fn(plan, layout, K, runtime)
        t0 = _time.perf_counter()
        if (os.environ.get("KUEUE_BURST_DEBUG")
                and t0 - t_fn > 0.05):
            print(f"sharded fn build: {(t0 - t_fn)*1e3:.1f}ms",
                  file=sys.stderr)
        out = fn(
            a["wl_req"], a["wl_rank"], a["wl_cycle_rank"],
            a["wl_prio"], a["wl_uidrank"], a["vec_ok"],
            elig0, parked0, resume0,
            adm0, adm_seq0, adm_usage0,
            adm_uses0, death0, np.int32(seq_base),
            u_cq0,
            a["potential0"], a["subtree"], a["guaranteed"],
            a["borrow_cap"], a["has_blim"], a["parent"],
            a["node_level"], a["nominal_cq"], a["npb_cq"],
            a["slot_fr"], a["slot_valid"], a["cq_can_preempt_borrow"],
            a["cq_wcb_borrow"], a["cq_wcp_preempt"],
            a["forest_of_cq"], a["strict_cq"],
            a["wcq_lower"], a["rwc_enabled"], a["rwc_only_lower"],
            a["preempt_ok"],
            a["members"], a["cand_rows"], a["cand_lmem"],
            a["self_lmem"],
            extr, extu)
        if os.environ.get("KUEUE_BURST_DEBUG"):
            t1 = _time.perf_counter()
            if t1 - t0 > 0.1:
                print(f"sharded dispatch call: {(t1 - t0)*1e3:.1f}ms "
                      f"(trace+lower on first shapes)", file=sys.stderr)
        self.stats["burst_dispatches"] += 1
        self.stats["burst_cycles_decided"] += K
        self.stats["burst_sharded_dispatches"] = (
            self.stats.get("burst_sharded_dispatches", 0) + 1)
        if speculative:
            self.stats["burst_spec_dispatches"] += 1
        else:
            self.stats["burst_serial_windows"] += 1
        dev = self._shard_mesh.devices.flat[0]
        if dev.platform != "cpu":
            self.stats["burst_accel_dispatches"] += 1
        return BurstHandle(plan=plan, K=K, runtime=runtime,
                           seq_base=seq_base, dev=dev, pending=out,
                           speculative=speculative, t_dispatch=t0,
                           sharded=True, layout=layout)

    def dispatch(self, plan: BurstPlan, K: int, runtime: int,
                 ext_release: np.ndarray,
                 ext_unpark: np.ndarray) -> BurstHandle:
        """Async dispatch of a freshly packed window."""
        a = plan.arrays
        state = (a["elig0"], a["parked0"], a["resume0"], a["adm0"],
                 a["adm_seq0"], a["adm_usage0"], a["adm_uses0"],
                 a["death0"], a["u_cq0"])
        return self._launch(plan, K, runtime, ext_release, ext_unpark,
                            state, plan.seq_base, speculative=False)

    def dispatch_next(self, handle: BurstHandle, ext_release: np.ndarray,
                      ext_unpark: np.ndarray) -> BurstHandle | None:
        """Speculatively chain the NEXT window off a fetched handle's
        final carry: the plan's static tensors are reused, the scan
        state stays device-resident, ``death`` is rebased by -K and
        ``seq_base`` advances by K.  Returns None when the composite-key
        seq field would overflow (the serial path re-packs and its gate
        decides).  The caller owns validity: any apply-side divergence
        from the modeled window must discard the handle unfetched."""
        import jax.numpy as jnp
        if handle.carry is None:
            return None
        # a carry from one dispatch mode can't chain into the other
        # (sharded carries live in shard layout): force a re-pack
        if handle.sharded != (self.n_shards > 1
                              and self._shard_mesh is not None):
            return None
        # nor across layouts: after lose_devices/refresh_layouts the
        # next _layout_for would re-partition and the carry's shard
        # blocks no longer line up with the new permutation
        if (handle.sharded and handle.layout is not None
                and self._shard_layouts.get(
                    self._layout_key(handle.plan)) is not handle.layout):
            return None
        seq_base = handle.seq_base + handle.K
        # same headroom discipline as pack_burst's overflow gate
        if seq_base + max(K_BURST_LADDER) >= (1 << 20):
            return None
        (elig, parked, resume, adm, adm_seq, adm_usage, adm_uses,
         death, u_cq) = handle.carry
        death = jnp.where(adm & (death != INF_I32),
                          death - np.int32(handle.K), INF_I32)
        state = (elig, parked, resume, adm, adm_seq, adm_usage,
                 adm_uses, death, u_cq)
        return self._launch(handle.plan, handle.K, handle.runtime,
                            ext_release, ext_unpark, state, seq_base,
                            speculative=True, permuted=handle.sharded)

    def fetch_flags(self, handle: BurstHandle):
        """Flags-first half of the fetch: block only for the tiny
        replicated (dirty, dirty_reason) planes — the speculation gate's
        whole input — park the final carry for ``dispatch_next``, and
        start async device→host copies of the decision planes.  The
        caller can then chain the next window's dispatch BEFORE the full
        ``fetch`` assembles decisions, so each shard's decision transfer
        overlaps the chained kernel and the host apply loop instead of
        serializing ahead of them."""
        import jax
        if handle.decisions is not None:
            return handle.decisions[5], handle.decisions[6]
        if handle.flags is not None:
            return handle.flags
        out = handle.pending
        handle.carry = out[-1]
        dirty = jax.device_get(out[5])
        dirty_reason = jax.device_get(out[6])
        for arr in out[:5]:
            try:
                arr.copy_to_host_async()
            except Exception:
                pass   # overlap is best-effort; fetch still blocks
        handle.flags = (dirty, dirty_reason)
        return handle.flags

    def fetch(self, handle: BurstHandle):
        """Block for a dispatched window's decisions.  Returns the numpy
        tuple (head_row, kind, slot, borrows, tgt_words, dirty,
        dirty_reason) and parks the final carry on the handle for
        ``dispatch_next``."""
        import jax
        import time as _time
        if handle.decisions is not None:
            return handle.decisions
        t0 = _time.perf_counter()
        out = handle.pending
        handle.carry = out[-1]
        if handle.sharded:
            # per-shard readiness: block each decision shard in device
            # order and attribute the incremental wait to that shard
            waits = self.stats.get("burst_shard_fetch_s")
            if waits is not None:
                try:
                    shards = sorted(out[0].addressable_shards,
                                    key=lambda sh: sh.device.id)
                    for i, sh in enumerate(shards[:len(waits)]):
                        t1 = _time.perf_counter()
                        sh.data.block_until_ready()
                        waits[i] += _time.perf_counter() - t1
                except Exception:
                    pass   # timing is best-effort, decisions are not
            dec = tuple(jax.device_get(out[:-1]))
            cp = handle.layout.cq_pos
            # decisions come back in shard layout [K, S*Cs, ...]; the
            # inverse permutation restores the global CQ axis.  tgt_words
            # values need no remap: bit j of a CQ's word row refers to
            # candidate slot j, and the local tables were value-remapped
            # at identical slot positions.
            handle.decisions = tuple(
                [np.ascontiguousarray(d[:, cp]) for d in dec[:5]]
                + [dec[5], dec[6]])
        else:
            handle.decisions = tuple(jax.device_get(out[:-1]))
        handle.pending = None
        # per-forest cycle-cost sample for the next layout's LPT
        self._note_forest_activity(handle.plan, handle.decisions[0])
        dt = _time.perf_counter() - t0
        if handle.speculative:
            # residual wait not hidden behind the previous window's
            # apply loop — the visible pipelined boundary cost
            self.stats["burst_spec_fetch_wait_s"] += dt
        else:
            self.stats["burst_dispatch_s"] += (
                _time.perf_counter() - handle.t_dispatch)
        import os
        if os.environ.get("KUEUE_BURST_DEBUG"):
            import sys
            plan = handle.plan
            print(f"burst fetch K={handle.K} M={plan.M} KC={plan.KC} "
                  f"C={plan.C} dev={handle.dev.platform} "
                  f"spec={handle.speculative}: wait {dt*1e3:.1f} ms",
                  file=sys.stderr)
        return handle.decisions

    def run(self, plan: BurstPlan, K: int, runtime: int,
            ext_release: np.ndarray, ext_unpark: np.ndarray):
        """One fused dispatch of K cycles, synchronously.  Returns numpy
        decision arrays (head_row, kind, slot, borrows, tgt_words,
        dirty, dirty_reason, u_cq)."""
        import jax
        handle = self.dispatch(plan, K, runtime, ext_release, ext_unpark)
        decisions = self.fetch(handle)
        u_cq = jax.device_get(handle.carry[-1])
        if handle.sharded:
            u_cq = np.ascontiguousarray(u_cq[handle.layout.cq_pos])
        return decisions + (u_cq,)
