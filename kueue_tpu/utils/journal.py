"""Between-window mutation journal feeding the incremental burst pack.

The queue manager and the cache each own one journal; their mutators
mark the ClusterQueues whose packed rows may have changed.  The burst
pack (ops/burst.py pack_burst_cached) drains both journals at every
window boundary and re-walks only the dirty CQs, reusing the persistent
per-CQ row records for everything else.

Two dirt grades keep the hot path clean:

- ``touch``: the CQ's row set or row facts changed (arrival, deletion,
  park/unpark, admission accounting) — the CQ must be re-walked.
- ``note_roundtrip``: a head was popped and requeued straight back
  (every scheduled head, every cycle).  The row set is unchanged; only
  per-row dynamic facts (the flavor-resume bit, the parked bit) could
  have moved, so the pack verifies those in O(1) per key instead of
  re-walking the CQ.

``touch_all`` covers global inputs the journal doesn't model per-CQ
(e.g. LimitRange summaries).  A fresh journal starts dirty-all so the
first pack is always a full walk.
"""

from __future__ import annotations


class PackJournal:
    __slots__ = ("dirty", "dirty_all", "soft")

    def __init__(self):
        self.dirty: set[str] = set()
        self.soft: dict[str, set[str]] = {}
        self.dirty_all = True

    def touch(self, cq_name: str) -> None:
        self.dirty.add(cq_name)

    def touch_all(self) -> None:
        self.dirty_all = True

    def note_roundtrip(self, cq_name: str, key: str) -> None:
        s = self.soft.get(cq_name)
        if s is None:
            s = self.soft[cq_name] = set()
        s.add(key)

    def drain_into(self, dirty: set, soft: dict) -> bool:
        """Merge this journal's content into the caller's accumulators
        and reset it; returns the dirty-all flag that was set."""
        was_all = self.dirty_all
        dirty |= self.dirty
        for name, keys in self.soft.items():
            acc = soft.get(name)
            if acc is None:
                soft[name] = set(keys)
            else:
                acc |= keys
        self.dirty.clear()
        self.soft.clear()
        self.dirty_all = False
        return was_all
