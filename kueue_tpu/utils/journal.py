"""Journals: the between-window pack journal and the write-ahead cycle log.

``PackJournal`` is the in-memory mutation journal feeding the
incremental burst pack.  The queue manager and the cache each own one;
their mutators mark the ClusterQueues whose packed rows may have
changed.  The burst pack (ops/burst.py pack_burst_cached) drains both
journals at every window boundary and re-walks only the dirty CQs,
reusing the persistent per-CQ row records for everything else.

Two dirt grades keep the hot path clean:

- ``touch``: the CQ's row set or row facts changed (arrival, deletion,
  park/unpark, admission accounting) — the CQ must be re-walked.
- ``note_roundtrip``: a head was popped and requeued straight back
  (every scheduled head, every cycle).  The row set is unchanged; only
  per-row dynamic facts (the flavor-resume bit, the parked bit) could
  have moved, so the pack verifies those in O(1) per key instead of
  re-walking the CQ.

``touch_all`` covers global inputs the journal doesn't model per-CQ
(e.g. LimitRange summaries).  A fresh journal starts dirty-all so the
first pack is always a full walk.

The journal also feeds a second, independent consumer: the cache's
incremental snapshot builder reads its own ``snap_dirty``/``snap_all``
channel via ``drain_snapshot`` so the burst pack's destructive
``drain_into`` and the snapshot's per-cycle drain never race for the
same dirt.

``CycleWAL`` is the durable sibling: a write-ahead log of the driver's
per-cycle decision batches (admits, evictions, requeue-state updates,
finishes).  Every op is journaled *before* the store mutation it
describes, and a commit mark closes each cycle's batch, so a crash at
any point leaves at most one partially-applied batch — the uncommitted
tail.  Recovery rolls the tail forward over the surviving workload
store (``replay_tail``, idempotent, using the journaled timestamps so
the replayed status is bit-identical), then ``Driver.restore_workload``
rebuilds cache and queues from the rolled-forward store.

The on-disk format is one JSON object per line::

    {"wal": "op", "op": "admit", "key": ..., ...}
    {"wal": "commit", "batch": 0, "n": 3}

``CycleWAL(path=...)`` appends per line and *group-commits*: the file
buffer is flushed (and optionally fsynced) every ``commit_every``-th
``commit()`` instead of per line, so a 1M-decision window pays
O(decisions / commit_every) syscalls.  ``KUEUE_TPU_WAL_COMMIT_EVERY``
sets the default interval (1 = the durable-per-cycle seed behaviour).
With an interval of N, a crash can lose at most the last N-1 *committed*
batches plus the open tail — recovery then observes a consistent,
slightly older prefix, exactly as if the crash had happened N-1 cycles
earlier.  When a chaos injector is installed the WAL falls back to
per-line flushing regardless of the interval, because the crash-parity
harness reasons about single-op boundaries.

``CycleWAL.compact()`` folds all committed batches into one checkpoint
record and rewrites the file as checkpoint + uncommitted tail
(atomically, via ``os.replace``), so recovery never re-reads a
1M-decision history: replay only ever needed the tail, and the
checkpoint preserves batch numbering (``folded_batches``).
``CycleWAL.load(path)`` rebuilds batches and tail from the file.

``IngestJournal`` is the serving-side third journal: accepted
submissions (serving/service.py) are journaled durably before their
ack, apply markers record cycle-boundary drains, and shed markers
record backpressure drops — together with the CycleWAL tail this is
what makes SIGKILL+restart lose zero accepted submissions and
duplicate zero admissions.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional

from ..chaos import injector as _chaos
from ..features import env_int


class PackJournal:
    __slots__ = ("dirty", "dirty_all", "soft", "rows", "tainted",
                 "snap_dirty", "snap_all")

    def __init__(self):
        self.dirty: set[str] = set()
        self.soft: dict[str, set[str]] = {}
        # Row-grade dirt: workload key -> owning CQ, last-writer-wins.
        # Multiple touches of the same key inside one cycle collapse to
        # a single row patch (dict assignment is the dedupe).  Consumers
        # that don't understand row grade (the classic delta pack)
        # escalate each entry to its CQ in drain_into.
        self.rows: dict[str, str] = {}
        self.dirty_all = True
        # chaos: a simulated lost update (journal.drop_touch) taints the
        # journal; the next drain reports dirty-all so the pack falls
        # back to a full walk instead of trusting incomplete dirt
        self.tainted = False
        # Second consumer channel: the incremental snapshot builder
        # (cache.Cache.snapshot).  The burst pack's drain_into is
        # destructive, so the snapshot keeps its own dirt accumulator,
        # fed by the same mutators and drained independently.  A lost
        # update (drop_touch) poisons this channel immediately — unlike
        # ``tainted`` it cannot wait for the next burst drain, because
        # the two consumers drain at different times.
        self.snap_dirty: set[str] = set()
        self.snap_all = True

    def touch(self, cq_name: str) -> None:
        if _chaos.ACTIVE is not None:
            if _chaos.ACTIVE.hit("journal.drop_touch") is not None:
                self.tainted = True
                self.snap_all = True
                return
            if _chaos.ACTIVE.hit("journal.spurious_dirty_all") is not None:
                self.dirty_all = True
                self.snap_all = True
        self.dirty.add(cq_name)
        self.snap_dirty.add(cq_name)

    def touch_all(self) -> None:
        self.dirty_all = True
        self.snap_all = True

    def drain_snapshot(self) -> tuple[set, bool]:
        """Drain the snapshot consumer's channel: returns
        ``(dirty_cq_names, was_all)`` and resets only this channel —
        the burst pack's ``dirty``/``soft``/``dirty_all`` state is
        untouched, and vice versa for :meth:`drain_into`."""
        was_all = self.snap_all
        out = self.snap_dirty
        self.snap_dirty = set()
        self.snap_all = False
        return out, was_all

    def note_roundtrip(self, cq_name: str, key: str) -> None:
        s = self.soft.get(cq_name)
        if s is None:
            s = self.soft[cq_name] = set()
        s.add(key)

    def touch_row(self, cq_name: str, key: str) -> None:
        """Row-grade dirt: exactly one workload's row facts changed and
        the CQ's aggregates/membership did not.  Cheaper than
        :meth:`touch` for the streaming patcher (one row re-walked
        instead of the whole CQ); duplicate touches of the same key
        coalesce last-writer-wins."""
        if _chaos.ACTIVE is not None:
            if _chaos.ACTIVE.hit("journal.drop_touch") is not None:
                self.tainted = True
                self.snap_all = True
                return
            if _chaos.ACTIVE.hit("journal.spurious_dirty_all") is not None:
                self.dirty_all = True
                self.snap_all = True
        self.rows[key] = cq_name
        self.snap_dirty.add(cq_name)

    def drain_into(self, dirty: set, soft: dict, row_of: dict = None,
                   ranges_out: list = None, rows_out: dict = None) -> bool:
        """Merge this journal's content into the caller's accumulators
        and reset it; returns the dirty-all flag that was set.  Soft
        roundtrip keys for CQs in the hard dirty set are dropped — those
        CQs are re-walked anyway, so their keys would only bloat the
        O(1) verify set.

        ``row_of`` maps CQ name → packed row index; when given together
        with ``ranges_out``, the drained hard-dirty rows are coalesced
        into ``[lo, hi)`` ranges (see :meth:`coalesce`) and appended, so
        the scatter that pushes the dirty rows back to the device can
        issue one transfer per contiguous run instead of one per row.

        ``rows_out`` receives the deduped row-grade channel
        (``{workload key: cq name}``, last-writer-wins) minus keys whose
        CQ is hard-dirty (the re-walk covers them).  Callers that don't
        pass it get the legacy escalation: each row touch dirties its
        CQ, so consumers unaware of row grade stay correct."""
        was_all = self.dirty_all or self.tainted
        if self.rows:
            if rows_out is None:
                # legacy consumer: escalate row dirt to CQ dirt
                self.dirty.update(self.rows.values())
            else:
                for key, cq in self.rows.items():
                    if cq not in self.dirty and cq not in dirty:
                        rows_out[key] = cq
        if row_of is not None and ranges_out is not None and self.dirty:
            rows = sorted(row_of[n] for n in self.dirty if n in row_of)
            ranges_out.extend(self.coalesce(rows))
        dirty |= self.dirty
        for name, keys in self.soft.items():
            if name in dirty:
                continue
            acc = soft.get(name)
            if acc is None:
                soft[name] = set(keys)
            else:
                acc |= keys
        for name in dirty:
            soft.pop(name, None)
        if rows_out is not None:
            for key in [k for k, cq in rows_out.items() if cq in dirty]:
                del rows_out[key]
        self.dirty.clear()
        self.soft.clear()
        self.rows.clear()
        self.dirty_all = False
        self.tainted = False
        return was_all

    @staticmethod
    def coalesce(rows) -> list:
        """Coalesce sorted row indices into ``[lo, hi)`` ranges.

        Adjacent dirty rows are the common case (cohort members pack
        consecutively), and the device update for a contiguous run is a
        single slice transfer — N singleton scatters would each pay a
        dispatch.  Duplicate indices collapse into their range."""
        out: list[tuple[int, int]] = []
        lo = hi = None
        for r in rows:
            r = int(r)
            if hi is not None and r <= hi:
                hi = max(hi, r + 1)
                continue
            if lo is not None:
                out.append((lo, hi))
            lo, hi = r, r + 1
        if lo is not None:
            out.append((lo, hi))
        return out


# ---------------------------------------------------------------------------
# Write-ahead cycle journal
# ---------------------------------------------------------------------------

class CycleWAL:
    """Write-ahead journal of admission-cycle decision batches.

    ``log(op)`` opens a batch implicitly; ``commit()`` closes it.  The
    driver logs each op just before applying it to the store, and
    commits at cycle boundaries, so the uncommitted ``tail`` is exactly
    the set of decisions a crash may have half-applied.

    Group commit: ``commit_every=N`` flushes the OS file buffer (and
    fsyncs when ``fsync=True``) only every Nth commit, amortising the
    syscall over N cycles.  N=1 (the default, overridable via
    ``KUEUE_TPU_WAL_COMMIT_EVERY``) keeps the seed's flush-per-line
    durability.  Chaos runs always flush per line — the crash-parity
    harness reasons about single-op boundaries.

    ``compact_every=B`` (0 = never) auto-compacts after every B
    committed batches; see :meth:`compact`."""

    def __init__(self, path: Optional[str] = None,
                 commit_every: Optional[int] = None,
                 fsync: bool = False,
                 compact_every: int = 0):
        self.path = path
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self.batches: list[list[dict]] = []   # committed batches
        self._open: Optional[list[dict]] = None
        if commit_every is None:
            commit_every = env_int("KUEUE_TPU_WAL_COMMIT_EVERY")
        self.commit_every = max(1, commit_every)
        self.fsync = fsync
        self.compact_every = max(0, compact_every)
        self._commits_since_flush = 0
        # batches folded away by compaction (keeps batch ids monotonic
        # across a compact; surfaced in the checkpoint record)
        self.folded_batches = 0
        self.folded_ops = 0
        self.stats = {"wal_appends": 0, "wal_commits": 0,
                      "wal_flushes": 0, "wal_fsyncs": 0,
                      "wal_compactions": 0}

    # -- writing --

    def register_appender(self, name) -> None:
        """No-op; duck-compat with ShardedCycleWAL's appender census."""

    def unregister_appender(self, name) -> None:
        """No-op; duck-compat with ShardedCycleWAL's appender census."""

    def log(self, op: dict) -> None:
        from ..obs.trace import span as _span
        # counted leaf: per-op appends are ~2µs, a retained record
        # would cost more than the op — histogram-only timing
        with _span("wal.append", counted=True):
            if self._open is None:
                self._open = []
            self._open.append(op)
            self._emit(dict(op, wal="op"))

    def commit(self) -> None:
        if self._open is None:
            return
        from ..obs.trace import span as _span
        with _span("wal.commit"):
            self._emit({"wal": "commit",
                        "batch": self.folded_batches + len(self.batches),
                        "n": len(self._open)})
            self.batches.append(self._open)
            self._open = None
            self.stats["wal_commits"] += 1
            self._commits_since_flush += 1
            if self._commits_since_flush >= self.commit_every:
                self._flush()
            if self.compact_every and len(self.batches) >= self.compact_every:
                self.compact()

    def _emit(self, rec: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self.stats["wal_appends"] += 1
        # chaos crash tests cut the process between arbitrary ops: every
        # line must be on disk the instant it is journaled, so group
        # commit is disabled while an injector is installed
        if self.commit_every == 1 or _chaos.ACTIVE is not None:
            self._fh.flush()

    def _flush(self) -> None:
        if self._fh is None:
            return
        self._fh.flush()
        self.stats["wal_flushes"] += 1
        if self.fsync:
            os.fsync(self._fh.fileno())
            self.stats["wal_fsyncs"] += 1
        self._commits_since_flush = 0

    def compact(self) -> int:
        """Fold all committed batches into a checkpoint record and
        atomically rewrite the file as checkpoint + uncommitted tail.

        Recovery only ever replays the tail (committed batches are, by
        definition, fully applied to the store), so dropping their ops
        from the file changes nothing about replay — it just stops a
        long-lived journal growing without bound and makes ``load`` of
        a 1M-decision history O(tail).  Returns the number of batches
        folded by this call."""
        if self._fh is None or self.path is None:
            # in-memory WAL: just fold the batch list
            n = len(self.batches)
            self.folded_batches += n
            self.folded_ops += sum(len(b) for b in self.batches)
            self.batches = []
            return n
        from ..obs.trace import span as _span
        with _span("wal.compact"):
            n = len(self.batches)
            self.folded_batches += n
            self.folded_ops += sum(len(b) for b in self.batches)
            self.batches = []
            tmp = self.path + ".compact"
            with open(tmp, "w", encoding="utf-8") as out:
                out.write(json.dumps(
                    {"wal": "checkpoint",
                     "folded_batches": self.folded_batches,
                     "folded_ops": self.folded_ops}, sort_keys=True) + "\n")
                for op in (self._open or ()):
                    out.write(json.dumps(dict(op, wal="op"),
                                         sort_keys=True) + "\n")
                out.flush()
                os.fsync(out.fileno())
            self._fh.flush()
            self._fh.close()
            self._fh = None   # a crash below must leave close() safe
            if _chaos.ACTIVE is not None:
                # crash here leaves the old journal intact plus a stray
                # .compact temp file: recovery reads the uncompacted log
                _chaos.ACTIVE.crashpoint("wal.compact")
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._commits_since_flush = 0
            self.stats["wal_compactions"] += 1
            return n

    def close(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    # -- reading --

    @property
    def tail(self) -> list[dict]:
        """Ops journaled since the last commit (possibly half-applied)."""
        return list(self._open or ())

    @classmethod
    def resume(cls, path: str) -> "CycleWAL":
        """Crash recovery for a process that keeps running: rebuild
        batches and tail from disk *and* reopen the file for appending.

        The loaded ``_open`` tail is carried over, so after the caller
        replays it (``replay_tail``) a plain ``commit()`` writes only
        the commit marker — the tail's ops are already on disk — and
        the journal continues exactly where the killed process left it.
        ``commit_every`` falls back to the registry default, as in
        ``__init__``."""
        wal = cls.load(path)
        wal._fh = open(path, "a", encoding="utf-8")
        return wal

    @classmethod
    def load(cls, path: str) -> "CycleWAL":
        """Rebuild a WAL from its JSON-lines file (the recovery read
        path).  The returned WAL is read-only-ish: it has no file handle
        so replay tooling can't accidentally extend the original log."""
        wal = cls()
        wal.path = path
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("wal")
                if kind == "commit":
                    wal.batches.append(wal._open or [])
                    wal._open = None
                elif kind == "checkpoint":
                    # a compaction boundary: the folded batches are
                    # fully applied history, only their count survives
                    wal.folded_batches = rec.get("folded_batches", 0)
                    wal.folded_ops = rec.get("folded_ops", 0)
                else:
                    rec.pop("wal", None)
                    if wal._open is None:
                        wal._open = []
                    wal._open.append(rec)
        return wal

    # -- replay --

    def replay_tail(self, store: dict) -> int:
        """Roll the uncommitted tail forward over ``store`` (a
        ``{key: Workload}`` dict).  Idempotent: ops whose effect is
        already visible are skipped, so replay after a crash anywhere
        between journal write and store write converges to the same
        state as the uncrashed apply.  Returns the op count replayed."""
        n = 0
        for op in self.tail:
            if replay_op(store, op):
                n += 1
        return n

    def replay_history(self, store: dict) -> int:
        """Roll *committed* batches forward over ``store``, in order.

        The normal recovery path never needs this — committed batches
        are by definition fully applied to the durable store.  The
        distributed children invert that: their durable store is the
        ingest/manifest journal of *initial* payloads, and the WAL is
        the only record of every decision since, so recovery is
        initial-state + full history + tail.  Refuses a compacted
        journal (the folded ops are gone); dist children therefore run
        with compaction off."""
        if self.folded_batches:
            raise RuntimeError(
                f"replay_history on a compacted WAL ({self.folded_batches} "
                f"batches folded away): full history is gone")
        n = 0
        for batch in self.batches:
            for op in batch:
                if replay_op(store, op):
                    n += 1
        return n


class ShardedCycleWAL:
    """CycleWAL striped across K journal segments.

    At high admission rates the single-file group commit serializes:
    every cycle's ops funnel through one ``write``+``flush`` stream and
    one fsync cadence.  This variant routes each op to one of K
    ``CycleWAL`` segments by a *stable* hash of its workload key (CQ
    shard affinity: one workload's ops always land in one segment), so
    appends and group-commit flushes stripe across K files while a
    process-global monotone ``seq`` stamped into every op preserves the
    total order.  ``tail``/``replay_tail`` merge the per-segment tails
    back into seq order, so recovery converges to the same state as the
    unsharded journal byte for byte (crash-parity test-enforced at
    every ``wal.*`` chaos site).

    Duck-compatible with ``CycleWAL`` (``log``/``commit``/``tail``/
    ``replay_tail``/``compact``/``close``/``stats``/``path``) —
    ``Driver.attach_wal`` and ``recover_from`` take either.  Segment
    files live at ``{path}.s00 .. .s{K-1:02d}``; ``load_cycle_wal``
    autodetects them.  ``wal.shard_merge`` is the chaos crashpoint
    between per-segment compactions: a crash there leaves segments at
    mixed compaction generations, which the merged replay must absorb.

    Striping only pays when appenders are actually concurrent; with a
    single writer it spreads one stream across K buffered files and
    *loses* (0.84x commit wall in SCALE_r18.json).  Appenders therefore
    announce themselves via ``register_appender``/``unregister_appender``
    (the host worker pool does this), and with <=1 registered the router
    collapses every op to segment 0 — single-stream locality — while the
    seq stamp keeps the merged replay identical either way.
    """

    def __init__(self, path: Optional[str] = None, shards: int = 2,
                 commit_every: Optional[int] = None,
                 fsync: bool = False, compact_every: int = 0):
        self.path = path
        self.shards = max(2, int(shards))
        self._shards = [
            CycleWAL(self.shard_path(path, i) if path else None,
                     commit_every=commit_every, fsync=fsync,
                     compact_every=compact_every)
            for i in range(self.shards)]
        self._seq = 0
        self._appenders: set = set()

    @staticmethod
    def shard_path(path: str, i: int) -> str:
        return f"{path}.s{i:02d}"

    def register_appender(self, name) -> None:
        """Announce a concurrent appender; striping engages at >=2."""
        self._appenders.add(name)

    def unregister_appender(self, name) -> None:
        self._appenders.discard(name)

    def _route(self, op: dict) -> int:
        if len(self._appenders) <= 1:
            return 0   # single writer: keep one hot stream (no stripe tax)
        key = op.get("key") or (op.get("keys") or ("",))[0]
        return zlib.crc32(key.encode("utf-8", "replace")) % self.shards

    # -- writing --

    def log(self, op: dict) -> None:
        # stamp seq in place: CycleWAL.log stores the caller's dict by
        # reference anyway (ownership passes to the journal), and the
        # per-op copy was most of the single-appender stripe tax the
        # r19 collapse is meant to remove; the route branch is inlined
        # because a single hot stream takes it 100% of the time
        op["seq"] = self._seq
        self._seq += 1
        if len(self._appenders) <= 1:
            self._shards[0].log(op)   # single writer: one hot stream
        else:
            self._shards[self._route(op)].log(op)

    def commit(self) -> None:
        for sh in self._shards:
            sh.commit()   # no-op for segments with no open batch

    def compact(self) -> int:
        n = 0
        for i, sh in enumerate(self._shards):
            n += sh.compact()
            if i == 0 and _chaos.ACTIVE is not None:
                # crash between segment compactions: segments now sit
                # at mixed generations; the seq-merged replay converges
                _chaos.ACTIVE.crashpoint("wal.shard_merge")
        return n

    def close(self) -> None:
        for sh in self._shards:
            sh.close()

    # -- reading --

    @property
    def tail(self) -> list[dict]:
        """Union of segment tails, merged back into total (seq) order."""
        ops = [op for sh in self._shards for op in sh.tail]
        ops.sort(key=lambda op: op.get("seq", 0))
        return ops

    @property
    def stats(self) -> dict:
        out = {"wal_appends": 0, "wal_commits": 0, "wal_flushes": 0,
               "wal_fsyncs": 0, "wal_compactions": 0}
        appends = []
        for sh in self._shards:
            appends.append(sh.stats["wal_appends"])
            for k in out:
                out[k] += sh.stats[k]
        out["wal_shards"] = self.shards
        out["wal_shard_skew"] = max(appends) - min(appends)
        out["wal_appenders"] = len(self._appenders)
        return out

    @classmethod
    def load(cls, path: str) -> "ShardedCycleWAL":
        """Rebuild from segment files (the recovery read path); like
        ``CycleWAL.load`` the result carries no file handles."""
        wal = cls.__new__(cls)
        wal.path = path
        wal._shards = []
        wal._appenders = set()
        i = 0
        while os.path.exists(cls.shard_path(path, i)):
            wal._shards.append(CycleWAL.load(cls.shard_path(path, i)))
            i += 1
        wal.shards = len(wal._shards)
        wal._seq = 1 + max(
            (op.get("seq", -1) for sh in wal._shards
             for b in (sh.batches + [sh.tail]) for op in b),
            default=-1)
        return wal

    # -- replay --

    def replay_tail(self, store: dict) -> int:
        n = 0
        for op in self.tail:
            if replay_op(store, op):
                n += 1
        return n


def make_cycle_wal(path: Optional[str] = None,
                   commit_every: Optional[int] = None,
                   fsync: bool = False, compact_every: int = 0,
                   shards: Optional[int] = None):
    """WAL factory honoring ``KUEUE_TPU_WAL_SHARDS`` (1 = the classic
    single-file CycleWAL; >1 = the striped variant)."""
    if shards is None:
        shards = env_int("KUEUE_TPU_WAL_SHARDS")
    if shards <= 1:
        return CycleWAL(path, commit_every=commit_every, fsync=fsync,
                        compact_every=compact_every)
    return ShardedCycleWAL(path, shards=shards,
                           commit_every=commit_every, fsync=fsync,
                           compact_every=compact_every)


def load_cycle_wal(path: str):
    """Recovery read path for either WAL layout: segment files beside
    ``path`` mean it was sharded."""
    if os.path.exists(ShardedCycleWAL.shard_path(path, 0)):
        return ShardedCycleWAL.load(path)
    return CycleWAL.load(path)


# -- op encode/decode -------------------------------------------------------

def _encode_condition(c) -> dict:
    return {"type": c.type, "status": c.status.value, "reason": c.reason,
            "message": c.message, "ltt": c.last_transition_time,
            "gen": c.observed_generation}


def _encode_admission(adm) -> dict:
    return {"cluster_queue": adm.cluster_queue,
            "psa": [{"name": a.name, "flavors": dict(a.flavors),
                     "usage": dict(a.resource_usage), "count": a.count}
                    for a in adm.pod_set_assignments]}


def admit_op(wl) -> dict:
    """The SSA-shaped admit record: the workload's full post-decision
    status (admission, conditions, check states, requeue state).  Pure
    data — replay replaces the stored status wholesale, which makes the
    op trivially idempotent."""
    return {
        "op": "admit",
        "key": wl.key,
        "admission": _encode_admission(wl.admission),
        "conditions": [_encode_condition(c)
                       for c in wl.conditions.values()],
        "checks": [{"name": s.name, "state": s.state.value,
                    "message": s.message, "ltt": s.last_transition_time}
                   for s in wl.admission_check_states.values()],
        "requeue": (None if wl.requeue_state is None else
                    {"count": wl.requeue_state.count,
                     "at": wl.requeue_state.requeue_at}),
    }


def evict_op(key: str, reason: str, message: str,
             preempted_reason: Optional[str], now: float) -> dict:
    return {"op": "evict", "key": key, "reason": reason,
            "message": message, "pre": preempted_reason, "now": now}


def requeue_op(key: str, count: int, requeue_at: Optional[float]) -> dict:
    return {"op": "requeue", "key": key, "count": count, "at": requeue_at}


def finish_op(keys: list[str], message: str, now: float) -> dict:
    return {"op": "finish", "keys": list(keys), "message": message,
            "now": now}


def deactivate_op(key: str) -> dict:
    return {"op": "deactivate", "key": key}


def replay_op(store: dict, op: dict) -> bool:
    """Apply one journaled op to the plain workload store.  Pure status
    mutation — no cache or queue side effects; ``restore_workload``
    rebuilds those from the rolled-forward store afterwards.  Returns
    False when the op was already applied (or its workload is gone)."""
    from ..api.types import (Admission, AdmissionCheckState,
                             AdmissionCheckStatus, Condition,
                             ConditionStatus, PodSetAssignment,
                             RequeueState, WL_EVICTED)
    from ..workload import (set_evicted_condition, set_finished_condition,
                            set_pods_ready_condition,
                            set_preempted_condition, set_requeued_condition,
                            unset_quota_reservation)
    kind = op.get("op")
    if kind == "finish":
        any_done = False
        for key in op["keys"]:
            wl = store.get(key)
            if wl is None or wl.is_finished:
                continue
            set_finished_condition(wl, "JobFinished", op["message"],
                                   op["now"])
            any_done = True
        return any_done
    wl = store.get(op.get("key", ""))
    if wl is None:
        return False
    if kind == "admit":
        if wl.is_finished:
            return False
        enc = op["admission"]
        wl.admission = Admission(
            cluster_queue=enc["cluster_queue"],
            pod_set_assignments=[
                PodSetAssignment(name=a["name"], flavors=dict(a["flavors"]),
                                 resource_usage=dict(a["usage"]),
                                 count=a["count"])
                for a in enc["psa"]])
        wl.conditions = {
            c["type"]: Condition(type=c["type"],
                                 status=ConditionStatus(c["status"]),
                                 reason=c["reason"], message=c["message"],
                                 last_transition_time=c["ltt"],
                                 observed_generation=c["gen"])
            for c in op["conditions"]}
        wl.admission_check_states = {
            s["name"]: AdmissionCheckStatus(
                name=s["name"], state=AdmissionCheckState(s["state"]),
                message=s["message"], last_transition_time=s["ltt"])
            for s in op["checks"]}
        rq = op.get("requeue")
        wl.requeue_state = (None if rq is None else
                            RequeueState(count=rq["count"],
                                         requeue_at=rq["at"]))
        return True
    if kind == "evict":
        ev = wl.conditions.get(WL_EVICTED)
        if (ev is not None and ev.status == ConditionStatus.TRUE
                and ev.reason == op["reason"]
                and ev.last_transition_time == op["now"]):
            return False   # the mutation landed before the crash
        now = op["now"]
        set_evicted_condition(wl, op["reason"], op["message"], now)
        from ..api.types import WL_PODS_READY
        if WL_PODS_READY in wl.conditions:
            set_pods_ready_condition(wl, False, now)
        if op.get("pre") is not None:
            set_preempted_condition(wl, op["pre"], op["message"], now)
        for st in wl.admission_check_states.values():
            st.state = AdmissionCheckState.PENDING
        if wl.admission is not None:
            unset_quota_reservation(wl, op["reason"], op["message"], now)
        set_requeued_condition(wl, op["reason"], op["message"], True, now)
        return True
    if kind == "requeue":
        rs = wl.requeue_state
        if rs is not None and rs.count >= op["count"]:
            return False
        if rs is None:
            wl.requeue_state = RequeueState()
        wl.requeue_state.count = op["count"]
        wl.requeue_state.requeue_at = op["at"]
        return True
    if kind == "deactivate":
        if not wl.active:
            return False
        wl.active = False
        return True
    return False


# -- ingest journal ---------------------------------------------------------

class IngestJournal:
    """Durable journal of accepted service submissions.

    The CycleWAL's sibling on the ingest side of the admission service
    (serving/service.py): a submission's accept record is written and
    flushed *before* the submitter's ack and before the entry joins the
    in-memory ingest queue, so a SIGKILL at any point loses zero
    accepted submissions.  Three record kinds, one JSON object per
    line::

        {"ing": "accept", "seq": 7, "token": "t7", "wl": {...}}
        {"ing": "shed",   "seq": 3, "token": "t3"}
        {"ing": "apply",  "upto": 7, "cycle": 12}

    ``accept`` carries the full submission payload — including its
    creation time and runtime — so recovery rebuilds the workload
    bit-identically.  ``shed`` marks an accepted entry later dropped by
    the backpressure policy: a recorded, reported outcome, never a
    silent loss.  ``apply`` marks every seq up to ``upto`` as drained
    into the driver at a cycle boundary.  Recovery replays only the
    un-applied, un-shed suffix in seq order, skipping keys already
    present in the recovered store (the crash may have landed between
    the store apply and the ``apply`` marker) — zero lost, zero
    duplicated.

    Unlike the group-committing CycleWAL, every record flushes
    immediately: ingest records are rare relative to WAL ops (one per
    submission, not one per decision) and each one backs an ack the
    service has already returned.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self.seq = 0                       # last assigned accept seq
        self.applied_upto = 0
        self.accepted: list[dict] = []     # accept records, seq order
        self.shed_seqs: set[int] = set()
        self.stats = {"ing_accepts": 0, "ing_sheds": 0, "ing_applies": 0}

    # -- append --

    def accept(self, token: str, payload: dict) -> int:
        self.seq += 1
        rec = {"ing": "accept", "seq": self.seq, "token": token,
               "wl": payload}
        self.accepted.append(rec)
        self._emit(rec)
        self.stats["ing_accepts"] += 1
        return self.seq

    def shed(self, seq: int, token: str) -> None:
        self.shed_seqs.add(seq)
        self._emit({"ing": "shed", "seq": seq, "token": token})
        self.stats["ing_sheds"] += 1

    def mark_applied(self, upto: int, cycle: int) -> None:
        if upto <= self.applied_upto:
            return
        self.applied_upto = upto
        self._emit({"ing": "apply", "upto": upto, "cycle": cycle})
        self.stats["ing_applies"] += 1

    def _emit(self, rec: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    # -- read side --

    def unapplied(self) -> list[dict]:
        """Accept records not yet marked applied and not shed, in seq
        order — exactly what recovery must re-enqueue (minus any whose
        key already landed in the recovered store)."""
        return [r for r in self.accepted
                if r["seq"] > self.applied_upto
                and r["seq"] not in self.shed_seqs]

    @classmethod
    def load(cls, path: str) -> "IngestJournal":
        """Rebuild journal state from disk without an append handle
        (read-only inspection)."""
        j = cls(path=None)
        j.path = path
        if not os.path.exists(path):
            return j
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("ing")
                if kind == "accept":
                    j.accepted.append(rec)
                    j.seq = max(j.seq, rec["seq"])
                    j.stats["ing_accepts"] += 1
                elif kind == "shed":
                    j.shed_seqs.add(rec["seq"])
                    j.stats["ing_sheds"] += 1
                elif kind == "apply":
                    j.applied_upto = max(j.applied_upto, rec["upto"])
                    j.stats["ing_applies"] += 1
        return j

    @classmethod
    def resume(cls, path: str) -> "IngestJournal":
        """Crash recovery: rebuild state from disk *and* reopen the
        file for appending, continuing the seq numbering."""
        j = cls.load(path)
        j._fh = open(path, "a", encoding="utf-8")
        return j


# -- manifest journal -------------------------------------------------------

class ManifestJournal:
    """Durable store of workload *manifests* — the IngestJournal's
    federation-worker sibling.

    A federation worker process receives workloads through the remote
    CRUD API, not a serving front-end, so there is no accept record to
    recover the initial payload from.  This journal records each
    created workload's manifest (the same dict ``api.manifests``
    round-trips) before the create is acked, and a tombstone on delete;
    together with the worker's CycleWAL (full-history replay, see
    :meth:`CycleWAL.replay_history`) a SIGKILLed worker rebuilds its
    exact pre-kill state.  Two record kinds, one JSON object per line::

        {"mf": "put", "key": "ns/name", "doc": {...}}
        {"mf": "del", "key": "ns/name"}

    Every record flushes immediately — like ingest records, each one
    backs an ack already returned to the manager."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self.stats = {"mf_puts": 0, "mf_dels": 0}

    def put(self, key: str, doc: dict) -> None:
        self._emit({"mf": "put", "key": key, "doc": doc})
        self.stats["mf_puts"] += 1

    def delete(self, key: str) -> None:
        self._emit({"mf": "del", "key": key})
        self.stats["mf_dels"] += 1

    def _emit(self, rec: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    @classmethod
    def load(cls, path: str) -> dict:
        """Fold the journal into ``{key: manifest}`` with tombstones
        applied — the worker's surviving initial-state store."""
        docs: dict[str, dict] = {}
        if not os.path.exists(path):
            return docs
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("mf") == "put":
                    docs[rec["key"]] = rec["doc"]
                elif rec.get("mf") == "del":
                    docs.pop(rec["key"], None)
        return docs
