"""Key-deduplicating binary heap, with optional lazy repair.

Capability parity with reference pkg/util/heap: items are keyed; pushing an
existing key updates it in place and re-sifts; delete by key is O(log n).

Lazy mode (``lazy=True``, wired to ``KUEUE_TPU_LAZY_HEAP`` by the
cluster-queue layer) buffers ``push_or_update`` into a pending dict and
repairs the heap with ONE amortized pass at the next *ordered* read
(``peek``/``pop``).  Unordered reads — ``get``/``keys``/``items``/
``delete``/``len`` — are answered from the pending overlay without
settling, so a burst cycle's storm of requeues and deletes costs O(1)
each and the sift work is paid once when the next cycle reads heads.
Because the comparator is a strict total order (key tiebreak), the
settled heap's peek/pop sequence is *provably identical* to eager
repair: peek/pop always return the unique comparator-minimum of the
same membership, whatever the internal array layout (property-tested
in tests/test_lazy_heap.py).

Lazy repair only pays when keys are touched more than once between
ordered reads; at ~1 touch/key the overlay dict is pure overhead
(0.83x in the r18 microbench).  The heap therefore *measures*
touches-per-key at every settle (and estimates it from the update
fraction while demoted) and falls through to the eager sift path when
the EWMA drops below ``_ADAPT_THRESHOLD``, re-promoting itself when
churn returns — so ``KUEUE_TPU_LAZY_HEAP=1`` is never a regression.
Ordered-read results are identical in either mode.
"""

from __future__ import annotations

from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")

# process-wide lazy-repair counters (kueue_heap_repair_* metrics)
REPAIR_STATS = {
    "heap_repair_settles": 0,      # settle passes (one per ordered read
    #                                after >=1 deferred mutation)
    "heap_repair_deferred": 0,     # push/update ops buffered
    "heap_repair_settled_items": 0,  # items applied during settles
    "heap_repair_bulk": 0,         # settles that used O(n) heapify
    "heap_repair_eager_ops": 0,    # ops the adaptive gate routed to the
    #                                eager sift path (low-churn regime)
    "heap_repair_mode_flips": 0,   # lazy<->eager transitions
}

# Adaptive gate: below this measured touches-per-key the overlay dict
# costs more than it saves (r18 microbench: 0.83x at 1 touch/key), so
# the heap falls through to eager sifts until churn returns.
_ADAPT_THRESHOLD = 2.0
_ADAPT_MIN_WINDOW = 8    # ops before an eager window updates the EWMA
_ADAPT_ALPHA = 0.5       # EWMA weight of the newest window


class Heap(Generic[T]):
    def __init__(self, key_fn: Callable[[T], str],
                 less: Callable[[T, T], bool], lazy: bool = False):
        self._key = key_fn
        self._less = less
        self._items: list[T] = []
        self._index: dict[str, int] = {}
        self._lazy = lazy
        self._pending: dict[str, T] = {}
        self._pending_fresh = 0    # pending keys not already indexed
        # adaptive state: start lazy (matches r18 behavior for churny
        # workloads) and let measured touches-per-key demote/promote.
        self._lazy_active = lazy
        self._touch_ewma = 2.0 * _ADAPT_THRESHOLD
        self._pending_ops = 0      # ops buffered since last settle
        self._eager_ops = 0        # ops sifted eagerly this window
        self._eager_updates = 0    # ...of which hit an existing key

    def __len__(self) -> int:
        return len(self._items) + self._pending_fresh

    def keys(self) -> list[str]:
        if not self._pending:
            return list(self._index)
        return list(self._index) + [k for k in self._pending
                                    if k not in self._index]

    def get(self, key: str) -> Optional[T]:
        item = self._pending.get(key)
        if item is not None:
            return item
        idx = self._index.get(key)
        return self._items[idx] if idx is not None else None

    def items(self) -> list[T]:
        if not self._pending:
            return list(self._items)
        pend = self._pending
        return [it for it in self._items
                if self._key(it) not in pend] + list(pend.values())

    def push_or_update(self, item: T) -> None:
        if self._lazy:
            if self._lazy_active:
                key = self._key(item)
                if key not in self._pending and key not in self._index:
                    self._pending_fresh += 1
                self._pending[key] = item
                self._pending_ops += 1
                REPAIR_STATS["heap_repair_deferred"] += 1
                return
            # adaptive fall-through: sift eagerly, but keep measuring
            # churn (update fraction) so a storm re-enables deferral.
            self._eager_ops += 1
            if self._key(item) in self._index:
                self._eager_updates += 1
            REPAIR_STATS["heap_repair_eager_ops"] += 1
        self._push_now(item)

    def push_if_not_present(self, item: T) -> bool:
        key = self._key(item)
        if key in self._pending or key in self._index:
            return False
        self.push_or_update(item)
        return True

    def peek(self) -> Optional[T]:
        self._settle()
        self._adapt_window()
        return self._items[0] if self._items else None

    def pop(self) -> Optional[T]:
        self._settle()
        self._adapt_window()
        if not self._items:
            return None
        top = self._items[0]
        self._remove_at(0)
        return top

    def delete(self, key: str) -> bool:
        removed = False
        if key in self._pending:
            del self._pending[key]
            if key not in self._index:
                self._pending_fresh -= 1
            removed = True
        idx = self._index.get(key)
        if idx is not None:
            self._remove_at(idx)
            removed = True
        return removed

    # -- internals --

    def _push_now(self, item: T) -> None:
        key = self._key(item)
        idx = self._index.get(key)
        if idx is not None:
            self._items[idx] = item
            self._sift_up(idx)
            self._sift_down(idx)
        else:
            self._items.append(item)
            self._index[key] = len(self._items) - 1
            self._sift_up(len(self._items) - 1)

    def _settle(self) -> None:
        """Apply the pending overlay in one amortized repair pass."""
        pend = self._pending
        if not pend:
            return
        ops, self._pending_ops = self._pending_ops, 0
        self._pending = {}
        self._pending_fresh = 0
        if ops >= _ADAPT_MIN_WINDOW:
            self._observe_touches(ops / len(pend))
        REPAIR_STATS["heap_repair_settles"] += 1
        REPAIR_STATS["heap_repair_settled_items"] += len(pend)
        if len(pend) >= max(8, len(self._items) // 4):
            # bulk: place every item, then one O(n) heapify — cheaper
            # than len(pend) sifts when the overlay is a large fraction
            REPAIR_STATS["heap_repair_bulk"] += 1
            for key, item in pend.items():
                idx = self._index.get(key)
                if idx is not None:
                    self._items[idx] = item
                else:
                    self._items.append(item)
                    self._index[key] = len(self._items) - 1
            for idx in range(len(self._items) // 2 - 1, -1, -1):
                self._sift_down(idx)
        else:
            for item in pend.values():
                self._push_now(item)

    def _adapt_window(self) -> None:
        """Close an eager measurement window at an ordered read.

        While demoted, touches-per-key can't be read off an overlay, so
        it is estimated from the update fraction r = updates/ops: t
        touches of one key produce t-1 updates, so t ~= 1/(1-r)."""
        ops, upd = self._eager_ops, self._eager_updates
        if ops < _ADAPT_MIN_WINDOW:
            return
        self._eager_ops = 0
        self._eager_updates = 0
        r = min(upd / ops, 0.9)
        self._observe_touches(1.0 / (1.0 - r))

    def _observe_touches(self, touches_per_key: float) -> None:
        self._touch_ewma = ((1.0 - _ADAPT_ALPHA) * self._touch_ewma
                            + _ADAPT_ALPHA * touches_per_key)
        want_lazy = self._touch_ewma >= _ADAPT_THRESHOLD
        # lazy->eager only flips here (settle just emptied the overlay,
        # or an eager window closed with nothing buffered), so the
        # overlay invariant "_pending empty while demoted" holds.
        if want_lazy != self._lazy_active and not self._pending:
            self._lazy_active = want_lazy
            REPAIR_STATS["heap_repair_mode_flips"] += 1

    def _remove_at(self, idx: int) -> None:
        key = self._key(self._items[idx])
        last = len(self._items) - 1
        if idx != last:
            self._swap(idx, last)
        self._items.pop()
        del self._index[key]
        if idx < len(self._items):
            self._sift_up(idx)
            self._sift_down(idx)

    def _swap(self, i: int, j: int) -> None:
        self._items[i], self._items[j] = self._items[j], self._items[i]
        self._index[self._key(self._items[i])] = i
        self._index[self._key(self._items[j])] = j

    def _sift_up(self, idx: int) -> None:
        while idx > 0:
            parent = (idx - 1) // 2
            if self._less(self._items[idx], self._items[parent]):
                self._swap(idx, parent)
                idx = parent
            else:
                break

    def _sift_down(self, idx: int) -> None:
        n = len(self._items)
        while True:
            left, right = 2 * idx + 1, 2 * idx + 2
            smallest = idx
            if left < n and self._less(self._items[left], self._items[smallest]):
                smallest = left
            if right < n and self._less(self._items[right], self._items[smallest]):
                smallest = right
            if smallest == idx:
                return
            self._swap(idx, smallest)
            idx = smallest
