"""Key-deduplicating binary heap.

Capability parity with reference pkg/util/heap: items are keyed; pushing an
existing key updates it in place and re-sifts; delete by key is O(log n).
"""

from __future__ import annotations

from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class Heap(Generic[T]):
    def __init__(self, key_fn: Callable[[T], str], less: Callable[[T, T], bool]):
        self._key = key_fn
        self._less = less
        self._items: list[T] = []
        self._index: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def keys(self) -> list[str]:
        return list(self._index)

    def get(self, key: str) -> Optional[T]:
        idx = self._index.get(key)
        return self._items[idx] if idx is not None else None

    def items(self) -> list[T]:
        return list(self._items)

    def push_or_update(self, item: T) -> None:
        key = self._key(item)
        idx = self._index.get(key)
        if idx is not None:
            self._items[idx] = item
            self._sift_up(idx)
            self._sift_down(idx)
        else:
            self._items.append(item)
            self._index[key] = len(self._items) - 1
            self._sift_up(len(self._items) - 1)

    def push_if_not_present(self, item: T) -> bool:
        if self._key(item) in self._index:
            return False
        self.push_or_update(item)
        return True

    def peek(self) -> Optional[T]:
        return self._items[0] if self._items else None

    def pop(self) -> Optional[T]:
        if not self._items:
            return None
        top = self._items[0]
        self._remove_at(0)
        return top

    def delete(self, key: str) -> bool:
        idx = self._index.get(key)
        if idx is None:
            return False
        self._remove_at(idx)
        return True

    # -- internals --

    def _remove_at(self, idx: int) -> None:
        key = self._key(self._items[idx])
        last = len(self._items) - 1
        if idx != last:
            self._swap(idx, last)
        self._items.pop()
        del self._index[key]
        if idx < len(self._items):
            self._sift_up(idx)
            self._sift_down(idx)

    def _swap(self, i: int, j: int) -> None:
        self._items[i], self._items[j] = self._items[j], self._items[i]
        self._index[self._key(self._items[i])] = i
        self._index[self._key(self._items[j])] = j

    def _sift_up(self, idx: int) -> None:
        while idx > 0:
            parent = (idx - 1) // 2
            if self._less(self._items[idx], self._items[parent]):
                self._swap(idx, parent)
                idx = parent
            else:
                break

    def _sift_down(self, idx: int) -> None:
        n = len(self._items)
        while True:
            left, right = 2 * idx + 1, 2 * idx + 2
            smallest = idx
            if left < n and self._less(self._items[left], self._items[smallest]):
                smallest = left
            if right < n and self._less(self._items[right], self._items[smallest]):
                smallest = right
            if smallest == idx:
                return
            self._swap(idx, smallest)
            idx = smallest
