"""Expectations store (reference pkg/util/expectations/store.go:30).

A UID-set synchronization barrier: a controller records the object UIDs
whose updates it initiated and only trusts its cache once every expected
update has been observed — the pod-group integration uses it to avoid
racing its own ungate patches (reference pod integration)."""

from __future__ import annotations

import threading


class Store:
    def __init__(self, name: str = "expectations"):
        self.name = name
        self._lock = threading.Lock()
        self._store: dict[str, set[str]] = {}

    def expect_uids(self, key: str, uids: list[str]) -> None:
        """reference store.go ExpectUIDs."""
        with self._lock:
            self._store.setdefault(key, set()).update(uids)

    def observed_uid(self, key: str, uid: str) -> None:
        """reference store.go ObservedUID."""
        with self._lock:
            uids = self._store.get(key)
            if uids is not None:
                uids.discard(uid)
                if not uids:
                    del self._store[key]

    def satisfied(self, key: str) -> bool:
        """reference store.go Satisfied: all expected updates observed."""
        with self._lock:
            return not self._store.get(key)

    def forget(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
