"""Parallel host apply/pack plane: a deterministic fork-join pool.

The r18 residue ledger measured the host apply/pack path as the last
serial bottleneck (~1.4k workloads/s on one core while the sharded WAL
sustains 83k append/s).  ``HostPool`` is the worker-pool executor the
driver threads through the post-cycle host work — the cache-rebuild
root fan-out, the dirty-CQ pack walk, the requeue-wakeup pass, and the
per-segment WAL group-commit flushes — partitioned by cohort forest,
the natural no-shared-state key (the same partition the ``("cq",)``
mesh shards by): no two forests share a resource node, an arena row
range, or a quota pool, so partition tasks never race.

Determinism is structural, not lock-based: work is submitted as an
ordered list of independent tasks and results are gathered **in
submission order** (ascending forest id for ``map_partitions``),
whatever order the OS scheduler finishes them in.  WAL ordering is
likewise structural: op seq numbers are stamped serially by the
coordinator in decision order *before* any fan-out, so the seq-merged
sharded replay is byte-identical to the serial path; the pool only
parallelizes the per-segment ``commit`` flush/fsync (which release the
GIL) and registers its workers with the sharded WAL so hash striping
engages.  Decisions are therefore bit-identical to the serial control
— test-enforced in tests/test_parallel_host.py and the SCALE_r19 arms.

``workers <= 1`` (the ``KUEUE_TPU_HOST_WORKERS`` default) never builds
a thread: every entry point degrades to the plain serial loop, so the
serial path stays the zero-surprise control arm.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Optional, Sequence, TypeVar

from ..features import env_int

T = TypeVar("T")

# process-wide counters (kueue_host_pool_* metrics)
POOL_STATS = {
    "host_pool_tasks": 0,          # tasks executed on pool threads
    "host_pool_serial_tasks": 0,   # tasks the pool ran inline (serial
    #                                mode, or batches of one)
    "host_pool_batches": 0,        # fork-join rounds that fanned out
    "host_pool_partitions": 0,     # forest partitions dispatched
    "host_pool_wal_commits": 0,    # per-segment commit flushes fanned out
}


class HostPool:
    """Fork-join executor with deterministic, submission-order gather."""

    def __init__(self, workers: int = 0):
        self.workers = max(0, int(workers))
        self._ex: Optional[ThreadPoolExecutor] = None

    @property
    def active(self) -> bool:
        return self.workers >= 2

    def _executor(self) -> ThreadPoolExecutor:
        if self._ex is None:
            self._ex = ThreadPoolExecutor(
                max_workers=self.workers,
                thread_name_prefix="kueue-host")
        return self._ex

    def run(self, thunks: Sequence[Callable[[], T]]) -> list[T]:
        """Run independent thunks, return results in submission order.

        All thunks run to completion even when one raises (a half-done
        sibling mutating in the background after an early re-raise
        would be a race); the first exception in submission order is
        then re-raised — same observable behavior as the serial loop.
        """
        if not self.active or len(thunks) < 2:
            POOL_STATS["host_pool_serial_tasks"] += len(thunks)
            return [fn() for fn in thunks]
        POOL_STATS["host_pool_batches"] += 1
        POOL_STATS["host_pool_tasks"] += len(thunks)
        futures = [self._executor().submit(fn) for fn in thunks]
        out, first_err = [], None
        for fut in futures:               # submission order, not as_completed
            try:
                out.append(fut.result())
            except BaseException as exc:  # noqa: BLE001 - must drain all
                if first_err is None:
                    first_err = exc
                out.append(None)
        if first_err is not None:
            raise first_err
        return out

    def map_partitions(self, items: Iterable[T],
                       key_fn: Callable[[T], object],
                       fn: Callable[[object, list[T]], object]) -> list:
        """Partition ``items`` by ``key_fn`` (ascending key = forest id
        order), run ``fn(key, partition)`` per partition, and return the
        per-partition results in key order."""
        parts: dict = {}
        for it in items:
            parts.setdefault(key_fn(it), []).append(it)
        keys = sorted(parts, key=repr)
        POOL_STATS["host_pool_partitions"] += len(keys)
        results = self.run([
            (lambda k=k: fn(k, parts[k])) for k in keys])
        return results

    # -- WAL plumbing -------------------------------------------------------

    def attach_wal(self, wal) -> None:
        """Announce the pool's workers to a (possibly sharded) WAL so
        segment striping engages; no-op census on the single-file WAL."""
        for i in range(self.workers if self.active else 0):
            wal.register_appender(f"host-pool-w{i}")

    def detach_wal(self, wal) -> None:
        for i in range(self.workers if self.active else 0):
            wal.unregister_appender(f"host-pool-w{i}")

    def commit_wal(self, wal) -> None:
        """Group-commit ``wal``: per-segment flushes fan out across the
        pool (file write/flush/fsync release the GIL).  Seq stamps were
        assigned serially at append time, so the merged order is already
        fixed — this only parallelizes the I/O."""
        segments = getattr(wal, "_shards", None)
        if not self.active or not segments:
            wal.commit()
            return
        POOL_STATS["host_pool_wal_commits"] += 1
        self.run([sh.commit for sh in segments])

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=True)
            self._ex = None


def host_pool_from_env() -> HostPool:
    """The driver's pool factory, wired to ``KUEUE_TPU_HOST_WORKERS``."""
    return HostPool(env_int("KUEUE_TPU_HOST_WORKERS"))
