"""Remote-cluster transport for MultiKueue (reference
pkg/controller/admissionchecks/multikueue/multikueuecluster.go).

The reference talks to worker clusters through kubeconfig REST clients
with watch re-establishment and exponential retry.  The equivalent here
is a small HTTP API served by each worker process next to its admission
daemon (``cli serve --listen PORT``), and a manager-side client that
marks the cluster lost on connection errors:

    GET    /healthz
    GET    /apis/workloads                       → {"keys": [...]}
    GET    /apis/workloads/<ns>/<name>           → workload manifest
    POST   /apis/workloads                       → create from manifest
    DELETE /apis/workloads/<ns>/<name>
    POST   /apis/workloads/<ns>/<name>/finish    → fake execution hook
           (the perf-runner's condition flip; real jobs finish via the
           worker's own jobframework)

``LocalWorkerClient`` wraps an in-process Driver with the same surface
(the multi-envtest-in-one-process pattern, SURVEY §4.3), so the
MultiKueue controller is transport-agnostic.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .api import manifests as m
from .api.types import Workload
from .chaos import injector as _chaos


class ConnectionLost(Exception):
    """A transport failure: the cluster should be marked lost.

    ``kind`` classifies the failure for the retry policy over real
    sockets:

    - ``refused``: connect() was rejected — nothing reached the worker,
      so the request is trivially safe to retry (the worker is
      restarting behind its supervisor);
    - ``mid_body``: the connection died after the request went out
      (reset, broken pipe, truncated response) — the worker may have
      applied a mutation before the reply was lost, so a mutating retry
      first probes the watch epoch for a restart;
    - ``timeout`` / ``http`` / ``transport``: the undifferentiated rest.
    """

    def __init__(self, msg: str, kind: str = "transport"):
        super().__init__(msg)
        self.kind = kind


def state_digest(driver) -> str:
    """Digest of every workload's full durable status (timestamps
    included), shared by both ends of the distributed parity checks: a
    worker process answers ``/admin/digest`` with it and the
    single-process control computes it locally, so bit-identical state
    compares as equal strings with no JSON round-trip in between."""
    import hashlib
    from .federation.sim import full_state
    blob = repr(sorted(full_state(driver).items())).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class LocalWorkerClient:
    """In-process worker (a Driver in the same process).

    ``ok`` is the fault-injection switch for tests and the federation
    sim: False severs the cluster — health probes fail and every API
    call raises ConnectionLost (a partitioned worker is unreachable for
    mutations too, not just watches — the multi-envtest pattern's
    killed transport)."""

    def __init__(self, driver):
        self.driver = driver
        self.ok = True

    def _check(self, what: str) -> None:
        if not self.ok:
            raise ConnectionLost(f"{what}: worker unreachable")

    def healthy(self) -> bool:
        return self.ok

    def create_workload(self, wl: Workload) -> None:
        self._check("create")
        if wl.key not in self.driver.workloads:
            self.driver.create_workload(wl)

    def get_workload(self, key: str) -> Optional[Workload]:
        self._check("get")
        return self.driver.workloads.get(key)

    def delete_workload(self, key: str) -> None:
        self._check("delete")
        self.driver.delete_workload(key)

    def list_workload_keys(self) -> list[str]:
        self._check("list")
        return list(self.driver.workloads)

    def list_workloads(self) -> dict[str, bool]:
        self._check("list")
        return {k: wl.is_finished
                for k, wl in list(self.driver.workloads.items())}

    def finish_workload(self, key: str, message: str = "finished") -> None:
        self._check("finish")
        self.driver.finish_workload(key, message)

    def watch_events(self, since: int, timeout: float = 0.0):
        """In-process watch: read the driver's append-only event log
        from the resume token (no blocking — the caller polls)."""
        if not self.ok:
            raise ConnectionLost("watch: worker down")
        events = self.driver.events
        batch = [tuple(e) for e in events[since:]]
        return batch, since + len(batch), str(id(self.driver))


class ChaosWorkerClient:
    """Transport fault injection for MultiKueue sync (chaos sites
    ``remote.delay`` / ``remote.duplicate`` / ``remote.partition``),
    wrapping any worker client with the same surface.

    Faults model the reference's unreliable kubeconfig transport:

    - *delay*: the call sleeps ``payload`` seconds first (a slow link);
    - *duplicate*: a mutation is issued twice (an at-least-once retry
      crossing a success) — workers absorb replays because ``create``
      is keyed and ``delete``/``finish`` are idempotent;
    - *partition*: the next ``times`` calls raise ConnectionLost; this
      wrapper heals them with capped exponential-backoff retry
      (multikueuecluster.go:67 retryAfter), so a partition shorter than
      the retry budget is invisible to the controller and a longer one
      surfaces as the usual mark-lost flow.
    """

    #: remote methods that mutate worker state (duplication targets)
    _MUTATORS = ("create_workload", "delete_workload", "finish_workload")

    def __init__(self, inner, injector=None, max_retries: int = 5,
                 backoff_base: float = 0.01, backoff_max: float = 0.5):
        self.inner = inner
        self.injector = injector
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.stats = {"calls": 0, "delays": 0, "duplicates": 0,
                      "partitioned": 0, "retries": 0}

    def _inj(self):
        return self.injector if self.injector is not None else _chaos.ACTIVE

    def _call(self, name: str, *args, **kw):
        import time as _time
        inner_fn = getattr(self.inner, name)
        inj = self._inj()
        self.stats["calls"] += 1
        if inj is None:
            return inner_fn(*args, **kw)
        backoff = self.backoff_base
        last_err = None
        for _ in range(self.max_retries + 1):
            if inj.hit("remote.partition") is not None:
                self.stats["partitioned"] += 1
                self.stats["retries"] += 1
                last_err = ConnectionLost(f"{name}: injected partition")
                _time.sleep(backoff)
                backoff = min(backoff * 2.0, self.backoff_max)
                continue
            f = inj.hit("remote.delay")
            if f is not None:
                self.stats["delays"] += 1
                _time.sleep(float(f.payload or 0.01))
            out = inner_fn(*args, **kw)
            if (name in self._MUTATORS
                    and inj.hit("remote.duplicate") is not None):
                self.stats["duplicates"] += 1
                inner_fn(*args, **kw)
            return out
        raise last_err or ConnectionLost(f"{name}: retries exhausted")

    def healthy(self) -> bool:
        try:
            return bool(self._call("healthy"))
        except ConnectionLost:
            return False

    def create_workload(self, wl: Workload) -> None:
        self._call("create_workload", wl)

    def get_workload(self, key: str) -> Optional[Workload]:
        return self._call("get_workload", key)

    def delete_workload(self, key: str) -> None:
        self._call("delete_workload", key)

    def list_workload_keys(self) -> list[str]:
        return self._call("list_workload_keys")

    def list_workloads(self) -> dict[str, bool]:
        return self._call("list_workloads")

    def finish_workload(self, key: str, message: str = "finished") -> None:
        self._call("finish_workload", key, message)

    def watch_events(self, since: int, timeout: float = 0.0):
        # no retry loop here: the WatchLoop owns watch backoff and its
        # lost/reconnected markers must see the raw failure
        inj = self._inj()
        if inj is not None and inj.hit("remote.partition") is not None:
            self.stats["partitioned"] += 1
            raise ConnectionLost("watch: injected partition")
        return self.inner.watch_events(since, timeout=timeout)


class WatchLoop:
    """Manager-side per-cluster watch thread (reference
    multikueuecluster.go:187-226 watch re-establishment).

    Long-polls the worker's event stream and pushes (kind, key, note)
    tuples into a thread-safe queue the controller drains on reconcile;
    connection loss pushes a ``("__lost__", ...)`` marker, then the loop
    keeps retrying with exponential backoff and pushes
    ``("__reconnected__", ...)`` when the stream is back — resuming from
    the last seen token, so every missed event is replayed.

    ``pump()`` is one poll-and-push step: the watch thread calls it in
    a loop, and deterministic harnesses (the federation sim, the
    delivery-order tests) call it directly with no thread in play so
    event delivery happens at controlled points."""

    def __init__(self, client, poll_timeout: float = 10.0):
        import queue as _queue
        self.client = client
        self.poll_timeout = poll_timeout
        self.events: "_queue.Queue" = _queue.Queue()
        self.since = 0
        self._epoch = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._was_lost = False
        self._backoff = 0.2

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.pump(wait=self._stop.wait)

    def pump(self, wait=None) -> int:
        """One poll-and-push iteration; returns the number of workload
        events pushed.  ``wait`` is the pacing/backoff sleep — the watch
        thread passes its stop-aware wait, direct callers leave it None
        (no sleeping, the harness owns time)."""
        if wait is None:
            wait = lambda _s: None
        try:
            batch, nxt, epoch = self._poll()
        except Exception as e:
            # ANY failure is a connection loss (a dead watch thread
            # would silently stop all sync for the cluster)
            if not self._was_lost:
                self._was_lost = True
                self.events.put(("__lost__", "", str(e)))
            wait(self._backoff)
            self._backoff = min(self._backoff * 2.0, 30.0)
            return 0
        if (epoch is not None and self._epoch is not None
                and epoch != self._epoch):
            # the worker restarted with a fresh event log: the resume
            # token is meaningless — replay from 0 and tell the
            # controller to resync this cluster's assignments
            self._epoch = epoch
            self.since = 0
            self.events.put(("__resync__", "", ""))
            return 0
        if epoch is not None:
            self._epoch = epoch
        if self._was_lost:
            self._was_lost = False
            self.events.put(("__reconnected__", "", ""))
        self._backoff = 0.2
        inj = _chaos.ACTIVE
        if (inj is not None and batch
                and inj.hit("remote.duplicate_event") is not None):
            # at-least-once delivery: push the batch but do NOT advance
            # the resume token, so the next poll re-delivers all of it
            # (plus anything newer) — the controller's sync must absorb
            # the replay
            pass
        else:
            self.since = nxt
        for ev in batch:
            self.events.put(tuple(ev))
        if not batch:
            # blocking clients already waited out the long poll; the
            # in-process client returns instantly — pace either way
            wait(0.05)
        return len(batch)

    def _poll(self):
        out = self.client.watch_events(self.since,
                                       timeout=self.poll_timeout)
        if len(out) == 3:
            return out
        batch, nxt = out
        return batch, nxt, None


class HttpWorkerClient:
    """Manager-side remote client (multikueuecluster.go remoteClient).

    Transient transport failures are retried in place with jittered
    exponential backoff under a total-deadline budget: each request
    gets up to ``retries`` re-attempts, the i-th backoff is
    ``backoff_base·2^i`` stretched by a deterministic per-(path,
    attempt) jitter (0.5×–1.5×, crc32 not random so retry storms
    replay identically under test), and the whole request — attempts
    plus sleeps — must fit inside ``deadline_s``.  Retrying mutations
    is safe because the worker API is idempotent: create is keyed,
    delete/finish are no-ops when already applied.  Only once the
    budget is spent does ConnectionLost surface; the MultiKueue
    controller then marks the cluster inactive and retries with its
    own exponential backoff (multikueuecluster.go:67 retryAfter).
    Watch polls are never retried here — the WatchLoop owns watch
    backoff and must see the raw failure.

    ``KUEUE_TPU_REMOTE_RETRIES`` / ``KUEUE_TPU_REMOTE_DEADLINE_S``
    override the defaults (see ``features.ENV_FLAGS``)."""

    def __init__(self, base_url: str, timeout: float = 5.0,
                 retries: Optional[int] = None,
                 backoff_base: float = 0.05, backoff_max: float = 1.0,
                 deadline_s: Optional[float] = None):
        from .features import env_int
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = (env_int("KUEUE_TPU_REMOTE_RETRIES")
                        if retries is None else retries)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.deadline_s = (float(env_int("KUEUE_TPU_REMOTE_DEADLINE_S"))
                           if deadline_s is None else deadline_s)
        self.stats = {"requests": 0, "retries": 0, "deadline_exhausted": 0,
                      "refused_retries": 0, "midbody_retries": 0,
                      "epoch_resyncs": 0}
        # last watch epoch seen (from /healthz or the watch stream);
        # the mid-body retry path probes against it to detect a worker
        # restart hiding behind a half-delivered response
        self._epoch: Optional[str] = None

    def _note_epoch(self, epoch) -> None:
        if not epoch:
            return
        if self._epoch is not None and epoch != self._epoch:
            self.stats["epoch_resyncs"] += 1
        self._epoch = epoch

    def _probe_epoch(self):
        """One unretried health probe for the current watch epoch;
        None when the worker is (still) unreachable."""
        try:
            out = self._request_once("GET", "/healthz")
        except ConnectionLost:
            return None
        return (out or {}).get("epoch")

    @staticmethod
    def _jitter(path: str, attempt: int) -> float:
        import zlib
        return (zlib.crc32(f"{path}#{attempt}".encode()) % 1000) / 1000.0

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 timeout_override: Optional[float] = None,
                 retries: Optional[int] = None, mutating: bool = False):
        import time as _time
        budget = self.retries if retries is None else retries
        deadline = _time.monotonic() + self.deadline_s
        attempt = 0
        while True:
            try:
                return self._request_once(method, path, body,
                                          timeout_override)
            except ConnectionLost as e:
                if attempt >= budget:
                    raise
                backoff = min(self.backoff_base * (2 ** attempt),
                              self.backoff_max)
                backoff *= 0.5 + self._jitter(path, attempt)
                if _time.monotonic() + backoff >= deadline:
                    self.stats["deadline_exhausted"] += 1
                    raise
                self.stats["retries"] += 1
                if e.kind == "refused":
                    # nothing reached the worker: a plain retry within
                    # the deadline rides out a restarting process
                    self.stats["refused_retries"] += 1
                elif e.kind == "mid_body":
                    self.stats["midbody_retries"] += 1
                    if mutating:
                        # the worker may have applied the mutation and
                        # died before answering; if it restarted, the
                        # epoch moved — noting it here bumps the resync
                        # counter so the watch replays from zero.  The
                        # retry itself stays safe either way: the worker
                        # API is idempotent (create keyed, delete/finish
                        # no-ops when already applied)
                        self._note_epoch(self._probe_epoch())
                _time.sleep(backoff)
                attempt += 1

    def _request_once(self, method: str, path: str,
                      body: Optional[dict] = None,
                      timeout_override: Optional[float] = None):
        import urllib.error
        import urllib.request
        self.stats["requests"] += 1
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_override or self.timeout) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else None
        except urllib.error.HTTPError as e:
            if e.code < 500:
                # application-level error (404 missing, 400 bad
                # manifest): the cluster itself is healthy — don't flap
                # it lost (multikueuecluster.go only reconnects on
                # transport failures)
                return None
            raise ConnectionLost(f"{method} {path}: HTTP {e.code}",
                                 kind="http") from e
        except ConnectionRefusedError as e:
            raise ConnectionLost(f"{method} {path}: {e}",
                                 kind="refused") from e
        except (ConnectionResetError, BrokenPipeError) as e:
            raise ConnectionLost(f"{method} {path}: {e}",
                                 kind="mid_body") from e
        except OSError as e:
            # urllib wraps connect-phase failures in URLError(reason);
            # unwrap so refused-vs-reset keeps its meaning there too
            import socket
            reason = getattr(e, "reason", None)
            if isinstance(reason, ConnectionRefusedError):
                kind = "refused"
            elif isinstance(reason, (ConnectionResetError,
                                     BrokenPipeError)):
                kind = "mid_body"
            elif isinstance(e, socket.timeout) or isinstance(
                    reason, socket.timeout):
                kind = "timeout"
            else:
                kind = "transport"
            raise ConnectionLost(f"{method} {path}: {e}", kind=kind) from e
        except Exception as e:
            # http.client.IncompleteRead/BadStatusLine etc.: a worker
            # dying mid-response is a transport failure, not a crash —
            # and since the request went out, a possible partial apply
            import http.client
            if isinstance(e, http.client.HTTPException):
                raise ConnectionLost(f"{method} {path}: {e}",
                                     kind="mid_body") from e
            raise

    def healthy(self) -> bool:
        # no retries: this is the half-open probe — the controller's
        # reconnect backoff owns the retry cadence
        try:
            out = self._request("GET", "/healthz", retries=0)
        except ConnectionLost:
            return False
        if isinstance(out, dict):
            self._note_epoch(out.get("epoch"))
        return out is not None

    def create_workload(self, wl: Workload) -> None:
        self._request("POST", "/apis/workloads", m.to_manifest(wl),
                      mutating=True)

    def get_workload(self, key: str) -> Optional[Workload]:
        ns, _, name = key.partition("/")
        doc = self._request("GET", f"/apis/workloads/{ns}/{name}")
        return m.from_manifest(doc) if doc else None

    def delete_workload(self, key: str) -> None:
        ns, _, name = key.partition("/")
        self._request("DELETE", f"/apis/workloads/{ns}/{name}",
                      mutating=True)

    def list_workload_keys(self) -> list[str]:
        out = self._request("GET", "/apis/workloads")
        return list(out.get("keys", [])) if out else []

    def list_workloads(self) -> dict[str, bool]:
        """{key: is_finished} in ONE round trip (GC reads this)."""
        out = self._request("GET", "/apis/workloads")
        if not out:
            return {}
        if "finished" in out:
            return {k: bool(v) for k, v in out["finished"].items()}
        return {k: False for k in out.get("keys", [])}

    def finish_workload(self, key: str, message: str = "finished") -> None:
        """Test/executor hook: flip the remote workload finished."""
        ns, _, name = key.partition("/")
        self._request("POST", f"/apis/workloads/{ns}/{name}/finish",
                      {"message": message}, mutating=True)

    # -- lockstep-harness admin endpoints (WorkerServer admin=True) --

    def set_clock(self, t: float) -> None:
        """Pin the worker's virtual clock (idempotent: same t, same
        result — safe under the mutating retry path)."""
        self._request("POST", "/admin/clock", {"t": t}, mutating=True)

    def admin_step(self) -> Optional[dict]:
        """One scheduling cycle on the worker.  Safe to retry within a
        lockstep barrier: re-running with unchanged state admits
        nothing further."""
        return self._request("POST", "/admin/step", {}, mutating=True)

    def admin_status(self) -> dict:
        out = self._request("GET", "/admin/status") or {}
        return out.get("status", {})

    def admin_digest(self) -> Optional[str]:
        out = self._request("GET", "/admin/digest") or {}
        return out.get("digest")

    def watch_events(self, since: int, timeout: float = 20.0):
        """Long-poll the worker's event stream from resume token
        ``since``.  Returns (events, next_token); blocks worker-side
        until events exist or the poll times out."""
        out = self._request(
            "GET", f"/apis/watch?since={since}&timeout={timeout}",
            timeout_override=timeout + self.timeout, retries=0)
        if out is None:
            return [], since, None
        self._note_epoch(out.get("epoch"))
        return ([tuple(e) for e in out.get("events", [])],
                int(out.get("next", since)), out.get("epoch"))


class DrainingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer hardened for restart-under-test.

    - ``allow_reuse_address``: a supervisor restarting a killed child
      on the *same* bound port must not trip TIME_WAIT, so client
      base_urls survive the restart (bound-port handoff);
    - in-flight handler census: ``finish_request`` is bracketed by a
      counter so :meth:`drain` can wait for handlers already running to
      complete before the listening socket closes — graceful shutdown
      finishes in-flight work instead of resetting it;
    - ``draining`` flips the ``/readyz`` probe to 503 (and breaks the
      watch long-poll) so pollers stop routing new work mid-drain.
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        self.draining = False

    def finish_request(self, request, client_address):
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()
        try:
            super().finish_request(request, client_address)
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.set()

    def drain(self, timeout: float = 5.0) -> bool:
        """Stop advertising readiness and wait for in-flight handlers;
        True when the server went idle inside the timeout."""
        self.draining = True
        return self._idle.wait(timeout)


class _Handler(BaseHTTPRequestHandler):
    driver = None  # bound by WorkerServer

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, payload=None) -> None:
        body = b"" if payload is None else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _wl_key(self) -> Optional[str]:
        parts = self.path.strip("/").split("/")
        # apis/workloads/<ns>/<name>[/finish]
        if len(parts) >= 4 and parts[0] == "apis" and parts[1] == "workloads":
            return f"{parts[2]}/{parts[3]}"
        return None

    def do_GET(self):
        if self.path == "/healthz":
            # liveness + the watch epoch, so one probe tells a client
            # both "alive" and "did it restart since I last looked"
            self._send(200, {
                "ok": True,
                "epoch": getattr(self.server, "epoch", None),
                "ready": not getattr(self.server, "draining", False)})
            return
        if self.path == "/readyz":
            # readiness: the supervisor polls this instead of sleeping
            if getattr(self.server, "draining", False):
                self._send(503, {"ready": False})
            else:
                self._send(200, {"ready": True})
            return
        if self.path.startswith("/admin/"):
            self._admin_get()
            return
        if self.path.startswith("/apis/watch"):
            # long-poll watch stream (reference multikueuecluster.go:187
            # per-cluster watch channels): the driver's append-only event
            # log is the resume token space — ?since=N returns events[N:]
            # as soon as any exist (or an empty batch on timeout), so a
            # reconnecting manager replays everything it missed
            from urllib.parse import parse_qs, urlparse
            q = parse_qs(urlparse(self.path).query)
            since = int(q.get("since", ["0"])[0])
            timeout = min(30.0, float(q.get("timeout", ["20"])[0]))
            import time as _time
            deadline = _time.monotonic() + timeout
            events = self.driver.events
            while (len(events) <= since and _time.monotonic() < deadline
                   and not getattr(self.server, "draining", False)):
                _time.sleep(0.02)
            batch = [list(e) for e in events[since:]]
            self._send(200, {"events": batch,
                             "next": since + len(batch),
                             "epoch": self.server.epoch})
            return
        if self.path.rstrip("/") == "/apis/workloads":
            items = list(self.driver.workloads.items())
            self._send(200, {"keys": [k for k, _ in items],
                             "finished": {k: wl.is_finished
                                          for k, wl in items}})
            return
        key = self._wl_key()
        if key is not None:
            wl = self.driver.workloads.get(key)
            if wl is None:
                self._send(404)
            else:
                self._send(200, m.to_manifest(wl))
            return
        self._send(404)

    def _admin_get(self):
        """Lockstep-harness read endpoints (``admin=True`` servers only):
        the distributed soak's parent process reads worker state through
        these instead of reaching into another process's memory."""
        if not getattr(self.server, "admin", False):
            self._send(404)
            return
        if self.path == "/admin/status":
            self._send(200, {"status": {
                k: [wl.has_quota_reservation, wl.is_finished]
                for k, wl in list(self.driver.workloads.items())}})
            return
        if self.path == "/admin/digest":
            self._send(200, {"digest": state_digest(self.driver),
                             "n": len(self.driver.workloads)})
            return
        self._send(404)

    def _admin_post(self, body):
        """Lockstep-harness mutation endpoints: the parent advances a
        child's virtual clock and runs its admission cycles at step
        barriers, which is what keeps N processes bit-deterministic."""
        if not getattr(self.server, "admin", False):
            self._send(404)
            return
        if self.path == "/admin/step":
            with self.server.step_lock:
                stats = self.driver.schedule_once()
            self._send(200, {"admitted": sorted(stats.admitted)})
            return
        if self.path == "/admin/clock":
            clk = getattr(self.server, "clock", None)
            if clk is None:
                self._send(404)
                return
            with self.server.step_lock:
                clk.t = float(body["t"])
            self._send(200, {"t": clk.t})
            return
        self._send(404)

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(length)) if length else {}
        if self.path.startswith("/admin/"):
            self._admin_post(body)
            return
        if self.path.endswith("/finish"):
            key = self._wl_key()
            if key is None or key not in self.driver.workloads:
                self._send(404)
                return
            self.driver.finish_workload(
                key, body.get("message", "finished"))
            self._send(200, {"ok": True})
            return
        if self.path.rstrip("/") == "/apis/workloads":
            try:
                wl = m.from_manifest(body)
            except Exception:
                self._send(400)
                return
            if wl.key not in self.driver.workloads:
                self.driver.create_workload(wl)
                jr = getattr(self.server, "journal", None)
                if jr is not None:
                    # manifest durable before the ack: a SIGKILLed
                    # worker rebuilds its initial payloads from here
                    jr.put(wl.key, body)
            self._send(201, {"ok": True})
            return
        self._send(404)

    def do_DELETE(self):
        key = self._wl_key()
        if key is None:
            self._send(404)
            return
        self.driver.delete_workload(key)
        jr = getattr(self.server, "journal", None)
        if jr is not None:
            jr.delete(key)
        self._send(200, {"ok": True})


class WorkerServer:
    """The worker-side HTTP API, served next to the admission daemon.

    ``journal`` (a ``ManifestJournal``) makes creates/deletes durable
    before their ack.  ``admin=True`` exposes the lockstep harness
    endpoints (``/admin/step``, ``/admin/clock``, ``/admin/status``,
    ``/admin/digest``) the distributed soak drives child processes
    with; ``clock`` is the mutable virtual clock ``/admin/clock``
    sets.  ``epoch`` pins the watch-log epoch (tests); by default a
    restarted process serves a fresh one, which is what tells managers
    their resume tokens died with the old process."""

    def __init__(self, driver, port: int = 0, host: str = "127.0.0.1",
                 journal=None, admin: bool = False, clock=None,
                 epoch: Optional[str] = None):
        import uuid
        handler = type("BoundHandler", (_Handler,), {"driver": driver})
        self.httpd = DrainingHTTPServer((host, port), handler)
        # watch-log epoch: a restarted worker process serves a fresh
        # (shorter) event log, so resume tokens from the old epoch must
        # trigger a replay-from-zero + resync instead of silent skips
        self.httpd.epoch = epoch or uuid.uuid4().hex
        self.httpd.journal = journal
        self.httpd.admin = admin
        self.httpd.clock = clock
        self.httpd.step_lock = threading.Lock()
        self.driver = driver
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self, graceful: bool = True) -> None:
        if graceful:
            # finish in-flight handlers before the socket closes; the
            # draining flag also breaks pending watch long-polls
            self.httpd.drain(timeout=5.0)
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
