"""Leader election for the file-store deployment.

The reference gates the scheduler behind Kubernetes lease-based leader
election (pkg/config/config.go:97-110; scheduler.go:150-154 runs only
when elected).  The file-store equivalent is an exclusive ``flock`` on
``<state-dir>/leader.lock``: exactly one ``cli serve`` daemon per store
is active; others wait until the leader exits and then take over.
"""

from __future__ import annotations

import os
import threading
from typing import Optional


class FileLease:
    """Exclusive advisory lock on the store's leader.lock file."""

    def __init__(self, state_dir: str):
        self.path = os.path.join(state_dir, "leader.lock")
        self._fd: Optional[int] = None

    @property
    def held(self) -> bool:
        return self._fd is not None

    def try_acquire(self) -> bool:
        """Non-blocking acquisition attempt."""
        import fcntl
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._fd = fd
        return True

    def acquire(self, stop: Optional[threading.Event] = None,
                poll_interval: float = 0.1) -> bool:
        """Block until leadership is acquired or ``stop`` is set."""
        while True:
            if self.try_acquire():
                return True
            if stop is not None:
                if stop.wait(poll_interval):
                    return False
            else:
                import time
                time.sleep(poll_interval)

    def release(self) -> None:
        if self._fd is not None:
            import fcntl
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None
