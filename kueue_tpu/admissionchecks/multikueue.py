"""MultiKueue: multi-cluster dispatch (reference
pkg/controller/admissionchecks/multikueue, KEP 693).

Worker clusters are full in-process Drivers (the reference's multikueue
integration tests run multiple envtest apiservers in one process the same
way — SURVEY §4.3).  The dispatch protocol mirrors
multikueue/workload.go:

1. a workload reserves quota on the manager; its CQ carries a MultiKueue
   AdmissionCheck;
2. the controller mirrors the workload to every cluster in the check's
   MultiKueueConfig (nomination);
3. the first worker to reserve quota wins; mirrors elsewhere are deleted;
4. the check flips Ready; the local job stays suspended (managedBy);
5. remote status (admitted / finished) is copied back; a lost worker
   ejects the assignment after ``worker_lost_timeout`` and the check
   returns to Pending for re-dispatch (multikueuecluster.go:255 GC +
   workload.go ejection).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.types import (
    AdmissionCheckState,
    MultiKueueConfig,
    Workload,
)

MULTIKUEUE_CONTROLLER_NAME = "kueue.x-k8s.io/multikueue"


RETRY_BASE_S = 1.0
RETRY_MAX_S = 60.0


@dataclass
class WorkerCluster:
    """A remote cluster behind a connection that can drop (reference
    multikueuecluster.go remoteClient).

    ``driver`` — an in-process Driver (the multi-envtest pattern) — or
    ``client`` — any transport client (kueue_tpu.remote.HttpWorkerClient
    for a real process/socket boundary).  Reconnection follows the
    reference's exponential retry (multikueuecluster.go:67 retryAfter,
    :134-226 watch re-establishment): a failed operation marks the
    cluster lost; health probes retry with doubling backoff."""
    name: str
    driver: object = None             # in-process Driver (optional)
    client: object = None             # transport client
    active: bool = True
    lost_since: Optional[float] = None
    next_retry: float = 0.0
    retry_backoff: float = RETRY_BASE_S
    watch: object = None              # remote.WatchLoop when streaming

    def __post_init__(self):
        if self.client is None and self.driver is not None:
            from ..remote import LocalWorkerClient
            self.client = LocalWorkerClient(self.driver)

    def mark_lost(self, now: float) -> None:
        if self.active:
            self.active = False
            self.lost_since = now
            self.retry_backoff = RETRY_BASE_S
            self.next_retry = now + self.retry_backoff

    def try_reconnect(self, now: float) -> bool:
        """Health-probe with exponential backoff; True on reconnect."""
        if self.active or now < self.next_retry:
            return False
        if self.client.healthy():
            self.reconnect()
            return True
        self.retry_backoff = min(self.retry_backoff * 2.0, RETRY_MAX_S)
        self.next_retry = now + self.retry_backoff
        return False

    def reconnect(self) -> None:
        self.active = True
        self.lost_since = None
        self.retry_backoff = RETRY_BASE_S


@dataclass
class _Assignment:
    cluster: str
    nominated: list[str] = field(default_factory=list)


class MultiKueueController:
    """reference multikueue/workload.go wlReconciler."""

    def __init__(self, manager_driver, check_name: str,
                 config: MultiKueueConfig,
                 clusters: dict[str, WorkerCluster],
                 origin: str = "multikueue",
                 worker_lost_timeout: float = 300.0,
                 manager_jobs=None,
                 worker_jobs: dict[str, object] | None = None):
        self.manager = manager_driver
        self.check_name = check_name
        self.config = config
        self.clusters = clusters
        self.origin = origin
        self.worker_lost_timeout = worker_lost_timeout
        self.assignments: dict[str, _Assignment] = {}
        # mirrors that must be deleted on a currently-unreachable worker:
        # flushed when it reconnects (a lost delete would otherwise
        # orphan worker quota forever)
        self.pending_deletes: dict[str, set[str]] = {}
        # optional job-level dispatch (reference MultiKueueAdapter.SyncJob,
        # jobframework/interface.go:227): the manager's JobManager plus one
        # per worker cluster; jobs are mirrored instead of bare workloads
        self.manager_jobs = manager_jobs
        self.worker_jobs = worker_jobs or {}

    # ------------------------------------------------------------------

    def _relevant(self, wl: Workload) -> bool:
        return (self.check_name in wl.admission_check_states
                and wl.has_quota_reservation and not wl.is_finished)

    def _mirror(self, wl: Workload) -> Workload:
        remote = Workload(
            name=wl.name, namespace=wl.namespace, queue_name=wl.queue_name,
            pod_sets=[__import__("copy").deepcopy(ps) for ps in wl.pod_sets],
            priority=wl.priority, creation_time=wl.creation_time)
        return remote

    def _worker_op(self, cluster: WorkerCluster, fn, *args, default=None):
        """Run one transport operation; a connection failure marks the
        cluster lost (multikueuecluster.go:134 watch loss)."""
        from ..remote import ConnectionLost
        try:
            return fn(*args)
        except ConnectionLost:
            cluster.mark_lost(self.manager.clock())
            return default

    def start_watches(self, poll_timeout: float = 10.0) -> None:
        """Per-cluster watch streams (reference multikueuecluster.go:187
        watch channels): worker events are pushed to the controller
        instead of polled one GET per assigned workload per reconcile.
        Re-establishment + event replay are handled by the WatchLoop."""
        from ..remote import WatchLoop
        for cluster in self.clusters.values():
            if cluster.watch is None and cluster.client is not None:
                cluster.watch = WatchLoop(cluster.client,
                                          poll_timeout=poll_timeout)
                cluster.watch.start()

    def stop_watches(self) -> None:
        for cluster in self.clusters.values():
            if cluster.watch is not None:
                cluster.watch.stop()
                cluster.watch = None

    def _drain_watches(self, now: float) -> list[tuple[str, str]]:
        """Pull pending events from every watch queue.  Connection
        markers drive the cluster's lost/reconnected state; workload
        events return as (cluster, key) for targeted syncs."""
        import queue as _queue
        touched: list[tuple[str, str]] = []
        for cname, cluster in self.clusters.items():
            w = cluster.watch
            if w is None:
                continue
            while True:
                try:
                    kind, key, _note = w.events.get_nowait()
                except _queue.Empty:
                    break
                if kind == "__lost__":
                    cluster.mark_lost(now)
                elif kind == "__reconnected__":
                    was_lost = not cluster.active
                    cluster.reconnect()
                    if was_lost:
                        self._flush_pending_deletes(cname)
                elif kind == "__resync__":
                    # fresh worker epoch: the remote may have lost every
                    # mirror — resync everything tied to this cluster
                    for akey, asg in self.assignments.items():
                        if asg.cluster == cname or cname in asg.nominated:
                            touched.append((cname, akey))
                elif kind in ("QuotaReserved", "Finished", "Deleted",
                              "Preempted"):
                    touched.append((cname, key))
        return touched

    def reconcile(self) -> None:
        now = self.manager.clock()
        touched = self._drain_watches(now)
        # connection health: the watch loop is authoritative when
        # streaming; otherwise retry lost workers with exponential
        # backoff.  Either way, eject assignments once a worker stays
        # lost past the timeout.
        for name, cluster in self.clusters.items():
            # health probes run even with a watch attached: a transient
            # _worker_op failure can mark the cluster lost while the
            # watch stream (a separate connection) stays healthy and so
            # never emits a __reconnected__ marker
            if not cluster.active and cluster.try_reconnect(now):
                self._flush_pending_deletes(name)
            if (not cluster.active and cluster.lost_since is not None
                    and now - cluster.lost_since > self.worker_lost_timeout):
                self._eject_cluster(name)

        # with watches, remote state arrives as events: the per-workload
        # GET polling loop runs only for watchless transports (and for
        # job-level dispatch, whose execution-status copy-back has no
        # event source)
        watching = all(c.watch is not None
                       for c in self.clusters.values()) and self.clusters
        for key, wl in list(self.manager.workloads.items()):
            if not self._relevant(wl):
                if key in self.assignments:
                    self._cleanup(key)
                continue
            state = wl.admission_check_states[self.check_name]
            asg = self.assignments.get(key)
            if asg is None:
                self._nominate(key, wl)
            elif not watching or self.manager_jobs is not None:
                self._sync(key, wl, state.state, asg)

        if watching and self.manager_jobs is None:
            # targeted event-driven syncs (deduped; when the polling
            # loop ran above it already covered every assignment)
            for key in dict.fromkeys(k for _c, k in touched):
                asg = self.assignments.get(key)
                wl = self.manager.workloads.get(key)
                if asg is None or wl is None or not self._relevant(wl):
                    continue
                state = wl.admission_check_states[self.check_name]
                self._sync(key, wl, state.state, asg)

    # ------------------------------------------------------------------

    def _owner_job(self, wl: Workload):
        """The manager-side job owning this workload, if job-level
        dispatch is attached."""
        if self.manager_jobs is None:
            return None
        for job in self.manager_jobs.jobs.values():
            wl_key = self.manager_jobs.reconciler.workload_key_for(job)
            if wl_key == wl.key:
                return job
        return None

    def _sync_job(self, cname: str, job) -> None:
        """Mirror the job object to a worker cluster (adapter SyncJob):
        the worker's own jobframework creates and manages the workload."""
        import copy
        worker_jm = self.worker_jobs.get(cname)
        if worker_jm is None:
            return
        if job.key in worker_jm.jobs:
            return
        clone = copy.deepcopy(job)
        if hasattr(clone, "set_managed_by"):
            clone.set_managed_by(None)   # the worker runs it for real
        worker_jm.upsert(clone)

    def _nominate(self, key: str, wl: Workload) -> None:
        """Create mirrors on every configured active cluster
        (workload.go nominateAndSynchronizeWorkers)."""
        job = self._owner_job(wl)
        nominated = []
        for cname in self.config.clusters:
            cluster = self.clusters.get(cname)
            if cluster is None or not cluster.active:
                continue
            if job is not None and cname in self.worker_jobs:
                self._sync_job(cname, job)
            else:
                self._worker_op(cluster, cluster.client.create_workload,
                                self._mirror(wl))
                if not cluster.active:
                    # the create may have landed before the connection
                    # dropped: clean it up when the worker comes back
                    self.pending_deletes.setdefault(cname, set()).add(
                        wl.key)
                    continue
            nominated.append(cname)
        if not nominated:
            return
        self.assignments[key] = _Assignment(cluster="", nominated=nominated)

    def _sync(self, key: str, wl: Workload, state: AdmissionCheckState,
              asg: _Assignment) -> None:
        # give each nominated worker a scheduling chance, then pick the
        # first with quota reserved (workload.go: first to reserve wins)
        if not asg.cluster:
            for cname in asg.nominated:
                cluster = self.clusters.get(cname)
                if cluster is None or not cluster.active:
                    continue
                remote = self._worker_op(cluster,
                                         cluster.client.get_workload, key)
                if remote is not None and remote.has_quota_reservation:
                    asg.cluster = cname
                    break
            if asg.cluster:
                # delete the losing mirrors
                for cname in asg.nominated:
                    if cname != asg.cluster:
                        self._delete_remote(cname, key)
                asg.nominated = [asg.cluster]
                self.manager.set_admission_check_state(
                    key, self.check_name, AdmissionCheckState.READY,
                    f'The workload got reservation on "{asg.cluster}"')
            return

        cluster = self.clusters.get(asg.cluster)
        if cluster is None or not cluster.active:
            return  # lost; ejection handled by the timeout scan
        remote = self._worker_op(cluster, cluster.client.get_workload, key)
        if not cluster.active:
            return  # connection dropped mid-sync
        if remote is None:
            # remote deleted under us → re-dispatch
            self._reset(key)
            return
        # job-level dispatch: copy the remote job's execution status back
        # to the (suspended) manager job (reference workload.go copy-back)
        job = self._owner_job(wl)
        if job is not None:
            worker_jm = self.worker_jobs.get(asg.cluster)
            if worker_jm is not None:
                worker_job = worker_jm.jobs.get(job.key)
                if worker_job is not None:
                    job.sync_status_from(worker_job)
        if remote.is_finished:
            msg = remote.conditions.get("Finished")
            self.manager.finish_workload(
                key, msg.message if msg else "Finished on worker")
            self._cleanup(key)

    # ------------------------------------------------------------------

    def _delete_remote(self, cname: str, key: str) -> None:
        cluster = self.clusters.get(cname)
        if cluster is None:
            return
        if not cluster.active:
            # unreachable: remember the delete for the reconnect flush
            self.pending_deletes.setdefault(cname, set()).add(key)
            return
        worker_jm = self.worker_jobs.get(cname)
        if worker_jm is not None:
            # job-level mirrors: delete the worker job (cascades to its
            # workload via the worker JobManager)
            for jkey, job in list(worker_jm.jobs.items()):
                if worker_jm.reconciler.workload_key_for(job) == key:
                    worker_jm.delete(jkey)
        self._worker_op(cluster, cluster.client.delete_workload, key)
        if not cluster.active:
            self.pending_deletes.setdefault(cname, set()).add(key)

    def _flush_pending_deletes(self, cname: str) -> None:
        """A reconnected worker may hold mirrors whose deletes were lost
        while it was unreachable — its daemon could even have admitted
        them; delete them before anything else dispatches."""
        cluster = self.clusters.get(cname)
        pending = self.pending_deletes.get(cname)
        if cluster is None or not pending:
            return
        for key in list(pending):
            # keep the mirror if it is (again) this worker's assignment
            asg = self.assignments.get(key)
            if asg is not None and asg.cluster == cname:
                pending.discard(key)
                continue
            self._worker_op(cluster, cluster.client.delete_workload, key)
            if not cluster.active:
                return   # dropped again; retry on the next reconnect
            pending.discard(key)
        if not pending:
            self.pending_deletes.pop(cname, None)

    def _cleanup(self, key: str) -> None:
        asg = self.assignments.pop(key, None)
        if asg is None:
            return
        for cname in asg.nominated:
            wl = self.manager.workloads.get(key)
            if wl is None or not wl.is_finished:
                self._delete_remote(cname, key)

    def _reset(self, key: str) -> None:
        self.assignments.pop(key, None)
        self.manager.set_admission_check_state(
            key, self.check_name, AdmissionCheckState.RETRY,
            "Lost the remote reservation; will re-dispatch")

    def _eject_cluster(self, cname: str) -> None:
        """Worker lost beyond timeout: requeue everything assigned to it
        (workload.go workerLostTimeout ejection)."""
        for key, asg in list(self.assignments.items()):
            if asg.cluster == cname or cname in asg.nominated:
                self._reset(key)

    # ------------------------------------------------------------------

    def run_gc(self) -> None:
        """Remote GC (multikueuecluster.go:255 runGC): delete worker
        mirrors whose manager workload is gone.  One list round trip per
        cluster ({key: finished}); stops on connection loss."""
        managed = set(self.manager.workloads)
        for cluster in self.clusters.values():
            if not cluster.active:
                continue
            listing = self._worker_op(cluster,
                                      cluster.client.list_workloads,
                                      default={})
            for key, finished in listing.items():
                if not cluster.active:
                    break   # lost mid-GC: stop issuing doomed requests
                if key not in managed and not finished:
                    self._worker_op(cluster,
                                    cluster.client.delete_workload, key)
