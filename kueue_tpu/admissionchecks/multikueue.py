"""MultiKueue: multi-cluster dispatch (reference
pkg/controller/admissionchecks/multikueue, KEP 693).

Worker clusters are full in-process Drivers (the reference's multikueue
integration tests run multiple envtest apiservers in one process the same
way — SURVEY §4.3).  The dispatch protocol mirrors
multikueue/workload.go:

1. a workload reserves quota on the manager; its CQ carries a MultiKueue
   AdmissionCheck;
2. the controller mirrors the workload to every cluster in the check's
   MultiKueueConfig (nomination);
3. the first worker to reserve quota wins; mirrors elsewhere are deleted;
4. the check flips Ready; the local job stays suspended (managedBy);
5. remote status (admitted / finished) is copied back; a lost worker
   ejects the assignment after ``worker_lost_timeout`` and the check
   returns to Pending for re-dispatch (multikueuecluster.go:255 GC +
   workload.go ejection).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.types import (
    AdmissionCheckState,
    MultiKueueConfig,
    Workload,
)

MULTIKUEUE_CONTROLLER_NAME = "kueue.x-k8s.io/multikueue"


RETRY_BASE_S = 1.0
RETRY_MAX_S = 60.0


@dataclass
class WorkerCluster:
    """A remote cluster behind a connection that can drop (reference
    multikueuecluster.go remoteClient).

    ``driver`` — an in-process Driver (the multi-envtest pattern) — or
    ``client`` — any transport client (kueue_tpu.remote.HttpWorkerClient
    for a real process/socket boundary).  Reconnection follows the
    reference's exponential retry (multikueuecluster.go:67 retryAfter,
    :134-226 watch re-establishment) with a half-open circuit: a failed
    operation marks the cluster lost; health probes retry with doubling
    backoff; a *passing* probe only opens a half-open trial window —
    the controller must complete the rejoin reconciliation over the
    real API before ``reconnect()`` closes the circuit, and a failure
    during the trial re-opens it with the backoff escalated (a flapping
    worker never gets a fresh budget per flap).  ``reconnect_budget``
    > 0 caps total probes before the cluster is declared permanently
    failed."""
    name: str
    driver: object = None             # in-process Driver (optional)
    client: object = None             # transport client
    active: bool = True
    lost_since: Optional[float] = None
    next_retry: float = 0.0
    retry_backoff: float = RETRY_BASE_S
    watch: object = None              # remote.WatchLoop when streaming
    half_open: bool = False           # probe passed, rejoin unproven
    reconnect_attempts: int = 0       # probes since the cluster went lost
    reconnect_budget: int = 0         # 0 = unlimited probes
    failed_permanently: bool = False

    def __post_init__(self):
        if self.client is None and self.driver is not None:
            from ..remote import LocalWorkerClient
            self.client = LocalWorkerClient(self.driver)

    def mark_lost(self, now: float) -> None:
        if self.half_open:
            # a half-open trial failed: keep escalating the backoff
            # instead of resetting it
            self.half_open = False
            self.active = False
            if self.lost_since is None:
                self.lost_since = now
            self.retry_backoff = min(self.retry_backoff * 2.0, RETRY_MAX_S)
            self.next_retry = now + self.retry_backoff
            return
        if self.active:
            self.active = False
            self.lost_since = now
            self.retry_backoff = RETRY_BASE_S
            self.next_retry = now + self.retry_backoff

    def try_reconnect(self, now: float) -> bool:
        """Half-open health probe with exponential backoff and a probe
        budget.  True means the probe passed and the trial window is
        open — NOT that the cluster is active again; the caller runs
        the rejoin reconciliation and calls ``reconnect()`` (or
        ``mark_lost()`` on failure) to settle the circuit."""
        if self.active or self.failed_permanently or now < self.next_retry:
            return False
        self.reconnect_attempts += 1
        if self.client.healthy():
            self.half_open = True
            return True
        if (self.reconnect_budget
                and self.reconnect_attempts >= self.reconnect_budget):
            self.failed_permanently = True
            return False
        self.retry_backoff = min(self.retry_backoff * 2.0, RETRY_MAX_S)
        self.next_retry = now + self.retry_backoff
        return False

    def reconnect(self) -> None:
        self.active = True
        self.half_open = False
        self.lost_since = None
        self.retry_backoff = RETRY_BASE_S
        self.reconnect_attempts = 0


@dataclass
class _Assignment:
    cluster: str
    nominated: list[str] = field(default_factory=list)


class MultiKueueController:
    """reference multikueue/workload.go wlReconciler."""

    def __init__(self, manager_driver, check_name: str,
                 config: MultiKueueConfig,
                 clusters: dict[str, WorkerCluster],
                 origin: str = "multikueue",
                 worker_lost_timeout: float = 300.0,
                 manager_jobs=None,
                 worker_jobs: dict[str, object] | None = None):
        self.manager = manager_driver
        # back-reference for the debugger's federation-circuit section
        # (debugger.dump_state reads driver.multikueue.clusters)
        manager_driver.multikueue = self
        self.check_name = check_name
        self.config = config
        self.clusters = clusters
        self.origin = origin
        self.worker_lost_timeout = worker_lost_timeout
        self.assignments: dict[str, _Assignment] = {}
        # mirrors that must be deleted on a currently-unreachable worker:
        # flushed when it reconnects (a lost delete would otherwise
        # orphan worker quota forever)
        self.pending_deletes: dict[str, set[str]] = {}
        # optional job-level dispatch (reference MultiKueueAdapter.SyncJob,
        # jobframework/interface.go:227): the manager's JobManager plus one
        # per worker cluster; jobs are mirrored instead of bare workloads
        self.manager_jobs = manager_jobs
        self.worker_jobs = worker_jobs or {}

    # ------------------------------------------------------------------

    def _relevant(self, wl: Workload) -> bool:
        return (self.check_name in wl.admission_check_states
                and wl.has_quota_reservation and not wl.is_finished)

    def _mirror(self, wl: Workload) -> Workload:
        remote = Workload(
            name=wl.name, namespace=wl.namespace, queue_name=wl.queue_name,
            pod_sets=[__import__("copy").deepcopy(ps) for ps in wl.pod_sets],
            priority=wl.priority, creation_time=wl.creation_time)
        return remote

    def _worker_op(self, cluster: WorkerCluster, fn, *args, default=None):
        """Run one transport operation; a connection failure marks the
        cluster lost (multikueuecluster.go:134 watch loss)."""
        from ..remote import ConnectionLost
        try:
            return fn(*args)
        except ConnectionLost:
            cluster.mark_lost(self.manager.clock())
            return default

    def start_watches(self, poll_timeout: float = 10.0) -> None:
        """Per-cluster watch streams (reference multikueuecluster.go:187
        watch channels): worker events are pushed to the controller
        instead of polled one GET per assigned workload per reconcile.
        Re-establishment + event replay are handled by the WatchLoop."""
        from ..remote import WatchLoop
        for cluster in self.clusters.values():
            if cluster.watch is None and cluster.client is not None:
                cluster.watch = WatchLoop(cluster.client,
                                          poll_timeout=poll_timeout)
                cluster.watch.start()

    def stop_watches(self) -> None:
        for cluster in self.clusters.values():
            if cluster.watch is not None:
                cluster.watch.stop()
                cluster.watch = None

    def _drain_watches(self, now: float) -> list[tuple[str, str]]:
        """Pull pending events from every watch queue.  Connection
        markers drive the cluster's lost/reconnected state; workload
        events return as (cluster, key) for targeted syncs."""
        import queue as _queue
        touched: list[tuple[str, str]] = []
        for cname, cluster in self.clusters.items():
            w = cluster.watch
            if w is None:
                continue
            while True:
                try:
                    kind, key, _note = w.events.get_nowait()
                except _queue.Empty:
                    break
                if kind == "__lost__":
                    cluster.mark_lost(now)
                elif kind == "__reconnected__":
                    if cluster.active:
                        cluster.reconnect()   # refresh backoff state
                    else:
                        # the stream is back: treat it as a passing
                        # half-open probe — the rejoin reconciliation
                        # must prove the worker over the real API
                        # before the cluster reactivates
                        cluster.half_open = True
                        self.reconcile_rejoined(cname)
                elif kind == "__resync__":
                    # fresh worker epoch: the remote may have lost every
                    # mirror — resync everything tied to this cluster
                    for akey, asg in self.assignments.items():
                        if asg.cluster == cname or cname in asg.nominated:
                            touched.append((cname, akey))
                elif kind in ("QuotaReserved", "Finished", "Deleted",
                              "Preempted"):
                    touched.append((cname, key))
        return touched

    def reconcile(self) -> None:
        now = self.manager.clock()
        touched = self._drain_watches(now)
        # connection health: the watch loop is authoritative when
        # streaming; otherwise retry lost workers with exponential
        # backoff.  Either way, eject assignments once a worker stays
        # lost past the timeout.
        for name, cluster in self.clusters.items():
            # health probes run even with a watch attached: a transient
            # _worker_op failure can mark the cluster lost while the
            # watch stream (a separate connection) stays healthy and so
            # never emits a __reconnected__ marker
            if not cluster.active and cluster.try_reconnect(now):
                self.reconcile_rejoined(name)
            if (not cluster.active and cluster.lost_since is not None
                    and now - cluster.lost_since > self.worker_lost_timeout):
                self._eject_cluster(name)

        # with watches, remote state arrives as events: the per-workload
        # GET polling loop runs only for watchless transports (and for
        # job-level dispatch, whose execution-status copy-back has no
        # event source)
        watching = all(c.watch is not None
                       for c in self.clusters.values()) and self.clusters
        for key, wl in list(self.manager.workloads.items()):
            if not self._relevant(wl):
                if key in self.assignments:
                    self._cleanup(key)
                continue
            state = wl.admission_check_states[self.check_name]
            asg = self.assignments.get(key)
            if asg is None:
                self._nominate(key, wl)
            elif not watching or self.manager_jobs is not None:
                self._sync(key, wl, state.state, asg)

        if watching and self.manager_jobs is None:
            # targeted event-driven syncs (deduped; when the polling
            # loop ran above it already covered every assignment)
            for key in dict.fromkeys(k for _c, k in touched):
                asg = self.assignments.get(key)
                wl = self.manager.workloads.get(key)
                if asg is None or wl is None or not self._relevant(wl):
                    continue
                state = wl.admission_check_states[self.check_name]
                self._sync(key, wl, state.state, asg)

    # ------------------------------------------------------------------

    def _owner_job(self, wl: Workload):
        """The manager-side job owning this workload, if job-level
        dispatch is attached."""
        if self.manager_jobs is None:
            return None
        for job in self.manager_jobs.jobs.values():
            wl_key = self.manager_jobs.reconciler.workload_key_for(job)
            if wl_key == wl.key:
                return job
        return None

    def _sync_job(self, cname: str, job) -> None:
        """Mirror the job object to a worker cluster (adapter SyncJob):
        the worker's own jobframework creates and manages the workload."""
        import copy
        worker_jm = self.worker_jobs.get(cname)
        if worker_jm is None:
            return
        if job.key in worker_jm.jobs:
            return
        clone = copy.deepcopy(job)
        if hasattr(clone, "set_managed_by"):
            clone.set_managed_by(None)   # the worker runs it for real
        worker_jm.upsert(clone)

    def _nominate(self, key: str, wl: Workload) -> None:
        """Create mirrors on every configured active cluster
        (workload.go nominateAndSynchronizeWorkers)."""
        job = self._owner_job(wl)
        nominated = []
        for cname in self.config.clusters:
            cluster = self.clusters.get(cname)
            if cluster is None or not cluster.active:
                continue
            if job is not None and cname in self.worker_jobs:
                self._sync_job(cname, job)
            else:
                self._worker_op(cluster, cluster.client.create_workload,
                                self._mirror(wl))
                if not cluster.active:
                    # the create may have landed before the connection
                    # dropped: clean it up when the worker comes back
                    self.pending_deletes.setdefault(cname, set()).add(
                        wl.key)
                    continue
            nominated.append(cname)
        if not nominated:
            return
        self.assignments[key] = _Assignment(cluster="", nominated=nominated)

    def _sync(self, key: str, wl: Workload, state: AdmissionCheckState,
              asg: _Assignment) -> None:
        # give each nominated worker a scheduling chance, then pick the
        # first with quota reserved (workload.go: first to reserve wins)
        if not asg.cluster:
            for cname in asg.nominated:
                cluster = self.clusters.get(cname)
                if cluster is None or not cluster.active:
                    continue
                remote = self._worker_op(cluster,
                                         cluster.client.get_workload, key)
                if remote is not None and remote.has_quota_reservation:
                    asg.cluster = cname
                    break
            if asg.cluster:
                # delete the losing mirrors
                for cname in asg.nominated:
                    if cname != asg.cluster:
                        self._delete_remote(cname, key)
                asg.nominated = [asg.cluster]
                self.manager.set_admission_check_state(
                    key, self.check_name, AdmissionCheckState.READY,
                    f'The workload got reservation on "{asg.cluster}"')
            return

        cluster = self.clusters.get(asg.cluster)
        if cluster is None or not cluster.active:
            return  # lost; ejection handled by the timeout scan
        remote = self._worker_op(cluster, cluster.client.get_workload, key)
        if not cluster.active:
            return  # connection dropped mid-sync
        if remote is None:
            # remote deleted under us → re-dispatch
            self._reset(key)
            return
        # job-level dispatch: copy the remote job's execution status back
        # to the (suspended) manager job (reference workload.go copy-back)
        job = self._owner_job(wl)
        if job is not None:
            worker_jm = self.worker_jobs.get(asg.cluster)
            if worker_jm is not None:
                worker_job = worker_jm.jobs.get(job.key)
                if worker_job is not None:
                    job.sync_status_from(worker_job)
        if remote.is_finished:
            msg = remote.conditions.get("Finished")
            self.manager.finish_workload(
                key, msg.message if msg else "Finished on worker")
            self._cleanup(key)

    # ------------------------------------------------------------------

    def _delete_remote(self, cname: str, key: str) -> None:
        cluster = self.clusters.get(cname)
        if cluster is None:
            return
        if not cluster.active:
            # unreachable: remember the delete for the reconnect flush
            self.pending_deletes.setdefault(cname, set()).add(key)
            return
        worker_jm = self.worker_jobs.get(cname)
        if worker_jm is not None:
            # job-level mirrors: delete the worker job (cascades to its
            # workload via the worker JobManager)
            for jkey, job in list(worker_jm.jobs.items()):
                if worker_jm.reconciler.workload_key_for(job) == key:
                    worker_jm.delete(jkey)
        self._worker_op(cluster, cluster.client.delete_workload, key)
        if not cluster.active:
            self.pending_deletes.setdefault(cname, set()).add(key)

    def reconcile_rejoined(self, cname: str) -> bool:
        """WAL-consistent rejoin reconciliation — the half-open trial.

        The manager's journal-recovered store plus the assignment map
        rebuilt from it are its durable intent; a rejoining worker's
        listing is the actual state.  Replaying one against the other
        resolves every nominate/admit race a partition can leave:

        - mirrors whose deletes were lost while the worker was
          unreachable (its daemon may even have admitted them) die
          before anything else dispatches — the no-double-admission
          guarantee on rejoin;
        - mirrors still in a live nomination or assignment are kept
          (the normal sync resumes them), as are finished-winner
          records whose manager workload also finished;
        - assignments pointing at this worker whose mirror vanished
          (the worker restarted empty) reset for re-dispatch.

        Runs while the cluster is half-open: any transport failure
        aborts back to lost with the backoff escalated (the circuit
        re-opens); only a clean pass closes it via ``reconnect()``.
        Returns True when the cluster is active again."""
        cluster = self.clusters.get(cname)
        if cluster is None or cluster.active:
            return cluster is not None and cluster.active
        from ..remote import ConnectionLost
        pending = self.pending_deletes.get(cname, set())
        try:
            listing = cluster.client.list_workloads()
            for key in sorted(listing):
                finished = listing[key]
                asg = self.assignments.get(key)
                wl = self.manager.workloads.get(key)
                keep_assigned = (
                    asg is not None and wl is not None
                    and self._relevant(wl)
                    and (asg.cluster == cname
                         or (not asg.cluster and cname in asg.nominated)))
                keep_record = (finished and wl is not None
                               and wl.is_finished and key not in pending)
                if keep_assigned or keep_record:
                    pending.discard(key)
                    continue
                worker_jm = self.worker_jobs.get(cname)
                if worker_jm is not None:
                    for jkey, job in list(worker_jm.jobs.items()):
                        if worker_jm.reconciler.workload_key_for(job) == key:
                            worker_jm.delete(jkey)
                cluster.client.delete_workload(key)
                pending.discard(key)
            # deletes queued for mirrors the worker no longer holds are moot
            for key in list(pending):
                if key not in listing:
                    pending.discard(key)
            # the partition may have eaten this worker's mirrors: anything
            # assigned here but gone must re-dispatch
            for key, asg in list(self.assignments.items()):
                if asg.cluster == cname and key not in listing:
                    self._reset(key)
        except ConnectionLost:
            cluster.mark_lost(self.manager.clock())
            return False
        self.pending_deletes.pop(cname, None)
        cluster.reconnect()
        return True

    def _cleanup(self, key: str) -> None:
        asg = self.assignments.pop(key, None)
        if asg is None:
            return
        for cname in asg.nominated:
            wl = self.manager.workloads.get(key)
            if wl is None or not wl.is_finished:
                self._delete_remote(cname, key)

    def _reset(self, key: str) -> None:
        self.assignments.pop(key, None)
        self.manager.set_admission_check_state(
            key, self.check_name, AdmissionCheckState.RETRY,
            "Lost the remote reservation; will re-dispatch")

    def _eject_cluster(self, cname: str) -> None:
        """Worker lost beyond timeout: requeue everything assigned to it
        (workload.go workerLostTimeout ejection) and queue deletes for
        every mirror it may still hold — if the worker ever rejoins,
        its stale mirrors must die before they can double-admit against
        the re-dispatched assignment."""
        for key, asg in list(self.assignments.items()):
            if asg.cluster == cname or cname in asg.nominated:
                self.pending_deletes.setdefault(cname, set()).add(key)
                self._reset(key)
                self.manager.obs.emit("eject", key, reason="WorkerLost",
                                      note=cname)

    def recover_assignments(self) -> int:
        """Rebuild the assignment map after a manager restart
        (Driver.recover_from): the map itself is in-memory, but every
        fact it encodes is recoverable — the journal-recovered store
        says which workloads carry this check, and the workers' actual
        listings say who holds the mirror.  A READY check with multiple
        holders keeps the first in config order and deletes the rest
        (the same winner the original selection would have picked).
        Returns the number of assignments restored."""
        restored = 0
        listings: dict[str, dict[str, bool]] = {}
        for cname, cluster in self.clusters.items():
            if not cluster.active:
                continue
            out = self._worker_op(cluster, cluster.client.list_workloads,
                                  default=None)
            if out is not None:
                listings[cname] = out
        for key, wl in list(self.manager.workloads.items()):
            if key in self.assignments or not self._relevant(wl):
                continue
            holders = [c for c in self.config.clusters
                       if key in listings.get(c, {})]
            if not holders:
                continue
            state = wl.admission_check_states[self.check_name].state
            if state == AdmissionCheckState.READY:
                winner = holders[0]
                self.assignments[key] = _Assignment(cluster=winner,
                                                    nominated=[winner])
                for cname in holders[1:]:
                    self._delete_remote(cname, key)
            else:
                self.assignments[key] = _Assignment(cluster="",
                                                    nominated=holders)
            restored += 1
        return restored

    # ------------------------------------------------------------------

    def run_gc(self) -> None:
        """Remote GC (multikueuecluster.go:255 runGC): delete worker
        mirrors whose manager workload is gone.  One list round trip per
        cluster ({key: finished}); stops on connection loss."""
        managed = set(self.manager.workloads)
        for cluster in self.clusters.values():
            if not cluster.active:
                continue
            listing = self._worker_op(cluster,
                                      cluster.client.list_workloads,
                                      default={})
            for key, finished in listing.items():
                if not cluster.active:
                    break   # lost mid-GC: stop issuing doomed requests
                if key not in managed and not finished:
                    self._worker_op(cluster,
                                    cluster.client.delete_workload, key)
