"""ProvisioningRequest admission-check controller (reference
pkg/controller/admissionchecks/provisioning, KEP 1136).

For every workload with quota reserved whose CQ carries a provisioning
check, the controller owns one ProvisioningRequest per attempt
(syncOwnedProvisionRequest, controller.go:226).  A pluggable capacity
backend (the cluster-autoscaler stand-in) flips request states; on
Provisioned the check turns Ready and PodSetUpdates inject the
provisioning node selectors; on failure the controller retries with
exponential backoff up to the config's limit, then rejects
(controller.go:344 retry logic, :659 podSetUpdates).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import (
    AdmissionCheckState,
    ProvisioningRequestConfig,
    Workload,
)

PROVISIONING_CONTROLLER_NAME = "kueue.x-k8s.io/provisioning-request"


@dataclass
class ProvisioningRequest:
    """The autoscaler-facing object (stand-in for autoscaler.x-k8s.io
    ProvisioningRequest)."""
    name: str
    workload_key: str
    check_name: str
    attempt: int = 1
    provisioning_class: str = ""
    parameters: dict[str, str] = field(default_factory=dict)
    pod_sets: list = field(default_factory=list)
    state: str = "Pending"        # Pending|Accepted|Provisioned|Failed|
    #                               BookingExpired|CapacityRevoked
    failure_message: str = ""


def request_name(wl_name: str, check: str, attempt: int) -> str:
    """reference provisioning/controller.go ProvisioningRequestName."""
    return f"{wl_name}-{check}-{attempt}"


class ProvisioningController:
    """reference provisioning/controller.go Controller."""

    def __init__(self, driver, check_name: str,
                 config: ProvisioningRequestConfig,
                 capacity_backend: Optional[Callable[[ProvisioningRequest], None]] = None):
        self.driver = driver
        self.check_name = check_name
        self.config = config
        self.capacity_backend = capacity_backend
        self.requests: dict[str, ProvisioningRequest] = {}
        # wl key → (attempt, not_before_time)
        self.retry_state: dict[str, tuple[int, float]] = {}

    # ------------------------------------------------------------------

    def _relevant(self, wl: Workload) -> bool:
        return (self.check_name in wl.admission_check_states
                and wl.has_quota_reservation and not wl.is_finished)

    def _backoff(self, attempt: int) -> float:
        rs = self.config.retry_strategy
        return min(rs.backoff_base_seconds * (2 ** (attempt - 1)),
                   rs.backoff_max_seconds)

    def reconcile(self) -> None:
        now = self.driver.clock()
        live = set()
        for key, wl in list(self.driver.workloads.items()):
            if not self._relevant(wl):
                continue
            state = wl.admission_check_states[self.check_name].state
            if state == AdmissionCheckState.READY:
                live.add((key, self._attempt(key)))
                continue
            attempt, not_before = self.retry_state.get(key, (1, 0.0))
            if now < not_before:
                continue
            rname = request_name(wl.name, self.check_name, attempt)
            live.add((key, attempt))
            req = self.requests.get(rname)
            if req is None:
                req = ProvisioningRequest(
                    name=rname, workload_key=key,
                    check_name=self.check_name, attempt=attempt,
                    provisioning_class=self.config.provisioning_class_name,
                    parameters=dict(self.config.parameters),
                    pod_sets=[(ps.name, ps.count) for ps in wl.pod_sets])
                self.requests[rname] = req
                if self.capacity_backend is not None:
                    self.capacity_backend(req)
            self._sync_check_state(key, wl, req, now)

        # GC requests whose workload/attempt is gone (controller.go GC)
        for rname, req in list(self.requests.items()):
            if (req.workload_key, req.attempt) not in live:
                wl = self.driver.workloads.get(req.workload_key)
                if wl is None or not self._relevant(wl):
                    del self.requests[rname]

    def _attempt(self, key: str) -> int:
        return self.retry_state.get(key, (1, 0.0))[0]

    # ------------------------------------------------------------------

    def _sync_check_state(self, key: str, wl: Workload,
                          req: ProvisioningRequest, now: float) -> None:
        if req.state == "Provisioned":
            self._set_ready(key, wl)
        elif req.state in ("Failed", "BookingExpired", "CapacityRevoked"):
            attempt = req.attempt
            limit = self.config.retry_strategy.backoff_limit_count
            if attempt < limit:
                self.retry_state[key] = (attempt + 1,
                                         now + self._backoff(attempt))
                self.driver.set_admission_check_state(
                    key, self.check_name, AdmissionCheckState.RETRY,
                    f"Retrying after {req.state}: {req.failure_message}")
            else:
                self.driver.set_admission_check_state(
                    key, self.check_name, AdmissionCheckState.REJECTED,
                    f"{req.state}: {req.failure_message}")
        # Pending/Accepted → leave the check Pending

    def _set_ready(self, key: str, wl: Workload) -> None:
        """Ready + PodSetUpdates (controller.go:659 podSetUpdates)."""
        updates = []
        if self.config.provisioning_class_name:
            for ps in wl.pod_sets:
                updates.append({
                    "name": ps.name,
                    "annotations": {
                        "cluster-autoscaler.kubernetes.io/consume-provisioning-request":
                            request_name(wl.name, self.check_name,
                                         self._attempt(key)),
                        "cluster-autoscaler.kubernetes.io/provisioning-class-name":
                            self.config.provisioning_class_name,
                    }})
        st = wl.admission_check_states.get(self.check_name)
        if st is not None:
            st.pod_set_updates = updates
        self.driver.set_admission_check_state(
            key, self.check_name, AdmissionCheckState.READY, "Provisioned")
