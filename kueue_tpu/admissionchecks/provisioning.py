"""ProvisioningRequest admission-check controller (reference
pkg/controller/admissionchecks/provisioning, KEP 1136).

For every workload with quota reserved whose CQ carries a provisioning
check, the controller owns one ProvisioningRequest per attempt
(syncOwnedProvisionRequest, controller.go:226), each referencing one
PodTemplate object per podset (``ppt-`` prefix, controller.go:60,
createPodTemplate controller.go:380, re-synced by
syncProvisionRequestsPodTemplates controller.go:420 and GC'd with their
request).  A pluggable capacity backend (the cluster-autoscaler
stand-in) flips request states; the per-condition handling mirrors
controller.go:575-625:

- ``Provisioned`` → the check turns Ready and PodSetUpdates inject the
  consume-provisioning-request annotations (controller.go:659).
- ``Failed`` → retry with exponential backoff up to the config's limit,
  then reject (controller.go:344).
- ``BookingExpired`` → same retry-vs-reject decision, but ONLY while the
  workload is not yet admitted; an admitted workload ignores booking
  expiry (controller.go:253-254,598-614).
- ``CapacityRevoked`` → the check is rejected outright while the
  workload is active, triggering deactivation, because the autoscaled
  nodes are already gone (controller.go:590-597).

With the ``KeepQuotaForProvReqRetry`` gate a retry keeps the check
Pending (quota held) instead of flipping to Retry (controller.go:577).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import features
from ..api.types import (
    AdmissionCheckState,
    ProvisioningRequestConfig,
    Workload,
)

PROVISIONING_CONTROLLER_NAME = "kueue.x-k8s.io/provisioning-request"
POD_TEMPLATES_PREFIX = "ppt"      # controller.go:60
CONSUME_ANNOTATION = \
    "cluster-autoscaler.kubernetes.io/consume-provisioning-request"
CLASS_ANNOTATION = \
    "cluster-autoscaler.kubernetes.io/provisioning-class-name"


@dataclass
class PodTemplateObject:
    """Stand-in for the corev1.PodTemplate the reference creates per
    podset of a ProvisioningRequest (controller.go:380-418)."""
    name: str
    namespace: str
    requests: dict[str, int] = field(default_factory=dict)
    count: int = 0
    node_selector: dict[str, str] = field(default_factory=dict)


@dataclass
class ProvisioningRequest:
    """The autoscaler-facing object (stand-in for autoscaler.x-k8s.io
    ProvisioningRequest)."""
    name: str
    namespace: str
    workload_key: str
    check_name: str
    attempt: int = 1
    provisioning_class: str = ""
    parameters: dict[str, str] = field(default_factory=dict)
    # [{"name", "count", "pod_template_ref"}] — PodTemplateRef per podset
    pod_sets: list = field(default_factory=list)
    state: str = "Pending"        # Pending|Accepted|Provisioned|Failed|
    #                               BookingExpired|CapacityRevoked
    failure_message: str = ""


def request_name(wl_name: str, check: str, attempt: int) -> str:
    """reference provisioning/controller.go ProvisioningRequestName."""
    return f"{wl_name}-{check}-{attempt}"


def pod_template_name(req_name: str, ps_name: str) -> str:
    """reference getProvisioningRequestPodTemplateName."""
    return f"{POD_TEMPLATES_PREFIX}-{req_name}-{ps_name}"


class ProvisioningController:
    """reference provisioning/controller.go Controller."""

    def __init__(self, driver, check_name: str,
                 config: ProvisioningRequestConfig,
                 capacity_backend: Optional[Callable[[ProvisioningRequest], None]] = None):
        self.driver = driver
        self.check_name = check_name
        self.config = config
        self.capacity_backend = capacity_backend
        # both maps are keyed "<namespace>/<object name>" — same-named
        # workloads in different namespaces own distinct objects
        self.requests: dict[str, ProvisioningRequest] = {}
        self.pod_templates: dict[str, PodTemplateObject] = {}
        # wl key → (attempt, not_before_time)
        self.retry_state: dict[str, tuple[int, float]] = {}

    # ------------------------------------------------------------------

    def _relevant(self, wl: Workload) -> bool:
        return (self.check_name in wl.admission_check_states
                and wl.has_quota_reservation and not wl.is_finished)

    def _backoff(self, attempt: int) -> float:
        rs = self.config.retry_strategy
        return min(rs.backoff_base_seconds * (2 ** (attempt - 1)),
                   rs.backoff_max_seconds)

    def reconcile(self) -> None:
        now = self.driver.clock()
        live = set()
        for key, wl in list(self.driver.workloads.items()):
            if not self._relevant(wl):
                continue
            state = wl.admission_check_states[self.check_name].state
            if state == AdmissionCheckState.READY:
                live.add((key, self._attempt(key)))
                # a provisioned booking can still be revoked or expire
                # under an admitted workload (controller.go:590-614)
                rname = request_name(wl.name, self.check_name,
                                     self._attempt(key))
                req = self.requests.get(f"{wl.namespace}/{rname}")
                if req is not None:
                    self._sync_pod_templates(wl, req)
                    if req.state in ("CapacityRevoked", "BookingExpired"):
                        self._sync_check_state(key, wl, req, now)
                continue
            attempt, not_before = self.retry_state.get(key, (1, 0.0))
            if now < not_before:
                continue
            rname = request_name(wl.name, self.check_name, attempt)
            live.add((key, attempt))
            req = self.requests.get(f"{wl.namespace}/{rname}")
            if req is None:
                req = self._create_request(rname, key, wl, attempt)
                if self.capacity_backend is not None:
                    self.capacity_backend(req)
            self._sync_pod_templates(wl, req)
            self._sync_check_state(key, wl, req, now)

        # GC requests + their pod templates once the workload/attempt is
        # gone — including requests superseded by a newer attempt
        # (controller.go GC of owned objects)
        for rkey, req in list(self.requests.items()):
            if (req.workload_key, req.attempt) not in live:
                for ps in req.pod_sets:
                    self.pod_templates.pop(
                        f"{req.namespace}/{ps['pod_template_ref']}", None)
                del self.requests[rkey]

    def _attempt(self, key: str) -> int:
        return self.retry_state.get(key, (1, 0.0))[0]

    # ------------------------------------------------------------------

    def _flavor_node_selector(self, wl: Workload, ps_name: str) -> dict:
        """Merge the assigned flavors' node labels into the template's
        selector (createPodTemplate merging psa.Flavors,
        controller.go:380-418)."""
        selector: dict[str, str] = {}
        if wl.admission is None:
            return selector
        flavors = getattr(self.driver.cache, "resource_flavors", {})
        for psa in wl.admission.pod_set_assignments:
            if psa.name != ps_name:
                continue
            for flavor_name in psa.flavors.values():
                flavor = flavors.get(flavor_name)
                if flavor is not None:
                    selector.update(flavor.node_labels)
        return selector

    def _make_pod_template(self, wl: Workload, ps, ptname: str,
                           count: int) -> None:
        """createPodTemplate (controller.go:380-418): the podset's shape
        plus the assigned flavors' node labels."""
        selector = dict(ps.node_selector)
        selector.update(self._flavor_node_selector(wl, ps.name))
        self.pod_templates[f"{wl.namespace}/{ptname}"] = PodTemplateObject(
            name=ptname, namespace=wl.namespace,
            requests=dict(ps.requests), count=count,
            node_selector=selector)

    def _create_request(self, rname: str, key: str, wl: Workload,
                        attempt: int) -> ProvisioningRequest:
        pod_sets = []
        for ps in wl.pod_sets:
            ptname = pod_template_name(rname, ps.name)
            self._make_pod_template(wl, ps, ptname, ps.count)
            pod_sets.append({"name": ps.name, "count": ps.count,
                             "pod_template_ref": ptname})
        req = ProvisioningRequest(
            name=rname, namespace=wl.namespace, workload_key=key,
            check_name=self.check_name, attempt=attempt,
            provisioning_class=self.config.provisioning_class_name,
            parameters=dict(self.config.parameters),
            pod_sets=pod_sets)
        self.requests[f"{wl.namespace}/{rname}"] = req
        return req

    def _sync_pod_templates(self, wl: Workload,
                            req: ProvisioningRequest) -> None:
        """Recreate any template deleted out from under a live request
        (syncProvisionRequestsPodTemplates, controller.go:420-440)."""
        by_name = {ps.name: ps for ps in wl.pod_sets}
        for entry in req.pod_sets:
            if f"{wl.namespace}/{entry['pod_template_ref']}" \
                    in self.pod_templates:
                continue
            ps = by_name.get(entry["name"])
            if ps is None:
                continue
            self._make_pod_template(wl, ps, entry["pod_template_ref"],
                                    entry["count"])

    # ------------------------------------------------------------------

    def _retry_or_reject(self, key: str, req: ProvisioningRequest,
                         now: float, reason: str) -> None:
        attempt = req.attempt
        limit = self.config.retry_strategy.backoff_limit_count
        if attempt < limit:
            self.retry_state[key] = (attempt + 1,
                                     now + self._backoff(attempt))
            next_state = (AdmissionCheckState.PENDING
                          if features.enabled("KeepQuotaForProvReqRetry")
                          else AdmissionCheckState.RETRY)
            self.driver.set_admission_check_state(
                key, self.check_name, next_state,
                f"Retrying after {reason}: {req.failure_message}")
        else:
            self.driver.set_admission_check_state(
                key, self.check_name, AdmissionCheckState.REJECTED,
                f"{reason}: {req.failure_message}")

    def _sync_check_state(self, key: str, wl: Workload,
                          req: ProvisioningRequest, now: float) -> None:
        if req.state == "Provisioned":
            self._set_ready(key, wl, req)
        elif req.state == "Failed":
            self._retry_or_reject(key, req, now, "Failed")
        elif req.state == "CapacityRevoked":
            # nodes already deleted by the autoscaler: reject to force
            # deactivation so replacement pods don't pend forever
            # (controller.go:590-597)
            if wl.is_active and not wl.is_finished:
                self.driver.set_admission_check_state(
                    key, self.check_name, AdmissionCheckState.REJECTED,
                    f"CapacityRevoked: {req.failure_message}")
        elif req.state == "BookingExpired":
            # an admitted workload keeps running through booking expiry
            # (controller.go:253-254,598-614)
            if not wl.is_admitted:
                self._retry_or_reject(key, req, now, "booking expired")
        # Pending/Accepted → leave the check Pending

    def _set_ready(self, key: str, wl: Workload,
                   req: ProvisioningRequest) -> None:
        """Ready + PodSetUpdates (controller.go:659 podSetUpdates)."""
        updates = []
        if self.config.provisioning_class_name:
            for ps in wl.pod_sets:
                updates.append({
                    "name": ps.name,
                    "annotations": {
                        CONSUME_ANNOTATION: req.name,
                        CLASS_ANNOTATION:
                            self.config.provisioning_class_name,
                    }})
        st = wl.admission_check_states.get(self.check_name)
        if st is not None:
            st.pod_set_updates = updates
        self.driver.set_admission_check_state(
            key, self.check_name, AdmissionCheckState.READY, "Provisioned")
