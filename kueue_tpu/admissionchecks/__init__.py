"""Admission-check controllers (reference pkg/controller/admissionchecks).

Two-phase admission (KEP 993): the scheduler reserves quota and attaches
pending check states; these controllers flip them to Ready/Retry/Rejected
and the workload only starts when every check is Ready.
"""

from .multikueue import MULTIKUEUE_CONTROLLER_NAME, MultiKueueController, WorkerCluster
from .provisioning import (
    PROVISIONING_CONTROLLER_NAME,
    ProvisioningController,
    ProvisioningRequest,
)

__all__ = [
    "MULTIKUEUE_CONTROLLER_NAME", "MultiKueueController", "WorkerCluster",
    "PROVISIONING_CONTROLLER_NAME", "ProvisioningController",
    "ProvisioningRequest",
]
