"""kueuectl-equivalent CLI (reference cmd/kueuectl, ~5.5k LoC of cobra).

Run as ``python -m kueue_tpu.cli``.  Commands mirror the kubectl-kueue
plugin surface (app/cmd.go:59): create/apply/delete, list, stop/resume,
plus ``schedule`` (run admission cycles), ``state`` (debugger dump),
``import`` (cmd/importer-equivalent bulk import of running pods) and
``version``.

State model: a directory of manifests (JSON) is the API-server stand-in;
every command replays it into a Driver (the reference's cache/queue
rebuild from CRD watch replay — SURVEY §5.4), mutates, schedules if
asked, and writes status back.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .api import manifests as m
from .api.types import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    ResourceFlavor,
    ResourceQuota,
    FlavorQuotas,
    ResourceGroup,
    StopPolicy,
    Topology,
    Workload,
    WorkloadPriorityClass,
)
from .controller.driver import Driver
from .features import env_value

VERSION = "0.1.0 (kueue reference parity ≈ v0.11)"
STATE_FILE = "state.json"


# ---------------------------------------------------------------------------
# State store
# ---------------------------------------------------------------------------

class Store:
    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        self.docs: list[dict] = []
        path = os.path.join(state_dir, STATE_FILE)
        if os.path.exists(path):
            with open(path) as f:
                self.docs = json.load(f)

    def save(self) -> None:
        os.makedirs(self.state_dir, exist_ok=True)
        path = os.path.join(self.state_dir, STATE_FILE)
        # write-then-rename: readers (the serve watcher) never see a
        # truncated/partial file
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.docs, f, indent=1)
        os.replace(tmp, path)

    # -- doc helpers ---------------------------------------------------

    @staticmethod
    def _ident(doc: dict) -> tuple:
        meta = doc.get("metadata") or {}
        return (doc.get("kind"), meta.get("namespace", "default"),
                meta.get("name"))

    def upsert(self, doc: dict) -> None:
        ident = self._ident(doc)
        self.docs = [d for d in self.docs if self._ident(d) != ident]
        self.docs.append(doc)

    def delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        ident = (kind, namespace, name)
        before = len(self.docs)
        self.docs = [d for d in self.docs if self._ident(d) != ident]
        return len(self.docs) != before

    def by_kind(self, kind: str) -> list[dict]:
        return [d for d in self.docs if d.get("kind") == kind]

    def get(self, kind: str, name: str,
            namespace: str = "default") -> dict | None:
        for d in self.docs:
            if self._ident(d) == (kind, namespace, name):
                return d
        return None


def apply_spec(d: Driver, doc: dict) -> None:
    """Apply one non-Workload manifest to a driver."""
    kind = doc.get("kind")
    obj = m.from_manifest(doc)
    if kind == "ResourceFlavor":
        d.apply_resource_flavor(obj)
    elif kind == "Topology":
        d.apply_topology(obj)
    elif kind == "AdmissionCheck":
        d.apply_admission_check(obj)
    elif kind == "WorkloadPriorityClass":
        d.apply_workload_priority_class(obj)
    elif kind == "Cohort":
        d.apply_cohort(obj)
    elif kind == "ClusterQueue":
        d.apply_cluster_queue(obj)
    elif kind == "LocalQueue":
        d.apply_local_queue(obj)


def build_driver(store: Store, use_device: bool = False) -> Driver:
    """Replay the store into a fresh Driver."""
    d = Driver(use_device_solver=use_device)
    order = ["ResourceFlavor", "Topology", "AdmissionCheck",
             "WorkloadPriorityClass", "Cohort", "ClusterQueue", "LocalQueue"]
    for kind in order:
        for doc in store.by_kind(kind):
            apply_spec(d, doc)
    for doc in store.by_kind("Workload"):
        d.restore_workload(m.from_manifest(doc))
    return d


def save_workloads(store: Store, driver: Driver) -> None:
    for wl in driver.workloads.values():
        store.upsert(m.to_manifest(wl))
    live = {("Workload", wl.namespace, wl.name)
            for wl in driver.workloads.values()}
    store.docs = [d for d in store.docs
                  if d.get("kind") != "Workload"
                  or Store._ident(d) in live]


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------

def cmd_apply(store: Store, args) -> int:
    text = (sys.stdin.read() if args.filename == "-"
            else open(args.filename).read())
    objs = []
    import yaml
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        obj = m.from_manifest(doc)   # validates the kind is supported
        objs.append((doc, obj))
    driver = build_driver(store)     # validates existing state
    for doc, obj in objs:
        # webhook-equivalent validation before persisting
        from . import webhooks
        if isinstance(obj, ClusterQueue):
            webhooks.validate_cluster_queue(obj)
        elif isinstance(obj, Workload):
            webhooks.default_workload(obj)
            webhooks.validate_workload(obj)
        elif isinstance(obj, LocalQueue):
            webhooks.validate_local_queue(obj)
        elif isinstance(obj, ResourceFlavor):
            webhooks.validate_resource_flavor(obj)
        elif isinstance(obj, Cohort):
            webhooks.validate_cohort(obj)
        store.upsert(doc)
        print(f"{doc['kind'].lower()}/{doc['metadata']['name']} applied")
    store.save()
    return 0


def _mk(kind: str, name: str, spec: dict, namespace: str | None = None) -> dict:
    meta: dict = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    version = "v1alpha1" if kind in ("Cohort", "Topology") else "v1beta1"
    return {"apiVersion": f"kueue.x-k8s.io/{version}", "kind": kind,
            "metadata": meta, "spec": spec}


def cmd_create(store: Store, args) -> int:
    if args.resource == "clusterqueue":
        spec: dict = {"queueingStrategy": "BestEffortFIFO"}
        if args.cohort:
            spec["cohort"] = args.cohort
        groups = []
        if args.nominal_quota:
            resources = []
            for part in args.nominal_quota.split(","):
                rname, qty = part.split("=", 1)
                resources.append({"name": rname, "nominalQuota": qty})
            groups.append({
                "coveredResources": [r["name"] for r in resources],
                "flavors": [{"name": args.flavor or "default",
                             "resources": resources}]})
        spec["resourceGroups"] = groups
        doc = _mk("ClusterQueue", args.name, spec)
    elif args.resource == "localqueue":
        doc = _mk("LocalQueue", args.name,
                  {"clusterQueue": args.clusterqueue},
                  namespace=args.namespace)
    elif args.resource == "resourceflavor":
        labels = {}
        for part in (args.node_labels or "").split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                labels[k] = v
        doc = _mk("ResourceFlavor", args.name, {"nodeLabels": labels})
    else:
        print(f"unknown resource {args.resource}", file=sys.stderr)
        return 1
    obj = m.from_manifest(doc)
    from . import webhooks
    if isinstance(obj, ClusterQueue):
        webhooks.validate_cluster_queue(obj)
    store.upsert(doc)
    store.save()
    print(f"{doc['kind'].lower()}/{args.name} created")
    return 0


def cmd_list(store: Store, args) -> int:
    kind_map = {"clusterqueue": "ClusterQueue", "cq": "ClusterQueue",
                "localqueue": "LocalQueue", "lq": "LocalQueue",
                "workload": "Workload", "wl": "Workload",
                "resourceflavor": "ResourceFlavor", "rf": "ResourceFlavor"}
    kind = kind_map.get(args.resource)
    if kind is None:
        print(f"unknown resource {args.resource}", file=sys.stderr)
        return 1
    driver = build_driver(store)
    if kind == "Workload":
        print(f"{'NAMESPACE':<12} {'NAME':<40} {'QUEUE':<16} "
              f"{'ADMITTED':<9} STATUS")
        for wl in driver.workloads.values():
            status = ("Finished" if wl.is_finished else
                      "Admitted" if wl.is_admitted else
                      "QuotaReserved" if wl.has_quota_reservation else
                      "Pending" if wl.is_active else "Inactive")
            print(f"{wl.namespace:<12} {wl.name:<40} {wl.queue_name:<16} "
                  f"{str(wl.is_admitted):<9} {status}")
    elif kind == "ClusterQueue":
        print(f"{'NAME':<24} {'COHORT':<12} {'PENDING':<8} USAGE")
        for name in driver.cache.cluster_queue_names():
            cq = driver.cache.cluster_queue(name)
            usage = {f"{fr.flavor}/{fr.resource}": v
                     for fr, v in sorted(driver.cache.usage(name).items())
                     if v}
            cohort = (store.get("ClusterQueue", name) or {}).get(
                "spec", {}).get("cohort") or ""
            print(f"{name:<24} {cohort:<12} "
                  f"{driver.queues.pending_workloads(name):<8} {usage}")
    else:
        for doc in store.by_kind(kind):
            print(f"{doc['kind'].lower()}/{doc['metadata']['name']}")
    return 0


def cmd_delete(store: Store, args) -> int:
    kind_map = {"clusterqueue": "ClusterQueue", "localqueue": "LocalQueue",
                "workload": "Workload", "resourceflavor": "ResourceFlavor",
                "cohort": "Cohort"}
    kind = kind_map.get(args.resource)
    if kind is None or not store.delete(kind, args.name,
                                        args.namespace or "default"):
        print(f"{args.resource}/{args.name} not found", file=sys.stderr)
        return 1
    store.save()
    print(f"{args.resource}/{args.name} deleted")
    return 0


def _set_stop_policy(store: Store, args, policy: StopPolicy) -> int:
    """stop/resume {workload,clusterqueue,localqueue} (kueuectl KEP 2076)."""
    if args.resource == "workload":
        doc = store.get("Workload", args.name, args.namespace or "default")
        if doc is None:
            print(f"workload/{args.name} not found", file=sys.stderr)
            return 1
        doc.setdefault("spec", {})["active"] = (policy == StopPolicy.NONE)
        driver = build_driver(store)
        if policy != StopPolicy.NONE:
            driver.deactivate_workload(f"{args.namespace or 'default'}/{args.name}")
        save_workloads(store, driver)
    else:
        kind = {"clusterqueue": "ClusterQueue",
                "localqueue": "LocalQueue"}.get(args.resource)
        if kind is None:
            print(f"unknown resource {args.resource}", file=sys.stderr)
            return 1
        doc = store.get(kind, args.name,
                        None if kind == "ClusterQueue"
                        else (args.namespace or "default"))
        if doc is None:
            doc = store.get(kind, args.name, "default")
        if doc is None:
            print(f"{args.resource}/{args.name} not found", file=sys.stderr)
            return 1
        doc.setdefault("spec", {})["stopPolicy"] = policy.value
    store.save()
    print(f"{args.resource}/{args.name} "
          + ("stopped" if policy != StopPolicy.NONE else "resumed"))
    return 0


def cmd_schedule(store: Store, args) -> int:
    from .profiling import trace
    driver = build_driver(store, use_device=getattr(args, "device_solver",
                                                    False))
    with trace(getattr(args, "profile_dir", None)):
        driver.run_until_settled(max_cycles=args.cycles)
    save_workloads(store, driver)
    store.save()
    admitted = sorted(driver.admitted_keys())
    print(f"admitted {len(admitted)} workloads")
    for key in admitted:
        print(f"  {key}")
    return 0


def cmd_state(store: Store, args) -> int:
    from .debugger import dump_state
    print(dump_state(build_driver(store)))
    return 0


def cmd_serve(store: Store, args) -> int:
    """Daemon mode (reference cmd/kueue manager + scheduler Runnable):
    a long-running admission loop over blocking heads with speed-signal
    backoff, a store watcher that picks up `cli apply` edits from other
    processes, SIGUSR2 state dumps, and graceful SIGINT/SIGTERM
    shutdown with workload status persisted back to the store."""
    import signal as _signal
    import threading

    stop = threading.Event()

    # leader election: exactly one daemon per store (reference
    # config.go:97 leader election; the scheduler runs only when elected)
    from .leaderelection import FileLease
    lease = FileLease(args.state_dir)
    if not lease.try_acquire():
        print(f"waiting for leadership on {args.state_dir}", flush=True)
        if not lease.acquire(stop):
            return 0
    store = Store(args.state_dir)  # reload: the old leader wrote status
    driver = build_driver(store, use_device=getattr(args, "device_solver",
                                                    False))

    from .debugger import Dumper
    dumper = Dumper(driver)
    try:
        dumper.listen_for_signal()          # SIGUSR2 → state dump
        for sig in (_signal.SIGINT, _signal.SIGTERM):
            _signal.signal(sig, lambda *_: stop.set())
    except ValueError:
        pass  # not on the main thread (tests drive serve threaded)

    store_path = os.path.join(store.state_dir, STATE_FILE)

    def store_stat():
        try:
            st = os.stat(store_path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    seen_stat = store_stat()

    def watch_store():
        """Poll the store file; mirror spec changes made by other
        processes (the API-server watch equivalent).  New workloads are
        restored (admitted status charges the cache — cli import),
        removed ones are deleted.  Any torn read or bad manifest skips
        the poll; the watcher never dies."""
        nonlocal seen_stat
        while not stop.wait(args.poll_interval):
            try:
                st = store_stat()
                if st is None or st == seen_stat:
                    continue
                seen_stat = st
                fresh = Store(args.state_dir)
                store_keys = set()
                for doc in fresh.docs:
                    kind = doc.get("kind")
                    if kind == "Workload":
                        meta = doc.get("metadata") or {}
                        key = (f"{meta.get('namespace', 'default')}"
                               f"/{meta.get('name')}")
                        store_keys.add(key)
                        if driver.workload(key) is None:
                            driver.restore_workload(m.from_manifest(doc))
                    elif kind:
                        apply_spec(driver, doc)
                for key in list(driver.workloads):
                    if key not in store_keys:
                        driver.delete_workload(key)
                driver.queues.broadcast()
            except Exception as exc:       # torn read / bad manifest
                print(f"store watch: skipping poll: {exc}", flush=True)

    def drained() -> bool:
        """No workload can make progress: every active heap is empty
        (parked-inadmissible workloads wait on events, not cycles)."""
        return not any(driver.queues.pending_active_workloads(name)
                       for name in driver.queues.cluster_queue_names())

    watcher = threading.Thread(target=watch_store, daemon=True)
    watcher.start()
    if args.exit_when_drained:
        def drain_check():
            while not stop.wait(0.1):
                if drained():
                    stop.set()
        threading.Thread(target=drain_check, daemon=True).start()

    profile_dir = getattr(args, "profile_dir", None)
    if profile_dir:
        from .profiling import start_trace
        start_trace(profile_dir)
    worker_server = None
    if getattr(args, "listen", None) is not None:
        # MultiKueue worker mode: serve the remote-cluster API next to
        # the admission daemon (kueue_tpu.remote.WorkerServer)
        from .remote import WorkerServer
        worker_server = WorkerServer(driver, port=args.listen)
        worker_server.start()
        print(f"worker API on http://127.0.0.1:{worker_server.port}",
              flush=True)
    print(f"serving from {args.state_dir} (SIGUSR2 dumps state, "
          f"SIGTERM stops)", flush=True)
    try:
        driver.run(stop)                     # blocks until stop
        if profile_dir:
            from .profiling import stop_trace
            stop_trace()                     # may raise: lease still freed
        # status write-back against a FRESH store read: spec edits made
        # by other processes while serving are preserved, and workloads
        # deleted from the store stay deleted
        final = Store(args.state_dir)
        for wl in list(driver.workloads.values()):
            if final.get("Workload", wl.name, wl.namespace) is not None:
                final.upsert(m.to_manifest(wl))
        final.save()
    finally:
        if worker_server is not None:
            worker_server.stop()
        lease.release()
    admitted = sorted(driver.admitted_keys())
    print(f"serve exiting: {len(admitted)} workloads holding quota")
    return 0


def cmd_import(store: Store, args) -> int:
    """cmd/importer equivalent: adopt already-running pods as admitted
    workloads (check + import phases)."""
    import yaml
    text = (sys.stdin.read() if args.filename == "-"
            else open(args.filename).read())
    driver = build_driver(store)
    count = skipped = 0
    for doc in yaml.safe_load_all(text):
        if not doc or doc.get("kind") != "Pod":
            continue
        meta = doc.get("metadata") or {}
        queue = (meta.get("labels") or {}).get(args.queue_label)
        if not queue:
            skipped += 1
            continue
        spec = doc.get("spec") or {}
        requests: dict[str, int] = {}
        for c in spec.get("containers", []):
            for rname, v in ((c.get("resources") or {})
                             .get("requests") or {}).items():
                requests[rname] = (requests.get(rname, 0)
                                   + m._parse_qty(rname, v))
        req_strs = {r: m._format_qty(r, v) for r, v in requests.items()}
        pod_set = {"name": "main", "count": 1,
                   "template": {"spec": {"containers": [
                       {"name": "main",
                        "resources": {"requests": req_strs}}]}}}
        wl_doc = _mk("Workload", f"pod-{meta.get('name')}",
                     {"queueName": queue, "podSets": [pod_set]},
                     namespace=meta.get("namespace", "default"))
        store.upsert(wl_doc)
        count += 1
    store.save()
    # import phase: admit them through the scheduler
    driver = build_driver(store)
    driver.run_until_settled()
    save_workloads(store, driver)
    store.save()
    print(f"imported {count} pods ({skipped} skipped), "
          f"{len(driver.admitted_keys())} admitted")
    return 0


# ---------------------------------------------------------------------------

def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kueuectl", description="kueue-tpu control CLI")
    parser.add_argument("--state-dir",
                        default=env_value("KUEUE_TPU_STATE"))
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("apply", help="apply -f manifests")
    p.add_argument("-f", "--filename", required=True)

    p = sub.add_parser("create")
    p.add_argument("resource",
                   choices=["clusterqueue", "localqueue", "resourceflavor"])
    p.add_argument("name")
    p.add_argument("--cohort", default="")
    p.add_argument("--nominal-quota", default="",
                   help="cpu=10,memory=64Gi")
    p.add_argument("--flavor", default="default")
    p.add_argument("--clusterqueue", default="")
    p.add_argument("--node-labels", default="")
    p.add_argument("-n", "--namespace", default="default")

    p = sub.add_parser("list")
    p.add_argument("resource")
    p.add_argument("-n", "--namespace", default=None)

    p = sub.add_parser("delete")
    p.add_argument("resource")
    p.add_argument("name")
    p.add_argument("-n", "--namespace", default=None)

    for name in ("stop", "resume"):
        p = sub.add_parser(name)
        p.add_argument("resource",
                       choices=["workload", "clusterqueue", "localqueue"])
        p.add_argument("name")
        p.add_argument("-n", "--namespace", default=None)

    p = sub.add_parser("schedule", help="run admission cycles")
    p.add_argument("--cycles", type=int, default=1000)
    p.add_argument("--device-solver", action="store_true",
                   help="decide cycles with the batched device solver")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace here")

    sub.add_parser("state", help="dump queues/cache state")

    p = sub.add_parser("serve", help="run the admission daemon")
    p.add_argument("--poll-interval", type=float, default=0.5,
                   help="store-watch poll interval (seconds)")
    p.add_argument("--exit-when-drained", action="store_true",
                   help="exit once no workloads are pending (tests)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace here")
    p.add_argument("--listen", type=int, default=None,
                   help="serve the MultiKueue worker API on this port")
    p.add_argument("--device-solver", action="store_true",
                   help="decide cycles with the batched device solver")

    p = sub.add_parser("import", help="bulk-import running pods")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--queue-label", default="kueue.x-k8s.io/queue-name")

    sub.add_parser("version")

    args = parser.parse_args(argv)
    if args.command == "version":
        print(VERSION)
        return 0
    store = Store(args.state_dir)
    handlers = {
        "apply": cmd_apply, "create": cmd_create, "list": cmd_list,
        "delete": cmd_delete, "schedule": cmd_schedule, "state": cmd_state,
        "import": cmd_import, "serve": cmd_serve,
        "stop": lambda s, a: _set_stop_policy(s, a, StopPolicy.HOLD_AND_DRAIN),
        "resume": lambda s, a: _set_stop_policy(s, a, StopPolicy.NONE),
    }
    return handlers[args.command](store, args)


if __name__ == "__main__":
    sys.exit(main())
