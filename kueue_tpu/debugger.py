"""State dump on signal (reference pkg/debugger: SIGUSR2 → dump queue
heads + cache usage to logs; queue/dumper.go).

Extended with the observability plane: when the driver carries an
``obs`` ObsPlane (it always does), the dump appends the in-flight
cycle, the flight-recorder tail (decision digests + span names), the
event-stream counts, and — when attached — WAL, arena, and federation
circuit state.  The same information is served as JSON from
``/debug/flightrecorder`` (visibility.VisibilityServer)."""

from __future__ import annotations

import signal
import sys
from typing import Optional, TextIO


def dump_state(driver, out: Optional[TextIO] = None,
               flight_tail: int = 8) -> str:
    """Render the queues + cache state (debugger.go:33 + dumper.go),
    plus the obs plane's flight recorder and subsystem state."""
    lines = []
    lines.append("=== kueue-tpu state dump ===")
    lines.append("-- pending queues --")
    for name in sorted(driver.cache.cluster_queue_names()):
        infos = driver.queues.pending_workloads_info(name)
        heads = ", ".join(i.obj.name for i in infos[:5])
        lines.append(f"  {name}: {len(infos)} pending"
                     + (f" (head: {heads})" if heads else ""))
    lines.append("-- cache usage --")
    for name in sorted(driver.cache.cluster_queue_names()):
        usage = driver.cache.usage(name)
        used = {f"{fr.flavor}/{fr.resource}": v
                for fr, v in sorted(usage.items()) if v}
        lines.append(f"  {name}: {used if used else '{}'}")
    lines.append("-- admitted workloads --")
    for key in sorted(driver.admitted_keys()):
        lines.append(f"  {key}")
    obs = getattr(driver, "obs", None)
    if obs is not None:
        lines.extend(_dump_obs(driver, obs, flight_tail))
    text = "\n".join(lines)
    if out is not None:
        print(text, file=out)
    return text


def _dump_obs(driver, obs, flight_tail: int) -> list:
    """The obs-plane section: in-flight cycle, flight tail, events,
    tracer, and (when attached) WAL / arena / circuit state."""
    lines = []
    lines.append("-- in-flight cycle --")
    lines.append(f"  scheduling_cycle: {driver.scheduler.scheduling_cycle}")
    t = obs._tracer_view()
    if t is not None:
        open_now = t.open_spans()
        lines.append(f"  open spans: {open_now if open_now else '[]'}")
        lines.append(f"  spans finished: {t.finished_total}")
    lines.append(f"-- flight recorder (last {flight_tail} of "
                 f"{obs.flight.recorded_total}) --")
    for rec in list(obs.flight.ring)[-flight_tail:]:
        span_names = sorted({s.name for s in rec.spans})
        chaos = (f" chaos={rec.chaos_hits}" if rec.chaos_hits else "")
        lines.append(
            f"  cycle {rec.cycle}: digest={rec.digest}"
            f" admitted={len(rec.admitted)}"
            f" preempting={len(rec.preempting)}"
            f" evicted={len(rec.evicted)}"
            + (f" spans={span_names}" if span_names else "") + chaos)
    lines.append("-- events --")
    rep = obs.events.report()
    lines.append(f"  {rep['counts']} total={rep['total']}"
                 f" dropped={rep['dropped']}")
    wal = getattr(driver, "_wal", None)
    if wal is not None and hasattr(wal, "stats"):
        lines.append("-- wal --")
        lines.append(f"  {dict(wal.stats)}")
    solver = getattr(driver, "_burst_solver", None)
    if solver is not None:
        bs = solver.stats
        arena = {k: bs[k] for k in ("pack_arena_planes", "pack_arena_bytes",
                                    "pack_arena_used_bytes") if k in bs}
        if arena:
            lines.append("-- arena --")
            lines.append(f"  {arena}")
    # federation circuit state, when this driver manages workers
    ctl = getattr(driver, "multikueue", None)
    if ctl is not None and hasattr(ctl, "clusters"):
        lines.append("-- federation circuits --")
        for cname, cluster in sorted(ctl.clusters.items()):
            state = "active" if cluster.active else "lost"
            lines.append(f"  {cname}: {state}")
    return lines


class Dumper:
    """reference debugger.NewDumper(...).ListenForSignal."""

    def __init__(self, driver, out: Optional[TextIO] = None):
        self.driver = driver
        self.out = out or sys.stderr

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        signal.signal(signum, lambda s, f: dump_state(self.driver, self.out))
