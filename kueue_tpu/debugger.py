"""State dump on signal (reference pkg/debugger: SIGUSR2 → dump queue
heads + cache usage to logs; queue/dumper.go)."""

from __future__ import annotations

import signal
import sys
from typing import Optional, TextIO


def dump_state(driver, out: Optional[TextIO] = None) -> str:
    """Render the queues + cache state (debugger.go:33 + dumper.go)."""
    lines = []
    lines.append("=== kueue-tpu state dump ===")
    lines.append("-- pending queues --")
    for name in sorted(driver.cache.cluster_queue_names()):
        infos = driver.queues.pending_workloads_info(name)
        heads = ", ".join(i.obj.name for i in infos[:5])
        lines.append(f"  {name}: {len(infos)} pending"
                     + (f" (head: {heads})" if heads else ""))
    lines.append("-- cache usage --")
    for name in sorted(driver.cache.cluster_queue_names()):
        usage = driver.cache.usage(name)
        used = {f"{fr.flavor}/{fr.resource}": v
                for fr, v in sorted(usage.items()) if v}
        lines.append(f"  {name}: {used if used else '{}'}")
    lines.append("-- admitted workloads --")
    for key in sorted(driver.admitted_keys()):
        lines.append(f"  {key}")
    text = "\n".join(lines)
    if out is not None:
        print(text, file=out)
    return text


class Dumper:
    """reference debugger.NewDumper(...).ListenForSignal."""

    def __init__(self, driver, out: Optional[TextIO] = None):
        self.driver = driver
        self.out = out or sys.stderr

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        signal.signal(signum, lambda s, f: dump_state(self.driver, self.out))
