"""Pending-workload queue manager.

Capability parity with reference pkg/queue/manager.go:86: one queue per
ClusterQueue wired into the cohort forest, LocalQueue routing, blocking
``heads`` (sync.Cond equivalent), cohort-wide inadmissible wakeups
(manager.go:490), and requeue with reasons.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Optional

from .. import hierarchy
from ..api.types import ClusterQueue, LocalQueue, StopPolicy, Workload
from ..utils.journal import PackJournal
from ..workload import Info, InfoOptions, Ordering
from .cluster_queue import ClusterQueueQueue, RequeueReason


class _QueueCohort:
    """Cohort payload for the queue-side hierarchy (wiring only)."""

    def __init__(self, name: str):
        self.name = name


class Manager:
    def __init__(self, ordering: Ordering | None = None,
                 clock: Callable[[], float] = time.time,
                 info_options: InfoOptions | None = None):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.ordering = ordering or Ordering()
        self.clock = clock
        self.info_options = info_options or InfoOptions()
        self._mgr: hierarchy.Manager[ClusterQueueQueue, _QueueCohort] = (
            hierarchy.Manager(_QueueCohort))
        self.local_queues: dict[str, LocalQueue] = {}
        self._lq_members: dict[str, set[str]] = {}  # lq key -> workload keys
        self._wl_route: dict[str, str] = {}         # workload key -> lq key
        self.stopped = False
        # dirty-CQ journal feeding the incremental burst pack; every
        # registered ClusterQueueQueue shares it (utils/journal.py)
        self.pack_journal = PackJournal()
        # O(active) indices shared with every registered queue: names
        # whose heap may hold entries (head collection iterates these in
        # registration order, matching the old full-dict scan), and
        # names with an armed requeue-backoff timer (wakeup scans these
        # only).  Conservative: stale names are dropped lazily.
        self._ready: set[str] = set()
        self._timers: set[str] = set()
        self._reg_seq: dict[str, int] = {}
        self._next_seq = 0
        # requeue-storm accounting (cohort-wide unpark bursts), surfaced
        # through Driver.stats and the open-loop traffic metrics
        self.requeue_storm_last = 0
        self.requeue_storm_peak = 0
        self.requeue_storms_total = 0
        self.requeue_unparked_total = 0

    # ------------------------------------------------------------------
    # ClusterQueues / LocalQueues / Cohorts
    # ------------------------------------------------------------------

    def add_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._lock:
            if spec.name in self._mgr.cluster_queues:
                # Idempotent upsert: a resync must not drop queued workloads
                # (the reference errors with errQueueAlreadyExists instead).
                self.update_cluster_queue(spec)
                return
            q = ClusterQueueQueue(spec.name, spec.queueing_strategy,
                                  self.ordering, self.clock)
            q.active = spec.stop_policy == StopPolicy.NONE
            q.journal = self.pack_journal
            q.ready = self._ready
            q.timers = self._timers
            self._next_seq += 1
            self._reg_seq[spec.name] = self._next_seq
            self._ready.add(spec.name)
            self.pack_journal.touch(spec.name)
            self._mgr.add_cluster_queue(spec.name, q)
            self._mgr.update_cluster_queue_edge(spec.name, spec.cohort)
            self._cond.notify_all()

    def update_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._lock:
            q = self._mgr.cluster_queues.get(spec.name)
            if q is None:
                self.add_cluster_queue(spec)
                return
            q.queueing_strategy = spec.queueing_strategy
            q.active = spec.stop_policy == StopPolicy.NONE
            self.pack_journal.touch(spec.name)
            self._mgr.update_cluster_queue_edge(spec.name, spec.cohort)
            if q.active:
                self._ready.add(spec.name)
                q.queue_inadmissible_workloads()
            self._cond.notify_all()

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            self.pack_journal.touch(name)
            self._ready.discard(name)
            self._timers.discard(name)
            self._reg_seq.pop(name, None)
            self._mgr.delete_cluster_queue(name)

    def set_cluster_queue_active(self, name: str, active: bool) -> None:
        with self._lock:
            q = self._mgr.cluster_queues.get(name)
            if q is None:
                return
            self.pack_journal.touch(name)
            q.active = active
            if active:
                # reactivation makes any existing heap poppable again
                self._ready.add(name)
                q.queue_inadmissible_workloads()
            self._cond.notify_all()

    def update_cohort_edge(self, name: str, parent: Optional[str]) -> None:
        with self._lock:
            self._mgr.update_cohort_edge(name, parent)

    def add_local_queue(self, lq: LocalQueue,
                        existing_workloads: Iterable[Workload] = ()) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq
            self._lq_members.setdefault(lq.key, set())
            for wl in existing_workloads:
                self.add_or_update_workload(wl)

    def delete_local_queue(self, lq_key: str) -> None:
        with self._lock:
            lq = self.local_queues.pop(lq_key, None)
            members = self._lq_members.pop(lq_key, set())
            if lq is None:
                return
            q = self._mgr.cluster_queues.get(lq.cluster_queue)
            if q is not None:
                for wkey in members:
                    q.delete(wkey)

    # ------------------------------------------------------------------
    # Workloads
    # ------------------------------------------------------------------

    def _route(self, wl: Workload) -> Optional[ClusterQueueQueue]:
        lq = self.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        if lq is None or lq.stop_policy != StopPolicy.NONE:
            return None
        return self._mgr.cluster_queues.get(lq.cluster_queue)

    def add_or_update_workload(self, wl: Workload) -> bool:
        """reference manager.go AddOrUpdateWorkload / UpdateWorkload: a
        queue-name change removes the entry from the old queue first."""
        with self._lock:
            self._remove_stale_route(wl)
            if wl.is_finished or not wl.is_active or wl.admission is not None:
                # A previously queued workload that became ineligible must
                # leave the queue (reference manager.go UpdateWorkload).
                self.delete_workload(wl)
                return False
            q = self._route(wl)
            if q is None:
                return False
            info = Info(wl, self.info_options)
            q.push_or_update(info)
            lq_key = f"{wl.namespace}/{wl.queue_name}"
            self._lq_members.setdefault(lq_key, set()).add(wl.key)
            self._wl_route[wl.key] = lq_key
            self._cond.notify_all()
            return True

    def add_workloads(self, wls) -> int:
        """Bulk add for the serving ingest drain: one lock acquisition
        for the whole batch (the lock is reentrant, so the per-workload
        path runs unchanged inside it).  Returns how many queued."""
        n = 0
        with self._lock:
            for wl in wls:
                if self.add_or_update_workload(wl):
                    n += 1
        return n

    def _remove_stale_route(self, wl: Workload) -> None:
        old_lq_key = self._wl_route.get(wl.key)
        if old_lq_key is None or old_lq_key == f"{wl.namespace}/{wl.queue_name}":
            return
        members = self._lq_members.get(old_lq_key)
        if members is not None:
            members.discard(wl.key)
        old_lq = self.local_queues.get(old_lq_key)
        if old_lq is not None:
            old_q = self._mgr.cluster_queues.get(old_lq.cluster_queue)
            if old_q is not None:
                old_q.delete(wl.key)
        del self._wl_route[wl.key]

    def requeue_workload(self, info: Info, reason: RequeueReason) -> bool:
        """reference manager.go:404 RequeueWorkload."""
        with self._lock:
            if info.obj.is_finished or not info.obj.is_active or info.obj.admission is not None:
                return False
            q = self._route(info.obj)
            if q is None:
                return False
            inserted = q.requeue_if_not_present(info, reason)
            if inserted:
                self._cond.notify_all()
            return inserted

    def delete_workload(self, wl: Workload) -> None:
        with self._lock:
            # Remove via the recorded route (survives queue_name edits),
            # falling back to the current queue name.
            lq_key = self._wl_route.pop(wl.key, f"{wl.namespace}/{wl.queue_name}")
            members = self._lq_members.get(lq_key)
            if members is not None:
                members.discard(wl.key)
            lq = self.local_queues.get(lq_key)
            if lq is not None:
                q = self._mgr.cluster_queues.get(lq.cluster_queue)
                if q is not None:
                    q.delete(wl.key)

    def qualified_name(self, wl: Workload) -> str:
        return f"{wl.namespace}/{wl.queue_name}"

    # ------------------------------------------------------------------
    # Cohort-wide wakeups — reference manager.go:490
    # ------------------------------------------------------------------

    def queue_inadmissible_workloads(self, cq_names: Iterable[str],
                                     pool=None) -> None:
        """Move parked workloads back for these CQs and everything sharing
        their cohort trees (quota may have freed anywhere in the tree).

        ``pool`` (a ``HostPool``) fans the per-queue unpark passes out
        across workers: each pass touches only that queue's parked set
        and heap, so queues are the natural partition; the gather is in
        sorted-name order so the storm counters and unpark results are
        identical to the serial walk."""
        with self._lock:
            names = set()
            for name in cq_names:
                names.add(name)
                parent = self._mgr.cq_parent(name)
                if parent is not None:
                    for cq_name in (q.name for q in parent.root().subtree_cqs()):
                        names.add(cq_name)
            queues = [q for name in sorted(names)
                      if (q := self._mgr.cluster_queues.get(name)) is not None]
            if pool is not None and pool.active and len(queues) >= 2:
                moved = sum(pool.run(
                    [q.queue_inadmissible_workloads for q in queues]))
            else:
                moved = sum(q.queue_inadmissible_workloads()
                            for q in queues)
            if moved:
                self.requeue_storm_last = moved
                self.requeue_storm_peak = max(self.requeue_storm_peak, moved)
                self.requeue_storms_total += 1
                self.requeue_unparked_total += moved
                self._cond.notify_all()

    def broadcast(self) -> None:
        with self._lock:
            self._cond.notify_all()

    def wake_expired_backoffs(self) -> None:
        """RequeueAfter-timer equivalent: unpark workloads whose requeue
        backoff expired (called per cycle and on daemon ticks).  Scans
        only queues in the armed-timer set — O(armed), not O(all CQs);
        each queue recomputes its own membership after the wake."""
        with self._lock:
            moved = 0
            for name in list(self._timers):
                q = self._mgr.cluster_queues.get(name)
                if q is None:
                    self._timers.discard(name)
                    continue
                moved += q.wake_expired_backoffs()
            if moved:
                self.requeue_unparked_total += moved
                self._cond.notify_all()

    # ------------------------------------------------------------------
    # Heads — reference manager.go:586
    # ------------------------------------------------------------------

    def heads_nonblocking(self) -> list[Info]:
        with self._lock:
            return self._collect_heads()

    def heads(self, timeout: Optional[float] = None) -> list[Info]:
        """Block until at least one head exists (reference manager.go:586).

        The timeout is wall-clock (condition-variable waits are real time
        even when a fake clock drives queue ordering/backoff).
        """
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            while not self.stopped:
                out = self._collect_heads()
                if out:
                    return out
                if deadline is None:
                    self._cond.wait(timeout=1.0)
                    continue
                wait = deadline - _time.monotonic()
                if wait <= 0.0:
                    return []
                self._cond.wait(timeout=wait)
            return []

    def stop(self) -> None:
        with self._lock:
            self.stopped = True
            self._cond.notify_all()

    def _collect_heads(self) -> list[Info]:
        """One head per active CQ with pending entries, O(ready).
        Registration-sequence iteration reproduces the old full-dict
        insertion-order scan exactly (dict insertion order == first-add
        order; deletes + re-adds get a fresh, higher sequence, matching
        the dict's end-append)."""
        out = []
        ready = self._ready
        if not ready:
            return out
        seq = self._reg_seq
        cqs = self._mgr.cluster_queues
        for name in sorted(ready, key=lambda n: seq.get(n, 0)):
            q = cqs.get(name)
            if q is None:
                ready.discard(name)
                continue
            if not q.active:
                continue   # stays ready: reactivation resumes popping
            info = q.pop()
            if info is not None:
                out.append(info)
            if not len(q.heap):
                ready.discard(name)   # lazy removal; pushes re-mark
        return out

    # ------------------------------------------------------------------
    # Introspection / visibility
    # ------------------------------------------------------------------

    def queue_for(self, name: str) -> Optional[ClusterQueueQueue]:
        return self._mgr.cluster_queues.get(name)

    def pending_workloads(self, cq_name: str) -> int:
        with self._lock:
            q = self._mgr.cluster_queues.get(cq_name)
            return q.pending() if q else 0

    def pending_active_workloads(self, cq_name: str) -> int:
        """Heap + inflight only — excludes the inadmissible parking lot
        (workloads there wait on cluster events, not cycles)."""
        with self._lock:
            q = self._mgr.cluster_queues.get(cq_name)
            return q.pending_active() if q else 0

    def pending_workloads_info(self, cq_name: str) -> list[Info]:
        """Sorted pending list for the visibility API (reference
        pkg/visibility pending_workloads_cq.go)."""
        with self._lock:
            q = self._mgr.cluster_queues.get(cq_name)
            if q is None:
                return []
            out = q.snapshot_sorted()
            if q.inflight is not None:
                out.insert(0, q.inflight)
            return out

    def cluster_queue_names(self) -> list[str]:
        return list(self._mgr.cluster_queues)
