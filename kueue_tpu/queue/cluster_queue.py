"""Per-ClusterQueue pending-workload queue.

Capability parity with reference pkg/queue/cluster_queue.go:53: an active
heap ordered by (priority desc, queue-order timestamp asc), an
``inadmissible`` parking lot for BestEffortFIFO, an inflight slot for the
workload currently in a scheduling cycle, and requeue-backoff gating.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

from ..api.types import ConditionStatus, QueueingStrategy, WL_REQUEUED
from ..features import env_value
from ..utils.heap import Heap
from ..workload import Info, Ordering


class RequeueReason(str, enum.Enum):
    FAILED_AFTER_NOMINATION = "FailedAfterNomination"
    PENDING_PREEMPTION = "PendingPreemption"
    NAMESPACE_MISMATCH = "NamespaceMismatch"
    GENERIC = "Generic"


def queue_ordering_less(ordering: Ordering) -> Callable[[Info, Info], bool]:
    """reference cluster_queue.go:408 queueOrderingFunc."""
    def less(a: Info, b: Info) -> bool:
        if a.obj.priority != b.obj.priority:
            return a.obj.priority > b.obj.priority
        ta = ordering.queue_order_timestamp(a.obj)
        tb = ordering.queue_order_timestamp(b.obj)
        if ta != tb:
            return ta < tb
        return a.key < b.key  # deterministic total order on ties
    return less


class ClusterQueueQueue:
    def __init__(self, name: str, strategy: QueueingStrategy,
                 ordering: Ordering, clock: Callable[[], float]):
        self.name = name
        self.queueing_strategy = strategy
        self.ordering = ordering
        self.clock = clock
        # lazy repair defers decision-storm pushes to one settle pass
        # at the next heads read; pop/peek order is identical to eager
        # (strict total order via the key tiebreak, test-enforced)
        self.heap: Heap[Info] = Heap(
            key_fn=lambda i: i.key,
            less=queue_ordering_less(ordering),
            lazy=env_value("KUEUE_TPU_LAZY_HEAP") != "0")
        self.inadmissible: dict[str, Info] = {}
        self.inflight: Optional[Info] = None
        self.pop_cycle = 0
        self.queue_inadmissible_cycle = -1
        self.active = True  # mirrors CQ activeness (stop policies, missing refs)
        # PackJournal shared with the queue manager (set on registration):
        # mutators that can change this CQ's packed burst rows mark it
        # dirty; pop/requeue roundtrips only soft-mark (utils/journal.py)
        self.journal = None
        # Manager-shared index sets (set on registration).  ``ready``
        # holds CQ names whose heap may be non-empty, so per-cycle head
        # collection is O(ready) instead of O(all CQs); ``timers`` holds
        # CQ names with at least one parked entry carrying a live
        # requeue_at, so backoff wakeups scan only armed queues.  Both
        # are conservative over-approximations maintained lazily.
        self.ready = None
        self.timers = None

    def _touch(self) -> None:
        j = self.journal
        if j is not None:
            j.touch(self.name)

    def _mark_ready(self) -> None:
        r = self.ready
        if r is not None:
            r.add(self.name)

    def _note_timer(self, info: Info) -> None:
        t = self.timers
        if t is None:
            return
        rs = info.obj.requeue_state
        if rs is not None and rs.requeue_at is not None:
            t.add(self.name)

    # ------------------------------------------------------------------

    def backoff_waiting_time_expired(self, info: Info) -> bool:
        """reference cluster_queue.go:176."""
        c = info.obj.conditions.get(WL_REQUEUED)
        if c is not None and c.status == ConditionStatus.FALSE:
            return False
        rs = info.obj.requeue_state
        if rs is None or rs.requeue_at is None:
            return True
        return self.clock() >= rs.requeue_at

    def push_or_update(self, info: Info) -> None:
        """reference cluster_queue.go PushOrUpdate (via AddOrUpdateWorkload)."""
        key = info.key
        # even the `same` short-circuit swaps the stored Info for one
        # with equal ordering facts but possibly different gate inputs
        # (admission checks aren't compared) — always a hard touch
        self._touch()
        self._forget_inflight(key)
        old = self.inadmissible.pop(key, None)
        if old is not None:
            same = (old.obj.pod_sets == info.obj.pod_sets
                    and old.obj.priority == info.obj.priority
                    and old.obj.queue_name == info.obj.queue_name
                    and old.obj.active == info.obj.active
                    and old.obj.reclaimable_pods == info.obj.reclaimable_pods
                    and old.obj.conditions.get("Evicted") == info.obj.conditions.get("Evicted")
                    and old.obj.conditions.get(WL_REQUEUED) == info.obj.conditions.get(WL_REQUEUED))
            if same:
                self.inadmissible[key] = info
                self._note_timer(info)
                return
        if self.heap.get(key) is None and not self.backoff_waiting_time_expired(info):
            self.inadmissible[key] = info
            self._note_timer(info)
            return
        self.heap.push_or_update(info)
        self._mark_ready()

    def delete(self, key: str) -> None:
        parked = self.inadmissible.pop(key, None)
        in_heap = self.heap.delete(key)
        if parked is not None or in_heap:
            # only when a tracked row actually left: the manager calls
            # delete unconditionally for every removed workload, and
            # dirtying CQs on finishes of never-queued workloads would
            # defeat the delta pack
            self._touch()
        self._forget_inflight(key)

    def requeue_if_not_present(self, info: Info, reason: RequeueReason) -> bool:
        """reference cluster_queue.go:225,402-406."""
        if self.queueing_strategy == QueueingStrategy.STRICT_FIFO:
            immediate = reason != RequeueReason.NAMESPACE_MISMATCH
        else:
            immediate = reason in (RequeueReason.FAILED_AFTER_NOMINATION,
                                   RequeueReason.PENDING_PREEMPTION)
        return self._requeue_if_not_present(info, immediate)

    def _requeue_if_not_present(self, info: Info, immediate: bool) -> bool:
        key = info.key
        was_inflight = (self.inflight is not None
                        and self.inflight.key == key)
        self._forget_inflight(key)
        pending_flavors = (info.last_assignment is not None
                           and getattr(info.last_assignment, "pending_flavors", False))
        j = self.journal
        if self.backoff_waiting_time_expired(info) and (
                immediate or self.queue_inadmissible_cycle >= self.pop_cycle
                or pending_flavors):
            parked = self.inadmissible.pop(key, None)
            if parked is not None:
                info = parked
            pushed = self.heap.push_if_not_present(info)
            self._mark_ready()
            if parked is not None or (pushed and not was_inflight):
                # unpark or external (re)arrival: packed rows changed
                self._touch()
            elif j is not None:
                # pop -> straight requeue: membership unchanged; only
                # the parked/resume bits could move — soft-verified
                j.note_roundtrip(self.name, key)
            return pushed
        if key in self.inadmissible:
            if j is not None:
                j.note_roundtrip(self.name, key)
            return False
        if self.heap.get(key) is not None:
            if j is not None:
                j.note_roundtrip(self.name, key)
            return False
        self.inadmissible[key] = info
        self._note_timer(info)
        self._touch()
        return True

    def wake_expired_backoffs(self) -> int:
        """Unpark workloads whose requeue backoff just expired — the
        in-process stand-in for the reference's RequeueAfter timers
        (workload_controller.go requeues when the backoff fires).  The
        consumed requeue_at is cleared so the workload isn't re-woken
        every tick if it parks again.  Returns the number of workloads
        moved to the heap (0 = nothing moved, truth-compatible with the
        old bool)."""
        moved = 0
        still: dict[str, Info] = {}
        before = len(self.inadmissible)
        for key, info in self.inadmissible.items():
            rs = info.obj.requeue_state
            if (rs is not None and rs.requeue_at is not None
                    and self.backoff_waiting_time_expired(info)):
                rs.requeue_at = None   # timer fired
                # drop from the parking lot even when already in the heap
                # (mirrors queue_inadmissible_workloads: never track an
                # entry in both structures)
                if self.heap.push_if_not_present(info):
                    moved += 1
                continue
            still[key] = info
        self.inadmissible = still
        if moved or len(still) != before:
            # a cleared requeue_at flips the row from pack-excluded to
            # packed even when the heap already held it (moved 0)
            self._touch()
            self._mark_ready()
        self._retime()
        return moved

    def _retime(self) -> None:
        """Recompute membership in the shared timer set from the parked
        entries that still carry a live requeue_at."""
        t = self.timers
        if t is None:
            return
        for info in self.inadmissible.values():
            rs = info.obj.requeue_state
            if rs is not None and rs.requeue_at is not None:
                t.add(self.name)
                return
        t.discard(self.name)

    def queue_inadmissible_workloads(self) -> int:
        """Move the parking lot back into the heap (reference
        cluster_queue.go QueueInadmissibleWorkloads).  Returns the
        number of workloads moved (0 = nothing, truth-compatible with
        the old bool)."""
        self.queue_inadmissible_cycle = self.pop_cycle
        if not self.inadmissible:
            return 0
        moved = 0
        still_waiting: dict[str, Info] = {}
        before = len(self.inadmissible)
        for key, info in self.inadmissible.items():
            if not self.backoff_waiting_time_expired(info):
                still_waiting[key] = info
                continue
            if self.heap.push_if_not_present(info):
                moved += 1
        self.inadmissible = still_waiting
        if moved or len(still_waiting) != before:
            self._touch()
        if moved:
            self._mark_ready()
        self._retime()
        return moved

    def pop(self) -> Optional[Info]:
        self.pop_cycle += 1
        info = self.heap.pop()
        if info is not None:
            self.inflight = info
        return info

    def _forget_inflight(self, key: str) -> None:
        if self.inflight is not None and self.inflight.key == key:
            self.inflight = None

    # -- introspection --

    def pending_active(self) -> int:
        return len(self.heap) + (1 if self.inflight is not None else 0)

    def pending_inadmissible(self) -> int:
        return len(self.inadmissible)

    def pending(self) -> int:
        return self.pending_active() + self.pending_inadmissible()

    def snapshot_sorted(self) -> list[Info]:
        """Active heap + inadmissible parking lot in queue order, for
        visibility APIs (reference cluster_queue.go Snapshot includes
        inadmissibleWorkloads)."""
        items = self.heap.items() + list(self.inadmissible.values())
        less = queue_ordering_less(self.ordering)
        import functools
        return sorted(items, key=functools.cmp_to_key(
            lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)))
