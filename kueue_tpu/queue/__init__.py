from .cluster_queue import ClusterQueueQueue, RequeueReason  # noqa: F401
from .manager import Manager  # noqa: F401
