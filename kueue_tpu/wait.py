"""Speed-signal scheduling loop (reference pkg/util/wait/backoff.go:19).

``until_with_backoff`` runs ``f`` until the stop event is set.  ``f``
returns a speed signal: KEEP_GOING (True) reruns immediately with zero
backoff; SLOW_DOWN (False) sleeps with exponential backoff from 1 ms
doubling to a 100 ms cap, reset to zero by the next KEEP_GOING — the
reference's speedyBackoffManager semantics.
"""

from __future__ import annotations

import threading
from typing import Callable

KEEP_GOING = True
SLOW_DOWN = False

INITIAL_BACKOFF_S = 0.001
MAX_BACKOFF_S = 0.1


def until_with_backoff(f: Callable[[], bool], stop: threading.Event) -> None:
    """Run ``f`` in a loop until ``stop`` is set, applying the
    speed-signal backoff (UntilWithBackoff, backoff.go:30-44).

    The sleep waits on the stop event, so shutdown interrupts a backoff
    immediately."""
    backoff = 0.0
    while not stop.is_set():
        if f():
            backoff = 0.0
            continue
        backoff = (INITIAL_BACKOFF_S if backoff == 0.0
                   else min(backoff * 2.0, MAX_BACKOFF_S))
        stop.wait(backoff)
