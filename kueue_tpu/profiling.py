"""jax.profiler integration: per-cycle step markers + on-demand traces.

SURVEY §5.1: the reference's observability is zap logging + a pprof flag
on the perf harness; the TPU-native equivalent is a jax.profiler trace
with one StepTraceAnnotation per scheduling cycle, so device dispatches
(admit scans, preemption searches) line up under named cycle steps in
TensorBoard/Perfetto.

Usage: ``start_trace(logdir)`` / ``stop_trace()`` around any driver
activity, or ``cli schedule --profile-dir`` / ``cli serve
--profile-dir`` (traced until SIGTERM).  ``cycle_step`` is a no-op until
a trace is active.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

_active = threading.Event()


def start_trace(logdir: str) -> None:
    """Begin a jax.profiler trace (host + device activity) to logdir."""
    import jax
    jax.profiler.start_trace(logdir)
    _active.set()


def stop_trace() -> None:
    import jax
    if _active.is_set():
        _active.clear()
        jax.profiler.stop_trace()


def trace_active() -> bool:
    return _active.is_set()


@contextlib.contextmanager
def trace(logdir: Optional[str]):
    """start_trace/stop_trace as a context; no-op when logdir is None."""
    if not logdir:
        yield
        return
    start_trace(logdir)
    try:
        yield
    finally:
        stop_trace()


@contextlib.contextmanager
def cycle_step(cycle: int):
    """Mark one scheduling cycle as a profiler step (the step markers
    SURVEY §5.1 names as the TPU equivalent of per-cycle logging)."""
    if not _active.is_set():
        yield
        return
    import jax
    with jax.profiler.StepTraceAnnotation("schedule_cycle",
                                          step_num=cycle):
        yield


@contextlib.contextmanager
def annotation(name: str):
    """Named sub-span (nominate / admit-scan / preemption-search)."""
    if not _active.is_set():
        yield
        return
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield
