"""The quota currency: (flavor, resource) keyed integer quantities.

Capability parity with reference pkg/resources/resource.go + requests.go:
``FlavorResource`` keys and ``FlavorResourceQuantities`` /``Requests`` maps
with add/sub/clone algebra.  All values are canonical integers (milli-units
for cpu, whole units otherwise — see kueue_tpu.api.quantity).
"""

from __future__ import annotations

from typing import Iterable, NamedTuple


class FlavorResource(NamedTuple):
    flavor: str
    resource: str


class Requests(dict):
    """map[resource]→int with algebra (reference pkg/resources/requests.go)."""

    def add(self, other: "Requests | dict[str, int]") -> "Requests":
        for k, v in other.items():
            self[k] = self.get(k, 0) + v
        return self

    def sub(self, other: "Requests | dict[str, int]") -> "Requests":
        for k, v in other.items():
            self[k] = self.get(k, 0) - v
        return self

    def mul(self, factor: int) -> "Requests":
        for k in self:
            self[k] *= factor
        return self

    def clone(self) -> "Requests":
        return Requests(self)

    def count_in(self, capacity: "Requests | dict[str, int]") -> int:
        """How many copies of self fit in capacity (reference requests.go CountIn)."""
        fits = None
        for name, per_unit in self.items():
            if per_unit <= 0:
                continue
            avail = max(0, capacity.get(name, 0))
            n = avail // per_unit
            fits = n if fits is None else min(fits, n)
        return 0 if fits is None else fits


class FlavorResourceQuantities(dict):
    """map[FlavorResource]→int with algebra."""

    def add(self, other: "FlavorResourceQuantities | dict") -> "FlavorResourceQuantities":
        for k, v in other.items():
            self[k] = self.get(k, 0) + v
        return self

    def sub(self, other: "FlavorResourceQuantities | dict") -> "FlavorResourceQuantities":
        for k, v in other.items():
            self[k] = self.get(k, 0) - v
        return self

    def clone(self) -> "FlavorResourceQuantities":
        return FlavorResourceQuantities(self)

    def flavors(self) -> set[str]:
        return {fr.flavor for fr in self}

    def resources(self) -> set[str]:
        return {fr.resource for fr in self}


def sum_requests(items: Iterable[Requests]) -> Requests:
    total = Requests()
    for r in items:
        total.add(r)
    return total
