"""Federation robustness layer: the N-worker-cluster MultiKueue
simulation driven by ``scripts/federation_soak.py`` and the
``tests/test_federation.py`` parity suite."""

from .sim import (
    FederationSim,
    FedSpec,
    VirtualClock,
    full_state,
    global_digest,
    global_state,
    outcome,
    schedule_traffic,
)

__all__ = [
    "FederationSim",
    "FedSpec",
    "VirtualClock",
    "full_state",
    "global_digest",
    "global_state",
    "outcome",
    "schedule_traffic",
]
