"""Federation over real sockets: manager in-process, workers as
``WorkerServer`` child processes.

:class:`ProcFederation` runs the exact :class:`~.sim.FederationSim`
step choreography — ingest, manager cycle, nomination reconcile,
worker cycles, worker finishes, watch pump, winner reconcile, local
finishes, invariants — but every worker interaction crosses a real
TCP socket through :class:`~kueue_tpu.remote.HttpWorkerClient`.  The
r15 machinery this finally exercises honestly: the reconnect circuit
sees actual connection refusals while a worker is down, retry and
deadline budgets burn against real transport faults (optionally
through a :class:`~kueue_tpu.dist.proxy.SocketFaultProxy`), and a
SIGKILLed worker's restart presents a fresh watch epoch whose
``__resync__`` replays the event log from zero over the wire.

Determinism contract: all virtual clocks advance only at lockstep
barriers — the harness POSTs ``/admin/clock`` to every worker right
after advancing its own clock, so condition timestamps land
bit-identical to a :class:`FederationSim` control fed the same
traffic.  Parity is judged by ``state_digest`` on both managers and
on every worker (the control's drivers locally, the processes over
``GET /admin/digest``).
"""

from __future__ import annotations

from typing import Optional

from ..api.types import (
    AdmissionCheck,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    MultiKueueConfig,
    PodSet,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from ..admissionchecks.multikueue import MultiKueueController, WorkerCluster
from ..controller.driver import Driver
from ..remote import ConnectionLost, HttpWorkerClient, WatchLoop
from .sim import VirtualClock


def manager_topology(n_cqs: int, remote_cqs: int, quota_m: int = 8000):
    """The FederationSim manager shape: cohorts of 4, the first
    ``remote_cqs`` ClusterQueues carrying the ``mk`` MultiKueue check."""
    def fn(d):
        d.apply_resource_flavor(ResourceFlavor(name="default"))
        d.apply_admission_check(AdmissionCheck(
            name="mk", controller_name="kueue.x-k8s.io/multikueue"))
        with d.bulk_apply():
            for q in range(n_cqs):
                checks = ("mk",) if q < remote_cqs else ()
                d.apply_cluster_queue(ClusterQueue(
                    name=f"cq-{q}", cohort=f"co-{q // 4}",
                    queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                    preemption=PreemptionPolicy(),
                    admission_checks=list(checks),
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(name="default", resources={
                            "cpu": ResourceQuota(nominal=quota_m)})])]))
                d.apply_local_queue(LocalQueue(
                    name=f"lq-{q}", cluster_queue=f"cq-{q}"))
    return fn


def fed_traffic(steps: int, per_step: int, n_cqs: int,
                runtime_s: int = 2, start_step: int = 1) -> dict[int, list]:
    """Deterministic federation traffic: the same
    ``(key, lq, cpu_m, prio, runtime_s)`` tuples for the process run
    and its in-process control.  Queues round-robin over all manager
    LocalQueues, so the schedule covers both the MultiKueue range and
    the locally-admitted remainder."""
    by_step: dict[int, list] = {}
    idx = 0
    for s in range(start_step, start_step + steps):
        lane = []
        for _ in range(per_step):
            lane.append((f"default/fw-{idx}", f"lq-{idx % n_cqs}",
                         1000, 0, runtime_s))
            idx += 1
        by_step[s] = lane
    return by_step


class ProcFederation:
    """The manager side of a multi-process federation (see module doc).

    ``worker_urls`` maps worker name → base URL — normally the child
    process's bound port, optionally a :class:`SocketFaultProxy` in
    front of it.  The caller owns the worker processes (spawning,
    killing, recovering them); this harness only talks to their
    sockets and keeps its bookkeeping identical to FederationSim's."""

    def __init__(self, worker_urls: dict[str, str], n_cqs: int = 6,
                 remote_cqs: int = 4, manager_quota_m: int = 8000,
                 worker_quota_m: int = 4000, runtime_steps: int = 2,
                 worker_lost_timeout: float = 3.0,
                 reconnect_budget: int = 0,
                 client_timeout: float = 5.0,
                 client_retries: Optional[int] = None,
                 client_deadline_s: Optional[float] = None):
        self.clock = VirtualClock()
        self.step_no = 0
        self.n_cqs = n_cqs
        self.remote_cqs = remote_cqs
        self.runtime_steps = runtime_steps
        self.worker_names = list(worker_urls)
        self.manager = Driver(clock=self.clock)
        manager_topology(n_cqs, remote_cqs, manager_quota_m)(self.manager)
        self.worker_quota_m = worker_quota_m

        self.clients: dict[str, HttpWorkerClient] = {}
        self.clusters: dict[str, WorkerCluster] = {}
        for name, url in worker_urls.items():
            client = HttpWorkerClient(
                url, timeout=client_timeout, retries=client_retries,
                backoff_base=0.02, backoff_max=0.2,
                deadline_s=client_deadline_s)
            self.clients[name] = client
            cluster = WorkerCluster(name=name, client=client,
                                    reconnect_budget=reconnect_budget)
            # pumped at the barrier, never a thread
            cluster.watch = WatchLoop(client, poll_timeout=0.0)
            self.clusters[name] = cluster
        self.config = MultiKueueConfig(name="fed",
                                       clusters=list(worker_urls))
        self.ctl = MultiKueueController(
            self.manager, check_name="mk", config=self.config,
            clusters=self.clusters, origin="fed",
            worker_lost_timeout=worker_lost_timeout)

        self._traffic: dict[int, list] = {}
        self._runtime: dict[str, int] = {}
        self._w_admit_step: dict[str, dict[str, int]] = {
            n: {} for n in self.worker_names}
        self._m_admit_step: dict[str, int] = {}
        self._finished_on: dict[str, set] = {}
        self.ingested = 0
        self.violations: list[dict] = []
        self.counters = {"worker_unreachable": 0, "status_skips": 0}

    # -- traffic -------------------------------------------------------

    def load_traffic(self, by_step: dict[int, list]) -> None:
        self._traffic = dict(by_step)

    def _ingest(self):
        for key, lq, cpu_m, prio, runtime_s in self._traffic.pop(
                self.step_no, []):
            ns, _, name = key.partition("/")
            self.manager.create_workload(Workload(
                name=name, namespace=ns, queue_name=lq, priority=prio,
                creation_time=self.clock(),
                pod_sets=[PodSet(name="main", count=1,
                                 requests={"cpu": cpu_m})]))
            self._runtime[key] = max(1, int(runtime_s))
            self.ingested += 1

    # -- the socket-crossing choreography ------------------------------

    def _push_clock(self):
        """Pin every reachable worker's virtual clock to the manager's
        — first thing after the barrier advance, so every condition a
        worker stamps this step carries the manager's timestamp."""
        for name, client in self.clients.items():
            try:
                client.set_clock(self.clock.t)
            except ConnectionLost:
                self.counters["worker_unreachable"] += 1

    def _step_workers(self):
        for name, client in self.clients.items():
            try:
                client.admin_step()
            except ConnectionLost:
                self.counters["worker_unreachable"] += 1

    def _worker_status(self, name: str) -> Optional[dict]:
        try:
            return self.clients[name].admin_status()
        except ConnectionLost:
            self.counters["status_skips"] += 1
            return None

    def _drive_worker_finishes(self):
        """FederationSim._drive_worker_finishes over the wire: observe
        reservation status via ``/admin/status``, finish the winner's
        job through the public finish endpoint once its modeled
        runtime elapsed."""
        for name in self.worker_names:
            status = self._worker_status(name)
            if status is None:
                continue   # unreachable == dead this step
            seen = self._w_admit_step[name]
            for key, (has_qr, finished) in status.items():
                if has_qr and not finished and key not in seen:
                    seen[key] = self.step_no
            for key in list(seen):
                st = status.get(key)
                if st is None or not st[0]:
                    if st is None or not st[1]:
                        seen.pop(key, None)
                    continue
                if st[1]:
                    continue
                asg = self.ctl.assignments.get(key)
                if asg is None or asg.cluster != name:
                    continue   # only the winner's job executes
                rt = self._runtime.get(key, self.runtime_steps)
                if self.step_no - seen[key] >= rt:
                    try:
                        self.clients[name].finish_workload(
                            key, f"Finished on {name}")
                    except ConnectionLost:
                        self.counters["worker_unreachable"] += 1
                        continue
                    self._finished_on.setdefault(key, set()).add(name)

    def _drive_local_finishes(self):
        seen = self._m_admit_step
        for key, wl in self.manager.workloads.items():
            if "mk" in wl.admission_check_states:
                continue   # remote: finishes arrive via copy-back
            if (wl.has_quota_reservation and not wl.is_finished
                    and key not in seen):
                seen[key] = self.step_no
        for key in list(seen):
            wl = self.manager.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                if wl is None or not wl.is_finished:
                    seen.pop(key, None)
                continue
            if wl.is_finished:
                continue
            rt = self._runtime.get(key, self.runtime_steps)
            if self.step_no - seen[key] >= rt:
                self.manager.finish_workload(key, "Finished locally")

    def _pump_watches(self):
        for cluster in self.clusters.values():
            cluster.watch.pump()

    def _check_invariants(self):
        """Zero-double-admission, judged from live socket status."""
        statuses = {name: self._worker_status(name)
                    for name in self.worker_names}
        for key, asg in self.ctl.assignments.items():
            if not asg.cluster:
                continue
            holders = []
            for name, status in statuses.items():
                if not self.clusters[name].active or status is None:
                    continue
                st = status.get(key)
                if st is not None and st[0] and not st[1]:
                    holders.append(name)
            if len(holders) > 1:
                self.violations.append({
                    "step": self.step_no, "key": key,
                    "kind": "double_admission", "holders": holders})
        for key, names in self._finished_on.items():
            if len(names) > 1:
                self.violations.append({
                    "step": self.step_no, "key": key,
                    "kind": "double_execution",
                    "holders": sorted(names)})
                self._finished_on[key] = {sorted(names)[0]}

    def step(self) -> None:
        self.step_no += 1
        self.clock.t += 1.0
        self._push_clock()
        self._ingest()
        self.manager.schedule_once()
        self.ctl.reconcile()               # nomination
        self._step_workers()
        self._drive_worker_finishes()
        self._pump_watches()
        self.ctl.reconcile()               # winner selection, copy-back
        self._drive_local_finishes()
        self._check_invariants()

    def settled(self) -> bool:
        if self._traffic:
            return False
        return all(wl.is_finished
                   for wl in self.manager.workloads.values())

    def run(self, steps: int, drain_max: int = 200) -> bool:
        for _ in range(steps):
            self.step()
        drained = 0
        while drained < drain_max and not self.settled():
            self.step()
            drained += 1
        return self.settled()

    # -- parity & observability ----------------------------------------

    def digests(self) -> dict:
        """Manager digest locally, each worker's over the socket."""
        from ..remote import state_digest
        out = {"manager": state_digest(self.manager), "workers": {}}
        for name, client in self.clients.items():
            try:
                out["workers"][name] = client.admin_digest()
            except ConnectionLost:
                out["workers"][name] = None
        return out

    def client_stats(self) -> dict:
        return {name: dict(c.stats) for name, c in self.clients.items()}
