"""Seeded N-worker-cluster MultiKueue federation simulation.

A manager Driver spills admissions across N worker Drivers through the
real MultiKueue protocol — ``MultiKueueController`` nomination, watch
streams with resume tokens, winner selection, copy-back — under a
single shared virtual clock, with every delivery pumped at explicit
points so the whole federation is deterministic: same spec + same seed
+ same chaos arming ⇒ bit-identical global state, which is what the
soak's control-arm parity checks ride on.

Time model: one ``step()`` is one virtual second.  Each step ingests
the traffic due, runs one manager scheduling cycle, one cycle per
worker, pumps every watch, and reconciles the controller twice (before
worker cycles: nomination; after: winner selection + copy-back).
Workload execution is modeled the way the reference runs MultiKueue
jobs: mirrors reserve quota on every nominated worker, but only the
*winner's* job executes (managedBy keeps the rest suspended) — the sim
finishes a mirror ``runtime`` steps after its admission only while the
manager's assignment points at that cluster.

Chaos sites consulted inside ``step()`` (see ``chaos/injector.py``):

- ``fed.partition``   — twice per step (step start, and mid-step
  between the watch pump and the second reconcile, which is how a
  partition lands *between* nomination/admission and winner selection);
  payload ``([cluster, ...], duration_steps)``;
- ``fed.cluster_loss`` — once per step (start); payload ``cluster``:
  the cluster is *destroyed* — severed forever, its scheduler stops,
  and its modeled jobs stop executing (a loss is dead machines, not a
  slow link; the partition action is the slow link);
- ``fed.worker_crash`` — once per step (before worker cycles); payload
  ``cluster``: kills that worker mid-admission (its WAL tail holds the
  journaled-but-unapplied op), rebuilds it from store + journal at the
  same virtual instant, and re-runs the interrupted cycle.

Invariants sampled after every step's final reconcile:

- *no double-admission*: for every key with an established assignment,
  at most one ACTIVE cluster holds a quota reservation;
- *exactly-once execution*: no key ever finishes on two workers.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

from ..api.types import (
    AdmissionCheck,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    MultiKueueConfig,
    PodSet,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from ..admissionchecks.multikueue import MultiKueueController, WorkerCluster
from ..chaos import injector as _chaos
from ..chaos.injector import ChaosInjector, InjectedCrash
from ..controller.driver import Driver
from ..remote import ChaosWorkerClient, LocalWorkerClient, WatchLoop
from ..utils.journal import CycleWAL


class VirtualClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


@dataclass
class FedSpec:
    """Deterministic federation shape (same spec ⇒ same topology)."""
    n_workers: int = 4
    n_cqs: int = 1000          # manager CQs; the first remote_cqs carry "mk"
    remote_cqs: int = 250      # mirrored CQ range on every worker
    manager_quota_m: int = 8000
    worker_quota_m: int = 4000
    runtime_steps: int = 2     # modeled execution time per workload
    worker_lost_timeout: float = 3.0
    reconnect_budget: int = 0  # 0 = unlimited half-open probes
    drift_every: int = 0       # 0 disables capacity drift
    drift_factors: tuple = (0.5, 1.0, 1.5)
    seed: int = 0
    use_device_solver: bool = False
    chaos_transport: bool = False   # wrap clients for remote.* faults


def _drift_pick(seed: int, worker: str, epoch: int,
                factors: tuple) -> float:
    """Seeded, order-free drift choice: a pure function of
    (seed, worker, epoch) so both arms of a parity pair agree."""
    import zlib
    h = zlib.crc32(f"{seed}/{worker}/{epoch}".encode())
    return factors[h % len(factors)]


def schedule_traffic(events, n_cqs: int, remote_cqs: int,
                     start_step: int = 1):
    """Quantize a traffic stream's submit events onto sim steps.

    Remote-marked events route into the manager's MultiKueue CQ range
    ``[0, remote_cqs)``; local events into ``[remote_cqs, n_cqs)``.
    Returns ({step: [(key, lq, cpu_m, priority, runtime_s)]}, n_remote).
    """
    by_step: dict[int, list] = {}
    n_remote = 0
    local_span = max(1, n_cqs - remote_cqs)
    for ev in events:
        if ev.kind != "submit":
            continue
        if ev.remote:
            q = ev.cq % max(1, remote_cqs)
            n_remote += 1
        else:
            q = remote_cqs + (ev.cq % local_span)
        step = start_step + int(ev.t)
        by_step.setdefault(step, []).append(
            (ev.key, f"lq-{q}", ev.cpu_m, ev.priority, ev.runtime_s))
    return by_step, n_remote


class FederationSim:
    """The federation under one deterministic clock (see module doc)."""

    def __init__(self, spec: FedSpec, wal_dir: str,
                 config_clusters=None):
        self.spec = spec
        self.clock = VirtualClock()
        self.step_no = 0
        os.makedirs(wal_dir, exist_ok=True)
        self.wal_dir = wal_dir
        self._drift_scale: dict[str, float] = {}
        self._heal_at: dict[str, int] = {}
        self._dead: set[str] = set()
        self._w_admit_step: dict[str, dict[str, int]] = {}
        self._m_admit_step: dict[str, int] = {}
        self._runtime: dict[str, int] = {}
        self._finished_on: dict[str, set] = {}
        self._traffic: dict[int, list] = {}
        self.ingested = 0
        self.violations: list[dict] = []
        self.counters = {"ejections": 0, "worker_crashes": 0,
                         "mid_admit_crashes": 0, "wal_tail_replayed": 0,
                         "partitions": 0, "heals": 0, "losses": 0,
                         "drift_changes": 0}

        names = [f"w{i}" for i in range(spec.n_workers)]
        self.worker_names = names
        self.manager = Driver(clock=self.clock,
                              use_device_solver=spec.use_device_solver)
        self._manager_topology()(self.manager)

        self.workers: dict[str, Driver] = {}
        self.wals: dict[str, CycleWAL] = {}
        self._local: dict[str, LocalWorkerClient] = {}
        self.clusters: dict[str, WorkerCluster] = {}
        for name in names:
            self._drift_scale[name] = 1.0
            self._w_admit_step[name] = {}
            d = Driver(clock=self.clock)
            self._worker_topology(name)(d)
            wal = CycleWAL(os.path.join(wal_dir, f"{name}.wal"))
            d.attach_wal(wal)
            self.workers[name] = d
            self.wals[name] = wal
            raw = LocalWorkerClient(d)
            self._local[name] = raw
            client = (ChaosWorkerClient(raw, backoff_base=0.0,
                                        backoff_max=0.0)
                      if spec.chaos_transport else raw)
            cluster = WorkerCluster(
                name=name, client=client,
                reconnect_budget=spec.reconnect_budget)
            # watches are pumped by the sim, never started as threads
            cluster.watch = WatchLoop(client, poll_timeout=0.0)
            self.clusters[name] = cluster

        self.config = MultiKueueConfig(
            name="fed", clusters=list(config_clusters
                                      if config_clusters is not None
                                      else names))
        self.ctl = MultiKueueController(
            self.manager, check_name="mk", config=self.config,
            clusters=self.clusters, origin="fed",
            worker_lost_timeout=spec.worker_lost_timeout)
        # count re-dispatches without changing controller behavior
        self._orig_reset = self.ctl._reset

        def counting_reset(key):
            self.counters["ejections"] += 1
            self._orig_reset(key)
        self.ctl._reset = counting_reset

    # -- topology ------------------------------------------------------

    def _cq(self, name: str, cohort: str, nominal_m: int,
            checks=()) -> ClusterQueue:
        return ClusterQueue(
            name=name, cohort=cohort,
            queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
            preemption=PreemptionPolicy(),
            admission_checks=list(checks),
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(name="default", resources={
                    "cpu": ResourceQuota(nominal=nominal_m)})])])

    def _manager_topology(self):
        sp = self.spec

        def fn(d):
            d.apply_resource_flavor(ResourceFlavor(name="default"))
            d.apply_admission_check(AdmissionCheck(
                name="mk",
                controller_name="kueue.x-k8s.io/multikueue"))
            with d.bulk_apply():
                for q in range(sp.n_cqs):
                    checks = ("mk",) if q < sp.remote_cqs else ()
                    d.apply_cluster_queue(self._cq(
                        f"cq-{q}", f"co-{q // 4}", sp.manager_quota_m,
                        checks))
                    d.apply_local_queue(LocalQueue(
                        name=f"lq-{q}", cluster_queue=f"cq-{q}"))
        return fn

    def _worker_topology(self, name: str):
        sp = self.spec
        scale = self._drift_scale.get(name, 1.0)

        def fn(d):
            d.apply_resource_flavor(ResourceFlavor(name="default"))
            with d.bulk_apply():
                for q in range(sp.remote_cqs):
                    d.apply_cluster_queue(self._cq(
                        f"cq-{q}", f"co-{q // 4}",
                        int(sp.worker_quota_m * scale)))
                    d.apply_local_queue(LocalQueue(
                        name=f"lq-{q}", cluster_queue=f"cq-{q}"))
        return fn

    def _apply_drift(self):
        sp = self.spec
        if not sp.drift_every or self.step_no % sp.drift_every:
            return
        epoch = self.step_no // sp.drift_every
        for name in self.worker_names:
            scale = _drift_pick(sp.seed, name, epoch, sp.drift_factors)
            if scale == self._drift_scale[name]:
                continue
            self._drift_scale[name] = scale
            self.counters["drift_changes"] += 1
            d = self.workers[name]
            with d.bulk_apply():
                for q in range(sp.remote_cqs):
                    d.apply_cluster_queue(self._cq(
                        f"cq-{q}", f"co-{q // 4}",
                        int(sp.worker_quota_m * scale)))

    # -- traffic -------------------------------------------------------

    def load_traffic(self, by_step: dict[int, list]) -> None:
        self._traffic = dict(by_step)

    def _ingest(self):
        for key, lq, cpu_m, prio, runtime_s in self._traffic.pop(
                self.step_no, []):
            ns, _, name = key.partition("/")
            self.manager.create_workload(Workload(
                name=name, namespace=ns, queue_name=lq, priority=prio,
                creation_time=self.clock(),
                pod_sets=[PodSet(name="main", count=1,
                                 requests={"cpu": cpu_m})]))
            self._runtime[key] = max(1, int(runtime_s))
            self.ingested += 1

    # -- faults --------------------------------------------------------

    def sever(self, name: str) -> None:
        self._local[name].ok = False

    def heal(self, name: str) -> None:
        self._local[name].ok = True

    def _consult_partition(self):
        inj = _chaos.ACTIVE
        if inj is None:
            return
        f = inj.hit("fed.partition")
        if f is not None:
            targets, duration = f.payload
            for name in targets:
                self.sever(name)
                self._heal_at[name] = self.step_no + int(duration)
                self.counters["partitions"] += 1

    def _consult_cluster_loss(self):
        inj = _chaos.ACTIVE
        if inj is None:
            return
        f = inj.hit("fed.cluster_loss")
        if f is not None:
            name = str(f.payload)
            self.sever(name)
            self._dead.add(name)
            self._heal_at.pop(name, None)
            self.counters["losses"] += 1

    def _consult_worker_crash(self):
        inj = _chaos.ACTIVE
        if inj is None:
            return None
        f = inj.hit("fed.worker_crash")
        return None if f is None else str(f.payload)

    def _heal_due(self):
        for name, at in list(self._heal_at.items()):
            if self.step_no >= at:
                self.heal(name)
                del self._heal_at[name]
                self.counters["heals"] += 1

    def _crash_and_recover_worker(self, name: str) -> None:
        """Kill the worker mid-admission — the WAL tail holds the
        journaled-but-unapplied admit — then rebuild it from store +
        journal at the same virtual instant and complete the
        interrupted cycle (the chaos_soak mid-admit protocol, here with
        the manager's watch stream observing the restart: the fresh
        driver's event-log epoch forces a replay-from-zero resync)."""
        old = self.workers[name]
        prev = _chaos.ACTIVE
        scoped = ChaosInjector(seed=self.spec.seed)
        # scoped injector: the manager's own wal.admit hits must not
        # consume this arming
        scoped.arm("wal.admit", at=1)
        _chaos.install(scoped)
        crashed = False
        try:
            old.schedule_once()
        except InjectedCrash:
            crashed = True
            self.counters["mid_admit_crashes"] += 1
        finally:
            if prev is None:
                _chaos.clear()
            else:
                _chaos.install(prev)
        d2 = Driver(clock=self.clock,
                    use_device_solver=False)
        self._worker_topology(name)(d2)
        replayed = d2.recover_from(old.workloads.values(),
                                   self.wals[name])
        self.workers[name] = d2
        self._local[name].driver = d2
        self.clusters[name].driver = d2
        self.counters["worker_crashes"] += 1
        self.counters["wal_tail_replayed"] += replayed
        if crashed:
            d2.schedule_once()   # finish the interrupted cycle

    # -- execution model -----------------------------------------------

    def _drive_worker_finishes(self):
        for name, w in self.workers.items():
            if name in self._dead:
                continue
            seen = self._w_admit_step[name]
            for key, wl in w.workloads.items():
                if (wl.has_quota_reservation and not wl.is_finished
                        and key not in seen):
                    seen[key] = self.step_no
            for key in list(seen):
                wl = w.workloads.get(key)
                if wl is None or not wl.has_quota_reservation:
                    if wl is None or not wl.is_finished:
                        seen.pop(key, None)
                    continue
                if wl.is_finished:
                    continue
                asg = self.ctl.assignments.get(key)
                if asg is None or asg.cluster != name:
                    continue   # only the winner's job executes
                rt = self._runtime.get(key, self.spec.runtime_steps)
                if self.step_no - seen[key] >= rt:
                    w.finish_workload(key, f"Finished on {name}")
                    self._finished_on.setdefault(key, set()).add(name)

    def _drive_local_finishes(self):
        seen = self._m_admit_step
        for key, wl in self.manager.workloads.items():
            if "mk" in wl.admission_check_states:
                continue   # remote: finishes arrive via copy-back
            if (wl.has_quota_reservation and not wl.is_finished
                    and key not in seen):
                seen[key] = self.step_no
        for key in list(seen):
            wl = self.manager.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                if wl is None or not wl.is_finished:
                    seen.pop(key, None)
                continue
            if wl.is_finished:
                continue
            rt = self._runtime.get(key, self.spec.runtime_steps)
            if self.step_no - seen[key] >= rt:
                self.manager.finish_workload(key, "Finished locally")

    # -- invariants ----------------------------------------------------

    def _check_invariants(self):
        for key, asg in self.ctl.assignments.items():
            if not asg.cluster:
                continue
            holders = []
            for name, w in self.workers.items():
                if not self.clusters[name].active:
                    continue
                wl = w.workloads.get(key)
                if (wl is not None and wl.has_quota_reservation
                        and not wl.is_finished):
                    holders.append(name)
            if len(holders) > 1:
                self.violations.append({
                    "step": self.step_no, "key": key,
                    "kind": "double_admission", "holders": holders})
        for key, names in self._finished_on.items():
            if len(names) > 1:
                self.violations.append({
                    "step": self.step_no, "key": key,
                    "kind": "double_execution",
                    "holders": sorted(names)})
                self._finished_on[key] = {sorted(names)[0]}

    # -- the step ------------------------------------------------------

    def _pump_watches(self):
        for cluster in self.clusters.values():
            cluster.watch.pump()

    def step(self) -> None:
        self.step_no += 1
        self.clock.t += 1.0
        self._consult_cluster_loss()
        self._consult_partition()          # consult #1: step start
        self._heal_due()
        self._apply_drift()
        from ..obs.trace import span as _span
        self._ingest()
        self.manager.schedule_once()
        with _span("fed.sync"):
            self.ctl.reconcile()           # nomination
        crash_target = self._consult_worker_crash()
        for name in self.worker_names:
            if name in self._dead:
                continue
            if name == crash_target:
                self._crash_and_recover_worker(name)
            else:
                self.workers[name].schedule_once()
        # finishes land before the pump so a winner's finish is copied
        # back the same virtual second it happens — a cluster destroyed
        # next step can never strand an already-finished result
        self._drive_worker_finishes()
        self._pump_watches()
        self._consult_partition()          # consult #2: mid-step
        with _span("fed.sync"):
            self.ctl.reconcile()           # winner selection, copy-back
        self._drive_local_finishes()
        self._check_invariants()

    def settled(self) -> bool:
        if self._traffic:
            return False
        return all(wl.is_finished
                   for wl in self.manager.workloads.values())

    def run(self, steps: int, drain_max: int = 200) -> bool:
        for _ in range(steps):
            self.step()
        drained = 0
        while drained < drain_max and not self.settled():
            self.step()
            drained += 1
        return self.settled()

    # -- observability -------------------------------------------------

    def assignment_spread(self) -> dict[str, int]:
        """How many finished executions each cluster took (the
        spillover picture capacity drift produces)."""
        spread = {name: 0 for name in self.worker_names}
        for _key, names in self._finished_on.items():
            for name in names:
                spread[name] += 1
        return spread


# ---------------------------------------------------------------------------
# Parity state (the chaos_soak bit-identical bar, federation-wide)
# ---------------------------------------------------------------------------

def full_state(d) -> dict:
    """Every workload's durable status, timestamps included."""
    out = {}
    for key, w in d.workloads.items():
        out[key] = (
            w.is_finished, w.is_active, w.has_quota_reservation,
            None if w.admission is None else (
                w.admission.cluster_queue,
                tuple((a.name, tuple(sorted(a.flavors.items())),
                       tuple(sorted(a.resource_usage.items())), a.count)
                      for a in w.admission.pod_set_assignments)),
            tuple(sorted((c.type, c.status.value, c.reason, c.message,
                          c.last_transition_time)
                         for c in w.conditions.values())),
            tuple(sorted((s.name, s.state.value)
                         for s in w.admission_check_states.values())),
            None if w.requeue_state is None else
            (w.requeue_state.count, w.requeue_state.requeue_at),
        )
    return out


def global_state(sim: FederationSim) -> dict:
    return {"manager": full_state(sim.manager),
            "workers": {name: full_state(w)
                        for name, w in sim.workers.items()}}


def global_digest(sim: FederationSim) -> str:
    g = global_state(sim)
    blob = repr((sorted(g["manager"].items()),
                 sorted((n, sorted(s.items()))
                        for n, s in g["workers"].items()))).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def outcome(sim: FederationSim) -> dict:
    """Placement-free outcome: which manager workloads finished.  The
    cluster-loss scenario compares this (plus the zero-double ledgers)
    instead of the bit-identical digest — losing a cluster necessarily
    shifts eviction conditions and timestamps."""
    return {key: wl.is_finished
            for key, wl in sim.manager.workloads.items()}
