"""Mesh construction and the sharded admission-cycle step.

``sharded_cycle_fn`` jits :func:`kueue_tpu.ops.cycle.solve_cycle` over a
2-D ``(wl, cq)`` mesh with explicit NamedShardings:

- workload tensors (``wl_*``) are sharded over ``wl`` — each chip
  classifies its slice of the pending batch against all flavors;
- quota-node tensors (``usage0``/``subtree``/…, first axis N) and the
  per-CQ flavor machinery (``nominal_cq``/``slot_fr``/…, first axis C) are
  sharded over ``cq`` — the quota plane is distributed and XLA all-gathers
  the slices a workload's CQ lookup needs.

The sequential admit scan (phase 2) carries the usage tensor; GSPMD keeps
it sharded over ``cq`` and reduces the per-step delta with ICI
collectives.  This is the multi-chip story for the north-star scale
(100k workloads × 1k CQs — BASELINE.json): wl for throughput, cq for a
quota plane too big for one chip's HBM.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.cycle import solve_cycle
from ..ops.packing import PackedCycle


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 2-D (wl, cq) mesh over the first ``n_devices`` devices.

    ``n`` is factored as evenly as possible (8 → 4×2, 4 → 2×2, prime
    p → p×1) so both axes exist even on small meshes.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    wl = n
    for cand in range(int(np.sqrt(n)), 0, -1):
        if n % cand == 0:
            wl = n // cand
            break
    cq = n // wl
    dev_array = np.asarray(devices).reshape(wl, cq)
    return Mesh(dev_array, axis_names=("wl", "cq"))


def cycle_args(packed: PackedCycle) -> tuple:
    """Positional args for solve_cycle, in signature order."""
    return (packed.usage0, packed.subtree_quota, packed.guaranteed,
            packed.borrow_cap, packed.has_borrow_limit, packed.parent,
            packed.nominal_cq, packed.slot_fr, packed.slot_valid,
            packed.cq_can_preempt_borrow, packed.wl_cq, packed.wl_requests,
            packed.wl_priority, packed.wl_timestamp)


def cycle_shardings(mesh: Mesh):
    """NamedShardings matching the cycle_args order."""
    node = NamedSharding(mesh, P("cq"))          # [N] / [N, F]
    cqax = NamedSharding(mesh, P("cq"))          # [C, ...]
    wl = NamedSharding(mesh, P("wl"))            # [W] / [W, R]
    rep = NamedSharding(mesh, P())
    return (node, node, node, node, node, rep,   # usage0..has_blim, parent
            cqax, cqax, cqax, cqax,              # nominal_cq..can_preempt
            wl, wl, wl, wl)                      # wl_cq..wl_timestamp


def sharded_cycle_fn(mesh: Mesh, depth: int, run_scan: bool = True):
    """A jitted solve_cycle bound to ``mesh`` with the standard shardings.

    Inputs whose sharded axis is not divisible by the mesh axis are left
    to GSPMD's uneven-sharding support; callers should still prefer
    bucket-padded shapes (the packer pads W) to keep layouts tight.
    """
    in_shardings = cycle_shardings(mesh)

    def step(*args):
        return solve_cycle(*args, depth=depth, run_scan=run_scan)

    return jax.jit(step, in_shardings=in_shardings)


# ---------------------------------------------------------------------------
# Production admit-scan sharding (CycleSolver.set_mesh routing)
# ---------------------------------------------------------------------------

def admit_scan_fns(mesh: Mesh, depth: int):
    """Factory for mesh-bound jitted variants of the production admit
    scans (ops.cycle.admit_scan{,_forests,_preempt}) with the standard
    shardings: quota plane over ``cq``, per-head tensors over ``wl``,
    the preemption-target universe replicated (targets are shared state
    every step may touch).  Returns {name: fn} with the same positional
    signatures as the unsharded kernels (statics bound per call via the
    ``forests``/``preempt`` wrappers)."""
    from ..ops.cycle import admit_scan, admit_scan_forests, admit_scan_preempt

    node = NamedSharding(mesh, P("cq"))
    rep = NamedSharding(mesh, P())
    wl = NamedSharding(mesh, P("wl"))
    # admit_scan(usage0, subtree, guaranteed, borrow_cap, has_blim,
    #            parent, nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt,
    #            fit_mask, res_fr, res_amt, res_mask, res_borrows, order)
    base = (node, node, node, node, node, rep, node, node,
            wl, wl, wl, wl, wl, wl, wl, wl)

    flat = jax.jit(lambda *a: admit_scan(*a, depth=depth),
                   in_shardings=base + (wl,))

    forest_cache: dict = {}

    def forests(*args, forest_of_node, n_forests, max_forest_wl):
        key = (n_forests, max_forest_wl)
        fn = forest_cache.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: admit_scan_forests(
                    *a, depth=depth, n_forests=n_forests,
                    max_forest_wl=max_forest_wl),
                in_shardings=base + (wl, rep))
            forest_cache[key] = fn
        return fn(*args, forest_of_node)

    preempt = jax.jit(
        lambda *a: admit_scan_preempt(*a, depth=depth),
        in_shardings=base + (wl, wl, wl, wl, rep, rep, wl))

    return {"flat": flat, "forest": forests, "preempt": preempt}


# ---------------------------------------------------------------------------
# Multi-host (DCN) mesh layout
# ---------------------------------------------------------------------------

def make_hybrid_mesh(n_hosts: int | None = None, devices=None) -> Mesh:
    """A two-tier (wl, cq) mesh laid out so collective traffic matches
    the interconnect hierarchy (the DCN story for SURVEY §5.8; reference
    analog: MultiKueue spreading managers across clusters).

    The admit scan's carried usage tensor triggers per-step collectives
    on the ``cq`` axis, so that axis is pinned WITHIN a host — its
    reduce/gather traffic rides ICI.  The ``wl`` axis needs one
    all-gather per cycle (head slices back to the scan), so it is the
    axis that spans hosts over DCN: slow-link traffic is paid once per
    cycle, not once per scan step.  This mirrors the scaling-book recipe
    of mapping the highest-frequency collective to the fastest axis.

    On a real multi-host platform hosts are discovered from
    ``device.process_index``; ``n_hosts`` partitions a single-process
    (or virtual CPU) device list into equal groups for testing the
    layout without multi-host hardware.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n_hosts is None:
        by_host: dict[int, list] = {}
        for d in devices:
            by_host.setdefault(getattr(d, "process_index", 0), []).append(d)
        groups = [by_host[k] for k in sorted(by_host)]
    else:
        if n % n_hosts:
            raise ValueError(f"{n} devices do not split into {n_hosts} hosts")
        per = n // n_hosts
        groups = [list(devices[i * per:(i + 1) * per])
                  for i in range(n_hosts)]
    local = len(groups[0])
    if any(len(g) != local for g in groups):
        raise ValueError("hosts expose unequal device counts")
    # cq axis = one whole host (the quota plane and its per-step
    # collectives live entirely on that host's ICI); wl axis = hosts
    dev_array = np.asarray(
        [np.asarray(g) for g in groups])          # [hosts, local]
    return Mesh(dev_array, axis_names=("wl", "cq"))
