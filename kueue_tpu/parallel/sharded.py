"""Mesh construction and the sharded admission-cycle step.

``sharded_cycle_fn`` jits :func:`kueue_tpu.ops.cycle.solve_cycle` over a
2-D ``(wl, cq)`` mesh with explicit NamedShardings:

- workload tensors (``wl_*``) are sharded over ``wl`` — each chip
  classifies its slice of the pending batch against all flavors;
- quota-node tensors (``usage0``/``subtree``/…, first axis N) and the
  per-CQ flavor machinery (``nominal_cq``/``slot_fr``/…, first axis C) are
  sharded over ``cq`` — the quota plane is distributed and XLA all-gathers
  the slices a workload's CQ lookup needs.

The sequential admit scan (phase 2) carries the usage tensor; GSPMD keeps
it sharded over ``cq`` and reduces the per-step delta with ICI
collectives.  This is the multi-chip story for the north-star scale
(100k workloads × 1k CQs — BASELINE.json): wl for throughput, cq for a
quota plane too big for one chip's HBM.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.cycle import solve_cycle
from ..ops.packing import PackedCycle


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 2-D (wl, cq) mesh over the first ``n_devices`` devices.

    ``n`` is factored as evenly as possible (8 → 4×2, 4 → 2×2, prime
    p → p×1) so both axes exist even on small meshes.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    wl = n
    for cand in range(int(np.sqrt(n)), 0, -1):
        if n % cand == 0:
            wl = n // cand
            break
    cq = n // wl
    dev_array = np.asarray(devices).reshape(wl, cq)
    return Mesh(dev_array, axis_names=("wl", "cq"))


def cycle_args(packed: PackedCycle) -> tuple:
    """Positional args for solve_cycle, in signature order."""
    return (packed.usage0, packed.subtree_quota, packed.guaranteed,
            packed.borrow_cap, packed.has_borrow_limit, packed.parent,
            packed.nominal_cq, packed.slot_fr, packed.slot_valid,
            packed.cq_can_preempt_borrow, packed.wl_cq, packed.wl_requests,
            packed.wl_priority, packed.wl_timestamp)


def cycle_shardings(mesh: Mesh):
    """NamedShardings matching the cycle_args order."""
    node = NamedSharding(mesh, P("cq"))          # [N] / [N, F]
    cqax = NamedSharding(mesh, P("cq"))          # [C, ...]
    wl = NamedSharding(mesh, P("wl"))            # [W] / [W, R]
    rep = NamedSharding(mesh, P())
    return (node, node, node, node, node, rep,   # usage0..has_blim, parent
            cqax, cqax, cqax, cqax,              # nominal_cq..can_preempt
            wl, wl, wl, wl)                      # wl_cq..wl_timestamp


def sharded_cycle_fn(mesh: Mesh, depth: int, run_scan: bool = True):
    """A jitted solve_cycle bound to ``mesh`` with the standard shardings.

    Inputs whose sharded axis is not divisible by the mesh axis are left
    to GSPMD's uneven-sharding support; callers should still prefer
    bucket-padded shapes (the packer pads W) to keep layouts tight.
    """
    in_shardings = cycle_shardings(mesh)

    def step(*args):
        return solve_cycle(*args, depth=depth, run_scan=run_scan)

    return jax.jit(step, in_shardings=in_shardings)


# ---------------------------------------------------------------------------
# Production admit-scan sharding (CycleSolver.set_mesh routing)
# ---------------------------------------------------------------------------

def admit_scan_fns(mesh: Mesh, depth: int):
    """Factory for mesh-bound jitted variants of the production admit
    scans (ops.cycle.admit_scan{,_forests,_preempt}) with the standard
    shardings: quota plane over ``cq``, per-head tensors over ``wl``,
    the preemption-target universe replicated (targets are shared state
    every step may touch).  Returns {name: fn} with the same positional
    signatures as the unsharded kernels (statics bound per call via the
    ``forests``/``preempt`` wrappers)."""
    from ..ops.cycle import admit_scan, admit_scan_forests, admit_scan_preempt

    node = NamedSharding(mesh, P("cq"))
    rep = NamedSharding(mesh, P())
    wl = NamedSharding(mesh, P("wl"))
    # admit_scan(usage0, subtree, guaranteed, borrow_cap, has_blim,
    #            parent, nominal_cq, npb_cq, wl_cq, dec_fr, dec_amt,
    #            fit_mask, res_fr, res_amt, res_mask, res_borrows, order)
    base = (node, node, node, node, node, rep, node, node,
            wl, wl, wl, wl, wl, wl, wl, wl)

    flat = jax.jit(lambda *a: admit_scan(*a, depth=depth),
                   in_shardings=base + (wl,))

    forest_cache: dict = {}

    def forests(*args, forest_of_node, n_forests, max_forest_wl):
        key = (n_forests, max_forest_wl)
        fn = forest_cache.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: admit_scan_forests(
                    *a, depth=depth, n_forests=n_forests,
                    max_forest_wl=max_forest_wl),
                in_shardings=base + (wl, rep))
            forest_cache[key] = fn
        return fn(*args, forest_of_node)

    preempt = jax.jit(
        lambda *a: admit_scan_preempt(*a, depth=depth),
        in_shardings=base + (wl, wl, wl, wl, rep, rep, wl))

    return {"flat": flat, "forest": forests, "preempt": preempt}


# ---------------------------------------------------------------------------
# Sharded fair-sharing tournament (CycleSolver.set_mesh routing)
# ---------------------------------------------------------------------------

def fs_scan_fn(mesh: Mesh, depth: int, n_levels: int):
    """A mesh-bound jitted fs_admit_scan with the standard shardings:
    quota-plane node tensors over ``cq``, per-head entry tensors over
    ``wl``, the tree-walk tables (parent/node_level/weights/child_order,
    gathered at every tournament level) replicated.  GSPMD partitions
    the SAME program the serial path jits — the W sequential rounds,
    the argmax winner selection, and every integer DRS division are
    unchanged — so decisions are bit-identical by construction."""
    from ..ops.fs_scan import fs_admit_scan

    node = NamedSharding(mesh, P("cq"))
    rep = NamedSharding(mesh, P())
    wl = NamedSharding(mesh, P("wl"))
    # fs_admit_scan(usage0, subtree, sq_mask, guaranteed, borrow_cap,
    #               has_blim, parent, node_level, weights, lendable_r,
    #               onehot, child_order, wl_cq, u_e, nofit, prio,
    #               ts_rank, valid)
    in_shardings = (node, node, node, node, node, node,
                    rep, rep, rep, node, rep, rep,
                    wl, wl, wl, wl, wl, wl)
    jf = jax.jit(
        lambda *a: fs_admit_scan(*a, depth=depth, n_levels=n_levels),
        in_shardings=in_shardings)
    n_cq = int(mesh.shape["cq"])
    n_wl = int(mesh.shape["wl"])

    def call(usage0, subtree, sq_mask, guaranteed, borrow_cap, has_blim,
             parent, node_level, weights, lendable_r, onehot,
             child_order, wl_cq, u_e, nofit, prio, ts_rank, valid):
        # GSPMD needs sharded dims divisible by their axis; pad nodes
        # to inert rows (parent -1, zero quota, never on any entry's
        # path) and heads to invalid rows (valid False, so they are
        # never `remaining` and the extra rounds yield winner -1),
        # then slice decisions back to the real head count
        N, W = usage0.shape[0], wl_cq.shape[0]
        Np = -(-N // n_cq) * n_cq
        Wp = -(-W // n_wl) * n_wl

        def pad(a, n, fill):
            return np.concatenate(
                [a, np.full((n - a.shape[0],) + a.shape[1:], fill,
                            a.dtype)]) if n != a.shape[0] else a

        args = (pad(usage0, Np, 0), pad(subtree, Np, 0),
                pad(sq_mask, Np, False), pad(guaranteed, Np, 0),
                pad(borrow_cap, Np, 0), pad(has_blim, Np, False),
                pad(parent, Np, -1), pad(node_level, Np, 0),
                pad(weights, Np, 1), pad(lendable_r, Np, 0),
                onehot, pad(child_order, Np, 0),
                pad(wl_cq, Wp, -1), pad(u_e, Wp, 0),
                pad(nofit, Wp, True), pad(prio, Wp, 0),
                pad(ts_rank, Wp, 0), pad(valid, Wp, False))
        order, admitted, processed = jf(*args)
        if Wp != W:
            # winners fill rounds 0..n_valid-1 (< W); the padded tail
            # is all -1, so the slice loses nothing
            order, admitted, processed = (
                order[:W], admitted[:W], processed[:W])
        return order, admitted, processed

    return call


# ---------------------------------------------------------------------------
# Sharded fused-burst dispatch (BurstSolver.set_shards routing)
# ---------------------------------------------------------------------------

def make_burst_mesh(n_devices: int):
    """A 1-D ``("cq",)`` mesh for the forest-partitioned burst kernel,
    or None when fewer than ``n_devices`` devices exist (the caller
    degrades to the serial path)."""
    if n_devices is None or n_devices < 2:
        return None
    devices = jax.devices()
    if len(devices) < n_devices:
        return None
    return Mesh(np.asarray(devices[:n_devices]), axis_names=("cq",))


_I32_MAX = np.int32(2**31 - 1)

# pad fills per kernel input: a padded CQ row must never grow a head
# (wl_rank=INF), never hold quota, and never enter any forest's member
# or candidate tables — everything else about it is then inert
_C_FILLS = {
    "wl_req": 0, "wl_rank": _I32_MAX, "wl_cycle_rank": 0, "wl_prio": 0,
    "wl_uidrank": 0, "vec_ok": False,
    "elig0": False, "parked0": False, "resume0": 0, "adm0": False,
    "adm_seq0": 0, "adm_usage0": 0, "adm_uses0": False,
    "death0": _I32_MAX, "u_cq0": 0,
    "nominal_cq": 0, "npb_cq": 0, "slot_fr": -1, "slot_valid": False,
    "cq_can_preempt_borrow": False, "strict_cq": False,
    "cq_wcb_borrow": True, "cq_wcp_preempt": False,
    "wcq_lower": False, "rwc_enabled": False, "rwc_only_lower": False,
    "preempt_ok": False, "self_lmem": 0,
}
_N_FILLS = {
    "potential0": 0, "subtree": 0, "guaranteed": 0, "borrow_cap": 0,
    "has_blim": False,
}
_STATE_FILLS = (False, False, 0, False, 0, 0, False, _I32_MAX, 0)
_STATE_NAMES = ("elig0", "parked0", "resume0", "adm0", "adm_seq0",
                "adm_usage0", "adm_uses0", "death0", "u_cq0")

# Residency tiers for the shard-resident boundary (BurstSolver keeps the
# permuted kernel inputs on the mesh between windows; only the tier that
# actually changed crosses the host→device boundary at a fresh pack):
#
# - STATIC:  pure functions of (structure generation, M, KC) — the
#   layout's value-remapped tables plus the quota plane and per-CQ
#   structure facts.  Permuted + uploaded once per layout lifetime.
# - SCATTER: per-record row facts.  The delta pack re-walks only
#   journal-dirty CQs and splices every other record verbatim
#   (_concat_row_fields), so for a chained delta pack these planes are
#   bit-identical outside the dirty rows — only those rows scatter.
# - GLOBAL:  globally recomputed each pack — dense cross-CQ ranks
#   (cycle/uid), the reservation-seq plane, and the modeling envelope
#   (preempt_ok depends on global scalars).  Always re-uploaded; all
#   are small relative to the row tier.
_ROW_STATIC = ("nominal_cq", "npb_cq", "slot_fr", "slot_valid",
               "cq_can_preempt_borrow", "cq_wcb_borrow",
               "cq_wcp_preempt", "wcq_lower", "rwc_enabled",
               "rwc_only_lower", "self_lmem")
SCATTER_PLANES = ("wl_req", "wl_rank", "wl_prio", "vec_ok", "strict_cq",
                  "elig0", "parked0", "resume0", "adm0", "adm_usage0",
                  "adm_uses0", "death0", "u_cq0")
GLOBAL_PLANES = ("wl_cycle_rank", "wl_uidrank", "adm_seq0", "preempt_ok")


class BurstShardLayout:
    """Forest-partition of a burst plan across a 1-D ``cq`` mesh.

    Cohort forests are the fused kernel's independence boundary: every
    comparison it makes (heads argmin, candidate ordering, the
    entryOrdering sort, the admit scan's lanes) stays inside one forest,
    and all ordering keys are host-precomputed GLOBAL ranks carried by
    value — so partitioning whole forests onto shards, with the dirty
    reduction as a psum, reproduces the serial decisions bit-for-bit.

    The layout assigns forests to shards greedily onto the least-loaded
    shard — by CQ count, or by measured per-forest cycle cost when the
    solver has an EWMA from prior windows (``forest_cost``; assignment
    never affects decisions, every rank is carried by value).  It gives
    every shard equally padded
    local index spaces (Cs CQ slots, Gs forest rows, Ns = Cs + Hs quota
    nodes with CQ nodes first — the kernel's ``usage[:C]`` convention),
    and VALUE-REMAPS the member/candidate tables into local ids at
    identical slot positions, so ``tgt_words`` bit j still means global
    candidate slot j and the driver's apply path is untouched."""

    def __init__(self, plan, n_shards: int, forest_cost=None):
        a = plan.arrays
        st = plan.structure
        C, M, G, L, KC = plan.C, plan.M, plan.G, plan.L, plan.KC
        S = int(n_shards)
        self.n_shards = S
        self.M = M
        self._static_dev = None   # device-resident statics (solver tier)
        forest_of_cq = np.asarray(a["forest_of_cq"])
        parent = np.asarray(a["parent"])
        node_level = np.asarray(a["node_level"])
        members = np.asarray(a["members"])
        cand_rows = np.asarray(a["cand_rows"])
        cand_lmem = np.asarray(a["cand_lmem"])
        N = parent.shape[0]
        forest_of_node = np.asarray(st.forest_of_node)

        # greedy LPT: big forests first onto the least-loaded shard.
        # "Big" is CQ count by default; with a measured per-forest cycle
        # cost (EWMA of decided heads per window) the cost is the load,
        # with a small size term so never-fired forests still spread.
        counts = np.bincount(forest_of_cq, minlength=G)
        if forest_cost is not None and len(forest_cost) == G:
            weight = (np.asarray(forest_cost, dtype=np.float64)
                      + 1e-6 * counts)
            self.cost_balanced = True
        else:
            weight = counts.astype(np.float64)
            self.cost_balanced = False
        load = [0.0] * S
        forests_of: list[list[int]] = [[] for _ in range(S)]
        for g in sorted(range(G), key=lambda g: (-float(weight[g]), g)):
            s = min(range(S), key=lambda i: (load[i], i))
            forests_of[s].append(g)
            load[s] += float(weight[g])
        self.shard_cost = [round(x, 6) for x in load]
        mean_load = sum(load) / max(1, S)
        self.cost_ratio = (round(max(load) / mean_load, 4)
                           if mean_load > 0 else 1.0)
        for fl in forests_of:
            fl.sort()
        shard_of_forest = np.zeros(max(G, 1), dtype=np.int32)
        local_forest = np.zeros(max(G, 1), dtype=np.int32)
        for s, fl in enumerate(forests_of):
            for j, g in enumerate(fl):
                shard_of_forest[g] = s
                local_forest[g] = j

        cqs_of: list[list[int]] = [[] for _ in range(S)]
        for s, fl in enumerate(forests_of):
            for g in fl:
                for cq in members[g]:
                    if cq >= 0:
                        cqs_of[s].append(int(cq))
        cohorts_of: list[list[int]] = [[] for _ in range(S)]
        for nd in range(C, N):
            f = int(forest_of_node[nd])
            s = int(shard_of_forest[f]) if 0 <= f < G else 0
            cohorts_of[s].append(nd)

        Cs = max(1, max(len(x) for x in cqs_of))
        Gs = max(1, max(len(x) for x in forests_of))
        Hs = max(len(x) for x in cohorts_of)
        self.Cs, self.Gs, self.Ns = Cs, Gs, Cs + Hs
        Ns = self.Ns

        cq_perm = np.full((S, Cs), -1, dtype=np.int32)
        cq_pos = np.zeros(C, dtype=np.int64)
        local_cq = np.zeros(C, dtype=np.int32)
        for s, cqs in enumerate(cqs_of):
            for j, cq in enumerate(cqs):
                cq_perm[s, j] = cq
                cq_pos[cq] = s * Cs + j
                local_cq[cq] = j
        node_perm = np.full((S, Ns), -1, dtype=np.int32)
        node_perm[:, :Cs] = cq_perm
        local_node = np.zeros(N, dtype=np.int32)
        local_node[:C] = local_cq
        for s, cohs in enumerate(cohorts_of):
            for j, nd in enumerate(cohs):
                node_perm[s, Cs + j] = nd
                local_node[nd] = Cs + j
        forest_perm = np.full((S, Gs), -1, dtype=np.int32)
        for s, fl in enumerate(forests_of):
            for j, g in enumerate(fl):
                forest_perm[s, j] = g
        self.cq_perm = cq_perm
        self.cq_pos = cq_pos
        self.node_perm = node_perm
        self.forest_perm = forest_perm

        # value-remapped static tables (slot positions preserved)
        members_l = np.full((S * Gs, L), -1, dtype=np.int32)
        cand_rows_l = np.full((S * Gs, KC), -1, dtype=np.int32)
        cand_lmem_l = np.zeros((S * Gs, KC), dtype=np.int32)
        for s, fl in enumerate(forests_of):
            for j, g in enumerate(fl):
                r = s * Gs + j
                mrow = members[g]
                mv = mrow >= 0
                members_l[r][mv] = local_cq[mrow[mv]]
                crow = cand_rows[g]
                cv = crow >= 0
                crs = crow[cv]
                cand_rows_l[r][cv] = (local_cq[crs // M] * M
                                      + crs % M).astype(np.int32)
                cand_lmem_l[r] = cand_lmem[g]
        parent_l = np.full(S * Ns, -1, dtype=np.int32)
        node_level_l = np.zeros(S * Ns, dtype=np.int32)
        flat_nodes = node_perm.ravel()
        nv = flat_nodes >= 0
        pv = parent[flat_nodes[nv]]
        parent_l[nv] = np.where(pv >= 0, local_node[np.maximum(pv, 0)],
                                -1).astype(np.int32)
        node_level_l[nv] = node_level[flat_nodes[nv]]
        forest_of_cq_l = np.zeros(S * Cs, dtype=np.int32)
        fc = cq_perm.ravel()
        cvv = fc >= 0
        forest_of_cq_l[cvv] = local_forest[forest_of_cq[fc[cvv]]]
        self._static = {
            "members": members_l, "cand_rows": cand_rows_l,
            "cand_lmem": cand_lmem_l, "parent": parent_l,
            "node_level": node_level_l, "forest_of_cq": forest_of_cq_l,
        }

    # -- per-shard-timed permutation helpers ---------------------------
    def _permute(self, src, perm, fill, timers):
        import time as _time
        S, B = perm.shape
        out = np.full((S * B,) + src.shape[1:], fill, dtype=src.dtype)
        for s in range(S):
            t0 = _time.perf_counter()
            row = perm[s]
            v = row >= 0
            out[s * B:(s + 1) * B][v] = src[row[v]]
            if timers is not None and s < len(timers):
                timers[s] += _time.perf_counter() - t0
        return out

    def permute_rows(self, arr, fill=0, timers=None):
        """[C, ...] → [S*Cs, ...] (pad rows filled)."""
        return self._permute(np.asarray(arr), self.cq_perm, fill, timers)

    def permute_nodes(self, arr, fill=0, timers=None):
        """[N, ...] → [S*Ns, ...] (CQ nodes first per shard)."""
        return self._permute(np.asarray(arr), self.node_perm, fill,
                             timers)

    def permute_state(self, state, timers=None):
        """The 9-tuple of scan-state arrays, global → shard layout."""
        return tuple(
            self.permute_rows(arr, fill, timers)
            for arr, fill in zip(state, _STATE_FILLS))

    def permute_ext(self, ext_release, ext_unpark):
        """Event schedules [K, C, F] / [K, G] → shard layout on axis 1."""
        def ax1(arr, perm, fill):
            flat = perm.ravel()
            out = np.full((arr.shape[0], flat.size) + arr.shape[2:],
                          fill, dtype=arr.dtype)
            v = flat >= 0
            out[:, v] = arr[:, flat[v]]
            return out
        return (ax1(np.asarray(ext_release), self.cq_perm, 0),
                ax1(np.asarray(ext_unpark), self.forest_perm, False))

    def static_arrays(self, plan, timers=None):
        """The permuted STATIC-tier planes: the value-remapped layout
        tables plus every input that is a pure function of (structure
        generation, M, KC).  Cached on the layout — valid for its whole
        lifetime, which is exactly one (generation, C, M, G, L, KC)."""
        cached = getattr(self, "_static_host", None)
        if cached is not None:
            return cached
        a = plan.arrays
        out = dict(self._static)
        for name in _ROW_STATIC:
            out[name] = self.permute_rows(a[name], _C_FILLS[name], timers)
        for name, fill in _N_FILLS.items():
            out[name] = self.permute_nodes(a[name], fill, timers)
        self._static_host = out
        return out

    def plan_arrays(self, plan, timers=None):
        """The permuted kernel-input dict for ``plan``, cached on the
        plan object (chained windows reuse it untouched).  Scan-state
        planes flow through permute_state, not this dict."""
        cached = getattr(plan, "_shard_arrays", None)
        if cached is not None and cached[0] is self:
            return cached[1]
        a = plan.arrays
        out = dict(self.static_arrays(plan, timers))
        for name in SCATTER_PLANES + GLOBAL_PLANES:
            if name in _STATE_NAMES:
                continue   # scan state flows through permute_state
            out[name] = self.permute_rows(a[name], _C_FILLS[name], timers)
        plan._shard_arrays = (self, out)
        return out


def sharded_burst_fn(mesh: Mesh, *, K: int, depth: int, L: int, S: int,
                     KC: int, n_levels: int, G: int, runtime: int):
    """shard_map-wrapped fused burst kernel over the 1-D ``cq`` axis.

    Every input whose leading axis is CQ-, node- or forest-indexed is
    split across shards; the event schedules split on axis 1; seq_base
    is replicated.  The per-cycle decision planes come back concatenated
    on the CQ axis, the dirty flags replicated (the kernel psums them),
    and the final carry stays sharded on device for window chaining."""
    from functools import partial as _partial
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # pragma: no cover - newer JAX moved it
        from jax.shard_map import shard_map
    from ..ops.burst import _burst_cycles

    row = P("cq")
    rep = P()
    kc = P(None, "cq")
    in_specs = (row,) * 14 + (rep,) + (row,) * 25 + (kc, kc)
    out_specs = (kc, kc, kc, kc, kc, rep, rep, (row,) * 9)
    body = _partial(_burst_cycles, K=K, depth=depth, L=L, S=S, KC=KC,
                    n_levels=n_levels, G=G, runtime=runtime,
                    axis_name="cq")
    return jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False))

def make_hybrid_mesh(n_hosts: int | None = None, devices=None) -> Mesh:
    """A two-tier (wl, cq) mesh laid out so collective traffic matches
    the interconnect hierarchy (the DCN story for SURVEY §5.8; reference
    analog: MultiKueue spreading managers across clusters).

    The admit scan's carried usage tensor triggers per-step collectives
    on the ``cq`` axis, so that axis is pinned WITHIN a host — its
    reduce/gather traffic rides ICI.  The ``wl`` axis needs one
    all-gather per cycle (head slices back to the scan), so it is the
    axis that spans hosts over DCN: slow-link traffic is paid once per
    cycle, not once per scan step.  This mirrors the scaling-book recipe
    of mapping the highest-frequency collective to the fastest axis.

    On a real multi-host platform hosts are discovered from
    ``device.process_index``; ``n_hosts`` partitions a single-process
    (or virtual CPU) device list into equal groups for testing the
    layout without multi-host hardware.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n_hosts is None:
        by_host: dict[int, list] = {}
        for d in devices:
            by_host.setdefault(getattr(d, "process_index", 0), []).append(d)
        groups = [by_host[k] for k in sorted(by_host)]
    else:
        if n % n_hosts:
            raise ValueError(f"{n} devices do not split into {n_hosts} hosts")
        per = n // n_hosts
        groups = [list(devices[i * per:(i + 1) * per])
                  for i in range(n_hosts)]
    local = len(groups[0])
    if any(len(g) != local for g in groups):
        raise ValueError("hosts expose unequal device counts")
    # cq axis = one whole host (the quota plane and its per-step
    # collectives live entirely on that host's ICI); wl axis = hosts
    dev_array = np.asarray(
        [np.asarray(g) for g in groups])          # [hosts, local]
    return Mesh(dev_array, axis_names=("wl", "cq"))
