"""Multi-chip parallelism: device meshes and sharded cycle solving.

The admission cycle is SPMD over two axes (SURVEY §2.5, §7):

- ``wl``  — the pending-workload batch axis (data-parallel analog): the
  phase-1 nominate/classify pass is embarrassingly parallel over heads.
- ``cq``  — the quota plane (ClusterQueue/cohort node axis, model-parallel
  analog): quota/usage tensors are sharded over nodes; XLA inserts the
  gather collectives where a workload reads a remote CQ's availability.

There is no NCCL/MPI here by design: collectives are XLA's, riding ICI
within a host; across hosts, :func:`make_hybrid_mesh` lays the mesh out
so only the once-per-cycle ``wl`` gather crosses DCN while the per-step
``cq`` collectives stay on ICI (reference equivalent: the API-server
watch fabric, SURVEY §5.8).
"""

from .sharded import (cycle_args, make_hybrid_mesh, make_mesh,
                      sharded_cycle_fn)

__all__ = ["cycle_args", "make_hybrid_mesh", "make_mesh",
           "sharded_cycle_fn"]
