"""Long-lived admission service wrapping ``Driver``.

The batch harnesses feed a pre-built event list through
``Driver.schedule_once`` in one thread with virtual time; this module
is the production shape of the same loop: concurrent submitters, a
durable ingest journal, wall-clock SLOs, overload backpressure,
graceful drain, and crash-restart continuity.

Data path
---------

``submit()`` (any thread) journals an accept record durably
(utils.journal.IngestJournal), then enqueues the submission on the
thread-safe :class:`~kueue_tpu.serving.ingest.IngestQueue`.  ``step()``
(the service thread, only thread that touches the driver) drains the
queue at the cycle boundary, bulk-creates the batch through
``Driver.ingest_workloads`` — one queue-manager lock acquisition, the
PackJournal dirt marked per workload exactly as the batch path does —
marks the journal applied, and runs ``schedule_once`` K times.

Backpressure
------------

Past the ``high_water`` ingest depth the service rejects with a
retry-after estimate derived from the arrival-rate EWMA; a submission
that outranks the lowest-priority pending entry shed-replaces it
instead (shed is journaled and reported to the victim's token — a
recorded outcome, never a silent drop).

Adaptive burst window
---------------------

K is chosen online per step: the expected work for the step (pending
backlog + EWMA arrivals over ``dt``) divided by the admitted-per-cycle
capacity estimate, snapped up a power-of-two ladder.  Clearing each
step's expected arrivals within the step bounds queueing delay to
~``dt`` ≪ the p99 SLO across diurnal/MMPP swings; the SLO block of the
SERVE artifact (scripts/serve_soak.py) is the evidence.

Crash-restart continuity
------------------------

Three chaos sites (``svc.ingest`` / ``svc.cycle`` / ``svc.shutdown``)
let the soak SIGKILL the service at the nastiest boundaries.
:func:`recover_service` replays the CycleWAL tail over the surviving
store (``Driver.recover_from``), then replays the ingest journal:
accepted-but-unapplied submissions re-enter the queue in acceptance
order, skipping keys already present in the recovered store (the crash
may have landed between the store apply and the apply marker) — zero
accepted submissions lost, zero admissions duplicated, enforced
decision-bit-identically against an unkilled control.  Submission
tokens are idempotent: resubmitting an accepted token returns its
prior outcome without re-journaling or re-enqueueing.

Cycle accounting assumes crashes land at the ``svc.*`` boundaries (step
start, submit path, drain epilogue); mid-cycle WAL crash sites keep
their existing recovery semantics through ``Driver.recover_from`` but
are exercised by the chaos soak, not this service's kill arms.

SIGTERM (``install_signal_handlers``) triggers graceful drain: stop
accepting, finish in-flight cycles until the ingest queue is empty,
flush the WAL and close the ingest journal, exit clean.
"""

from __future__ import annotations

import math
import signal
import threading
import time
from dataclasses import dataclass, replace
from typing import Optional

from ..api.types import WL_QUOTA_RESERVED, PodSet, Workload
from ..chaos import injector as _chaos
from ..features import env_int, env_value
from ..obs.trace import span as _span
from ..traffic.runner import RateEWMA
from ..utils.journal import IngestJournal
from .ingest import IngestQueue, Submission


@dataclass
class ServiceConfig:
    """Knobs for one AdmissionService.  ``None`` fields fall back to
    the registered ``KUEUE_TPU_SVC_*`` env flags at construction."""

    dt_s: float = 0.05              # seconds per service step
    high_water: Optional[int] = None        # ingest backpressure depth
    slo_p99_s: Optional[float] = None       # p99 admission-latency SLO
    drain_timeout_s: Optional[float] = None  # graceful-drain deadline
    journal_path: Optional[str] = None      # ingest journal ("" = memory)
    k_ladder: tuple = (1, 2, 4, 8, 16, 32)  # burst-window rungs
    k_max: int = 32                 # cap (1 pins the deterministic arms)
    ewma_halflife_s: float = 5.0    # arrival-rate tracking speed
    epoch_t: Optional[float] = None  # virtual epoch (recovery continuity)

    def resolved(self) -> "ServiceConfig":
        return replace(
            self,
            high_water=(self.high_water if self.high_water is not None
                        else env_int("KUEUE_TPU_SVC_HIGH_WATER")),
            slo_p99_s=(self.slo_p99_s if self.slo_p99_s is not None
                       else float(env_value("KUEUE_TPU_SVC_SLO_P99_S"))),
            drain_timeout_s=(
                self.drain_timeout_s if self.drain_timeout_s is not None
                else float(env_int("KUEUE_TPU_SVC_DRAIN_TIMEOUT_S"))),
            journal_path=(
                self.journal_path if self.journal_path is not None
                else env_value("KUEUE_TPU_SVC_INGEST_JOURNAL")))


@dataclass
class SubmitResult:
    """What a submitter gets back, every outcome explicit."""

    status: str              # accepted | rejected | shed | draining
    token: str = ""
    seq: int = 0
    reason: str = ""
    retry_after_s: float = 0.0
    queue_depth: int = 0
    duplicate: bool = False  # a repeat of an already-settled token


class AdmissionService:
    """The long-lived service loop around one ``Driver``.

    Thread contract: ``submit`` / ``queue_position`` / ``pending`` are
    safe from any thread; ``step`` / ``serve`` / ``drain`` run on the
    single service thread, which is the only thread that touches the
    driver (scheduler, cache, queues, WAL, spans)."""

    def __init__(self, driver, config: Optional[ServiceConfig] = None,
                 wal=None, journal: Optional[IngestJournal] = None):
        self.driver = driver
        self.clock = driver.clock
        self.metrics = driver.metrics
        self.cfg = (config or ServiceConfig()).resolved()
        self.wal = wal if wal is not None else driver._wal
        if journal is not None:
            self.journal = journal
        else:
            self.journal = IngestJournal(self.cfg.journal_path or None)
        self.ingest = IngestQueue()
        self.ewma = RateEWMA(halflife_s=self.cfg.ewma_halflife_s)
        self._lock = threading.RLock()
        self._tokens: dict[str, SubmitResult] = {}
        self._virtual = hasattr(self.clock, "t")
        self.epoch = (self.cfg.epoch_t if self.cfg.epoch_t is not None
                      else float(self.clock()))
        self.cycle_index = int(round(
            (float(self.clock()) - self.epoch) / self.cfg.dt_s))
        self._finish_at: dict[int, list[str]] = {}
        self._runtime_of: dict[str, float] = {}
        self._service_keys: set[str] = set()   # applied, not yet admitted
        self._accept_wall: dict[str, float] = {}
        self._arrivals_since_step = 0
        self._admit_cap = 1.0          # admitted-per-cycle estimate
        self._retry_after = self.cfg.dt_s
        self.k_last = 1
        self._draining = False
        self._drain_requested = False
        self.drained_clean = False
        self.stopped = False
        self.accepted_total = 0
        self.rejected_total = 0
        self.duplicate_total = 0
        self.shed_total = 0
        self.admitted_total = 0
        self._wall0 = time.perf_counter()
        self.telemetry: list[dict] = []      # per-step soak samples
        self.latency_log: list[tuple] = []   # (t_wall_rel, latency_s)

    # -- submit path (any thread) --------------------------------------

    def submit(self, name: str, queue_name: str, requests: dict,
               priority: int = 0, namespace: str = "default",
               creation_time: Optional[float] = None,
               runtime_s: float = 0.0, count: int = 1,
               token: Optional[str] = None) -> SubmitResult:
        """Accept (journal + enqueue), reject with retry-after, or
        shed-replace — one outcome per call, idempotent per token."""
        with self._lock:
            if self._draining:
                self.metrics.svc_submission("draining")
                self.rejected_total += 1
                return SubmitResult(status="draining", reason="draining",
                                    retry_after_s=self._retry_after)
            tok = token if token is not None else f"{namespace}/{name}"
            prior = self._tokens.get(tok)
            if prior is not None:
                self.metrics.svc_submission("duplicate")
                self.duplicate_total += 1
                return replace(prior, duplicate=True)
            depth = self.ingest.depth()
            victim: Optional[Submission] = None
            if depth >= self.cfg.high_water:
                victim = self.ingest.lowest_priority()
                if victim is None or victim.priority >= priority:
                    self.metrics.svc_submission("rejected")
                    self.rejected_total += 1
                    return SubmitResult(
                        status="rejected", token=tok,
                        reason="backpressure", queue_depth=depth,
                        retry_after_s=self._retry_after)
            ct = (creation_time if creation_time is not None
                  else float(self.clock()))
            sub = Submission(token=tok, seq=0, name=name,
                             namespace=namespace, queue_name=queue_name,
                             priority=priority, creation_time=ct,
                             requests=dict(requests), count=count,
                             runtime_s=runtime_s)
            sub.seq = self.journal.accept(tok, sub.payload())
            if victim is not None:
                self.ingest.remove(victim)
                self.journal.shed(victim.seq, victim.token)
                self._tokens[victim.token] = SubmitResult(
                    status="shed", token=victim.token, seq=victim.seq,
                    reason="displaced by higher priority")
                self.shed_total += 1
                self.metrics.svc_submission("shed")
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.crashpoint("svc.ingest")
            self.ingest.append(sub)
            self._accept_wall[sub.key] = time.perf_counter()
            self._arrivals_since_step += 1
            self.accepted_total += 1
            self.metrics.svc_submission("accepted")
            res = SubmitResult(status="accepted", token=tok, seq=sub.seq,
                               queue_depth=self.ingest.depth())
            self._tokens[tok] = res
            return res

    # -- visibility (any thread) ---------------------------------------

    def queue_position(self, token: str) -> dict:
        """Live status of one token: settled outcome, pending position
        in the ingest queue, or admitted/finished from the store."""
        with self._lock:
            res = self._tokens.get(token)
            if res is None:
                return {"token": token, "status": "unknown"}
            pos = self.ingest.position(token)
            if pos is not None:
                return {"token": token, "status": "pending",
                        "position": pos, "depth": self.ingest.depth()}
            out = {"token": token, "status": res.status, "seq": res.seq}
            if res.status == "accepted":
                wl = self.driver.workloads.get(
                    self._key_of_token(token, res))
                if wl is not None:
                    if wl.is_finished:
                        out["status"] = "finished"
                    elif wl.has_quota_reservation:
                        out["status"] = "admitted"
                        out["cluster_queue"] = wl.admission.cluster_queue
                    else:
                        out["status"] = "queued"
            return out

    def _key_of_token(self, token: str, res: SubmitResult) -> str:
        for rec in self.journal.accepted:
            if rec["seq"] == res.seq:
                p = rec["wl"]
                return f"{p['namespace']}/{p['name']}"
        return token

    def pending(self, limit: int = 100) -> dict:
        """The serving pending-workload listing: ingest entries not yet
        drained plus the per-step counters."""
        subs = self.ingest.snapshot()[:limit]
        return {
            "ingest_depth": self.ingest.depth(),
            "high_water": self.cfg.high_water,
            "draining": self._draining,
            "items": [{"token": s.token, "seq": s.seq, "key": s.key,
                       "queue_name": s.queue_name,
                       "priority": s.priority} for s in subs],
        }

    def stats(self) -> dict:
        return {
            "accepted": self.accepted_total,
            "rejected": self.rejected_total,
            "duplicate": self.duplicate_total,
            "shed": self.shed_total,
            "admitted": self.admitted_total,
            "ingest_depth": self.ingest.depth(),
            "cycle_index": self.cycle_index,
            "k_last": self.k_last,
            "arrival_rate_ewma": self.ewma.rate_per_s,
            "draining": self._draining,
            "drained_clean": self.drained_clean,
            "journal": dict(self.journal.stats),
        }

    # -- the service cycle (service thread only) -----------------------

    def _choose_k(self, backlog: int) -> int:
        """Online burst window: cycles this step needed to clear the
        pending backlog plus the EWMA-expected arrivals, snapped up the
        ladder.  Clearing each step's expected work within the step
        keeps queueing delay near ``dt``, which is what holds the p99
        SLO across the load swing."""
        if self.cfg.k_max <= 1:
            return 1
        need = backlog + self.ewma.rate_per_s * self.cfg.dt_s
        raw = need / max(1.0, self._admit_cap)
        target = max(1, min(self.cfg.k_max, math.ceil(raw)))
        for rung in self.cfg.k_ladder:
            if rung >= target:
                return max(1, min(rung, self.cfg.k_max))
        return max(1, min(self.cfg.k_ladder[-1], self.cfg.k_max))

    def _workload_of(self, sub: Submission) -> Workload:
        return Workload(name=sub.name, namespace=sub.namespace,
                        queue_name=sub.queue_name, priority=sub.priority,
                        creation_time=sub.creation_time,
                        pod_sets=[PodSet(name="main", count=sub.count,
                                         requests=dict(sub.requests))])

    def step(self) -> dict:
        """One service step: drain the ingest queue at the cycle
        boundary, bulk-apply, run K scheduling cycles, settle finishes
        and latency accounting.  Mirrors traffic.runner.run_open_loop's
        per-cycle order exactly (clock, finishes, inject, schedule), so
        service-path decisions are bit-identical to the batch runner on
        identical traffic."""
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("svc.cycle")
        decisions: list[list[str]] = []
        with _span("svc.cycle"):
            with self._lock:
                batch = self.ingest.drain()
                self._arrivals_since_step = 0
            self.ewma.update(len(batch), self.cfg.dt_s)
            k = self._choose_k(len(self._service_keys) + len(batch))
            self.k_last = k
            admitted_n = 0
            for i in range(k):
                c = self.cycle_index
                if self._virtual:
                    self.clock.t = self.epoch + (c + 1) * self.cfg.dt_s
                for key in self._finish_at.pop(c, ()):
                    wl = self.driver.workloads.get(key)
                    if wl is not None and wl.has_quota_reservation \
                            and not wl.is_finished:
                        self.driver.finish_workload(key)
                if i == 0 and batch:
                    with _span("svc.ingest"):
                        self.driver.ingest_workloads(
                            [self._workload_of(s) for s in batch])
                        for s in batch:
                            self._runtime_of[s.key] = s.runtime_s
                            self._service_keys.add(s.key)
                        self.journal.mark_applied(batch[-1].seq, c)
                stats = self.driver.schedule_once()
                admitted = sorted(stats.admitted)
                decisions.append(admitted)
                now_w = time.perf_counter()
                for key in admitted:
                    if key not in self._service_keys:
                        continue   # re-admission of an evicted workload
                    self._service_keys.discard(key)
                    self.admitted_total += 1
                    admitted_n += 1
                    t0 = self._accept_wall.pop(key, None)
                    if t0 is not None:
                        lat = now_w - t0
                        self.metrics.svc_admission_latency(lat)
                        self.latency_log.append(
                            (now_w - self._wall0, lat))
                    rt = self._runtime_of.pop(key, 0.0)
                    if rt > 0:
                        fin = c + max(1, int(round(rt / self.cfg.dt_s)))
                        self._finish_at.setdefault(fin, []).append(key)
                self.cycle_index = c + 1
            # capacity estimate feeding the next step's K choice
            if admitted_n > 0:
                self._admit_cap = (0.8 * self._admit_cap
                                   + 0.2 * (admitted_n / k))
            depth = self.ingest.depth()
            self._retry_after = min(
                60.0, max(self.cfg.dt_s,
                          depth / max(self.ewma.rate_per_s,
                                      1.0 / self.cfg.dt_s)))
            self.metrics.svc_sample(
                depth=depth, high_water=self.cfg.high_water, burst_k=k,
                ewma_rate=self.ewma.rate_per_s,
                retry_after_s=self._retry_after)
            sample = {"t_wall": time.perf_counter() - self._wall0,
                      "cycle": self.cycle_index, "k": k,
                      "batch": len(batch), "depth": depth,
                      "ewma_rate": self.ewma.rate_per_s,
                      "admitted": admitted_n, "decisions": decisions}
            self.telemetry.append(sample)
            return sample

    # -- drain / shutdown ----------------------------------------------

    def request_drain(self) -> None:
        """Stop accepting; ``serve``/``drain`` finish the in-flight
        work.  Safe from any thread and from a signal handler."""
        with self._lock:
            self._draining = True
            self._drain_requested = True

    def install_signal_handlers(self) -> None:
        """SIGTERM → graceful drain.  Call from the main thread."""
        signal.signal(signal.SIGTERM, lambda signum, frame:
                      self.request_drain())

    def drain(self) -> bool:
        """Synchronous graceful drain on the service thread: stop
        accepting, step until the ingest queue is empty (every accepted
        submission applied) or the deadline passes, then flush the WAL
        and close the ingest journal.  Returns (and records) whether
        the drain was clean."""
        self.request_drain()
        deadline = time.perf_counter() + self.cfg.drain_timeout_s
        while self.ingest.depth() > 0 \
                and time.perf_counter() < deadline:
            self.step()
        clean = self.ingest.depth() == 0
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.crashpoint("svc.shutdown")
        with _span("svc.shutdown"):
            if self.wal is not None:
                self.wal.commit()
            self.journal.close()
        self.drained_clean = clean
        self.stopped = True
        return clean

    def serve(self, stop: Optional[threading.Event] = None) -> dict:
        """Wall-clock loop: one step per ``dt``, until a drain is
        requested (SIGTERM or ``request_drain``) or ``stop`` is set —
        both exits run the graceful drain.  Returns final stats."""
        self._wall0 = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            self.step()
            if stop is not None and stop.is_set():
                self.request_drain()
            if self._drain_requested:
                self.drain()
                break
            lag = self.cfg.dt_s - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
        return self.stats()

    # -- recovery ------------------------------------------------------

    def _rebuild_from_journal(self) -> None:
        """Post-crash state rebuild from the resumed ingest journal:
        token outcomes, the un-applied ingest suffix, and the finish
        schedule of admitted-in-flight workloads (admit cycle derived
        from the QuotaReserved transition time against the epoch)."""
        dt = self.cfg.dt_s
        for rec in self.journal.accepted:
            tok, seq = rec["token"], rec["seq"]
            if seq in self.journal.shed_seqs:
                self._tokens[tok] = SubmitResult(status="shed",
                                                 token=tok, seq=seq)
            else:
                self._tokens[tok] = SubmitResult(status="accepted",
                                                 token=tok, seq=seq)
        for rec in self.journal.unapplied():
            sub = Submission.from_payload(rec["wl"], token=rec["token"],
                                          seq=rec["seq"])
            if sub.key in self.driver.workloads:
                continue   # applied pre-crash; only the marker was lost
            self.ingest.append(sub)
        for rec in self.journal.accepted:
            if rec["seq"] in self.journal.shed_seqs:
                continue
            p = rec["wl"]
            key = f"{p['namespace']}/{p['name']}"
            wl = self.driver.workloads.get(key)
            if wl is None or wl.is_finished:
                continue
            rt = p.get("runtime_s", 0.0)
            if wl.has_quota_reservation:
                if rt > 0:
                    cond = wl.conditions.get(WL_QUOTA_RESERVED)
                    c_admit = int(round(
                        (cond.last_transition_time - self.epoch)
                        / dt)) - 1
                    fin = c_admit + max(1, int(round(rt / dt)))
                    self._finish_at.setdefault(
                        max(fin, self.cycle_index), []).append(key)
            else:
                self._runtime_of[key] = rt
                self._service_keys.add(key)


def recover_service(driver, stored, wal, config: Optional[ServiceConfig]
                    = None, journal_path: Optional[str] = None
                    ) -> AdmissionService:
    """Crash recovery: the CycleWAL tail replays over the surviving
    store (``Driver.recover_from``), then the durable ingest journal
    rebuilds the token map, re-enqueues the accepted-but-unapplied
    suffix in acceptance order (skipping keys the recovered store
    already holds — the crash may have landed between the store apply
    and the apply marker), and reconstructs the finish schedule for
    admitted-in-flight workloads.  ``driver`` is a fresh driver with
    cluster state already applied; ``stored`` is the crashed driver's
    durable workload store.  Pass the original service's ``epoch_t`` in
    ``config`` so cycle accounting continues where the crashed process
    stopped."""
    cfg = (config or ServiceConfig()).resolved()
    driver.recover_from(stored, wal)
    path = journal_path if journal_path is not None else cfg.journal_path
    journal = IngestJournal.resume(path) if path else IngestJournal(None)
    svc = AdmissionService(driver, config=cfg, wal=wal, journal=journal)
    svc._rebuild_from_journal()
    return svc
