"""Serving: the long-lived concurrent admission service around Driver.

``service`` owns the loop (submit → durable ingest journal →
cycle-boundary drain → K scheduling cycles), the backpressure and
adaptive-burst-window policies, graceful drain, and crash recovery;
``ingest`` is the thread-safe queue between submitter threads and the
service thread.  The HTTP surface (submit, queue position, pending
listing) hangs off ``visibility.VisibilityServer``.
"""

from .ingest import IngestQueue, Submission
from .service import (
    AdmissionService,
    ServiceConfig,
    SubmitResult,
    recover_service,
)

__all__ = [
    "AdmissionService",
    "IngestQueue",
    "ServiceConfig",
    "SubmitResult",
    "Submission",
    "recover_service",
]
