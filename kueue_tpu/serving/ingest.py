"""Thread-safe bounded ingest queue for the admission service.

Concurrent submitter threads append :class:`Submission` entries; the
service thread drains the whole queue at each cycle boundary
(service.py step) into ``Driver.ingest_workloads``.  Entries keep
their journal sequence number, so a drain hands the batch over in
exact acceptance order and recovery can re-enqueue the un-applied
suffix in the same order the original process accepted it.

The queue itself is mechanics only — append / remove / drain /
introspection under one lock.  The backpressure *policy* (reject past
the high-water mark, shed lowest-priority pending first) lives in the
service, which composes a policy decision with the journal append and
the queue mutation under its own lock.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Submission:
    """One accepted submission, as journaled and as queued."""

    token: str               # idempotency token (defaults to the key)
    seq: int                 # ingest-journal sequence number
    name: str
    namespace: str
    queue_name: str
    priority: int
    creation_time: float     # the driver clock's time at acceptance
    requests: dict = field(default_factory=dict)
    count: int = 1
    runtime_s: float = 0.0   # service time once admitted (0 = external)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def payload(self) -> dict:
        """The journaled form — everything needed to rebuild the
        workload bit-identically after a crash."""
        return {"name": self.name, "namespace": self.namespace,
                "queue_name": self.queue_name, "priority": self.priority,
                "creation_time": self.creation_time,
                "requests": dict(self.requests), "count": self.count,
                "runtime_s": self.runtime_s}

    @classmethod
    def from_payload(cls, payload: dict, token: str,
                     seq: int) -> "Submission":
        return cls(token=token, seq=seq, name=payload["name"],
                   namespace=payload["namespace"],
                   queue_name=payload["queue_name"],
                   priority=payload["priority"],
                   creation_time=payload["creation_time"],
                   requests=dict(payload["requests"]),
                   count=payload["count"],
                   runtime_s=payload["runtime_s"])


class IngestQueue:
    """Seq-ordered pending submissions, safe under concurrent append
    (submitters) and drain (the service cycle loop)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: list[Submission] = []

    def append(self, sub: Submission) -> None:
        with self._lock:
            self._entries.append(sub)

    def remove(self, sub: Submission) -> bool:
        with self._lock:
            try:
                self._entries.remove(sub)
                return True
            except ValueError:
                return False

    def drain(self) -> list[Submission]:
        """Atomically take everything, in acceptance (seq) order."""
        with self._lock:
            out, self._entries = self._entries, []
        out.sort(key=lambda s: s.seq)
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def lowest_priority(self) -> Optional[Submission]:
        """The shed candidate: lowest priority, youngest (largest seq)
        among ties — the entry whose loss costs the least and whose
        submitter waited the shortest."""
        with self._lock:
            if not self._entries:
                return None
            return min(self._entries, key=lambda s: (s.priority, -s.seq))

    def position(self, token: str) -> Optional[int]:
        """0-based drain position of a pending submission, None when
        the token is not (or no longer) pending."""
        with self._lock:
            ordered = sorted(self._entries, key=lambda s: s.seq)
        for i, sub in enumerate(ordered):
            if sub.token == token:
                return i
        return None

    def snapshot(self) -> list[Submission]:
        with self._lock:
            return sorted(self._entries, key=lambda s: s.seq)
