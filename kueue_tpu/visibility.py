"""On-demand visibility API (reference pkg/visibility + apis/visibility).

The reference embeds an aggregated API server (server.go:62) serving live
pending-workload summaries straight from the queue manager
(api/v1beta1/pending_workloads_cq.go / _lq.go).  Here the same data is
exposed two ways: typed accessors (``VisibilityService``) and a real HTTP
endpoint (``serve``) speaking the reference's REST shape — which also
doubles as the kueueviz dashboard feed (cmd/kueueviz backend).

When constructed with a serving ``AdmissionService`` the same server
fronts the admission API: ``POST /apis/serving/v1/submit`` (accept /
429-with-Retry-After / 503-draining), ``GET /apis/serving/v1/position``
(idempotency-token status + queue position), ``GET
/apis/serving/v1/pending`` (ingest listing), and ``GET
/apis/serving/v1/stats`` — with the service's live ``kueue_svc_*``
gauges on the existing ``/metrics``.
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, dataclass, field
from typing import Optional


@dataclass
class PendingWorkload:
    """reference apis/visibility/v1beta1/types.go:64."""
    name: str
    namespace: str
    local_queue_name: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    """reference apis/visibility/v1beta1/types.go:85."""
    items: list[PendingWorkload] = field(default_factory=list)


class VisibilityService:
    """reference visibility/api/v1beta1 REST storage."""

    def __init__(self, driver):
        self.driver = driver

    def pending_workloads_cq(self, cq_name: str, limit: Optional[int] = None,
                             offset: int = 0) -> PendingWorkloadsSummary:
        """GET .../clusterqueues/{cq}/pendingworkloads
        (pending_workloads_cq.go)."""
        infos = self.driver.queues.pending_workloads_info(cq_name)
        lq_positions: dict[str, int] = {}
        items = []
        for pos, info in enumerate(infos):
            wl = info.obj
            lq = f"{wl.namespace}/{wl.queue_name}"
            lq_pos = lq_positions.get(lq, 0)
            lq_positions[lq] = lq_pos + 1
            if pos < offset:
                continue
            if limit is not None and len(items) >= limit:
                continue
            items.append(PendingWorkload(
                name=wl.name, namespace=wl.namespace,
                local_queue_name=wl.queue_name, priority=wl.priority,
                position_in_cluster_queue=pos,
                position_in_local_queue=lq_pos))
        return PendingWorkloadsSummary(items=items)

    def pending_workloads_lq(self, namespace: str, lq_name: str,
                             limit: Optional[int] = None,
                             offset: int = 0) -> PendingWorkloadsSummary:
        """GET .../localqueues/{lq}/pendingworkloads
        (pending_workloads_lq.go)."""
        lq = self.driver.queues.local_queues.get(f"{namespace}/{lq_name}")
        if lq is None:
            return PendingWorkloadsSummary()
        cq_summary = self.pending_workloads_cq(lq.cluster_queue)
        items = [w for w in cq_summary.items
                 if w.namespace == namespace and w.local_queue_name == lq_name]
        items = items[offset:]
        if limit is not None:
            items = items[:limit]
        return PendingWorkloadsSummary(items=items)

    # -- dashboard feed (kueueviz-equivalent aggregates) ---------------

    def cluster_queues_summary(self) -> dict:
        out = {}
        for name in self.driver.cache.cluster_queue_names():
            cq = self.driver.cache.cluster_queue(name)
            if cq is None:
                continue
            out[name] = {
                "active": cq.active,
                "pending": self.driver.queues.pending_workloads(name),
                "usage": {f"{fr.flavor}/{fr.resource}": v
                          for fr, v in sorted(
                              self.driver.cache.usage(name).items())},
            }
        return out


_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>kueue-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;color:#222}
 table{border-collapse:collapse;min-width:40rem}
 th,td{border:1px solid #ccc;padding:.35rem .7rem;text-align:left}
 th{background:#f5f5f5}
 .inactive{color:#b00}
 code{background:#f5f5f5;padding:0 .3rem}
</style></head><body>
<h1>kueue-tpu</h1>
<p>Cluster queues (auto-refreshes; endpoints:
<code>/apis/visibility/v1beta1/…</code>, <code>/metrics</code>)</p>
<table id="cqs"><thead><tr>
<th>ClusterQueue</th><th>Status</th><th>Pending</th><th>Usage</th>
</tr></thead><tbody></tbody></table>
<h2>Pending workloads</h2>
<table id="pending"><thead><tr>
<th>#</th><th>Workload</th><th>LocalQueue</th><th>Priority</th>
<th>ClusterQueue</th>
</tr></thead><tbody></tbody></table>
<script>
async function refresh(){
  const r = await fetch('/apis/visibility/v1beta1/clusterqueues');
  const cqs = await r.json();
  const body = document.querySelector('#cqs tbody');
  body.innerHTML = '';
  const pbody = document.querySelector('#pending tbody');
  pbody.innerHTML = '';
  for (const [name, info] of Object.entries(cqs)) {
    const tr = document.createElement('tr');
    tr.innerHTML = `<td>${name}</td>` +
      `<td class="${info.active ? '' : 'inactive'}">` +
      `${info.active ? 'active' : 'inactive'}</td>` +
      `<td>${info.pending}</td>` +
      `<td><code>${JSON.stringify(info.usage)}</code></td>`;
    body.appendChild(tr);
    if (info.pending > 0) {
      const pr = await fetch('/apis/visibility/v1beta1/clusterqueues/' +
                             name + '/pendingworkloads');
      const items = (await pr.json()).items;
      for (const w of items) {
        const tr2 = document.createElement('tr');
        tr2.innerHTML = `<td>${w.position_in_cluster_queue}</td>` +
          `<td>${w.namespace}/${w.name}</td>` +
          `<td>${w.local_queue_name}</td><td>${w.priority}</td>` +
          `<td>${name}</td>`;
        pbody.appendChild(tr2);
      }
    }
  }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


class VisibilityServer:
    """The aggregated-API-server equivalent: a real HTTP endpoint
    (reference visibility/server.go:62 + kueueviz backend)."""

    def __init__(self, driver, host: str = "127.0.0.1", port: int = 0,
                 admission=None, admin: bool = False):
        self.service = VisibilityService(driver)
        self.admission = admission   # serving.AdmissionService, optional
        self.admin = admin           # lockstep-harness admin endpoints
        self.host = host
        self.port = port
        self._httpd = None
        self._thread = None

    def start(self) -> int:
        from http.server import BaseHTTPRequestHandler
        from urllib.parse import parse_qs, urlsplit

        from .remote import DrainingHTTPServer, state_digest

        service = self.service
        admission = self.admission
        admin_enabled = self.admin
        step_lock = threading.Lock()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send_json(self, body, code=200, headers=()):
                payload = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                path = self.path.split("?")[0]
                if path.startswith("/admin/"):
                    # lockstep-harness mutations: the distributed soak's
                    # parent drives each shard's service steps through
                    # these barriers instead of a wall-clock serve loop
                    if not admin_enabled or admission is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    if path == "/admin/step":
                        with step_lock:
                            self._send_json(admission.step())
                    elif path == "/admin/drain":
                        with step_lock:
                            clean = admission.drain()
                        self._send_json({"clean": clean})
                    else:
                        self.send_response(404)
                        self.end_headers()
                    return
                # /apis/serving/v1/submit — the admission API: accept /
                # reject-with-retry-after / duplicate, all explicit
                if path != "/apis/serving/v1/submit" \
                        or admission is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    res = admission.submit(
                        name=req["name"],
                        queue_name=req["queue_name"],
                        requests=req.get("requests", {}),
                        priority=int(req.get("priority", 0)),
                        namespace=req.get("namespace", "default"),
                        runtime_s=float(req.get("runtime_s", 0.0)),
                        count=int(req.get("count", 1)),
                        token=req.get("token"))
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    self._send_json({"error": str(e)}, code=400)
                    return
                body = {"status": res.status, "token": res.token,
                        "seq": res.seq, "reason": res.reason,
                        "duplicate": res.duplicate,
                        "queue_depth": res.queue_depth,
                        "retry_after_s": res.retry_after_s}
                if res.status == "accepted":
                    self._send_json(body)
                elif res.status in ("rejected", "draining"):
                    code = 429 if res.status == "rejected" else 503
                    self._send_json(body, code=code, headers=(
                        ("Retry-After",
                         str(max(1, int(res.retry_after_s + 0.5)))),))
                else:
                    self._send_json(body)

            def do_GET(self):
                if self.path.split("?")[0] == "/healthz":
                    self._send_json({
                        "ok": True,
                        "ready": not getattr(self.server, "draining",
                                             False)})
                    return
                if self.path.split("?")[0] == "/readyz":
                    # readiness the supervisor polls instead of sleeping
                    if getattr(self.server, "draining", False):
                        self._send_json({"ready": False}, code=503)
                    else:
                        self._send_json({"ready": True})
                    return
                if self.path.split("?")[0] == "/admin/digest":
                    if not admin_enabled:
                        self.send_response(404)
                        self.end_headers()
                        return
                    with step_lock:
                        body = {"digest": state_digest(service.driver),
                                "n": len(service.driver.workloads)}
                        if admission is not None:
                            body["cycle"] = admission.cycle_index
                    self._send_json(body)
                    return
                if self.path.split("?")[0] in ("/", "/index.html"):
                    # kueueviz-equivalent dashboard (reference
                    # cmd/kueueviz): live CQ table fed by the visibility
                    # endpoints below
                    payload = _DASHBOARD_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path.split("?")[0] == "/metrics":
                    # Prometheus exposition (reference secure metrics
                    # endpoint, cmd/kueue/main.go:154-179)
                    driver = service.driver
                    if hasattr(driver, "refresh_resource_metrics"):
                        driver.refresh_resource_metrics()
                    payload = driver.metrics.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path.split("?")[0] == "/debug/flightrecorder":
                    # flight-recorder dump (the pkg/debugger analog,
                    # live over HTTP instead of SIGUSR2)
                    driver = service.driver
                    body = {"error": "no obs plane"}
                    if hasattr(driver, "obs"):
                        body = driver.obs.flight.dump()
                        body["events"] = driver.obs.events.report()
                        body["tracing"] = driver.obs.tracing
                    payload = json.dumps(body).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path.split("?")[0] == "/debug/spans":
                    # Chrome trace-event JSON: open in Perfetto /
                    # chrome://tracing next to jax.profiler traces
                    driver = service.driver
                    body = {"traceEvents": []}
                    if hasattr(driver, "obs"):
                        body = driver.obs.spans_chrome_trace()
                    payload = json.dumps(body).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                url = urlsplit(self.path)
                if url.path.startswith("/apis/serving/v1/"):
                    # serving admission/visibility API (tokens carry
                    # "/" so they travel as a query param)
                    if admission is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    rest = url.path[len("/apis/serving/v1/"):]
                    if rest == "pending":
                        q = parse_qs(url.query)
                        limit = int(q.get("limit", ["100"])[0])
                        self._send_json(admission.pending(limit=limit))
                    elif rest == "position":
                        q = parse_qs(url.query)
                        tok = q.get("token", [""])[0]
                        self._send_json(admission.queue_position(tok))
                    elif rest == "stats":
                        self._send_json(admission.stats())
                    else:
                        self.send_response(404)
                        self.end_headers()
                    return
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                # /apis/visibility/v1beta1/clusterqueues/{cq}/pendingworkloads
                # /apis/visibility/v1beta1/namespaces/{ns}/localqueues/{lq}/pendingworkloads
                # /apis/visibility/v1beta1/clusterqueues
                try:
                    if parts[:3] != ["apis", "visibility", "v1beta1"]:
                        raise KeyError(self.path)
                    rest = parts[3:]
                    if rest == ["clusterqueues"]:
                        body = service.cluster_queues_summary()
                    elif (len(rest) == 3 and rest[0] == "clusterqueues"
                          and rest[2] == "pendingworkloads"):
                        body = asdict(service.pending_workloads_cq(rest[1]))
                    elif (len(rest) == 5 and rest[0] == "namespaces"
                          and rest[2] == "localqueues"
                          and rest[4] == "pendingworkloads"):
                        body = asdict(
                            service.pending_workloads_lq(rest[1], rest[3]))
                    else:
                        raise KeyError(self.path)
                except (KeyError, IndexError):
                    self.send_response(404)
                    self.end_headers()
                    return
                payload = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = DrainingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self, graceful: bool = True) -> None:
        if self._httpd is not None:
            if graceful:
                # finish in-flight submits before the socket closes
                self._httpd.drain(timeout=5.0)
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
