"""Native (C++) solver-plane backend.

``classify_cycle(packed)`` runs the batched nominate/classify pass in the
compiled core (kueue_tpu/native/cycle_core.cpp) — identical decisions to
the JAX kernel (ops/cycle.solve_cycle, run_scan=False) and the scalar
host oracle.  The shared library is built lazily with g++ on first use
and cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "cycle_core.cpp")
_LIB = os.path.join(_HERE, "libcyclecore.so")

_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _i32(a):
    return np.ascontiguousarray(a, dtype=np.int32)


def _u8(a):
    return np.ascontiguousarray(a, dtype=np.uint8)


def _build() -> None:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"building cycle core failed: {proc.stderr[-2000:]}")


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_LIB)
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.classify_cycle.restype = None
        lib.classify_cycle.argtypes = (
            [ctypes.c_int32] * 6
            + [i32p, i32p, i32p, i32p, u8p, i32p, i32p, i32p, u8p, u8p,
               i32p, i32p]
            + [i32p, u8p, u8p])
        lib.admit_scan.restype = None
        lib.admit_scan.argtypes = (
            [ctypes.c_int32] * 5
            + [i32p, i32p, i32p, i32p, u8p, i32p, i32p, i32p,
               i32p, i32p, i32p, u8p, i32p, i32p, u8p, u8p, i32p]
            + [u8p])
        _lib = lib
        return lib


def available() -> bool:
    """Whether the native backend can be used (g++ present or prebuilt)."""
    if os.path.exists(_LIB):
        return True
    from shutil import which
    return which("g++") is not None


def classify_cycle(packed):
    """Run the native classify over a PackedCycle.

    Returns (fit_slot0 [W] int32, borrows0 [W] bool, preempt [W] bool),
    matching ops/cycle.solve_cycle(..., run_scan=False) outputs 4-6.
    """
    lib = _load()
    N = packed.node_count
    C, S, R = packed.slot_fr.shape
    F = packed.usage0.shape[1]
    W = packed.wl_cq.shape[0]

    fit_slot = np.empty(W, dtype=np.int32)
    borrows = np.empty(W, dtype=np.uint8)
    preempt = np.empty(W, dtype=np.uint8)
    lib.classify_cycle(
        N, F, C, S, R, W,
        _i32(packed.usage0), _i32(packed.subtree_quota),
        _i32(packed.guaranteed), _i32(packed.borrow_cap),
        _u8(packed.has_borrow_limit), _i32(packed.parent),
        _i32(packed.nominal_cq), _i32(packed.slot_fr),
        _u8(packed.slot_valid), _u8(packed.cq_can_preempt_borrow),
        _i32(packed.wl_cq), _i32(packed.wl_requests),
        fit_slot, borrows, preempt)
    return fit_slot, borrows.astype(bool), preempt.astype(bool)


def admit_scan_raw(usage0, subtree_quota, guaranteed, borrow_cap,
                   has_borrow_limit, parent, nominal_cq, npb_cq,
                   wl_cq, dec_fr, dec_amt, fit_mask, res_fr, res_amt,
                   res_mask, res_borrows, order):
    """Array-level admit loop (same argument order as the jitted
    ops/cycle.admit_scan) — lets the solver's warmup time the native
    core with the same synthetic tensors it times the XLA backends on."""
    lib = _load()
    N, F = np.asarray(usage0).shape
    C = np.asarray(nominal_cq).shape[0]
    W, K = np.asarray(dec_fr).shape
    admitted = np.empty(W, dtype=np.uint8)
    lib.admit_scan(
        N, F, C, K, W,
        _i32(usage0), _i32(subtree_quota), _i32(guaranteed),
        _i32(borrow_cap), _u8(has_borrow_limit), _i32(parent),
        _i32(nominal_cq), _i32(npb_cq),
        _i32(wl_cq), _i32(dec_fr), _i32(dec_amt), _u8(fit_mask),
        _i32(res_fr), _i32(res_amt), _u8(res_mask), _u8(res_borrows),
        _i32(order), admitted)
    return admitted.astype(bool)


def admit_scan(packed, dec_fr, dec_amt, fit_mask, res_fr, res_amt,
               res_mask, res_borrows, order):
    """The sequential admit loop in the compiled core — identical
    decisions to ops/cycle.admit_scan (tests/test_native_core.py).

    Decision inputs are the (flavor-resource, amount) pair tensors the
    solver builds (CycleSolver._build_pair_tensors).  Returns
    admitted [W] bool in head order."""
    st = packed.structure
    return admit_scan_raw(
        packed.usage0, packed.subtree_quota, packed.guaranteed,
        packed.borrow_cap, packed.has_borrow_limit, packed.parent,
        packed.nominal_cq, st.nominal_plus_blimit_cq,
        packed.wl_cq, dec_fr, dec_amt, fit_mask, res_fr, res_amt,
        res_mask, res_borrows, order)
