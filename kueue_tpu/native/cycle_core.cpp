// Native cycle core: the batched nominate/classify pass AND the
// sequential admit scan of the admission cycle (the same semantics as
// kueue_tpu/ops/cycle.py solve_cycle / admit_scan, which themselves
// mirror reference flavorassigner.go:499/:692 and scheduler.go:176-284)
// as a C library.
//
// This is the CPU-native backend of the solver plane: deployments without
// an accelerator (or cycles too small to amortize a device dispatch) run
// the identical classification + admit loop here; decision parity with
// both the JAX kernels and the scalar host oracle is enforced by
// tests/test_native_core.py.
//
// Build: g++ -O2 -shared -fPIC -o libcyclecore.so cycle_core.cpp
// (driven lazily by kueue_tpu/native/__init__.py).

#include <cstdint>
#include <vector>
#include <algorithm>

namespace {

// available() for one (node, fr): top-down fold over the parent chain
// (reference resource_node.go:89; mirrors ops/quota_kernel.available_all).
int64_t available(int node, int f, int F,
                  const int32_t* usage, const int32_t* subtree,
                  const int32_t* guaranteed, const int32_t* borrow_cap,
                  const uint8_t* has_blim, const int32_t* parent) {
    // collect the chain root→node
    std::vector<int> chain;
    for (int cur = node; cur >= 0; cur = parent[cur]) chain.push_back(cur);
    // root first
    int root = chain.back();
    int64_t avail = (int64_t)subtree[root * F + f] - usage[root * F + f];
    for (int i = (int)chain.size() - 2; i >= 0; --i) {
        int cur = chain[i];
        int64_t u = usage[cur * F + f];
        int64_t g = guaranteed[cur * F + f];
        int64_t local = std::max<int64_t>(0, g - u);
        int64_t parent_avail = avail;
        if (has_blim[cur * F + f]) {
            int64_t used_in_parent = std::max<int64_t>(0, u - g);
            int64_t blim_cap = (int64_t)borrow_cap[cur * F + f] - used_in_parent;
            parent_avail = std::min(blim_cap, parent_avail);
        }
        avail = local + parent_avail;
    }
    return avail;
}

}  // namespace

extern "C" {

// Classify every (workload, slot) pair; outputs per workload:
//   fit_slot[w]  : first Fit slot index or -1
//   borrows[w]   : the chosen slot borrows (usage+req > subtreeQuota, in cohort)
//   preempt[w]   : no Fit anywhere but some slot is preempt-capable
// Mirrors ops/cycle.py classify() exactly (per-resource mode lattice).
void classify_cycle(
    int32_t N, int32_t F, int32_t C, int32_t S, int32_t R, int32_t W,
    const int32_t* usage0,        // [N,F]
    const int32_t* subtree,       // [N,F]
    const int32_t* guaranteed,    // [N,F]
    const int32_t* borrow_cap,    // [N,F]
    const uint8_t* has_blim,      // [N,F]
    const int32_t* parent,        // [N]
    const int32_t* nominal_cq,    // [C,F]
    const int32_t* slot_fr,       // [C,S,R] F-index or -1
    const uint8_t* slot_valid,    // [C,S]
    const uint8_t* cq_can_preempt_borrow,  // [C]
    const int32_t* wl_cq,         // [W]
    const int32_t* wl_requests,   // [W,R]
    int32_t* fit_slot_out,        // [W]
    uint8_t* borrows_out,         // [W]
    uint8_t* preempt_out) {       // [W]

    // potential available = available with zero usage; precompute per (n,f)
    std::vector<int32_t> zero_usage((size_t)N * F, 0);
    std::vector<int64_t> avail((size_t)N * F), potential((size_t)N * F);
    for (int n = 0; n < N; ++n)
        for (int f = 0; f < F; ++f) {
            avail[(size_t)n * F + f] = available(
                n, f, F, usage0, subtree, guaranteed, borrow_cap,
                has_blim, parent);
            potential[(size_t)n * F + f] = available(
                n, f, F, zero_usage.data(), subtree, guaranteed, borrow_cap,
                has_blim, parent);
        }

    for (int w = 0; w < W; ++w) {
        fit_slot_out[w] = -1;
        borrows_out[w] = 0;
        preempt_out[w] = 0;
        int cq = wl_cq[w];
        if (cq < 0) continue;
        bool any_preempt = false;
        for (int s = 0; s < S && fit_slot_out[w] < 0; ++s) {
            bool missing = false, all_fit = true, any_nofit = false,
                 slot_borrows = false;
            for (int r = 0; r < R; ++r) {
                int64_t req = wl_requests[(size_t)w * R + r];
                if (req <= 0) continue;               // not requested
                int f = slot_fr[((size_t)cq * S + s) * R + r];
                if (f < 0) { missing = true; break; } // resource not covered
                int64_t av = avail[(size_t)cq * F + f];
                int64_t pot = potential[(size_t)cq * F + f];
                int64_t nom = nominal_cq[(size_t)cq * F + f];
                int64_t use = usage0[(size_t)cq * F + f];
                int64_t sq = subtree[(size_t)cq * F + f];
                bool fit_r = req <= av;
                bool nofit_r = req > pot;
                bool preempt_capable_r =
                    (req <= nom) || cq_can_preempt_borrow[cq];
                if (!fit_r) all_fit = false;
                if (nofit_r || (!fit_r && !preempt_capable_r))
                    any_nofit = true;
                if (use + req > sq) slot_borrows = true;
            }
            bool valid = slot_valid[(size_t)cq * S + s] && !missing;
            bool fit = all_fit && valid;
            bool nofit = any_nofit || !valid;
            if (fit) {
                fit_slot_out[w] = s;
                borrows_out[w] = (slot_borrows && parent[cq] >= 0) ? 1 : 0;
            } else if (!nofit) {
                any_preempt = true;
            }
        }
        if (fit_slot_out[w] < 0 && any_preempt) preempt_out[w] = 1;
    }
}

// The sequential admit loop over `order` (ops/cycle.py admit_scan; the
// reference admit loop's fixed-assignment fits re-check + capacity
// reserves, scheduler.go:245,383-408).  Decisions are per-head
// (flavor-resource, amount) pairs — assignment.Usage, exactly what the
// reference re-checks.  Mutates a private copy of usage.
void admit_scan(
    int32_t N, int32_t F, int32_t C, int32_t K, int32_t W,
    const int32_t* usage0,        // [N,F]
    const int32_t* subtree,       // [N,F]
    const int32_t* guaranteed,    // [N,F]
    const int32_t* borrow_cap,    // [N,F]
    const uint8_t* has_blim,      // [N,F]
    const int32_t* parent,        // [N]
    const int32_t* nominal_cq,    // [C,F]
    const int32_t* npb_cq,        // [C,F] nominal+borrowingLimit
    const int32_t* wl_cq,         // [W]
    const int32_t* dec_fr,        // [W,K] F-index or -1
    const int32_t* dec_amt,       // [W,K]
    const uint8_t* fit_mask,      // [W]
    const int32_t* res_fr,        // [W,K]
    const int32_t* res_amt,       // [W,K]
    const uint8_t* res_mask,      // [W]
    const uint8_t* res_borrows,   // [W]
    const int32_t* order,         // [W] cycle order
    uint8_t* admitted_out) {      // [W]

    std::vector<int32_t> usage(usage0, usage0 + (size_t)N * F);
    std::vector<int> chain;

    auto add_chain = [&](int node, int f, int64_t val) {
        // addUsage bubbling (resource_node.go:123)
        int64_t carry = val;
        for (int cur = node; cur >= 0 && carry != 0; cur = parent[cur]) {
            int64_t u = usage[(size_t)cur * F + f];
            int64_t g = guaranteed[(size_t)cur * F + f];
            int64_t local_avail = std::max<int64_t>(0, g - u);
            usage[(size_t)cur * F + f] = (int32_t)(u + carry);
            carry = std::max<int64_t>(0, carry - local_avail);
        }
    };

    for (int w = 0; w < W; ++w) admitted_out[w] = 0;
    for (int oi = 0; oi < W; ++oi) {
        int wi = order[oi];
        if (wi < 0 || wi >= W) continue;
        int cq = wl_cq[wi];
        if (cq < 0) continue;

        // the entry's root→cq chain depends only on cq: collect once,
        // reuse across the K pairs (no per-pair allocation)
        chain.clear();
        for (int cur = cq; cur >= 0; cur = parent[cur]) chain.push_back(cur);

        // per-step int32 truncation bit-matches the jitted kernel's
        // int32 arithmetic (the packer's x64 headroom keeps real values
        // in range; parity, not extra range, is the contract here)
        auto avail_at = [&](int f) -> int32_t {
            int root = chain.back();
            int32_t a = (int32_t)((int64_t)subtree[(size_t)root * F + f]
                                  - usage[(size_t)root * F + f]);
            for (int i = (int)chain.size() - 2; i >= 0; --i) {
                int cur = chain[i];
                int64_t u = usage[(size_t)cur * F + f];
                int64_t g = guaranteed[(size_t)cur * F + f];
                int64_t parent_avail = a;
                if (has_blim[(size_t)cur * F + f]) {
                    int64_t used_in_parent = std::max<int64_t>(0, u - g);
                    int64_t blim_cap =
                        (int64_t)borrow_cap[(size_t)cur * F + f]
                        - used_in_parent;
                    parent_avail = std::min(blim_cap, parent_avail);
                }
                a = (int32_t)(std::max<int64_t>(0, g - u) + parent_avail);
            }
            return a;
        };

        if (fit_mask[wi]) {
            bool ok = true;
            for (int k = 0; k < K && ok; ++k) {
                int f = dec_fr[(size_t)wi * K + k];
                if (f < 0) continue;
                if (dec_amt[(size_t)wi * K + k] > avail_at(f)) ok = false;
            }
            if (ok) {
                admitted_out[wi] = 1;
                for (int k = 0; k < K; ++k) {
                    int f = dec_fr[(size_t)wi * K + k];
                    if (f >= 0)
                        add_chain(cq, f, dec_amt[(size_t)wi * K + k]);
                }
            }
        }
        if (res_mask[wi]) {
            // resourcesToReserve (scheduler.go:383-408)
            for (int k = 0; k < K; ++k) {
                int f = res_fr[(size_t)wi * K + k];
                if (f < 0) continue;
                int64_t amt = res_amt[(size_t)wi * K + k];
                int64_t cur = usage[(size_t)cq * F + f];
                int64_t rdelta;
                if (res_borrows[wi]) {
                    rdelta = std::min<int64_t>(
                        amt, (int64_t)npb_cq[(size_t)cq * F + f] - cur);
                } else {
                    rdelta = std::max<int64_t>(
                        0, std::min<int64_t>(
                            amt,
                            (int64_t)nominal_cq[(size_t)cq * F + f] - cur));
                }
                add_chain(cq, f, rdelta);
            }
        }
    }
}

}  // extern "C"
