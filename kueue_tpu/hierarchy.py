"""Generic cohort-forest manager shared by cache and queue.

Capability parity with reference pkg/hierarchy (manager.go:27, cohort.go:26,
cycle.go:31): ClusterQueue-nodes attach to Cohort-nodes; Cohorts attach to
parent Cohorts, forming a forest.  Cohorts can exist implicitly (referenced
before being created explicitly) and vanish when no longer referenced and
not explicit.  Cycle detection guards edge updates.
"""

from __future__ import annotations

from typing import Callable, Generic, Iterator, Optional, TypeVar

CQ = TypeVar("CQ")
C = TypeVar("C")


class CohortNode(Generic[CQ, C]):
    """Wiring record for one cohort: payload + tree links."""

    def __init__(self, name: str, payload: C):
        self.name = name
        self.payload = payload
        self.parent: Optional["CohortNode[CQ, C]"] = None
        self.child_cohorts: dict[str, "CohortNode[CQ, C]"] = {}
        self.child_cqs: dict[str, CQ] = {}
        self.explicit = False  # created by an explicit Cohort object

    def has_parent(self) -> bool:
        return self.parent is not None

    def childless(self) -> bool:
        return not self.child_cohorts and not self.child_cqs

    def root(self) -> "CohortNode[CQ, C]":
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def walk_subtree(self) -> Iterator["CohortNode[CQ, C]"]:
        yield self
        for child in self.child_cohorts.values():
            yield from child.walk_subtree()

    def subtree_cqs(self) -> Iterator[CQ]:
        for node in self.walk_subtree():
            yield from node.child_cqs.values()


class Manager(Generic[CQ, C]):
    """Maintains the CQ/Cohort forest (reference pkg/hierarchy/manager.go:27)."""

    def __init__(self, cohort_factory: Callable[[str], C]):
        self._cohort_factory = cohort_factory
        self.cluster_queues: dict[str, CQ] = {}
        self.cohorts: dict[str, CohortNode[CQ, C]] = {}
        self._cq_parent: dict[str, CohortNode[CQ, C]] = {}

    # -- ClusterQueues --

    def add_cluster_queue(self, name: str, cq: CQ) -> None:
        self.cluster_queues[name] = cq

    def update_cluster_queue_edge(self, name: str, cohort_name: Optional[str]) -> None:
        self._detach_cq(name)
        if cohort_name:
            node = self._get_or_create(cohort_name)
            node.child_cqs[name] = self.cluster_queues[name]
            self._cq_parent[name] = node

    def delete_cluster_queue(self, name: str) -> None:
        self._detach_cq(name)
        self.cluster_queues.pop(name, None)

    def cq_parent(self, name: str) -> Optional[CohortNode[CQ, C]]:
        return self._cq_parent.get(name)

    # -- Cohorts --

    def add_cohort(self, name: str) -> CohortNode[CQ, C]:
        node = self._get_or_create(name)
        node.explicit = True
        return node

    def update_cohort_edge(self, name: str, parent_name: Optional[str]) -> None:
        node = self._get_or_create(name)
        old_parent = node.parent
        if old_parent is not None:
            old_parent.child_cohorts.pop(name, None)
            node.parent = None
            self._maybe_gc(old_parent)
        if parent_name:
            parent = self._get_or_create(parent_name)
            parent.child_cohorts[name] = node
            node.parent = parent

    def delete_cohort(self, name: str) -> None:
        node = self.cohorts.get(name)
        if node is None:
            return
        node.explicit = False
        if node.parent is not None:
            node.parent.child_cohorts.pop(name, None)
            parent, node.parent = node.parent, None
            self._maybe_gc(parent)
        self._maybe_gc(node)

    def cohort(self, name: str) -> Optional[CohortNode[CQ, C]]:
        return self.cohorts.get(name)

    def roots(self) -> list[CohortNode[CQ, C]]:
        return [n for n in self.cohorts.values() if n.parent is None]

    # -- internals --

    def _detach_cq(self, name: str) -> None:
        node = self._cq_parent.pop(name, None)
        if node is not None:
            node.child_cqs.pop(name, None)
            self._maybe_gc(node)

    def _get_or_create(self, name: str) -> CohortNode[CQ, C]:
        node = self.cohorts.get(name)
        if node is None:
            node = CohortNode(name, self._cohort_factory(name))
            self.cohorts[name] = node
        return node

    def _maybe_gc(self, node: CohortNode[CQ, C]) -> None:
        if not node.explicit and node.childless() and node.parent is None:
            self.cohorts.pop(node.name, None)


def has_cycle(node: CohortNode) -> bool:
    """Cycle check walking parent pointers (reference pkg/hierarchy/cycle.go:31)."""
    seen = set()
    cur: Optional[CohortNode] = node
    while cur is not None:
        if id(cur) in seen:
            return True
        seen.add(id(cur))
        cur = cur.parent
    return False
