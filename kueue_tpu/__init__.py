"""kueue_tpu: a TPU-native job-queueing framework with the capabilities of Kueue.

Quota-based admission of gang workloads across hierarchical cohorts of
ClusterQueues with resource flavors, borrowing/lending, priority and
fair-share (DRF) preemption, two-phase admission checks, topology-aware
placement and multi-cluster dispatch.  The per-cycle admission core runs as
a batched JAX/XLA solver (see kueue_tpu.ops) driven by a thin control plane
that mirrors the reference's cache/queue/event semantics.
"""

__version__ = "0.1.0"
