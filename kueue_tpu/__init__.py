"""kueue_tpu: a TPU-native job-queueing framework with the capabilities of Kueue.

Quota-based admission of gang workloads across hierarchical cohorts of
ClusterQueues with resource flavors, borrowing/lending, priority and
fair-share (DRF) preemption, two-phase admission checks, topology-aware
placement and multi-cluster dispatch.  The per-cycle admission core runs as
a batched JAX/XLA solver (see kueue_tpu.ops) driven by a thin control plane
that mirrors the reference's cache/queue/event semantics.
"""

__version__ = "0.1.0"

# Loading XLA:CPU AOT compilation-cache entries logs two multi-KB ERROR
# lines about tuning pseudo-features per load; the env var must be set
# before jaxlib's static initialization, so it lives here rather than in
# compilecache.enable().  KUEUE_TPU_COMPILE_CACHE=0 restores full logs.
import os as _os

from .features import env_value as _env_value

if _env_value("KUEUE_TPU_COMPILE_CACHE") != "0":
    _os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
del _os
