"""The Configuration file format, defaulting and validation.

Capability parity with reference apis/config/v1beta1/configuration_types.go
(Configuration :31, WaitForPodsReady :216, Integrations :351, Resources
:418, FairSharing :452, MultiKueue :248) plus pkg/config/config.go:156
Load and pkg/config/validation.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..features import DEFAULT_FEATURE_GATES

DEFAULT_NAMESPACE = "kueue-system"
DEFAULT_REQUEUING_BACKOFF_BASE_SECONDS = 60
DEFAULT_REQUEUING_BACKOFF_MAX_SECONDS = 3600
DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS = 60.0
DEFAULT_MULTIKUEUE_ORIGIN = "multikueue"
DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS = 15 * 60.0

KNOWN_FRAMEWORKS = (
    "batch/job", "pod", "pod-group", "jobset.x-k8s.io/jobset",
    "kubeflow.org/tfjob", "kubeflow.org/pytorchjob",
    "kubeflow.org/xgboostjob", "kubeflow.org/paddlejob",
    "kubeflow.org/jaxjob", "kubeflow.org/mpijob",
    "ray.io/rayjob", "ray.io/raycluster",
    "workload.codeflare.dev/appwrapper",
    "leaderworkerset.x-k8s.io/leaderworkerset",
    "statefulset", "deployment",
)

PREEMPTION_STRATEGIES = ("LessThanOrEqualToFinalShare", "LessThanInitialShare")


class ConfigValidationError(ValueError):
    def __init__(self, errors: list[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


@dataclass
class RequeuingStrategy:
    """configuration_types.go:270."""
    timestamp: str = "Eviction"          # Eviction | Creation
    backoff_limit_count: Optional[int] = None
    backoff_base_seconds: int = DEFAULT_REQUEUING_BACKOFF_BASE_SECONDS
    backoff_max_seconds: int = DEFAULT_REQUEUING_BACKOFF_MAX_SECONDS


@dataclass
class WaitForPodsReady:
    """configuration_types.go:216."""
    enable: bool = False
    timeout_seconds: float = 300.0
    block_admission: bool = False
    recovery_timeout_seconds: Optional[float] = None
    requeuing_strategy: RequeuingStrategy = field(
        default_factory=RequeuingStrategy)


@dataclass
class IntegrationsConfig:
    """configuration_types.go:351."""
    frameworks: list[str] = field(
        default_factory=lambda: ["batch/job"])
    external_frameworks: list[str] = field(default_factory=list)
    label_keys_to_copy: list[str] = field(default_factory=list)


@dataclass
class ResourceTransformation:
    """configuration_types.go:432."""
    input: str = ""
    strategy: str = "Retain"             # Retain | Replace
    outputs: dict[str, int] = field(default_factory=dict)


@dataclass
class ResourcesConfig:
    """configuration_types.go:418."""
    exclude_resource_prefixes: list[str] = field(default_factory=list)
    transformations: list[ResourceTransformation] = field(
        default_factory=list)


@dataclass
class FairSharingConfig:
    """configuration_types.go:452."""
    enable: bool = False
    preemption_strategies: list[str] = field(
        default_factory=lambda: list(PREEMPTION_STRATEGIES))


@dataclass
class MultiKueueConfigOptions:
    """configuration_types.go:248."""
    gc_interval_seconds: float = DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS
    origin: str = DEFAULT_MULTIKUEUE_ORIGIN
    worker_lost_timeout_seconds: float = (
        DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS)


@dataclass
class Configuration:
    """configuration_types.go:31."""
    namespace: str = DEFAULT_NAMESPACE
    manage_jobs_without_queue_name: bool = False
    managed_jobs_namespace_selector: dict[str, str] = field(
        default_factory=dict)
    leader_election: bool = True
    metrics_bind_address: str = ":8443"
    health_probe_bind_address: str = ":8081"
    enable_clusterqueue_resources_metrics: bool = False
    wait_for_pods_ready: WaitForPodsReady = field(
        default_factory=WaitForPodsReady)
    integrations: IntegrationsConfig = field(
        default_factory=IntegrationsConfig)
    resources: ResourcesConfig = field(default_factory=ResourcesConfig)
    fair_sharing: FairSharingConfig = field(default_factory=FairSharingConfig)
    multikueue: MultiKueueConfigOptions = field(
        default_factory=MultiKueueConfigOptions)
    queue_visibility_update_interval_seconds: float = 5.0
    feature_gates: dict[str, bool] = field(default_factory=dict)


def default_configuration() -> Configuration:
    return Configuration()


# ---------------------------------------------------------------------------
# Load (pkg/config/config.go:156)
# ---------------------------------------------------------------------------

def load(path: str) -> Configuration:
    import yaml
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    cfg = _from_dict(raw)
    errors = validate(cfg)
    if errors:
        raise ConfigValidationError(errors)
    return cfg


def _from_dict(raw: dict) -> Configuration:
    cfg = Configuration()
    cfg.namespace = raw.get("namespace", cfg.namespace)
    cfg.manage_jobs_without_queue_name = raw.get(
        "manageJobsWithoutQueueName", cfg.manage_jobs_without_queue_name)
    cfg.managed_jobs_namespace_selector = (
        (raw.get("managedJobsNamespaceSelector") or {}).get("matchLabels", {}))
    cfg.leader_election = (raw.get("leaderElection") or {}).get(
        "leaderElect", cfg.leader_election)
    cfg.metrics_bind_address = (raw.get("metrics") or {}).get(
        "bindAddress", cfg.metrics_bind_address)
    cfg.enable_clusterqueue_resources_metrics = (raw.get("metrics") or {}).get(
        "enableClusterQueueResources",
        cfg.enable_clusterqueue_resources_metrics)
    cfg.health_probe_bind_address = (raw.get("health") or {}).get(
        "healthProbeBindAddress", cfg.health_probe_bind_address)

    wfpr = raw.get("waitForPodsReady") or {}
    if wfpr:
        rq = wfpr.get("requeuingStrategy") or {}
        cfg.wait_for_pods_ready = WaitForPodsReady(
            enable=wfpr.get("enable", False),
            timeout_seconds=_seconds(wfpr.get("timeout", "5m")),
            block_admission=wfpr.get("blockAdmission",
                                     wfpr.get("enable", False)),
            recovery_timeout_seconds=(
                _seconds(wfpr["recoveryTimeout"])
                if "recoveryTimeout" in wfpr else None),
            requeuing_strategy=RequeuingStrategy(
                timestamp=rq.get("timestamp", "Eviction"),
                backoff_limit_count=rq.get("backoffLimitCount"),
                backoff_base_seconds=rq.get(
                    "backoffBaseSeconds",
                    DEFAULT_REQUEUING_BACKOFF_BASE_SECONDS),
                backoff_max_seconds=rq.get(
                    "backoffMaxSeconds",
                    DEFAULT_REQUEUING_BACKOFF_MAX_SECONDS)))

    integ = raw.get("integrations") or {}
    if integ:
        cfg.integrations = IntegrationsConfig(
            frameworks=integ.get("frameworks", ["batch/job"]),
            external_frameworks=integ.get("externalFrameworks", []),
            label_keys_to_copy=integ.get("labelKeysToCopy", []))

    res = raw.get("resources") or {}
    if res:
        cfg.resources = ResourcesConfig(
            exclude_resource_prefixes=res.get("excludeResourcePrefixes", []),
            transformations=[
                ResourceTransformation(
                    input=t.get("input", ""),
                    strategy=t.get("strategy", "Retain"),
                    outputs=t.get("outputs", {}))
                for t in res.get("transformations", [])])

    fs = raw.get("fairSharing") or {}
    if fs:
        cfg.fair_sharing = FairSharingConfig(
            enable=fs.get("enable", False),
            preemption_strategies=fs.get(
                "preemptionStrategies", list(PREEMPTION_STRATEGIES)))

    mk = raw.get("multiKueue") or {}
    if mk:
        cfg.multikueue = MultiKueueConfigOptions(
            gc_interval_seconds=_seconds(mk.get("gcInterval", "1m")),
            origin=mk.get("origin", DEFAULT_MULTIKUEUE_ORIGIN),
            worker_lost_timeout_seconds=_seconds(
                mk.get("workerLostTimeout", "15m")))

    cfg.feature_gates = dict(raw.get("featureGates") or {})
    return cfg


def _seconds(v) -> float:
    """Parse a metav1.Duration-ish value ("5m", "300s", 300)."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    for suffix in ("ms", "s", "m", "h"):
        if s.endswith(suffix):
            return float(s[:-len(suffix)]) * units[suffix]
    return float(s)


# ---------------------------------------------------------------------------
# Validate (pkg/config/validation.go)
# ---------------------------------------------------------------------------

def validate(cfg: Configuration) -> list[str]:
    errors: list[str] = []
    w = cfg.wait_for_pods_ready
    if w.enable:
        if w.timeout_seconds <= 0:
            errors.append("waitForPodsReady.timeout must be positive")
        rs = w.requeuing_strategy
        if rs.timestamp not in ("Eviction", "Creation"):
            errors.append(
                f"waitForPodsReady.requeuingStrategy.timestamp "
                f"{rs.timestamp!r} not in (Eviction, Creation)")
        if rs.backoff_limit_count is not None and rs.backoff_limit_count < 0:
            errors.append("requeuingStrategy.backoffLimitCount must be >= 0")
        if rs.backoff_base_seconds < 0:
            errors.append("requeuingStrategy.backoffBaseSeconds must be >= 0")
    for fw in cfg.integrations.frameworks:
        if fw not in KNOWN_FRAMEWORKS:
            errors.append(f"unknown framework {fw!r} in integrations")
    for st in cfg.fair_sharing.preemption_strategies:
        if st not in PREEMPTION_STRATEGIES:
            errors.append(f"unknown preemption strategy {st!r}")
    for t in cfg.resources.transformations:
        if not t.input:
            errors.append("resource transformation with empty input")
        if t.strategy not in ("Retain", "Replace"):
            errors.append(f"unknown transformation strategy {t.strategy!r}")
    seen = set()
    for t in cfg.resources.transformations:
        if t.input in seen:
            errors.append(f"duplicate transformation input {t.input!r}")
        seen.add(t.input)
    # ValidateFeatureGates (pkg/config/validation.go:359)
    for name in cfg.feature_gates:
        if name not in DEFAULT_FEATURE_GATES:
            errors.append(f"unknown feature gate {name!r}")
    return errors
