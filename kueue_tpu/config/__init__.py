"""Controller configuration (reference apis/config/v1beta1 + pkg/config)."""

from .configuration import (
    Configuration,
    ConfigValidationError,
    FairSharingConfig,
    IntegrationsConfig,
    MultiKueueConfigOptions,
    RequeuingStrategy,
    ResourceTransformation,
    ResourcesConfig,
    WaitForPodsReady,
    default_configuration,
    load,
    validate,
)

__all__ = [
    "Configuration", "ConfigValidationError", "FairSharingConfig",
    "IntegrationsConfig", "MultiKueueConfigOptions", "RequeuingStrategy",
    "ResourceTransformation", "ResourcesConfig", "WaitForPodsReady",
    "default_configuration", "load", "validate",
]
