"""Deterministic chaos harness: seeded fault injection for crash,
divergence, device-loss, journal-corruption, and transport scenarios.

See :mod:`kueue_tpu.chaos.injector` for the site catalogue and
``scripts/chaos_soak.py`` for the CHAOS_r09 soak that drives it."""

from .injector import (   # noqa: F401
    ACTIVE,
    ChaosInjector,
    Fault,
    InjectedCrash,
    active,
    clear,
    from_env,
    install,
)
