"""Seeded deterministic fault injection for the admission stack.

A :class:`ChaosInjector` owns a set of armed :class:`Fault`\\ s, each
bound to a named *site* — a fixed point in the hot path that consults
the injector when it executes.  Sites fire deterministically: either at
an exact hit index (``at=``) or by a seeded coin flip (``prob=``), so a
scenario replays identically under the same seed.  The whole layer is
inert unless an injector is installed: every site guards on the module
global ``ACTIVE`` and costs one attribute read when chaos is off.

Injection sites threaded through the stack:

==============================  =============================================
site                            effect at the call point
==============================  =============================================
``cycle.start``                 crash before a normal scheduling cycle
``burst.window_boundary``       crash between fused-burst windows
``burst.mid_window``            crash between applied cycles inside a window
``burst.force_spec_divergence`` discard a speculative window unconsumed
                                (forces the pipeline cancel path)
``wal.admit``                   crash after the admit op is journaled but
                                before the store write
``wal.evict``                   crash after the evict op is journaled but
                                before the status mutations
``wal.finish``                  crash after the finish op is journaled but
                                before the conditions flip
``wal.requeue``                 crash after the requeue-backoff op is
                                journaled but before the requeue state and
                                eviction land
``wal.compact``                 crash mid-compaction: the checkpoint temp
                                file is written and fsynced but the atomic
                                rename has not happened (recovery reads
                                the old, uncompacted journal)
``wal.shard_merge``             crash between per-segment compactions of a
                                sharded WAL: segments sit at mixed
                                compaction generations and the seq-merged
                                replay must still converge
``shard.device_loss``           drop ``payload`` devices from the burst mesh
                                (re-partition over the survivors)
``journal.drop_touch``          eat a PackJournal ``touch`` (lost update;
                                the journal taints itself and the next pack
                                falls back to a full walk)
``journal.spurious_dirty_all``  raise the PackJournal dirty-all flag
``remote.delay``                sleep ``payload`` seconds before a remote call
``remote.duplicate``            issue a remote mutation twice
``remote.partition``            fail the next ``times`` remote calls with
                                ConnectionLost (healed by backoff retry)
``remote.duplicate_event``      re-deliver a watch batch: events are pushed
                                but the resume token does not advance, so
                                the next poll replays the same batch
``fed.partition``               sever the payload worker clusters from the
                                federation sim for ``payload`` steps (every
                                client op raises ConnectionLost)
``fed.worker_crash``            kill the payload worker mid-admission (WAL
                                tail journaled but unapplied) and recover
                                it from its journal within the same step
``fed.cluster_loss``            sever the payload worker cluster forever
                                (drives the eject/re-dispatch path)
``obs.dump``                    crash mid-flight-recorder dump: the ring
                                snapshot is taken but serialization has not
                                happened (a re-dump after recovery must be
                                identical — dumping never mutates the ring)
``svc.ingest``                  crash after a submission's accept record is
                                journaled durably but before it enters the
                                in-memory ingest queue (recovery re-enqueues
                                it from the ingest journal)
``svc.cycle``                   crash at a service-step boundary, before the
                                ingest drain (pending ingest entries and the
                                WAL tail survive on disk)
``svc.shutdown``                crash mid graceful drain: in-flight cycles
                                finished but the final WAL/ingest-journal
                                flush has not happened
``dist.kill``                   SIGKILL the child process whose name matches
                                ``payload`` (empty payload = any candidate)
                                at the supervisor's next barrier consult —
                                a real process death, not an exception
``dist.proxy_fault``            inject a wire fault on the socket proxy's
                                next connection: ``action`` picks the verb
                                (reset/latency/truncate/blackhole),
                                ``payload`` the seconds or bytes
==============================  =============================================

``KUEUE_TPU_CHAOS_SEED`` seeds the process-default injector (see
:func:`from_env`); tests and the soak install one programmatically.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from ..features import env_value


class InjectedCrash(RuntimeError):
    """A chaos-armed crash site fired: the driver process 'dies' here.

    Carries the site name; harnesses catch it, discard the driver, and
    recover a fresh one from the durable store + WAL."""

    def __init__(self, site: str):
        super().__init__(f"injected crash at {site}")
        self.site = site


@dataclass
class Fault:
    """One armed fault: fires at hit ``at`` (1-based) or with seeded
    probability ``prob``, up to ``times`` times in total."""
    site: str
    at: Optional[int] = None       # exact hit index (1-based)
    prob: float = 0.0              # seeded per-hit coin flip
    times: int = 1                 # max fires
    action: str = "crash"          # "crash" | site-specific verb
    payload: object = None         # site-specific argument
    fired: int = 0                 # fires so far

    def should_fire(self, hit: int, rng: random.Random) -> bool:
        if self.fired >= self.times:
            return False
        if self.at is not None:
            return hit == self.at or (self.times > 1 and hit > self.at)
        return self.prob > 0 and rng.random() < self.prob


class ChaosInjector:
    """Deterministic, seeded fault injector.

    ``hit(site)`` is called from an injection point; it counts the hit
    and returns the armed :class:`Fault` that fires there (or None).
    ``crashpoint(site)`` additionally raises :class:`InjectedCrash`
    when the fired fault's action is ``"crash"``."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.faults: list[Fault] = []
        self.counts: dict[str, int] = {}
        self.log: list[tuple[str, int, str]] = []  # (site, hit, action)

    def arm(self, site: str, at: Optional[int] = None, prob: float = 0.0,
            times: int = 1, action: str = "crash",
            payload: object = None) -> Fault:
        f = Fault(site=site, at=at, prob=prob, times=times,
                  action=action, payload=payload)
        self.faults.append(f)
        return f

    def disarm(self, site: str) -> None:
        self.faults = [f for f in self.faults if f.site != site]

    def hit(self, site: str) -> Optional[Fault]:
        n = self.counts.get(site, 0) + 1
        self.counts[site] = n
        for f in self.faults:
            if f.site == site and f.should_fire(n, self.rng):
                f.fired += 1
                self.log.append((site, n, f.action))
                return f
        return None

    def crashpoint(self, site: str) -> None:
        f = self.hit(site)
        if f is not None and f.action == "crash":
            raise InjectedCrash(site)

    def report(self) -> dict:
        """The ``chaos`` block stamped into artifacts: what was armed,
        what actually fired, under which seed."""
        return {
            "seed": self.seed,
            "hits": dict(sorted(self.counts.items())),
            "armed": [{"site": f.site, "at": f.at, "prob": f.prob,
                       "times": f.times, "action": f.action,
                       "fired": f.fired} for f in self.faults],
            "fired": [{"site": s, "hit": h, "action": a}
                      for s, h, a in self.log],
        }


# The process-wide injector every site consults.  None = chaos off; the
# per-site cost is then a module-global read and a None check.
ACTIVE: Optional[ChaosInjector] = None


def install(inj: Optional[ChaosInjector]) -> Optional[ChaosInjector]:
    global ACTIVE
    ACTIVE = inj
    return inj


def clear() -> None:
    install(None)


def active() -> Optional[ChaosInjector]:
    return ACTIVE


def from_env() -> Optional[ChaosInjector]:
    """Install an injector seeded from ``KUEUE_TPU_CHAOS_SEED`` (unset
    or empty = chaos off).  The caller arms faults afterwards."""
    seed = env_value("KUEUE_TPU_CHAOS_SEED")
    if not seed:
        return None
    try:
        return install(ChaosInjector(seed=int(seed)))
    except ValueError:
        return None
