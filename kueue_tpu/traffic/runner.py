"""Open-loop runner: arrival-driven load against ``Driver.schedule_once``.

Timeline semantics — the key to replayability: events carry *virtual*
timestamps from the arrival process, and cycle ``k`` runs at virtual
time ``(k+1)·dt`` after injecting every event with ``t <= (k+1)·dt``.
The driver's clock is a virtual clock stepped by the runner, and
workload ``creation_time`` is the event's virtual time, so every
scheduling decision is a pure function of the event stream — a
recorded stream replayed through ``ReplayStream`` reproduces the
per-cycle decisions bit for bit.  Wall-clock is measured *around* each
cycle and reported separately: virtual latency answers "does the
schedule keep up with the offered rate", wall cost answers "how fast
does the host run".

Saturation search: ``find_sustainable_rate`` binary-searches the
highest arrival rate whose p99 submit→admit latency (censored —
workloads still waiting at the horizon count at their current age)
meets the SLO.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import PodSet, Workload
from ..metrics import LATENCY_BUCKETS


@dataclass
class OpenLoopConfig:
    duration_s: float = 60.0        # virtual horizon (arrivals stop here)
    dt_s: float = 1.0               # virtual seconds per scheduling cycle
    slo_p99_s: float = 8.0          # p99 submit→admit SLO, virtual seconds
    wall_budget_s: Optional[float] = None  # stop early past this wall time
    sample_every: int = 8           # gauge-sampling cadence, cycles


@dataclass
class OpenLoopResult:
    rate_per_s: float = 0.0         # annotated by the caller
    cycles: int = 0
    submitted: int = 0
    admitted: int = 0
    cancelled: int = 0
    churned: int = 0
    remote_submitted: int = 0
    p50_latency_s: float = 0.0      # censored-inclusive, virtual seconds
    p99_latency_s: float = 0.0
    mean_latency_s: float = 0.0
    end_depth: int = 0              # pending (submitted − admitted − cancelled)
    max_depth: int = 0
    latency_hist: list = field(default_factory=list)  # [bucket_le, count]
    wall_s: float = 0.0
    cycle_wall_p50_ms: float = 0.0
    cycle_wall_p99_ms: float = 0.0
    admissions_per_wall_s: float = 0.0
    requeue_unparked: int = 0
    requeue_storm_peak: int = 0
    snap_cqs_recloned_per_cycle: float = 0.0
    snap_trees_reused_per_cycle: float = 0.0
    snap_full_rebuilds: int = 0
    truncated: bool = False         # wall budget hit before the horizon
    meets_slo: bool = False
    events: list = field(default_factory=list)        # consumed stream
    decisions: list = field(default_factory=list)     # per-cycle admits


def _pctile(xs: list, q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


class RateEWMA:
    """Exponentially-weighted arrival-rate estimator (events/s).

    The admission service (serving/service.py) updates it once per
    service step with the count of arrivals observed over that step's
    ``dt`` and reads ``rate_per_s`` when choosing the burst-window K
    online; ``halflife_s`` sets how fast the estimate tracks a
    diurnal/MMPP swing (after one halflife of steady traffic the old
    estimate contributes half the weight).  The first update primes the
    estimate directly so a cold start doesn't spend a halflife climbing
    from zero."""

    def __init__(self, halflife_s: float = 5.0):
        if halflife_s <= 0:
            raise ValueError("halflife_s must be positive")
        self.halflife_s = float(halflife_s)
        self.rate_per_s = 0.0
        self._primed = False

    def update(self, n_events: int, dt_s: float) -> float:
        if dt_s <= 0:
            return self.rate_per_s
        inst = n_events / dt_s
        if not self._primed:
            self.rate_per_s = inst
            self._primed = True
        else:
            a = 0.5 ** (dt_s / self.halflife_s)
            self.rate_per_s = a * self.rate_per_s + (1.0 - a) * inst
        return self.rate_per_s


def _next_or_none(it):
    try:
        return next(it)
    except StopIteration:
        return None


def run_open_loop(driver, clock, stream, cfg: OpenLoopConfig,
                  remote_client=None) -> OpenLoopResult:
    """Drive ``driver`` with ``stream``'s events for the virtual
    horizon.  ``clock`` is the driver's virtual clock (an object with a
    mutable ``t``); ``remote_client`` (remote.py WorkerClient) receives
    remote-flagged submissions — the MultiKueue path."""
    epoch = clock.t
    res = OpenLoopResult()
    waiting: dict[str, float] = {}       # key → virtual submit time
    runtime_of: dict[str, float] = {}
    finish_at: dict[int, list[str]] = {}
    latencies: list[float] = []
    hist = [0] * (len(LATENCY_BUCKETS) + 1)
    cycle_walls: list[float] = []
    n_cycles = max(1, int(round(cfg.duration_s / cfg.dt_s)))
    snap0 = dict(driver.cache.snapshot_stats)
    unparked0 = driver.queues.requeue_unparked_total
    prev_unparked = unparked0
    it = iter(stream)
    buf = _next_or_none(it)
    wall0 = time.perf_counter()

    def observe_latency(lat: float) -> None:
        latencies.append(lat)
        driver.metrics.open_loop_latency(lat)
        for i, b in enumerate(LATENCY_BUCKETS):
            if lat <= b:
                hist[i] += 1
                return
        hist[-1] += 1

    for k in range(n_cycles):
        t_k = (k + 1) * cfg.dt_s
        clock.t = epoch + t_k
        # service completions scheduled for this cycle
        for key in finish_at.pop(k, ()):
            wl = driver.workloads.get(key)
            if wl is not None and wl.has_quota_reservation \
                    and not wl.is_finished:
                driver.finish_workload(key)
        # inject every event due by this cycle's virtual time
        while buf is not None and buf.t <= t_k:
            ev = buf
            res.events.append(ev)
            if ev.kind == "submit":
                ns, name = ev.key.split("/", 1)
                wl = Workload(name=name, namespace=ns,
                              queue_name=f"lq-{ev.cq}",
                              priority=ev.priority,
                              creation_time=epoch + ev.t,
                              pod_sets=[PodSet(name="main", count=1,
                                               requests={"cpu": ev.cpu_m})])
                if ev.remote and remote_client is not None:
                    remote_client.create_workload(wl)
                    res.remote_submitted += 1
                else:
                    driver.create_workload(wl)
                waiting[ev.key] = ev.t
                runtime_of[ev.key] = ev.runtime_s
                res.submitted += 1
            elif ev.kind == "cancel":
                if waiting.pop(ev.key, None) is not None:
                    driver.delete_workload(ev.key)
                    res.cancelled += 1
            elif ev.kind == "priority":
                if ev.key in waiting:
                    wl = driver.workloads.get(ev.key)
                    if wl is not None and wl.admission is None:
                        wl.priority = ev.priority
                        driver.queues.add_or_update_workload(wl)
                        res.churned += 1
            buf = _next_or_none(it)
        w0 = time.perf_counter()
        stats = driver.schedule_once()
        cycle_walls.append(time.perf_counter() - w0)
        res.cycles = k + 1
        admitted_now = sorted(stats.admitted)
        res.decisions.append(admitted_now)
        for key in admitted_now:
            t_sub = waiting.pop(key, None)
            if t_sub is None:
                continue   # re-admission of an evicted workload
            res.admitted += 1
            observe_latency(t_k - t_sub)
            runtime = runtime_of.pop(key, cfg.dt_s)
            finish_at.setdefault(
                k + max(1, int(round(runtime / cfg.dt_s))), []).append(key)
        res.max_depth = max(res.max_depth, len(waiting))
        unparked = driver.queues.requeue_unparked_total
        if unparked > prev_unparked:
            driver.metrics.open_loop_requeue_storm(unparked - prev_unparked)
            prev_unparked = unparked
        if (k + 1) % cfg.sample_every == 0 or k + 1 == n_cycles:
            ages = [t_k - ts for ts in waiting.values()]
            wall = time.perf_counter() - wall0
            driver.metrics.open_loop_sample(
                depth_active=len(waiting),
                depth_parked=sum(
                    q.pending_inadmissible()
                    for n in list(driver.queues._timers)
                    if (q := driver.queues.queue_for(n)) is not None),
                age_p50_s=_pctile(ages, 0.50),
                age_p99_s=_pctile(ages, 0.99),
                admissions_per_s=res.admitted / wall if wall > 0 else 0.0)
        if cfg.wall_budget_s is not None \
                and time.perf_counter() - wall0 > cfg.wall_budget_s:
            res.truncated = True
            break

    res.wall_s = time.perf_counter() - wall0
    t_end = res.cycles * cfg.dt_s
    # censored tail: a workload still waiting at the horizon has latency
    # of AT LEAST its current age — excluding it would make a saturated
    # run look healthy
    censored = [t_end - ts for ts in waiting.values()]
    all_lat = latencies + censored
    res.p50_latency_s = _pctile(all_lat, 0.50)
    res.p99_latency_s = _pctile(all_lat, 0.99)
    res.mean_latency_s = (sum(all_lat) / len(all_lat)) if all_lat else 0.0
    res.end_depth = len(waiting)
    res.latency_hist = [[LATENCY_BUCKETS[i] if i < len(LATENCY_BUCKETS)
                         else None, c]
                        for i, c in enumerate(hist) if c]
    res.cycle_wall_p50_ms = _pctile(cycle_walls, 0.50) * 1000.0
    res.cycle_wall_p99_ms = _pctile(cycle_walls, 0.99) * 1000.0
    res.admissions_per_wall_s = (res.admitted / res.wall_s
                                 if res.wall_s > 0 else 0.0)
    res.requeue_unparked = driver.queues.requeue_unparked_total - unparked0
    res.requeue_storm_peak = driver.queues.requeue_storm_peak
    snap1 = driver.cache.snapshot_stats
    cyc = max(1, res.cycles)
    res.snap_cqs_recloned_per_cycle = (
        (snap1["snap_cqs_recloned"] - snap0["snap_cqs_recloned"]) / cyc)
    res.snap_trees_reused_per_cycle = (
        (snap1["snap_trees_reused"] - snap0["snap_trees_reused"]) / cyc)
    res.snap_full_rebuilds = snap1["snap_full"] - snap0["snap_full"]
    res.meets_slo = (not res.truncated
                     and res.p99_latency_s <= cfg.slo_p99_s)
    return res


def find_sustainable_rate(run_at_rate: Callable[[float], OpenLoopResult],
                          lo: float, hi: float, iters: int = 5
                          ) -> tuple[float, list[OpenLoopResult]]:
    """Binary-search the highest sustainable arrival rate in [lo, hi].

    ``run_at_rate(rate)`` must build a fresh driver + stream and return
    its OpenLoopResult (with ``meets_slo`` set).  ``lo`` is assumed
    sustainable (probe it first and pass a lower lo if not); returns
    ``(best_rate, probes)`` where best_rate is the largest probed rate
    that met the SLO (lo if none did)."""
    probes: list[OpenLoopResult] = []
    best = lo
    r_lo, r_hi = lo, hi
    for _ in range(iters):
        mid = 0.5 * (r_lo + r_hi)
        r = run_at_rate(mid)
        r.rate_per_s = mid
        probes.append(r)
        if r.meets_slo:
            best = mid
            r_lo = mid
        else:
            r_hi = mid
    return best, probes
