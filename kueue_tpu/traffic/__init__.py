"""Open-loop traffic subsystem: seeded arrival processes + runner.

``arrivals`` generates replayable, deterministic workload event streams
(submissions, cancellations, priority churn) from Poisson / diurnal /
bursty-MMPP arrival processes; ``runner`` feeds them into
``Driver.schedule_once`` at a target rate and measures admission
latency, queue-depth growth, and requeue storms, with a binary-search
mode for the sustainable rate at a fixed p99 SLO.
"""

from .arrivals import (
    ArrivalStream,
    DiurnalProcess,
    MMPPProcess,
    PoissonProcess,
    ReplayStream,
    TrafficEvent,
    TrafficSpec,
)
from .runner import (
    OpenLoopConfig,
    OpenLoopResult,
    RateEWMA,
    find_sustainable_rate,
    run_open_loop,
)

__all__ = [
    "ArrivalStream",
    "DiurnalProcess",
    "MMPPProcess",
    "PoissonProcess",
    "ReplayStream",
    "TrafficEvent",
    "TrafficSpec",
    "OpenLoopConfig",
    "OpenLoopResult",
    "RateEWMA",
    "find_sustainable_rate",
    "run_open_loop",
]
