"""Seeded, deterministic arrival processes for open-loop traffic.

Every process and the stream wrapper are pure functions of their seed:
the same ``(process, spec, seed)`` triple yields the same event
sequence across runs, and a pickle round-trip mid-stream resumes with
the identical tail (``random.Random`` pickles its full Mersenne state).
Event timestamps are *virtual* seconds; the runner quantizes them onto
scheduling cycles, which is what makes a recorded stream replayable
decision-bit-identically (traffic/runner.py).

Seeds are mixed with integer constants only — never hashed tuples or
strings, whose hashes are salted per-process by PYTHONHASHSEED and
would silently break cross-run determinism.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Iterator, Optional

_MIX_PROCESS = 0x9E3779B1   # golden-ratio constants: decorrelate the
_MIX_MARKS = 0x85EBCA6B     # process clock from the mark draws


@dataclass(frozen=True)
class TrafficEvent:
    """One event in the open-loop stream.

    ``submit`` carries the full workload shape; ``cancel`` and
    ``priority`` target a previously-submitted key (``cq`` is -1 and
    the shape fields are unused)."""

    t: float                 # virtual arrival time, seconds
    kind: str                # "submit" | "cancel" | "priority"
    key: str                 # workload key ("<namespace>/<name>")
    cq: int                  # target ClusterQueue index (lq-<cq>)
    cpu_m: int = 0           # millicpu request
    priority: int = 0        # submit: initial prio; priority: new prio
    runtime_s: float = 0.0   # service time once admitted
    remote: bool = False     # route through the MultiKueue worker client


@dataclass(frozen=True)
class TrafficSpec:
    """Workload-mark distribution: what each arrival looks like."""

    n_cqs: int
    namespace: str = "default"
    cpu_choices: tuple = (1500,)
    priorities: tuple = (0, 10, 20)
    runtime_choices_s: tuple = (2.0,)
    cancel_fraction: float = 0.02     # share of arrivals that cancel
    churn_fraction: float = 0.02      # share that re-prioritize
    remote_fraction: float = 0.0      # share submitted via remote.py
    live_window: int = 4096           # recent-key pool for cancel/churn


class PoissonProcess:
    """Homogeneous Poisson arrivals: exponential inter-arrival gaps."""

    def __init__(self, rate_per_s: float, seed: int = 0):
        if rate_per_s <= 0:
            raise ValueError("rate_per_s must be positive")
        self.rate_per_s = float(rate_per_s)
        self._rng = random.Random(_MIX_PROCESS ^ (seed & 0xFFFFFFFF))

    def next_gap(self, t: float) -> float:
        return self._rng.expovariate(self.rate_per_s)

    def describe(self) -> dict:
        return {"process": "poisson", "rate_per_s": self.rate_per_s}


class DiurnalProcess:
    """Sinusoidal rate between trough and peak over ``period_s``,
    sampled exactly by Lewis–Shedler thinning against the peak rate."""

    def __init__(self, trough_rate_per_s: float, peak_rate_per_s: float,
                 period_s: float, seed: int = 0):
        if not (0 < trough_rate_per_s <= peak_rate_per_s):
            raise ValueError("need 0 < trough <= peak")
        if period_s <= 0:
            raise ValueError("period_s must be positive")
        self.trough_rate_per_s = float(trough_rate_per_s)
        self.peak_rate_per_s = float(peak_rate_per_s)
        self.period_s = float(period_s)
        self._rng = random.Random(_MIX_PROCESS ^ (seed & 0xFFFFFFFF))

    def rate_at(self, t: float) -> float:
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / self.period_s))
        return (self.trough_rate_per_s
                + (self.peak_rate_per_s - self.trough_rate_per_s) * phase)

    def next_gap(self, t: float) -> float:
        t0 = t
        while True:
            t0 += self._rng.expovariate(self.peak_rate_per_s)
            if self._rng.random() * self.peak_rate_per_s <= self.rate_at(t0):
                return t0 - t

    def describe(self) -> dict:
        return {"process": "diurnal",
                "trough_rate_per_s": self.trough_rate_per_s,
                "peak_rate_per_s": self.peak_rate_per_s,
                "period_s": self.period_s}


class MMPPProcess:
    """2-state Markov-modulated Poisson (bursty traffic): exponential
    dwell in a quiet and a burst state, Poisson arrivals at the active
    state's rate, simulated by competing exponentials."""

    def __init__(self, quiet_rate_per_s: float, burst_rate_per_s: float,
                 mean_dwell_s: float, seed: int = 0,
                 burst_dwell_s: Optional[float] = None):
        if quiet_rate_per_s < 0 or burst_rate_per_s <= 0:
            raise ValueError("rates must be non-negative (burst positive)")
        if mean_dwell_s <= 0:
            raise ValueError("mean_dwell_s must be positive")
        self.rates = (float(quiet_rate_per_s), float(burst_rate_per_s))
        self.dwells = (float(mean_dwell_s),
                       float(burst_dwell_s
                             if burst_dwell_s is not None else mean_dwell_s))
        self.state = 0
        self._dwell_left: Optional[float] = None
        self._rng = random.Random(_MIX_PROCESS ^ (seed & 0xFFFFFFFF))

    def next_gap(self, t: float) -> float:
        acc = 0.0
        while True:
            if self._dwell_left is None:
                self._dwell_left = self._rng.expovariate(
                    1.0 / self.dwells[self.state])
            rate = self.rates[self.state]
            gap = (self._rng.expovariate(rate) if rate > 0
                   else float("inf"))
            if gap <= self._dwell_left:
                self._dwell_left -= gap
                return acc + gap
            acc += self._dwell_left
            self.state ^= 1
            self._dwell_left = None

    def describe(self) -> dict:
        return {"process": "mmpp",
                "quiet_rate_per_s": self.rates[0],
                "burst_rate_per_s": self.rates[1],
                "mean_dwell_s": self.dwells[0],
                "burst_dwell_s": self.dwells[1]}


class ArrivalStream:
    """Infinite deterministic event iterator.

    Each process arrival is marked as a submit, a cancel of a recent
    key, or a priority churn of a recent key, using an independent
    seeded mark generator so changing the arrival process doesn't
    reshuffle the marks.  The recent-key pool is bounded
    (``spec.live_window``) so state stays O(1)."""

    def __init__(self, process, spec: TrafficSpec, seed: int = 0):
        self.process = process
        self.spec = spec
        self.seed = seed
        self._marks = random.Random(_MIX_MARKS ^ ((seed + 1) & 0xFFFFFFFF))
        self.t = 0.0
        self.n = 0
        self._recent: list[str] = []

    def __iter__(self) -> Iterator[TrafficEvent]:
        return self

    def __next__(self) -> TrafficEvent:
        sp = self.spec
        m = self._marks
        self.t += self.process.next_gap(self.t)
        roll = m.random()
        if self._recent and roll < sp.cancel_fraction:
            key = self._recent.pop(m.randrange(len(self._recent)))
            return TrafficEvent(t=self.t, kind="cancel", key=key, cq=-1)
        if self._recent and roll < sp.cancel_fraction + sp.churn_fraction:
            key = self._recent[m.randrange(len(self._recent))]
            return TrafficEvent(t=self.t, kind="priority", key=key, cq=-1,
                                priority=m.choice(sp.priorities))
        self.n += 1
        key = f"{sp.namespace}/t{self.n}"
        self._recent.append(key)
        if len(self._recent) > sp.live_window:
            self._recent.pop(0)
        return TrafficEvent(
            t=self.t, kind="submit", key=key,
            cq=m.randrange(sp.n_cqs),
            cpu_m=m.choice(sp.cpu_choices),
            priority=m.choice(sp.priorities),
            runtime_s=m.choice(sp.runtime_choices_s),
            remote=m.random() < sp.remote_fraction)

    def take(self, n: int) -> list[TrafficEvent]:
        return [next(self) for _ in range(n)]

    def describe(self) -> dict:
        d = dict(self.process.describe())
        d["seed"] = self.seed
        d["n_cqs"] = self.spec.n_cqs
        return d


class ReplayStream:
    """Finite iterator over a recorded event list — the replay arm of
    the decision-bit-identity check (runner records every event it
    consumed; rerunning through a ReplayStream must produce identical
    per-cycle decisions)."""

    def __init__(self, events):
        self._events = list(events)
        self._i = 0

    def __iter__(self) -> Iterator[TrafficEvent]:
        return self

    def __next__(self) -> TrafficEvent:
        if self._i >= len(self._events):
            raise StopIteration
        ev = self._events[self._i]
        self._i += 1
        return ev
