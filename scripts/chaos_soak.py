"""Chaos soak: seeded crash/recover/degrade scenarios at 1000 CQs.

Every scenario runs two arms from identically-built drivers:

  control — fault-free, per-cycle host path (schedule_once + the
            harness finish contract);
  chaos   — the same cluster with a seeded ChaosInjector armed, a
            write-ahead cycle journal attached, and (for the crash
            scenarios) a full kill + Driver.recover_from rebuild.

A scenario passes only if the recovered/degraded arm's per-cycle
decision records AND its final workload state — admissions, conditions,
check states, requeue backoffs, timestamps included — are bit-identical
to the control arm (``decisions_stable``).  The acceptance set includes
a crash between cycles, a crash with the admit op journaled but
unapplied, a crash inside a fused burst window, a forced speculation
divergence, an 8→4→1 shard-loss cascade, pack-journal corruption, and a
partitioned MultiKueue transport.

Usage:
    python scripts/chaos_soak.py [--cqs 1000] [--devices 8]
        [--seed N] [--quick] [--out CHAOS_r09.json]

The base seed comes from --seed or KUEUE_TPU_CHAOS_SEED (default 1009);
scenario i uses seed+i, so any single scenario replays in isolation.
Prints per-scenario progress on stderr and writes the JSON artifact.
"""

from __future__ import annotations

import argparse
import gc
import hashlib
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _peek_int_flag(argv, flag: str) -> int:
    """Read an int flag from raw argv (both '--f N' and '--f=N' forms)."""
    n = 0
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            try:
                n = max(n, int(argv[i + 1]))
            except ValueError:
                pass
        elif a.startswith(flag + "="):
            try:
                n = max(n, int(a.split("=", 1)[1]))
            except ValueError:
                pass
    return n


# the 8→4→1 cascade needs an 8-device mesh, which on a CPU host only
# exists if the XLA flag lands BEFORE jax initializes its backend (the
# kueue_tpu import below pulls jax in)
_n_dev = _peek_int_flag(sys.argv[1:], "--devices") or 8
if _n_dev > 1:
    _xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _xf:
        os.environ["XLA_FLAGS"] = (
            _xf + f" --xla_force_host_platform_device_count={_n_dev}"
        ).strip()

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from kueue_tpu.chaos import injector as chaos
from kueue_tpu.chaos.injector import ChaosInjector, InjectedCrash
from kueue_tpu.controller.driver import Driver
from kueue_tpu.features import env_value
from kueue_tpu.ops.burst import BurstSolver
from kueue_tpu.perf.harness import chaos_report
from kueue_tpu.remote import ChaosWorkerClient, LocalWorkerClient
from kueue_tpu.utils.journal import CycleWAL


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Cluster builders (deterministic: same args -> same driver, always)
# ---------------------------------------------------------------------------

def mk(name, lq, cpu, prio=0, t=0.0):
    return Workload(name=name, queue_name=lq, priority=prio,
                    creation_time=t,
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": cpu})])


def cluster_spec(n_cqs):
    """n_cqs ClusterQueues in cohorts of 4, 4000m cpu nominal each,
    BEST_EFFORT_FIFO (a skip parks instead of blocking, so a crash that
    re-wakes parked workloads cannot change the admission order)."""
    def fn(d):
        d.apply_resource_flavor(ResourceFlavor(name="default"))
        for q in range(n_cqs):
            name = f"cq-{q}"
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{q // 4}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                           cluster_queue=name))
    return fn


def workload_spec(n_cqs, per_cq):
    """per_cq pending 1500m workloads per CQ (2 concurrent slots each):
    more pending than quota, runtime-driven finishes feed re-admission."""
    def fn(d):
        cluster_spec(n_cqs)(d)
        n = 0
        for q in range(n_cqs):
            for i in range(per_cq):
                n += 1
                d.create_workload(mk(f"w-{q}-{i}", f"lq-{q}", 1500,
                                     prio=(i % 3) * 10, t=float(n)))
    return fn


def build(spec_fn):
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=True)
    spec_fn(d)
    return d, clock


# ---------------------------------------------------------------------------
# Run/resume/recover plumbing (mirrors tests/test_chaos_recovery.py —
# the tier-1 smoke proves this protocol at small scale; the soak holds
# it to the same bar at 1000 CQs)
# ---------------------------------------------------------------------------

def resume_host(d, clock, cycles, runtime, out, tick_first=True):
    """Continue the per-cycle harness loop from ``len(out)`` completed
    cycles.  ``tick_first=False`` re-runs a cycle whose clock tick was
    already consumed before the crash."""
    while len(out) < cycles:
        c = len(out)
        if tick_first:
            clock.t += 1.0
        tick_first = True
        stats = d.schedule_once()
        out.append(stats)
        if runtime > 0 and c - runtime >= 0:
            for key in out[c - runtime].admitted:
                w = d.workloads.get(key)
                if w is not None and w.has_quota_reservation:
                    d.finish_workload(key)
    return out


def run_host(d, clock, cycles, runtime):
    return resume_host(d, clock, cycles, runtime, [])


def run_host_until_crash(d, clock, cycles, runtime):
    out = []
    try:
        resume_host(d, clock, cycles, runtime, out)
    except InjectedCrash as e:
        return out, str(e)
    return out, None


def run_burst_until_crash(d, clock, cycles, runtime, pipeline=None):
    """schedule_burst that surfaces an injected crash, collecting each
    applied cycle's record through on_cycle (the burst's own return
    value is lost when the exception unwinds)."""
    recs = []

    def on_cycle_start(_k):
        clock.t += 1.0

    def on_cycle(_k, stats):
        recs.append(stats)

    try:
        d.schedule_burst(cycles, runtime=runtime,
                         on_cycle_start=on_cycle_start, on_cycle=on_cycle,
                         pipeline=pipeline)
    except InjectedCrash as e:
        return recs, str(e)
    return recs, None


def run_burst(d, clock, cycles, runtime, pipeline=None):
    def on_cycle_start(_k):
        clock.t += 1.0
    return d.schedule_burst(cycles, runtime=runtime,
                            on_cycle_start=on_cycle_start,
                            pipeline=pipeline)


def recover(n_cqs, crashed, wal):
    """Discard the crashed driver, rebuild from its durable store + WAL
    tail — same clock object so time stays aligned with the control."""
    d2 = Driver(clock=crashed.clock, use_device_solver=True)
    cluster_spec(n_cqs)(d2)
    replayed = d2.recover_from(crashed.workloads.values(), wal)
    return d2, replayed


def full_state(d):
    """Every workload's durable status, timestamps included — the
    bit-identical recovery bar."""
    out = {}
    for key, w in d.workloads.items():
        out[key] = (
            w.is_finished, w.is_active, w.has_quota_reservation,
            None if w.admission is None else (
                w.admission.cluster_queue,
                tuple((a.name, tuple(sorted(a.flavors.items())),
                       tuple(sorted(a.resource_usage.items())), a.count)
                      for a in w.admission.pod_set_assignments)),
            tuple(sorted((c.type, c.status.value, c.reason, c.message,
                          c.last_transition_time)
                         for c in w.conditions.values())),
            tuple(sorted((s.name, s.state.value)
                         for s in w.admission_check_states.values())),
            None if w.requeue_state is None else
            (w.requeue_state.count, w.requeue_state.requeue_at),
        )
    return out


def state_digest(d) -> str:
    blob = repr(sorted(full_state(d).items())).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class Checker:
    """Collects parity failures instead of raising, so one divergent
    scenario still yields a complete artifact."""

    def __init__(self):
        self.failures: list[str] = []

    def check(self, ok, msg):
        if not ok:
            self.failures.append(msg)
        return bool(ok)

    def prefix(self, got, want, label):
        for k, (x, y) in enumerate(zip(got, want)):
            if sorted(x.admitted) != sorted(y.admitted):
                self.failures.append(
                    f"{label} cycle {k}: admitted diverged "
                    f"({len(x.admitted)} vs {len(y.admitted)})")
                return
        for k, s in enumerate(want[len(got):]):
            if s.admitted or s.skipped or s.inadmissible or s.preempting:
                self.failures.append(
                    f"{label}: ended at cycle {len(got)} while control "
                    f"still active at {len(got) + k}")
                return

    def final(self, da, db, label):
        self.check(da.admitted_keys() == db.admitted_keys(),
                   f"{label}: final admitted sets differ")
        self.check(full_state(da) == full_state(db),
                   f"{label}: final workload state not bit-identical")


def mesh_info() -> dict:
    import jax
    devs = jax.devices()
    return {"n_devices": len(devs),
            "platform": devs[0].platform if devs else "none"}


# ---------------------------------------------------------------------------
# Scenarios.  Each returns the artifact block for its name; every one
# compares a faulted arm against the fault-free control built above.
# ---------------------------------------------------------------------------

def scenario_boundary_crash(cfg, seed, wal_path):
    """Driver dies entering a cycle: tick consumed, nothing decided,
    WAL tail empty.  Recovery re-runs the cycle."""
    n, per, cycles, runtime = cfg["cqs"], cfg["drain_per_cq"], \
        cfg["drain_cycles"], cfg["runtime"]
    spec = workload_spec(n, per)
    dc, cc = build(spec)
    control = run_host(dc, cc, cycles, runtime)

    d1, c1 = build(spec)
    wal = CycleWAL(wal_path)
    d1.attach_wal(wal)
    chaos.install(ChaosInjector(seed=seed)).arm(
        "cycle.start", at=cycles // 2 + 1)
    out, crash = run_host_until_crash(d1, c1, cycles, runtime)
    chaos.clear()
    ck = Checker()
    ck.check(crash is not None, "fault never fired")
    ck.check(wal.tail == [], "boundary crash left uncommitted ops")
    crashed_after = len(out)

    d2, replayed = recover(n, d1, wal)
    resume_host(d2, c1, cycles, runtime, out, tick_first=False)
    ck.prefix(out, control, "boundary")
    ck.final(d2, dc, "boundary")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "crashed_after_cycles": crashed_after,
        "cycles": cycles,
        "wal_tail_replayed": replayed,
        "total_admissions": sum(len(s.admitted) for s in control),
        "state_digest": {"control": state_digest(dc),
                         "recovered": state_digest(d2)},
        "chaos": chaos_report(injector=None, wal=wal),
    }


def scenario_mid_admit_crash(cfg, seed, wal_path):
    """The hard case: the admit op is journaled, the store write never
    lands.  Recovery rolls the tail forward with the journaled
    timestamps, the resume mask holds the replayed CQs out of the
    re-run cycle, and the replayed admits fold back into that cycle's
    record so the modeled-runtime finisher sees the same obligations."""
    n, per, cycles, runtime = cfg["cqs"], cfg["drain_per_cq"], \
        cfg["drain_cycles"], cfg["runtime"]
    spec = workload_spec(n, per)
    dc, cc = build(spec)
    control = run_host(dc, cc, cycles, runtime)

    d1, c1 = build(spec)
    wal = CycleWAL(wal_path)
    d1.attach_wal(wal)
    # cycle 0 admits one head per CQ, so hit n+7 dies 7 admits into
    # cycle 1 — journaled decisions and undecided heads in one cycle
    chaos.install(ChaosInjector(seed=seed)).arm("wal.admit", at=n + 7)
    out, crash = run_host_until_crash(d1, c1, cycles, runtime)
    chaos.clear()
    ck = Checker()
    ck.check(crash is not None, "fault never fired")
    tail_admits = {op["key"] for op in wal.tail if op["op"] == "admit"}
    ck.check(bool(tail_admits), "crash left no journaled-but-unapplied ops")
    crashed_after, n_tail = len(out), len(tail_admits)

    d2, replayed = recover(n, d1, wal)
    k = len(out)   # the interrupted cycle being completed
    resume_host(d2, c1, k + 1, runtime, out, tick_first=False)
    if k < len(control):
        ck.check(tail_admits <= set(control[k].admitted),
                 "replayed admits not a subset of control's cycle")
        ck.check(set(out[k].admitted) ==
                 set(control[k].admitted) - tail_admits,
                 "re-run cycle did not complete the interrupted batch")
        # the cycle's decision batch is WAL-recovered + re-run: fold the
        # replayed admits into its record for the finish contract
        out[k].admitted.extend(sorted(tail_admits))
    resume_host(d2, c1, cycles, runtime, out)
    ck.prefix(out, control, "mid-admit")
    ck.final(d2, dc, "mid-admit")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "crashed_after_cycles": crashed_after,
        "cycles": cycles,
        "wal_tail_replayed": replayed,
        "tail_admits": n_tail,
        "total_admissions": sum(len(s.admitted) for s in control),
        "state_digest": {"control": state_digest(dc),
                         "recovered": state_digest(d2)},
        "chaos": chaos_report(injector=None, wal=wal),
    }


def scenario_mid_burst_crash(cfg, seed, wal_path):
    """Driver dies between applied cycles INSIDE a fused burst window.
    The WAL commit at each applied cycle bounds the loss to zero full
    cycles; the recovered driver resumes per-cycle."""
    n, per, cycles, runtime = cfg["cqs"], cfg["sustained_per_cq"], \
        cfg["sustained_cycles"], cfg["runtime"]
    spec = workload_spec(n, per)
    dc, cc = build(spec)
    control = run_host(dc, cc, cycles, runtime)

    d1, c1 = build(spec)
    wal = CycleWAL(wal_path)
    d1.attach_wal(wal)
    chaos.install(ChaosInjector(seed=seed)).arm("burst.mid_window", at=7)
    out, crash = run_burst_until_crash(d1, c1, cycles, runtime)
    bstats = dict(d1._burst_solver.stats) if d1._burst_solver else {}
    chaos.clear()
    ck = Checker()
    ck.check(crash is not None, "fault never fired")
    ck.check(0 < len(out) < cycles, f"crash landed outside the run "
             f"({len(out)}/{cycles})")
    crashed_after = len(out)

    d2, replayed = recover(n, d1, wal)
    resume_host(d2, c1, cycles, runtime, out, tick_first=True)
    ck.prefix(out, control, "mid-burst")
    ck.final(d2, dc, "mid-burst")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "crashed_after_cycles": crashed_after,
        "cycles": cycles,
        "wal_tail_replayed": replayed,
        "burst_dispatches": bstats.get("burst_dispatches", 0),
        "total_admissions": sum(len(s.admitted) for s in control),
        "state_digest": {"control": state_digest(dc),
                         "recovered": state_digest(d2)},
        "chaos": chaos_report(injector=None, bstats=bstats, wal=wal),
    }


def scenario_spec_divergence(cfg, seed, wal_path):
    """Chaos discards pipelined speculative windows unconsumed; the
    serial fallback must decide identically to the fault-free host."""
    n, per, cycles, runtime = cfg["cqs"], cfg["sustained_per_cq"], \
        cfg["sustained_cycles"], cfg["runtime"]
    spec = workload_spec(n, per)
    dc, cc = build(spec)
    control = run_host(dc, cc, cycles, runtime)

    d1, c1 = build(spec)
    wal = CycleWAL(wal_path)
    d1.attach_wal(wal)
    inj = chaos.install(ChaosInjector(seed=seed))
    inj.arm("burst.force_spec_divergence", at=1, times=3, action="cancel")
    out = run_burst(d1, c1, cycles, runtime, pipeline=True)
    bstats = dict(d1._burst_solver.stats)
    report = chaos_report(injector=inj, bstats=bstats, wal=wal)
    chaos.clear()
    ck = Checker()
    ck.check(bstats.get("burst_chaos_divergences", 0) >= 1,
             "no speculative window was ever forced divergent")
    ck.prefix(out, control, "spec-divergence")
    ck.final(d1, dc, "spec-divergence")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "cycles": cycles,
        "divergences_forced": bstats.get("burst_chaos_divergences", 0),
        "spec_cancelled": bstats.get("burst_spec_cancelled", 0),
        "total_admissions": sum(len(s.admitted) for s in control),
        "state_digest": {"control": state_digest(dc),
                         "chaos": state_digest(d1)},
        "chaos": report,
    }


def scenario_shard_cascade(cfg, seed, wal_path):
    """The 8→4→1 cascade: chaos kills 4 devices at the first fresh
    window launch and 3 more at the second; the solver re-partitions
    over the survivors, then falls back to the serial path — decisions
    stay identical to an undegraded control arm throughout."""
    import jax
    if len(jax.devices()) < 8:
        return {"skipped": True,
                "reason": f"needs 8 devices, have {len(jax.devices())} "
                          "(run with --devices 8)"}
    n, per, cycles, runtime = cfg["cqs"], cfg["sustained_per_cq"], \
        cfg["sustained_cycles"], cfg["runtime"]
    spec = workload_spec(n, per)
    dc, cc = build(spec)
    control = run_host(dc, cc, cycles, runtime)

    d1, c1 = build(spec)
    bs = BurstSolver(backend="cpu")
    bs.set_shards(8)
    d1._burst_solver = bs
    wal = CycleWAL(wal_path)
    d1.attach_wal(wal)
    inj = chaos.install(ChaosInjector(seed=seed))
    inj.arm("shard.device_loss", at=1, action="degrade", payload=4)
    inj.arm("shard.device_loss", at=2, action="degrade", payload=3)
    out = run_burst(d1, c1, cycles, runtime, pipeline=False)
    report = chaos_report(injector=inj, bstats=bs.stats, wal=wal)
    chaos.clear()
    ck = Checker()
    ck.check(bs.stats["burst_shard_degradations"] == 2,
             f"expected 2 degradations, got "
             f"{bs.stats['burst_shard_degradations']}")
    ck.check(bs.stats["burst_shard_serial_fallbacks"] == 1,
             "cascade never fell back to the serial path")
    ck.check(bs.n_shards == 1, f"cascade ended at {bs.n_shards} shards")
    ck.prefix(out, control, "shard-cascade")
    ck.final(d1, dc, "shard-cascade")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "cycles": cycles,
        "shard_path": [8, 4, 1],
        "degradations": bs.stats["burst_shard_degradations"],
        "serial_fallbacks": bs.stats["burst_shard_serial_fallbacks"],
        "final_shards": bs.n_shards,
        "total_admissions": sum(len(s.admitted) for s in control),
        "state_digest": {"control": state_digest(dc),
                         "degraded": state_digest(d1)},
        "chaos": report,
    }


def scenario_journal_corruption(cfg, seed, wal_path):
    """A dropped pack-journal touch (lost update) and a spurious
    dirty-all: both must degrade the incremental pack to a full walk,
    never to a wrong decision."""
    n, per, cycles, runtime = cfg["cqs"], cfg["drain_per_cq"], \
        cfg["drain_cycles"], cfg["runtime"]
    spec = workload_spec(n, per)
    dc, cc = build(spec)
    control = run_host(dc, cc, cycles, runtime)

    d1, c1 = build(spec)
    wal = CycleWAL(wal_path)
    d1.attach_wal(wal)
    inj = chaos.install(ChaosInjector(seed=seed))
    inj.arm("journal.drop_touch", at=1)
    inj.arm("journal.spurious_dirty_all", at=n // 2 + 3)
    out = run_burst(d1, c1, cycles, runtime)
    bstats = dict(d1._burst_solver.stats) if d1._burst_solver else {}
    report = chaos_report(injector=inj, bstats=bstats, wal=wal)
    hits = {s["site"]: s["fired"] for s in report.get("armed", [])}
    chaos.clear()
    ck = Checker()
    ck.prefix(out, control, "journal-corruption")
    ck.final(d1, dc, "journal-corruption")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "cycles": cycles,
        "fired": hits,
        "total_admissions": sum(len(s.admitted) for s in control),
        "state_digest": {"control": state_digest(dc),
                         "corrupted": state_digest(d1)},
        "chaos": report,
    }


def scenario_multikueue_partition(cfg, seed, wal_path):
    """Mirror one workload per CQ to a MultiKueue worker through a
    transport with seeded partitions, duplicated deliveries, and
    delays; the worker's admissions must match a fault-free mirror."""
    n = cfg["cqs"]

    def worker():
        d = Driver(clock=VirtualClock())
        cluster_spec(n)(d)
        return d

    wc, wx = worker(), worker()
    direct = LocalWorkerClient(wc)
    inj = ChaosInjector(seed=seed)
    inj.arm("remote.partition", prob=0.01, times=40, action="partition")
    inj.arm("remote.duplicate", prob=0.02, times=40, action="duplicate")
    inj.arm("remote.delay", prob=0.02, times=40, action="delay",
            payload=0.0)
    faulty = ChaosWorkerClient(LocalWorkerClient(wx), injector=inj,
                               backoff_base=0.0, backoff_max=0.0)
    for q in range(n):
        wl = mk(f"w-{q}", f"lq-{q}", 1500, prio=q % 3, t=float(q + 1))
        direct.create_workload(wl)
        faulty.create_workload(mk(f"w-{q}", f"lq-{q}", 1500,
                                  prio=q % 3, t=float(q + 1)))
    wc.run_until_settled()
    wx.run_until_settled()
    ck = Checker()
    ck.check(faulty.stats["retries"] >= 1 or faulty.stats["partitioned"]
             == 0, "partitions fired but nothing retried")
    ck.check(sorted(direct.list_workload_keys()) ==
             sorted(faulty.list_workload_keys()),
             "worker stores diverged")
    ck.final(wc, wx, "multikueue")
    return {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "mirrored_workloads": n,
        "transport": dict(faulty.stats),
        "admitted_per_arm": len(wc.admitted_keys()),
        "state_digest": {"control": state_digest(wc),
                         "faulted": state_digest(wx)},
        "chaos": chaos_report(injector=inj),
    }


SCENARIOS = [
    ("boundary_crash", scenario_boundary_crash),
    ("mid_admit_crash", scenario_mid_admit_crash),
    ("mid_burst_crash", scenario_mid_burst_crash),
    ("spec_divergence", scenario_spec_divergence),
    ("shard_cascade_8_4_1", scenario_shard_cascade),
    ("journal_corruption", scenario_journal_corruption),
    ("multikueue_partition", scenario_multikueue_partition),
]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cqs", type=int, default=1000)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual device count (consumed pre-import)")
    ap.add_argument("--seed", type=int,
                    default=int(env_value("KUEUE_TPU_CHAOS_SEED",
                                          "1009")))
    ap.add_argument("--quick", action="store_true",
                    help="tiny cluster for a fast functional pass")
    ap.add_argument("--only", default=None,
                    help="comma-separated scenario names")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "CHAOS_r09.json"))
    args = ap.parse_args()

    cqs = 16 if args.quick else args.cqs
    if cqs < 16:
        ap.error("--cqs must be >= 16 (mid-admit arming assumes it)")
    cfg = {
        "cqs": cqs,
        "runtime": 2,
        # drain config: short, for the host-path crash scenarios
        "drain_per_cq": 4,
        "drain_cycles": 12,
        # sustained config: >1 full K=32 burst window busy, so the
        # pipeline speculates and fresh window launches repeat
        "sustained_per_cq": 40,
        "sustained_cycles": 72,
    }
    only = set(args.only.split(",")) if args.only else None

    gc.collect()
    scenarios: dict[str, dict] = {}
    walls: dict[str, float] = {}
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as td:
        for i, (name, fn) in enumerate(SCENARIOS):
            if only and name not in only:
                continue
            chaos.clear()
            log(f"[{i + 1}/{len(SCENARIOS)}] {name} "
                f"(cqs={cqs}, seed={args.seed + i}) ...")
            t0 = time.perf_counter()
            try:
                res = fn(cfg, args.seed + i,
                         os.path.join(td, f"{name}.wal.jsonl"))
            except Exception as e:   # a scenario bug is a failed scenario
                res = {"decisions_stable": False,
                       "failures": [f"{type(e).__name__}: {e}"]}
            finally:
                chaos.clear()
            walls[name] = round(time.perf_counter() - t0, 2)
            res["wall_s"] = walls[name]
            res["seed"] = args.seed + i
            scenarios[name] = res
            if res.get("skipped"):
                log(f"    SKIPPED: {res['reason']}")
            else:
                ok = res["decisions_stable"]
                log(f"    {'bit-identical' if ok else 'DIVERGED'} "
                    f"({walls[name]}s)"
                    + ("" if ok else f" — {res['failures'][:3]}"))
            gc.collect()

    ran = {k: v for k, v in scenarios.items() if not v.get("skipped")}
    stable = sum(1 for v in ran.values() if v["decisions_stable"])
    tail = {
        "metric": "chaos_soak_decision_parity",
        "unit": "scenarios bit-identical to fault-free control",
        "cqs": cqs,
        "seed": args.seed,
        "mesh": mesh_info(),
        "config": cfg,
        "scenarios": scenarios,
        "scenarios_total": len(ran),
        "scenarios_stable": stable,
        "all_stable": stable == len(ran) and len(ran) > 0,
        "value": stable,
        "hard_paths_exercised": [
            "cycle.start crash + recover_from",
            "wal.admit crash + tail replay + resume mask",
            "burst.mid_window crash inside a fused window",
            "burst.force_spec_divergence (pipeline fallback)",
            "shard.device_loss 8->4->1 cascade",
            "journal.drop_touch + journal.spurious_dirty_all",
            "remote.partition/duplicate/delay transport",
        ],
    }
    print(json.dumps({k: tail[k] for k in
                      ("metric", "cqs", "scenarios_total",
                       "scenarios_stable", "all_stable")}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out}")
    return 0 if tail["all_stable"] else 1


if __name__ == "__main__":
    sys.exit(main())
