"""Scale soak: the streaming delta-pack scaling law, the head-packed
1M-active-CQ ceiling, and the parallel host apply/pack plane.

Publishes ``SCALE_r19.json``:

  curve     — per-universe-size host pack cost for the streaming arena
              vs a from-scratch rebuild measured on the SAME live state
              at the SAME boundary (the rebuild doubles as the
              interleaved same-box control), plane-parity verdicts,
              bytes-to-device, and an APPLY-DOMINATED end-to-end burst
              A/B (one arrival per CQ per round, so admissions/cycle
              scale with the universe) across THREE arms: streaming
              (every r19 optimization on, pooled host plane included),
              rebuild-every-boundary, and "classic" (head-only packing,
              aggregate compression, lazy heap repair, cycle bulk apply
              and the worker pool all off — the full row-backed serial
              control) — decisions must be bit-identical across all
              arms at every probed size;
  ceiling   — the r19 wall broken: a universe of >= 1M ACTIVE CQs
              (every one holding pending work) whose head-packed budget
              rows stay under the kernel's 2^19 composite-key budget
              while the row-backed pack of the SAME state is ~4x over
              it, with a completed admission round and the measured
              per-round wall at that size;
  head_pack — the budget accounting at the ceiling: budget rows
              (charged) vs grid rows (packed) vs live workloads;
  host_pool — the parallel host apply/pack plane A/B at the largest
              curve size: pooled (>= 4 workers) vs serial apply+pack
              wall in the apply-dominated regime with the sharded
              fsync'd WAL attached, decision parity, the cores-vs-
              throughput curve of the pooled WAL-commit plane, and the
              honest ``cores_available`` of this box;
  aggregate — packed rows vs live rows per size with compression on vs
              off, and the ``max_res_ts`` (clock-anchor) equality
              verdicts;
  heap      — lazy vs eager heap repair: per-cycle decision-apply cost
              at 100k items across per-key touch rates (the 1-touch
              regime now exercises the adaptive demotion), plus the
              driver-level host apply+heap time: the single-flag
              bulk-apply A/B (stream vs the same arm with bulk off)
              and the everything-off classic reference;
  wal_shard — sharded vs single-file CycleWAL append+group-commit wall
              (the r19 single-appender auto-collapse closes r18's
              0.84x single-thread regression) and the seq-merged
              replay-parity verdict;
  soak      — a high-count streaming run at the largest size with the
              (sharded) group-committed, auto-compacting CycleWAL
              attached: workloads arrive, admit through the fused
              device path, finish, and are deleted in rounds until the
              target count has flowed through one box;
  residues  — the r18 residue ledger (pending-head row cap, serial
              host plane, WAL single-thread regression, lazy-heap
              low-churn regression) with post-r19 status, mechanism,
              flag and measured evidence, plus the walls that remain,
              named with measured numbers;
  parity    — every probed size must report bytes-identical planes AND
              bit-identical decisions between every pair of arms.

The claims under test (ISSUE 17): the 2^19 row budget charges only
rows of forests that can preempt (head-only packing), so the active-CQ
cap moves past 1M; the host apply/pack plane partitions by cohort
forest across a worker pool without changing one decision; and both
r18 regressions (sharded-WAL single thread, lazy heap at 1 touch/key)
are closed by auto-collapse and adaptive demotion.

Usage:
    python scripts/scale_soak.py [--sizes 1000,4000,...] [--seed N]
        [--boundaries N] [--rounds N] [--soak-workloads N]
        [--soak-cqs N] [--ceiling-cqs N] [--preempt-cohorts N]
        [--wal-shards K] [--workers N] [--quick]
        [--out SCALE_r19.json]
"""

from __future__ import annotations

import argparse
import gc
import glob
import json
import os
import random
import sys
import time
from contextlib import contextmanager

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
    PodSet,
)
from kueue_tpu.controller.driver import Driver
from kueue_tpu.features import env_value
from kueue_tpu.obs import trace as _trace
from kueue_tpu.ops.burst import pack_burst, pack_burst_cached
from kueue_tpu.ops.packing import TightenState, tighten_arrays
from kueue_tpu.perf.harness import ab_block
from kueue_tpu.utils.heap import Heap
from kueue_tpu.utils.journal import (
    CycleWAL,
    ShardedCycleWAL,
    load_cycle_wal,
    make_cycle_wal,
)

#: the kernel's composite-key row budget (ops/burst.py: uid rank packs
#: into 19 bits) — the ceiling this artifact is about
ROW_BUDGET = 1 << 19

_AGG_FLAG = "KUEUE_TPU_AGG_PLANES"
_HEAD_FLAG = "KUEUE_TPU_HEAD_PACK"
_POOL_FLAG = "KUEUE_TPU_HOST_WORKERS"


@contextmanager
def agg_planes_off():
    """The row-backed control pack: aggregate compression AND head-only
    packing forced off (every live workload charged a budget row),
    environment restored on exit."""
    old = {k: os.environ.get(k) for k in (_AGG_FLAG, _HEAD_FLAG)}
    os.environ[_AGG_FLAG] = "0"
    os.environ[_HEAD_FLAG] = "0"
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def mesh_info() -> dict:
    import jax
    devs = jax.devices()
    return {"n_devices": len(devs),
            "platform": devs[0].platform if devs else "none"}


def rss_mb() -> float:
    """Current resident set from /proc (no psutil dependency)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return -1.0


def build(n_cqs: int,
          preempt_cohorts: int = 0) -> tuple[Driver, VirtualClock]:
    """Cohorts of 4, 4000m cpu nominal, BEST_EFFORT_FIFO — the
    chaos/traffic soak cluster shape scaled out.  The first
    ``preempt_cohorts`` cohorts carry a reclaim+lower-priority
    preemption policy: their rows are the head-pack BUDGET rows; every
    other forest's rows ride outside the 2^19 budget."""
    from kueue_tpu.api.types import ReclaimWithinCohort, WithinClusterQueue
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=True)
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    pol_pre = PreemptionPolicy(
        reclaim_within_cohort=ReclaimWithinCohort.ANY,
        within_cluster_queue=WithinClusterQueue.LOWER_PRIORITY)
    with d.bulk_apply():   # one O(N) settle instead of N rebuilds
        for q in range(n_cqs):
            name = f"cq-{q}"
            pre = (q // 4) < preempt_cohorts
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{q // 4}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=pol_pre if pre else PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                           cluster_queue=name))
    return d, clock


def mk(name: str, lq: str, cpu: int, prio: int, t: float) -> Workload:
    return Workload(name=name, queue_name=lq, priority=prio,
                    creation_time=t,
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": cpu})])


def preload(d, clock, n_cqs: int, seed: int) -> None:
    """Two 2500m workloads per CQ (one fits the 4000m nominal, one
    queues behind it), then one fused cycle to admit the first wave —
    every CQ ends with one admitted + one pending row."""
    rng = random.Random(seed)
    for q in range(n_cqs):
        for j in range(2):
            d.create_workload(mk(f"pre-{q}-{j}", f"lq-{q}", 2500,
                                 prio=rng.choice([0, 10, 20]),
                                 t=float(q * 2 + j)))
    clock.t += 1.0
    d.schedule_burst(1)


def current_structure(d):
    solver = d.scheduler.solver
    st = solver._structure
    if st is None or st.generation != d.cache.structure_generation:
        st = solver._structure_for(d.cache.snapshot(), [])
    return st


def plans_equal(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    for attr in ("C", "M", "L", "G", "n_levels", "KC", "seq_base",
                 "max_res_ts"):
        if getattr(a, attr) != getattr(b, attr):
            return False
    if set(a.arrays) != set(b.arrays):
        return False
    for name in a.arrays:
        x, y = np.asarray(a.arrays[name]), np.asarray(b.arrays[name])
        if x.dtype != y.dtype or x.shape != y.shape \
                or not np.array_equal(x, y):
            return False
    return a.keys == b.keys and a.row_of_key == b.row_of_key


def churn(d, clock, rng, n_cqs: int, n_churn: int, tag: str,
          per_cq: int = 1) -> None:
    """O(activity) mutation batch: ``n_churn`` total arrivals land on
    ``n_churn // per_cq`` sampled CQs (``per_cq`` each), and half the
    sampled CQs also finish their admitted head (which is then deleted,
    the 10M-soak's row-retirement path).  ``per_cq=1`` is the classic
    spread regime; ``per_cq>1`` concentrates decisions per CQ per
    cycle — the regime where the cycle bulk apply's deduped requeue
    wakeups have redundancy to win (one wakeup per touched CQ instead
    of one per decision), mirroring how the lazy heap's win is the
    dedupe."""
    cqs = rng.sample(range(n_cqs),
                     min(max(1, n_churn // per_cq), n_cqs))
    clock.t += 1.0
    i = 0
    for k, q in enumerate(cqs):
        for j in range(per_cq):
            name = f"{tag}-{q}" if per_cq == 1 else f"{tag}-{q}-{j}"
            d.create_workload(mk(name, f"lq-{q}", 2500,
                                 prio=rng.choice([0, 10, 20]),
                                 t=clock.t + i * 1e-3))
            i += 1
        if k % 2 == 0:
            key = f"default/pre-{q}-0"
            wl = d.workloads.get(key)
            if wl is not None and wl.has_quota_reservation \
                    and not wl.is_finished:
                d.finish_workload(key)
                d.delete_workload(key)


# ---------------------------------------------------------------------------
# Phase A: pack scaling law (streaming vs rebuild on the same state)
# ---------------------------------------------------------------------------

def pack_curve_point(n_cqs: int, boundaries: int, n_churn: int,
                     seed: int) -> dict:
    log(f"[pack] cqs={n_cqs}: building cluster ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    preload(d, clock, n_cqs, seed)
    log(f"[pack] cqs={n_cqs}: preloaded in "
        f"{time.perf_counter() - t0:.1f}s, rss={rss_mb()}MB")

    rng = random.Random(seed + 1)
    stats: dict = {}
    state = None
    tight = TightenState()
    stream_ms, rebuild_ms = [], []
    planes_identical = True
    bytes_raw = bytes_tight = rows = 0
    for b in range(boundaries):
        churn(d, clock, rng, n_cqs, n_churn, f"ch{b}")
        st = current_structure(d)
        t1 = time.perf_counter()
        plan_s, state, _ = pack_burst_cached(
            st, d.queues, d.cache, d.scheduler, clock,
            state=state, stats=stats)
        t2 = time.perf_counter()
        plan_f = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
        t3 = time.perf_counter()
        if b > 0:   # boundary 0 is the counted cold full pack
            stream_ms.append((t2 - t1) * 1e3)
            rebuild_ms.append((t3 - t2) * 1e3)
        if not plans_equal(plan_s, plan_f):
            planes_identical = False
            log(f"[pack] cqs={n_cqs} boundary {b}: PLANES DIVERGED")
        if plan_s is not None:
            arrays = plan_s.arrays
            bytes_raw = sum(int(np.asarray(v).nbytes)
                            for v in arrays.values())
            bytes_tight = sum(
                int(np.asarray(v).nbytes)
                for v in tighten_arrays(arrays, tight).values())
            rows = sum(1 for row in plan_s.keys
                       for k in row if k is not None)
    # the row-backed control pack on the SAME final state: aggregate
    # compression off, everything else identical — the packed-row
    # shrink and the max_res_ts (clock-anchor) equality come from here
    with agg_planes_off():
        plan_row = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
    rows_row_backed = 0 if plan_row is None else sum(
        1 for row in plan_row.keys for k in row if k is not None)
    agg_max_ts_equal = (
        (plan_s is None) == (plan_row is None)
        and (plan_s is None or plan_s.max_res_ts == plan_row.max_res_ts))
    out = {
        "cqs": n_cqs,
        "rows": rows,
        "live_rows": len(d.workloads),
        "rows_row_backed": rows_row_backed,
        "agg_rows_compressed": int(stats.get("agg_rows_compressed", 0)),
        "agg_max_res_ts_equal": bool(agg_max_ts_equal),
        "boundaries": boundaries,
        "churn_cqs_per_boundary": n_churn,
        "pack_ms_stream": round(float(np.median(stream_ms)), 3),
        "pack_ms_rebuild": round(float(np.median(rebuild_ms)), 3),
        "pack_speedup": round(float(np.median(rebuild_ms))
                              / max(float(np.median(stream_ms)), 1e-9),
                              2),
        "planes_identical": planes_identical,
        "bytes_to_device_raw": bytes_raw,
        "bytes_to_device": bytes_tight,
        "tighten_ratio": round(bytes_raw / max(bytes_tight, 1), 2),
        "stream_packs": stats.get("stream_packs", 0),
        "stream_full_packs": stats.get("stream_full_packs", 0),
        "pack_rank_patches": stats.get("pack_rank_patches", 0),
        "arena_bytes": stats.get("pack_arena_bytes", 0),
        "rss_mb": rss_mb(),
    }
    log(f"[pack] cqs={n_cqs}: stream={out['pack_ms_stream']}ms "
        f"rebuild={out['pack_ms_rebuild']}ms "
        f"speedup={out['pack_speedup']}x "
        f"parity={'OK' if planes_identical else 'DIVERGED'}")
    del d
    gc.collect()
    return out


# ---------------------------------------------------------------------------
# Phase B: end-to-end decision A/B (streaming vs rebuild drivers)
# ---------------------------------------------------------------------------

_ARM_ENV = {
    # every r19 optimization on: head-only packing (default), aggregate
    # compression, lazy heap, bulk apply, pooled host plane
    "stream": {"KUEUE_TPU_STREAM_PACK": "1",
               "KUEUE_TPU_HOST_WORKERS": "4"},
    "rebuild": {"KUEUE_TPU_STREAM_PACK": "0",
                "KUEUE_BURST_DELTA_PACK": "0"},
    # the single-flag bulk-apply A/B: identical to "stream" except the
    # one-settle cycle bulk apply is off — the honest denominator for
    # the e2e bulk-apply speedup (classic also flips aggregate
    # compression, whose per-admission fold cost lands in the apply
    # path and would confound the measurement)
    "nobulk": {"KUEUE_TPU_STREAM_PACK": "1",
               "KUEUE_TPU_HOST_WORKERS": "4",
               "KUEUE_TPU_CYCLE_BULK_APPLY": "0"},
    # the r19 bit-identity control: streaming pack on, every scale
    # optimization off — head-only packing, aggregate compression,
    # lazy heap repair, one-settle cycle bulk apply, worker pool.
    # This is the full row-backed serial arm of the head-pack parity
    # claim.
    "classic": {"KUEUE_TPU_STREAM_PACK": "1",
                "KUEUE_TPU_AGG_PLANES": "0",
                "KUEUE_TPU_HEAD_PACK": "0",
                "KUEUE_TPU_LAZY_HEAP": "0",
                "KUEUE_TPU_CYCLE_BULK_APPLY": "0",
                "KUEUE_TPU_HOST_WORKERS": "0"},
}

_ARM_KEYS = ("KUEUE_TPU_STREAM_PACK", "KUEUE_BURST_DELTA_PACK",
             "KUEUE_TPU_AGG_PLANES", "KUEUE_TPU_HEAD_PACK",
             "KUEUE_TPU_LAZY_HEAP", "KUEUE_TPU_CYCLE_BULK_APPLY",
             "KUEUE_TPU_HOST_WORKERS")

#: span phases that are pack or device work — everything else inside
#: the timed wall is host decide+apply+heap+queue cost
_KERNEL_SPANS = ("burst.pack", "burst.dispatch", "burst.fetch")


def _span_totals(tracer) -> dict:
    return {n: tracer._hist_for(n).total for n in _KERNEL_SPANS}


def e2e_arm(arm: str, n_cqs: int, rounds: int, n_churn: int,
            seed: int, per_cq: int = 1) -> dict:
    old = {k: os.environ.get(k) for k in _ARM_KEYS}
    for k in _ARM_KEYS:
        os.environ.pop(k, None)
    os.environ.update(_ARM_ENV[arm])
    try:
        d, clock = build(n_cqs)
        preload(d, clock, n_cqs, seed)
        # span tracing is decision-neutral (OBS artifact contract) and
        # is enabled on every arm alike; the pack/dispatch/fetch span
        # sums subtracted from the timed wall leave the per-cycle HOST
        # apply+heap+queue cost the r18 bulk-apply stack targets
        tracer = d.obs.enable_tracing()
        rng = random.Random(seed + 2)
        decisions = []
        n_cycles = 0
        wall = 0.0
        # GC fairness: the cycle collector is 100-200ms/cycle of pure
        # threshold-timing luck inside the timed window (whichever arm
        # crosses a gen2 threshold first eats a full-heap scan —
        # measured 0.46x-1.2x swings on the SAME arm pair), and
        # refcounting frees non-cyclic garbage immediately anyway, so
        # every arm runs its timed rounds with the collector off
        gc.collect()
        gc.disable()
        base_spans = _span_totals(tracer)
        # round 0 is an untimed warmup: it absorbs the fused kernel's
        # JIT compiles (shape-dependent, cached process-wide) so the
        # timed rounds measure steady state — its DECISIONS still count
        # toward the parity check
        for r in range(rounds + 1):
            churn(d, clock, rng, n_cqs, n_churn, f"e2e{r}",
                  per_cq=per_cq)
            t0 = time.perf_counter()
            recs = d.schedule_burst(
                3, runtime=2,
                on_cycle_start=lambda k: setattr(clock, "t",
                                                 clock.t + 1.0))
            if r > 0:
                wall += time.perf_counter() - t0
                n_cycles += len(recs)
            else:
                base_spans = _span_totals(tracer)
            decisions.extend(
                (sorted(s.admitted), sorted(s.skipped),
                 sorted(s.preempted_targets)) for s in recs)
        spans = _span_totals(tracer)
        kernel_s = sum(spans[n] - base_spans[n] for n in _KERNEL_SPANS)
        host_apply_ms = round(
            max(wall - kernel_s, 0.0) * 1e3 / max(n_cycles, 1), 3)
        bs = dict(d._burst_solver.stats) if d._burst_solver else {}
        pack_block = d.stats.get("pack", {})
    finally:
        gc.enable()
        _trace.clear()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    del d
    gc.collect()
    return {"arm": arm, "decisions": decisions,
            "cycle_wall_ms": round(wall * 1e3 / max(n_cycles, 1), 2),
            "host_apply_ms": host_apply_ms,
            "n_cycles": n_cycles,
            "bytes_h2d": int(bs.get("burst_launch_bytes_h2d", 0)),
            "pack": pack_block}


# ---------------------------------------------------------------------------
# Phase B2: the lifted row ceiling + the host apply/WAL microbenches
# ---------------------------------------------------------------------------

def ceiling_probe(n_cqs: int, preempt_cohorts: int, seed: int) -> dict:
    """The r19 wall broken on one state: >= 1M ACTIVE CQs (every one
    holding pending work after the preload's completed admission round)
    whose head-packed BUDGET rows — rows of the ``preempt_cohorts``
    forests that can preempt — stay far under the kernel's 2^19
    composite-key budget, while the row-backed pack of the SAME state
    charges every live workload a row and lands ~4x over it.  One
    soak-style round (one arrival per CQ, fused cycles, retirement)
    measures the honest per-round wall at this size.

    The preload admits one wave in a single burst round, so admitted
    reservations share their timestamps — the seq gate (dense rank
    over DISTINCT admitted timestamps) stays global and tiny here;
    a universe with >= 2^20 distinct admitted timestamps remains a
    wall and is ledgered below."""
    log(f"[ceiling] cqs={n_cqs} (preempting cohorts="
        f"{preempt_cohorts}): building ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs, preempt_cohorts=preempt_cohorts)
    preload(d, clock, n_cqs, seed)
    build_s = time.perf_counter() - t0
    live_rows = len(d.workloads)
    active_pending = sum(
        1 for name in d.queues.cluster_queue_names()
        if d.queues.pending_workloads(name))
    st = current_structure(d)
    t1 = time.perf_counter()
    plan = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
    pack_agg_s = time.perf_counter() - t1
    rows_grid = 0 if plan is None else sum(
        1 for row in plan.keys for k in row if k is not None)
    # the quantity the 2^19 budget binds from r19 on: rows charged to
    # the composite-key uid rank + poison gates (preempting forests)
    rows_budget = 0 if plan is None else int(plan.budget_rows)
    with agg_planes_off():
        t2 = time.perf_counter()
        plan_row = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
        pack_row_s = time.perf_counter() - t2
    rows_row_backed = 0 if plan_row is None else sum(
        1 for row in plan_row.keys for k in row if k is not None)
    row_backed_budget = 0 if plan_row is None \
        else int(plan_row.budget_rows)
    del plan, plan_row
    gc.collect()
    # one soak-style round at the ceiling: the per-round wall that
    # sizes any longer soak at this universe
    clock.t += 1.0
    t3 = time.perf_counter()
    for i in range(n_cqs):
        d.create_workload(mk(f"ceil-{i}", f"lq-{i}", 2500,
                             prio=(i % 3) * 10, t=clock.t + i * 1e-4))
    recs = d.schedule_burst(
        4, runtime=2,
        on_cycle_start=lambda k: setattr(clock, "t", clock.t + 1.0))
    admitted = sum(len(s.admitted) for s in recs)
    done = [k for k, w in d.workloads.items() if w.is_finished]
    for k in done:
        d.delete_workload(k)
    round_s = time.perf_counter() - t3
    out = {
        "cqs": n_cqs,
        "active_cqs_pending": active_pending,
        "preempt_cohorts": preempt_cohorts,
        "row_budget": ROW_BUDGET,
        "live_rows": live_rows,
        "rows_packed": rows_budget,
        "rows_grid": rows_grid,
        "rows_row_backed": rows_row_backed,
        "rows_budget_row_backed": row_backed_budget,
        "packed_under_budget": rows_budget < ROW_BUDGET,
        "row_backed_over_budget": rows_row_backed >= ROW_BUDGET,
        "pack_ms_agg": round(pack_agg_s * 1e3, 1),
        "pack_ms_row_backed": round(pack_row_s * 1e3, 1),
        "build_s": round(build_s, 1),
        "round": {"arrivals": n_cqs, "admitted": admitted,
                  "retired": len(done), "wall_s": round(round_s, 1)},
        "rss_mb": rss_mb(),
    }
    log(f"[ceiling] cqs={n_cqs}: active_pending={active_pending} "
        f"live={live_rows} budget_rows={rows_budget} "
        f"grid={rows_grid} row_backed={rows_row_backed} "
        f"(budget {ROW_BUDGET}), round={out['round']['wall_s']}s, "
        f"rss={rss_mb()}MB")
    del d
    gc.collect()
    return out


def host_pool_arm(workers: int, n_cqs: int, rounds: int, seed: int,
                  wal_path: str) -> dict:
    """One arm of the parallel-host-plane A/B: the apply-dominated
    regime (one arrival per CQ per round, half the preloaded heads
    finishing) with the sharded fsync'd WAL attached, every other r19
    optimization on.  Returns the per-cycle apply+pack host wall (the
    timed cycle wall minus the pack/dispatch/fetch spans) and the full
    decision trace for the bit-identity check."""
    from kueue_tpu.utils.parallel_host import POOL_STATS
    old = {k: os.environ.get(k) for k in (_POOL_FLAG,)}
    os.environ[_POOL_FLAG] = str(workers)
    for p in glob.glob(wal_path + "*"):
        os.remove(p)
    base_pool = dict(POOL_STATS)
    try:
        d, clock = build(n_cqs)
        preload(d, clock, n_cqs, seed)
        wal = ShardedCycleWAL(wal_path, shards=4, commit_every=1,
                              fsync=True)
        d.attach_wal(wal)
        tracer = d.obs.enable_tracing()
        rng = random.Random(seed + 5)
        decisions = []
        n_cycles = 0
        wall = 0.0
        gc.collect()   # same GC discipline as e2e_arm: collector off
        gc.disable()   # inside the timed window (threshold-timing luck)
        base_spans = _span_totals(tracer)
        for r in range(rounds + 1):   # round 0: untimed JIT warmup
            churn(d, clock, rng, n_cqs, n_cqs, f"hp{r}", per_cq=4)
            t0 = time.perf_counter()
            recs = d.schedule_burst(
                3, runtime=2,
                on_cycle_start=lambda k: setattr(clock, "t",
                                                 clock.t + 1.0))
            if r > 0:
                wall += time.perf_counter() - t0
                n_cycles += len(recs)
            else:
                base_spans = _span_totals(tracer)
            decisions.extend(
                (sorted(s.admitted), sorted(s.skipped),
                 sorted(s.preempted_targets)) for s in recs)
        spans = _span_totals(tracer)
        kernel_s = sum(spans[n] - base_spans[n] for n in _KERNEL_SPANS)
        wal_stats = dict(wal.stats)
        wal.close()
    finally:
        gc.enable()
        _trace.clear()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for p in glob.glob(wal_path + "*"):
            os.remove(p)
    pool_stats = {k: POOL_STATS[k] - base_pool[k] for k in POOL_STATS}
    del d
    gc.collect()
    return {
        "workers": workers,
        "decisions": decisions,
        "n_cycles": n_cycles,
        "cycle_wall_ms": round(wall * 1e3 / max(n_cycles, 1), 2),
        "apply_pack_ms": round(
            max(wall - kernel_s, 0.0) * 1e3 / max(n_cycles, 1), 3),
        "pool_stats": pool_stats,
        "wal_appenders": wal_stats.get("wal_appenders", 0),
        "wal_commits": wal_stats.get("wal_commits", 0),
    }


def pool_plane_curve(prefix: str, n_ops: int, shards: int,
                     workers_list: list[int],
                     commit_every_ops: int = 8) -> list[dict]:
    """Cores-vs-throughput curve of the pooled WAL-commit plane: the
    same fsync'd decision stream driven through the sharded WAL with
    K pool workers fanning the per-segment group commits.  The commit
    flush+fsync releases the GIL, so this is the component of the
    apply/pack plane that genuinely overlaps on any core count.  Two
    bench appenders hold the stripe layout CONSTANT across worker
    counts — without them the workers=1 point would auto-collapse to
    one segment and the curve would measure segment count, not
    overlap; at workers=1 the pool is inline, so that point is the
    serial per-segment commit loop over the identical layout."""
    from kueue_tpu.utils.parallel_host import HostPool
    points = []
    for w in workers_list:
        path = f"{prefix}.w{w}"
        for p in glob.glob(path + "*"):
            os.remove(p)
        wal = ShardedCycleWAL(path, shards=shards, commit_every=1,
                              fsync=True)
        wal.register_appender("bench-a")
        wal.register_appender("bench-b")
        pool = HostPool(w)
        pool.attach_wal(wal)
        t0 = time.perf_counter()
        for i in range(n_ops):
            wal.log({"op": "admit", "key": f"ns/w{i}",
                     "cq": f"cq-{i % 257}", "at": float(i)})
            if (i + 1) % commit_every_ops == 0:
                pool.commit_wal(wal)
        pool.commit_wal(wal)
        wall = time.perf_counter() - t0
        seqs = [op.get("seq") for op in
                sorted((o for sh in wal._shards
                        for b in (sh.batches + [sh.tail]) for o in b),
                       key=lambda o: o.get("seq", 0))]
        order_ok = seqs == list(range(len(seqs)))
        pool.detach_wal(wal)
        pool.close()
        wal.close()
        for p in glob.glob(path + "*"):
            os.remove(p)
        points.append({"workers": w,
                       "wall_ms": round(wall * 1e3, 1),
                       "ops_per_s": round(n_ops / max(wall, 1e-9)),
                       "seq_order_ok": bool(order_ok)})
        log(f"[pool] plane workers={w}: {points[-1]['wall_ms']}ms "
            f"({points[-1]['ops_per_s']} ops/s)")
    return points


class HeapItem:
    __slots__ = ("key", "prio", "ts")

    def __init__(self, key, prio, ts):
        self.key = key
        self.prio = prio
        self.ts = ts


def _heap_less(a, b):
    if a.prio != b.prio:
        return a.prio > b.prio
    if a.ts != b.ts:
        return a.ts < b.ts
    return a.key < b.key


def heap_bench(n_items: int, batch: int, cycles: int, seed: int) -> dict:
    """Per-cycle decision-apply cost on the CQ heap, lazy vs eager.

    One burst cycle's apply touches each decided key several times
    (requeue, backoff bump, priority/park update) and only the NEXT
    cycle's head read needs order — the access pattern lazy repair
    amortizes: eager pays a sift per touch, lazy pays a dict write per
    touch and one sift per KEY at the settle.  The same scripted storm
    replays on both arms; drain parity at the end re-proves order
    equality at this size."""
    points = []
    order_parity = True
    for touches in (1, 4, 8):
        rng = random.Random(seed * 7 + touches)
        storms = []
        for _ in range(cycles):
            ops = []
            for _ in range(batch):
                key = f"w{rng.randrange(n_items)}"
                for _ in range(touches):
                    ops.append((key, rng.choice((0, 10, 50)),
                                round(rng.random() * 1e3, 3)))
            storms.append(ops)
        walls = {}
        drains = {}
        for lazy in (False, True):
            h = Heap(key_fn=lambda it: it.key, less=_heap_less,
                     lazy=lazy)
            for i in range(n_items):
                h.push_or_update(HeapItem(f"w{i}", i % 50, float(i)))
            h.peek()   # settle the prefill outside the timed region
            t0 = time.perf_counter()
            for ops in storms:
                for key, prio, ts in ops:
                    h.push_or_update(HeapItem(key, prio, ts))
                # the next cycle's head read + requeue roundtrip
                top = h.pop()
                if top is not None:
                    h.push_or_update(top)
            walls[lazy] = (time.perf_counter() - t0) * 1e3 / cycles
            seq = []
            while (it := h.pop()) is not None:
                seq.append(it.key)
            drains[lazy] = seq
        if drains[False] != drains[True]:
            order_parity = False
        points.append({
            "touches_per_key": touches,
            "eager_ms_per_cycle": round(walls[False], 3),
            "lazy_ms_per_cycle": round(walls[True], 3),
            "speedup": round(walls[False] / max(walls[True], 1e-9), 2),
        })
        log(f"[heap] items={n_items} touches={touches}: "
            f"eager={points[-1]['eager_ms_per_cycle']}ms "
            f"lazy={points[-1]['lazy_ms_per_cycle']}ms "
            f"({points[-1]['speedup']}x)")
    return {"items": n_items, "batch": batch, "cycles": cycles,
            "order_parity": order_parity, "points": points}


def wal_shard_bench(prefix: str, n_ops: int, shards: int,
                    commit_every: int) -> dict:
    """Append + group-commit wall for one high-rate decision stream,
    single-file vs sharded, and replay parity: the sharded tail merged
    back into seq order must equal the unsharded tail op for op (seq
    stamps aside), live and after a file round-trip.

    From r19 the sharded WAL with no registered appenders auto-
    collapses to one hot segment — the default ``sharded_ms`` arm
    measures that single-writer path (the fix for r18's 0.84x
    regression); ``striped_ms`` re-registers two appenders to engage
    the striping the concurrent host plane uses."""
    def drive(w, reps: int = 1):
        """Best-of-``reps`` appends of the same stream (the box is a
        shared single core; one GC pause or disk stall skews a single
        pass by 20%+).  Only the last pass leaves the tail behind."""
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for i in range(n_ops):
                w.log({"op": "admit", "key": f"ns/w{i}",
                       "cq": f"cq-{i % 257}", "at": float(i)})
                if (i + 1) % 32 == 0:
                    w.commit()
            best = min(best, (time.perf_counter() - t0) * 1e3)
        for i in range(5):   # the open tail a crash would replay
            w.log({"op": "evict", "key": f"ns/w{i}", "at": float(i)})
        return best

    p1, pk = prefix + ".one", prefix + ".striped"
    for p in glob.glob(p1 + "*") + glob.glob(pk + "*"):
        os.remove(p)
    w1 = CycleWAL(p1, commit_every=commit_every)
    ms1 = drive(w1, reps=2)
    wk = ShardedCycleWAL(pk, shards=shards, commit_every=commit_every)
    msk = drive(wk, reps=2)   # no appenders: collapsed single-writer path
    ws = ShardedCycleWAL(pk + ".eng", shards=shards,
                         commit_every=commit_every)
    ws.register_appender("bench-a")
    ws.register_appender("bench-b")
    mss = drive(ws, reps=2)   # two appenders: striping engaged
    striped_segments = sum(
        1 for sh in ws._shards
        if sh.tail or any(sh.batches))
    collapsed_segments = sum(
        1 for sh in wk._shards
        if sh.tail or any(sh.batches))
    ws.close()
    for p in glob.glob(pk + ".eng*"):
        os.remove(p)

    def strip(ops):
        return [{k: v for k, v in op.items() if k != "seq"}
                for op in ops]

    tails_equal = strip(wk.tail) == list(w1.tail)
    committed1 = sum(len(b) for b in w1.batches)
    committedk = sum(len(b) for sh in wk._shards for b in sh.batches)
    skew = wk.stats["wal_shard_skew"]
    w1.close()
    wk.close()
    l1, lk = load_cycle_wal(p1), load_cycle_wal(pk)
    roundtrip = (isinstance(lk, ShardedCycleWAL)
                 and strip(lk.tail) == list(l1.tail)
                 and strip(lk.tail) == strip(wk.tail))
    for p in glob.glob(p1 + "*") + glob.glob(pk + "*"):
        os.remove(p)
    out = {
        "ops": n_ops,
        "shards": shards,
        "commit_every": commit_every,
        "single_ms": round(ms1, 1),
        "sharded_ms": round(msk, 1),
        "striped_ms": round(mss, 1),
        "single_ops_per_s": round(n_ops / max(ms1 / 1e3, 1e-9)),
        "sharded_ops_per_s": round(n_ops / max(msk / 1e3, 1e-9)),
        "commit_speedup": round(ms1 / max(msk, 1e-9), 2),
        "collapsed_segments": collapsed_segments,
        "striped_segments": striped_segments,
        "shard_skew": skew,
        "replay_parity": bool(tails_equal and roundtrip
                              and committed1 == committedk),
    }
    log(f"[wal] {n_ops} ops: single={out['single_ms']}ms "
        f"sharded-collapsed({shards})={out['sharded_ms']}ms "
        f"striped={out['striped_ms']}ms "
        f"(segments {collapsed_segments}/{striped_segments}) "
        f"parity={'OK' if out['replay_parity'] else 'DIVERGED'}")
    return out


# ---------------------------------------------------------------------------
# Phase C: the high-count workload soak
# ---------------------------------------------------------------------------

def soak(n_cqs: int, target: int, seed: int, wal_path: str,
         commit_every: int, wal_shards: int = 1) -> dict:
    log(f"[soak] cqs={n_cqs} target={target} workloads, "
        f"wal commit_every={commit_every} shards={wal_shards} ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    wal = make_cycle_wal(wal_path, commit_every=commit_every,
                         compact_every=64, shards=wal_shards)
    d.attach_wal(wal)
    rng = random.Random(seed + 3)
    created = finished = admitted = 0
    rounds = 0
    prios = [0, 10, 20]
    peak_rss = rss_mb()
    t_report = t0
    while created < target:
        batch = min(n_cqs, target - created)
        clock.t += 1.0
        for i in range(batch):
            q = i % n_cqs
            d.create_workload(mk(f"s{rounds}-{i}", f"lq-{q}", 2500,
                                 prio=prios[(rounds + i) % 3],
                                 t=clock.t + i * 1e-4))
        created += batch
        recs = d.schedule_burst(
            4, runtime=2,
            on_cycle_start=lambda k: setattr(clock, "t",
                                             clock.t + 1.0))
        for s in recs:
            admitted += len(s.admitted)
        # retire finished rows so the live store stays O(active)
        done = [k for k, w in d.workloads.items() if w.is_finished]
        for k in done:
            d.delete_workload(k)
        finished += len(done)
        rounds += 1
        peak_rss = max(peak_rss, rss_mb())
        now = time.perf_counter()
        if now - t_report > 30.0:
            t_report = now
            log(f"[soak] {created}/{target} created, "
                f"{admitted} admitted, {finished} retired, "
                f"round {rounds}, rss={rss_mb()}MB, "
                f"{now - t0:.0f}s")
    # drain the in-flight tail
    for _ in range(4):
        recs = d.schedule_burst(
            4, runtime=2,
            on_cycle_start=lambda k: setattr(clock, "t",
                                             clock.t + 1.0))
        for s in recs:
            admitted += len(s.admitted)
        done = [k for k, w in d.workloads.items() if w.is_finished]
        for k in done:
            d.delete_workload(k)
        finished += len(done)
    wal_stats = dict(wal.stats)
    wal.close()
    # single-file layout is wal_path itself; sharded is wal_path.sNN
    wal_size = sum(os.path.getsize(p)
                   for p in glob.glob(wal_path + "*"))
    pack_block = d.stats.get("pack", {})
    wall = time.perf_counter() - t0
    out = {
        "cqs": n_cqs,
        "target_workloads": target,
        "created": created,
        "admitted": admitted,
        "finished": finished,
        "rounds": rounds,
        "completed": created >= target,
        "wall_s": round(wall, 1),
        "workloads_per_s": round(created / max(wall, 1e-9), 1),
        "peak_rss_mb": peak_rss,
        "wal": {**wal_stats,
                "commit_every": commit_every,
                "compact_every": 64,
                "layout": "sharded" if wal_shards > 1 else "single",
                "final_file_bytes": wal_size},
        "pack_counters": pack_block,
    }
    log(f"[soak] done: {created} workloads in {out['wall_s']}s "
        f"({out['workloads_per_s']}/s), {admitted} admitted, "
        f"wal compactions={wal_stats.get('wal_compactions', 0)} "
        f"file={wal_size}B")
    del d
    gc.collect()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", default="",
                    help="comma-separated CQ universe sizes")
    ap.add_argument("--seed", type=int,
                    default=int(env_value("KUEUE_TPU_SCALE_SEED")))
    ap.add_argument("--boundaries", type=int, default=8,
                    help="measured pack boundaries per size")
    ap.add_argument("--rounds", type=int, default=3,
                    help="churn+burst rounds per end-to-end arm")
    ap.add_argument("--churn", type=int, default=64,
                    help="CQs churned per boundary (the 'activity')")
    ap.add_argument("--soak-workloads", type=int, default=0,
                    help="0 = 10M full / 100k quick")
    ap.add_argument("--soak-cqs", type=int, default=0,
                    help="soak universe size (0 = largest curve size)")
    ap.add_argument("--ceiling-cqs", type=int, default=0,
                    help="row-ceiling probe size (0 = 1,052,672 full "
                         "/ 2x the largest curve size quick)")
    ap.add_argument("--preempt-cohorts", type=int, default=0,
                    help="preempting (budget-row) cohorts in the "
                         "ceiling probe (0 = 1024 full / 8 quick)")
    ap.add_argument("--wal-shards", type=int, default=4,
                    help="CycleWAL segments for the soak (1 = the "
                         "classic single file)")
    ap.add_argument("--workers", type=int, default=4,
                    help="pooled arm worker count for the host-plane "
                         "A/B (serial control is always workers=0)")
    ap.add_argument("--pool-cqs", type=int, default=0,
                    help="host-plane A/B universe size (0 = largest "
                         "curve size)")
    ap.add_argument("--pool-rounds", type=int, default=2,
                    help="timed apply-dominated rounds per pool arm")
    ap.add_argument("--quick", action="store_true",
                    help="8k-CQ ceiling + 100k-workload soak")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SCALE_r19.json"))
    args = ap.parse_args()

    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",") if s]
    elif args.quick:
        sizes = [1000, 4000]
    else:
        sizes = [1000, 4000, 10000, 30000, 100000]
    boundaries = 4 if args.quick else args.boundaries
    soak_target = args.soak_workloads or (100_000 if args.quick
                                          else 2_000_000)
    soak_cqs = args.soak_cqs or sizes[-1]
    # full: 263,168 cohorts of 4 = 1,052,672 CQs — past the 1M-active
    # mark with every CQ holding pending work; 1,024 of the cohorts
    # preempt, so the head-packed budget rows stay ~8k under the 2^19
    # budget while live rows run ~2.1M
    ceiling_cqs = args.ceiling_cqs or (
        2 * sizes[-1] if args.quick else 1_052_672)
    preempt_cohorts = args.preempt_cohorts or (8 if args.quick
                                               else 1024)
    pool_cqs = args.pool_cqs or sizes[-1]
    commit_every = int(env_value("KUEUE_TPU_WAL_COMMIT_EVERY", "64"))
    t_start = time.perf_counter()
    log(f"scale soak: sizes={sizes} boundaries={boundaries} "
        f"churn={args.churn} soak={soak_target}@{soak_cqs}cqs "
        f"ceiling={ceiling_cqs}cqs(+{preempt_cohorts} preempting "
        f"cohorts) pool={args.workers}w@{pool_cqs}cqs "
        f"wal_shards={args.wal_shards} seed={args.seed}")

    curve = []
    for n in sizes:
        point = pack_curve_point(n, boundaries, args.churn, args.seed)
        # end-to-end A/B in the APPLY-DOMINATED regime (one arrival
        # per CQ per round, so admissions/cycle scale with the
        # universe); rebuild and classic interleaved right after
        # streaming on the same box (the environment-drift control)
        # apply-dominated regime: n total arrivals per round,
        # concentrated 4 per CQ on a quarter of the CQs, so each cycle
        # carries several decisions per touched CQ — the redundancy the
        # one-settle bulk apply dedupes (spread 1-per-CQ churn is its
        # dedupe-free worst case, measured ~1.0x in r18)
        e_s = e2e_arm("stream", n, args.rounds, n, args.seed, per_cq=4)
        e_r = e2e_arm("rebuild", n, args.rounds, n, args.seed, per_cq=4)
        e_n = e2e_arm("nobulk", n, args.rounds, n, args.seed, per_cq=4)
        e_c = e2e_arm("classic", n, args.rounds, n, args.seed, per_cq=4)
        point["decisions_identical"] = \
            e_s["decisions"] == e_r["decisions"]
        point["decisions_identical_nobulk"] = \
            e_s["decisions"] == e_n["decisions"]
        point["decisions_identical_classic"] = \
            e_s["decisions"] == e_c["decisions"]
        point["cycle_wall_ms"] = e_s["cycle_wall_ms"]
        point["cycle_wall_ms_rebuild"] = e_r["cycle_wall_ms"]
        point["cycle_wall_ms_classic"] = e_c["cycle_wall_ms"]
        point["host_apply_ms"] = e_s["host_apply_ms"]
        point["host_apply_ms_nobulk"] = e_n["host_apply_ms"]
        point["host_apply_ms_classic"] = e_c["host_apply_ms"]
        # the e2e bulk-apply speedup: single-flag A/B (stream vs the
        # same arm with KUEUE_TPU_CYCLE_BULK_APPLY=0); classic is kept
        # as the everything-off reference — it also drops aggregate
        # compression, whose per-admission fold cost sits in apply, so
        # classic/stream under-reports the bulk win by that tax
        point["host_apply_speedup"] = round(
            e_n["host_apply_ms"] / max(e_s["host_apply_ms"], 1e-3), 2)
        point["host_apply_speedup_vs_classic"] = round(
            e_c["host_apply_ms"] / max(e_s["host_apply_ms"], 1e-3), 2)
        point["bytes_h2d_e2e"] = e_s["bytes_h2d"]
        point["e2e_cycles"] = e_s["n_cycles"]
        point["pack_counters"] = e_s["pack"]
        point["pack_counters_rebuild"] = e_r["pack"]
        log(f"[e2e] cqs={n}: cycle={e_s['cycle_wall_ms']}ms "
            f"(rebuild {e_r['cycle_wall_ms']}ms, classic "
            f"{e_c['cycle_wall_ms']}ms) host apply "
            f"{e_s['host_apply_ms']}ms vs {e_n['host_apply_ms']}ms "
            f"bulk-off ({point['host_apply_speedup']}x, classic "
            f"{e_c['host_apply_ms']}ms), decisions "
            f"{'identical' if point['decisions_identical'] and point['decisions_identical_nobulk'] and point['decisions_identical_classic'] else 'DIVERGED'}")
        curve.append(point)

    ceiling = ceiling_probe(ceiling_cqs, preempt_cohorts, args.seed)

    # the parallel host apply/pack plane A/B: serial control first,
    # pooled arm interleaved right after on the same box
    hp_serial = host_pool_arm(0, pool_cqs, args.pool_rounds, args.seed,
                              args.out + ".poolwal")
    hp_pooled = host_pool_arm(args.workers, pool_cqs, args.pool_rounds,
                              args.seed, args.out + ".poolwal")
    pool_curve = pool_plane_curve(
        args.out + ".planewal",
        n_ops=2_000 if args.quick else 20_000,
        shards=max(4, args.wal_shards),
        workers_list=[1, 2, args.workers, 2 * args.workers])
    host_pool = {
        "flag": "KUEUE_TPU_HOST_WORKERS",
        "cqs": pool_cqs,
        "workers": args.workers,
        "cores_available": os.cpu_count() or 1,
        "apply_pack_ms_serial": hp_serial["apply_pack_ms"],
        "apply_pack_ms_pooled": hp_pooled["apply_pack_ms"],
        "apply_pack_speedup": round(
            hp_serial["apply_pack_ms"]
            / max(hp_pooled["apply_pack_ms"], 1e-3), 2),
        "cycle_wall_ms_serial": hp_serial["cycle_wall_ms"],
        "cycle_wall_ms_pooled": hp_pooled["cycle_wall_ms"],
        "decisions_identical":
            hp_serial["decisions"] == hp_pooled["decisions"],
        "pool_stats": hp_pooled["pool_stats"],
        "wal_appenders_pooled": hp_pooled["wal_appenders"],
        "cores_curve": pool_curve,
        "plane_overlap_speedup": round(
            next(p["wall_ms"] for p in pool_curve
                 if p["workers"] == 1)
            / max(next(p["wall_ms"] for p in pool_curve
                       if p["workers"] == args.workers), 1e-9), 2),
    }
    log(f"[pool] cqs={pool_cqs}: apply+pack serial="
        f"{host_pool['apply_pack_ms_serial']}ms pooled="
        f"{host_pool['apply_pack_ms_pooled']}ms "
        f"({host_pool['apply_pack_speedup']}x, plane overlap "
        f"{host_pool['plane_overlap_speedup']}x, cores="
        f"{host_pool['cores_available']}), decisions "
        f"{'identical' if host_pool['decisions_identical'] else 'DIVERGED'}")

    heap_micro = heap_bench(
        n_items=5_000 if args.quick else 100_000,
        batch=256 if args.quick else 4096,
        cycles=5 if args.quick else 10, seed=args.seed)
    wal_block = wal_shard_bench(
        args.out + ".walbench",
        n_ops=5_000 if args.quick else 200_000,
        shards=max(2, args.wal_shards), commit_every=commit_every)

    wal_path = os.path.join(os.path.dirname(args.out),
                            "scale_soak_wal.jsonl")
    soak_block = soak(soak_cqs, soak_target, args.seed, wal_path,
                      commit_every, wal_shards=args.wal_shards)
    for p in glob.glob(wal_path + "*"):
        try:
            os.remove(p)
        except OSError:
            pass

    top = curve[-1]
    parity = {
        "planes_identical_all": all(p["planes_identical"]
                                    for p in curve),
        "decisions_identical_all": all(p["decisions_identical"]
                                       for p in curve),
        "decisions_identical_nobulk_all": all(
            p["decisions_identical_nobulk"] for p in curve),
        "decisions_identical_classic_all": all(
            p["decisions_identical_classic"] for p in curve),
        "max_res_ts_equal_all": all(p["agg_max_res_ts_equal"]
                                    for p in curve),
    }
    drift = ab_block(
        treatment={"arm": "stream", "cqs": top["cqs"],
                   "pack_ms": top["pack_ms_stream"],
                   "cycle_wall_ms": top["cycle_wall_ms"],
                   "pack": top["pack_counters"]},
        control={"arm": "rebuild", "interleaved": True,
                 "cqs": top["cqs"],
                 "pack_ms": top["pack_ms_rebuild"],
                 "cycle_wall_ms": top["cycle_wall_ms_rebuild"],
                 "pack": top["pack_counters_rebuild"]})

    aggregate = {
        "flag": "KUEUE_TPU_AGG_PLANES",
        "row_budget": ROW_BUDGET,
        "points": [{"cqs": p["cqs"], "live_rows": p["live_rows"],
                    "rows_packed": p["rows"],
                    "rows_row_backed": p["rows_row_backed"],
                    "rows_compressed": p["agg_rows_compressed"],
                    "max_res_ts_equal": p["agg_max_res_ts_equal"]}
                   for p in curve],
        "max_res_ts_equal_all": parity["max_res_ts_equal_all"],
        "compression_at_max": round(
            top["rows_row_backed"] / max(top["rows"], 1), 2),
    }
    heap_block = {
        "flag": "KUEUE_TPU_LAZY_HEAP",
        "microbench": heap_micro,
        "driver_host_apply": {
            "cqs": top["cqs"],
            "optimized_ms_per_cycle": top["host_apply_ms"],
            "bulk_off_ms_per_cycle": top["host_apply_ms_nobulk"],
            "classic_ms_per_cycle": top["host_apply_ms_classic"],
            "speedup": top["host_apply_speedup"],
            "speedup_vs_classic": top["host_apply_speedup_vs_classic"],
        },
    }
    heap_t1 = next(p["speedup"] for p in heap_micro["points"]
                   if p["touches_per_key"] == 1)
    heap_t8 = next(p["speedup"] for p in heap_micro["points"]
                   if p["touches_per_key"] == 8)
    soak_rate = soak_block["workloads_per_s"]
    head_pack = {
        "flag": "KUEUE_TPU_HEAD_PACK",
        "row_budget": ROW_BUDGET,
        "ceiling_cqs": ceiling["cqs"],
        "active_cqs_pending": ceiling["active_cqs_pending"],
        "budget_rows": ceiling["rows_packed"],
        "grid_rows": ceiling["rows_grid"],
        "live_rows": ceiling["live_rows"],
        "rows_row_backed": ceiling["rows_row_backed"],
        "budget_utilization": round(
            ceiling["rows_packed"] / ROW_BUDGET, 4),
        "row_backed_over_budget_x": round(
            ceiling["rows_row_backed"] / ROW_BUDGET, 2),
    }
    residues = {
        "baseline": "SCALE_r18",
        "entries": [
            {"id": "pending_head_row_cap",
             "residue": "pending heads stayed row-backed, so the 2^19 "
                        "composite-key budget capped ACTIVE CQs near "
                        "524,288 (r18 probed 500k CQs / 1M live rows)",
             "status": "lifted",
             "flag": "KUEUE_TPU_HEAD_PACK",
             "mechanism": "head-only packing: the uid rank and the "
                          "n/prio poison gates charge only rows of "
                          "forests that can preempt; pending rows of "
                          "never-preempting forests ride outside the "
                          "budget as rank context (their uidrank "
                          "cells are never read — candidate "
                          "eligibility needs the head CQ's "
                          "wcq_lower/rwc_enabled)",
             "evidence": {"cqs": ceiling["cqs"],
                          "active_cqs_pending":
                              ceiling["active_cqs_pending"],
                          "live_rows": ceiling["live_rows"],
                          "budget_rows": ceiling["rows_packed"],
                          "grid_rows": ceiling["rows_grid"],
                          "rows_row_backed": ceiling["rows_row_backed"],
                          "row_budget": ROW_BUDGET,
                          "round_admitted":
                              ceiling["round"]["admitted"]}},
            {"id": "host_apply_serial",
             "residue": "the host apply/pack plane ran serial on one "
                        "thread; at 100k CQs the apply dominated the "
                        "burst cycle (~1.4k workloads/s end to end)",
             "status": "reduced",
             "flag": "KUEUE_TPU_HOST_WORKERS",
             "mechanism": "worker-pool host plane: cache rebuild "
                          "fan-out, dirty-CQ pack walk, requeue "
                          "wakeups and WAL segment commits partition "
                          "by cohort forest / queue / segment and run "
                          "on a fork-join pool; WAL seq stamped "
                          "serially pre-fan-out keeps replay "
                          "byte-identical",
             "evidence": {
                 "apply_pack_speedup":
                     host_pool["apply_pack_speedup"],
                 "plane_overlap_speedup":
                     host_pool["plane_overlap_speedup"],
                 "decisions_identical":
                     host_pool["decisions_identical"],
                 "bulk_apply_e2e_speedup":
                     top["host_apply_speedup"],
                 "apply_vs_classic_e2e":
                     top["host_apply_speedup_vs_classic"],
                 "cores_available": host_pool["cores_available"]}},
            {"id": "wal_single_thread_regression",
             "residue": "the sharded WAL cost 0.84x on a single "
                        "appender (stripe tax with no concurrency to "
                        "win back)",
             "status": ("closed"
                        if wal_block["commit_speedup"] >= 0.95
                        else "reduced"),
             "flag": "KUEUE_TPU_WAL_SHARDS",
             "mechanism": "appender census: the sharded WAL routes "
                          "every op to one hot segment until >= 2 "
                          "appenders register (the host pool "
                          "registers its workers); striping engages "
                          "only when concurrency exists — the residue "
                          "left is the per-op seq stamp the merged "
                          "replay needs",
             "evidence": {
                 "commit_speedup": wal_block["commit_speedup"],
                 "collapsed_segments":
                     wal_block["collapsed_segments"],
                 "striped_segments": wal_block["striped_segments"],
                 "replay_parity": wal_block["replay_parity"],
                 "soak_workloads_per_s": soak_rate}},
            {"id": "lazy_heap_low_churn",
             "residue": "lazy heap repair cost 0.83x at 1 touch/key "
                        "(overlay bookkeeping with nothing to "
                        "amortize)",
             "status": "closed",
             "flag": "KUEUE_TPU_LAZY_HEAP",
             "mechanism": "adaptive repair: an EWMA of measured "
                          "touches-per-key demotes the overlay to the "
                          "eager sift below 2 touches/key and "
                          "re-promotes when churn returns; flips only "
                          "at empty-overlay boundaries so order "
                          "parity is structural",
             "evidence": {
                 "heap_speedup_touches_1": heap_t1,
                 "heap_speedup_touches_8": heap_t8,
                 "order_parity": heap_micro["order_parity"]}},
        ],
        "walls": [
            {"id": "preempting_rows",
             "wall": "budget rows now scale with PREEMPTING-forest "
                     "rows, so the 2^19 budget caps preempting rows "
                     f"near {ROW_BUDGET}; probed at {ceiling['cqs']} "
                     f"CQs with {ceiling['rows_packed']} budget rows "
                     f"({ceiling['preempt_cohorts']} preempting "
                     "cohorts) — a universe with >= 524k preempting "
                     "rows still poisons to the host path"},
            {"id": "distinct_ts_seq_wall",
             "wall": "the admission-seq gate stays GLOBAL (dense rank "
                     "over distinct admitted reservation timestamps, "
                     "20-bit field); the ceiling preload admits one "
                     "wave in one round so timestamps collapse — a "
                     "universe with >= 2^20 DISTINCT admitted "
                     "timestamps still poisons in-kernel preemption "
                     "modeling"},
            {"id": "apply_per_admission_wall",
             "wall": "the e2e apply wall is per-admission-dominated: "
                     "profiled at ~135us/admission across "
                     "prepare/assume/slot-assignment (plus the "
                     "O(ready-CQs) heads pop/park walk), while a "
                     "deduped requeue storm costs ~66us — so the "
                     "cycle-dedupe levers (bulk apply, lazy heap, "
                     "pool) each move <10% of this regime's apply "
                     "wall and the single-flag bulk A/B measures "
                     f"~{top['host_apply_speedup']}x (r18's ~1.0x "
                     "was structural, not measurement noise: r13's "
                     "incremental settles + batched finish API "
                     "already removed the redundancy); closing it "
                     "needs per-admission-chain work — "
                     "slot-assignment memoization, peek-based heads "
                     "collection — not more dedupe"},
            {"id": "single_core_wall",
             "wall": f"this box exposes "
                     f"{host_pool['cores_available']} core(s), so the "
                     "pooled host plane can only overlap GIL-released "
                     "I/O (WAL flush+fsync, measured "
                     f"{host_pool['plane_overlap_speedup']}x at "
                     f"{args.workers} workers) — CPU-bound apply work "
                     "gains from the pool only with real cores; one "
                     f"soak round at {ceiling['cqs']} CQs costs "
                     f"{ceiling['round']['wall_s']}s wall and the "
                     f"soak sustained {soak_rate} workloads/s at "
                     f"{soak_block['cqs']} CQs"},
        ],
    }

    tail = {
        "metric": "active_cqs_at_ceiling_under_row_budget",
        "unit": "active CQs (each holding pending work) packed with "
                "head-pack budget rows under the kernel's 2^19 "
                "composite-key budget, one admission round completed, "
                "decisions bit-identical to the row-backed arm at "
                "every probed curve size",
        "value": ceiling["active_cqs_pending"],
        "cqs": top["cqs"],
        "host_apply_speedup_at_max_cqs": top["host_apply_speedup"],
        "pack_speedup_at_max_cqs": top["pack_speedup"],
        "seed": args.seed,
        "quick": bool(args.quick),
        "mesh": mesh_info(),
        "sizes": sizes,
        "curve": curve,
        "parity": parity,
        "ceiling": ceiling,
        "head_pack": head_pack,
        "host_pool": host_pool,
        "aggregate": aggregate,
        "heap": heap_block,
        "wal_shard": wal_block,
        "soak": soak_block,
        "residues": residues,
        "control": drift["control"],
        "environment_drift": drift,
        "wall_s_total": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps({
        "metric": tail["metric"], "cqs": tail["cqs"],
        "value": tail["value"],
        "budget_rows": ceiling["rows_packed"],
        "planes_identical_all": parity["planes_identical_all"],
        "decisions_identical_all": parity["decisions_identical_all"],
        "decisions_identical_classic_all":
            parity["decisions_identical_classic_all"],
        "pool_decisions_identical": host_pool["decisions_identical"],
        "soak_completed": soak_block["completed"]}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out} ({tail['wall_s_total']}s total)")
    ok = (parity["planes_identical_all"]
          and parity["decisions_identical_all"]
          and parity["decisions_identical_nobulk_all"]
          and parity["decisions_identical_classic_all"]
          and parity["max_res_ts_equal_all"]
          and host_pool["decisions_identical"]
          and ceiling["packed_under_budget"]
          and heap_micro["order_parity"]
          and wal_block["replay_parity"]
          and soak_block["completed"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
