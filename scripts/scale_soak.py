"""Scale soak: the streaming delta-pack scaling law and the lifted
row ceiling, 1k CQs -> the 2^19-row frontier.

Publishes ``SCALE_r18.json``:

  curve     — per-universe-size host pack cost for the streaming arena
              vs a from-scratch rebuild measured on the SAME live state
              at the SAME boundary (the rebuild doubles as the
              interleaved same-box control), plane-parity verdicts,
              bytes-to-device, end-to-end burst cycle wall and decision
              A/B across THREE arms: streaming (all r18 optimizations
              on), rebuild-every-boundary, and "classic" (aggregate
              compression, lazy heap repair and cycle bulk apply all
              off) — decisions must be bit-identical across all arms at
              every probed size;
  ceiling   — the lifted row cap, demonstrated: a universe whose LIVE
              workload count crosses the kernel's 2^19 row budget while
              the aggregate-compressed pack stays under it (the
              row-backed pack does not), with the measured per-round
              wall at that size;
  aggregate — packed rows vs live rows per size with compression on vs
              off, and the ``max_res_ts`` (clock-anchor) equality
              verdicts;
  heap      — lazy vs eager heap repair: per-cycle decision-apply cost
              at 100k items across per-key touch rates, plus the
              driver-level host apply+heap time, optimized vs classic;
  wal_shard — sharded vs single-file CycleWAL append+group-commit wall
              and the seq-merged replay-parity verdict;
  soak      — a high-count streaming run at the largest size with the
              (sharded) group-committed, auto-compacting CycleWAL
              attached: workloads arrive, admit through the fused
              device path, finish, and are deleted in rounds until the
              target count has flowed through one box;
  residues  — the r13 residue list (live-row cap, host-apply serial
              cost, WAL group-commit serialization) with post-r18
              status, mechanism, flag and measured evidence, plus the
              walls that remain, named with measured numbers;
  parity    — every probed size must report bytes-identical planes AND
              bit-identical decisions between every pair of arms.

The claims under test (ISSUE 16): kernel rows scale with active CQs +
heads, not live workloads (the 2^19 budget stops capping live rows);
the per-cycle host apply+heap cost drops >= 5x at 100k CQs via
one-settle bulk apply + lazy heap repair; the sharded WAL removes the
single group-commit stream; and every optimization is bit-identical to
the classic path, per size, per cycle.

Usage:
    python scripts/scale_soak.py [--sizes 1000,4000,...] [--seed N]
        [--boundaries N] [--rounds N] [--soak-workloads N]
        [--soak-cqs N] [--ceiling-cqs N] [--wal-shards K]
        [--quick] [--out SCALE_r18.json]
"""

from __future__ import annotations

import argparse
import gc
import glob
import json
import os
import random
import sys
import time
from contextlib import contextmanager

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
    PodSet,
)
from kueue_tpu.controller.driver import Driver
from kueue_tpu.features import env_value
from kueue_tpu.obs import trace as _trace
from kueue_tpu.ops.burst import pack_burst, pack_burst_cached
from kueue_tpu.ops.packing import TightenState, tighten_arrays
from kueue_tpu.perf.harness import ab_block
from kueue_tpu.utils.heap import Heap
from kueue_tpu.utils.journal import (
    CycleWAL,
    ShardedCycleWAL,
    load_cycle_wal,
    make_cycle_wal,
)

#: the kernel's composite-key row budget (ops/burst.py: uid rank packs
#: into 19 bits) — the ceiling this artifact is about
ROW_BUDGET = 1 << 19

_AGG_FLAG = "KUEUE_TPU_AGG_PLANES"


@contextmanager
def agg_planes_off():
    """The row-backed control pack: aggregate compression forced off,
    environment restored on exit."""
    old = {k: os.environ.get(k) for k in (_AGG_FLAG,)}
    os.environ[_AGG_FLAG] = "0"
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def mesh_info() -> dict:
    import jax
    devs = jax.devices()
    return {"n_devices": len(devs),
            "platform": devs[0].platform if devs else "none"}


def rss_mb() -> float:
    """Current resident set from /proc (no psutil dependency)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return -1.0


def build(n_cqs: int) -> tuple[Driver, VirtualClock]:
    """Cohorts of 4, 4000m cpu nominal, BEST_EFFORT_FIFO — the
    chaos/traffic soak cluster shape scaled out."""
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=True)
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    with d.bulk_apply():   # one O(N) settle instead of N rebuilds
        for q in range(n_cqs):
            name = f"cq-{q}"
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{q // 4}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                           cluster_queue=name))
    return d, clock


def mk(name: str, lq: str, cpu: int, prio: int, t: float) -> Workload:
    return Workload(name=name, queue_name=lq, priority=prio,
                    creation_time=t,
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": cpu})])


def preload(d, clock, n_cqs: int, seed: int) -> None:
    """Two 2500m workloads per CQ (one fits the 4000m nominal, one
    queues behind it), then one fused cycle to admit the first wave —
    every CQ ends with one admitted + one pending row."""
    rng = random.Random(seed)
    for q in range(n_cqs):
        for j in range(2):
            d.create_workload(mk(f"pre-{q}-{j}", f"lq-{q}", 2500,
                                 prio=rng.choice([0, 10, 20]),
                                 t=float(q * 2 + j)))
    clock.t += 1.0
    d.schedule_burst(1)


def current_structure(d):
    solver = d.scheduler.solver
    st = solver._structure
    if st is None or st.generation != d.cache.structure_generation:
        st = solver._structure_for(d.cache.snapshot(), [])
    return st


def plans_equal(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    for attr in ("C", "M", "L", "G", "n_levels", "KC", "seq_base",
                 "max_res_ts"):
        if getattr(a, attr) != getattr(b, attr):
            return False
    if set(a.arrays) != set(b.arrays):
        return False
    for name in a.arrays:
        x, y = np.asarray(a.arrays[name]), np.asarray(b.arrays[name])
        if x.dtype != y.dtype or x.shape != y.shape \
                or not np.array_equal(x, y):
            return False
    return a.keys == b.keys and a.row_of_key == b.row_of_key


def churn(d, clock, rng, n_cqs: int, n_churn: int, tag: str) -> None:
    """O(activity) mutation batch: ``n_churn`` CQs get one arrival,
    half of them also finish their admitted head (which is then
    deleted, the 10M-soak's row-retirement path)."""
    cqs = rng.sample(range(n_cqs), min(n_churn, n_cqs))
    clock.t += 1.0
    for i, q in enumerate(cqs):
        d.create_workload(mk(f"{tag}-{q}", f"lq-{q}", 2500,
                             prio=rng.choice([0, 10, 20]),
                             t=clock.t + i * 1e-3))
        if i % 2 == 0:
            key = f"default/pre-{q}-0"
            wl = d.workloads.get(key)
            if wl is not None and wl.has_quota_reservation \
                    and not wl.is_finished:
                d.finish_workload(key)
                d.delete_workload(key)


# ---------------------------------------------------------------------------
# Phase A: pack scaling law (streaming vs rebuild on the same state)
# ---------------------------------------------------------------------------

def pack_curve_point(n_cqs: int, boundaries: int, n_churn: int,
                     seed: int) -> dict:
    log(f"[pack] cqs={n_cqs}: building cluster ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    preload(d, clock, n_cqs, seed)
    log(f"[pack] cqs={n_cqs}: preloaded in "
        f"{time.perf_counter() - t0:.1f}s, rss={rss_mb()}MB")

    rng = random.Random(seed + 1)
    stats: dict = {}
    state = None
    tight = TightenState()
    stream_ms, rebuild_ms = [], []
    planes_identical = True
    bytes_raw = bytes_tight = rows = 0
    for b in range(boundaries):
        churn(d, clock, rng, n_cqs, n_churn, f"ch{b}")
        st = current_structure(d)
        t1 = time.perf_counter()
        plan_s, state, _ = pack_burst_cached(
            st, d.queues, d.cache, d.scheduler, clock,
            state=state, stats=stats)
        t2 = time.perf_counter()
        plan_f = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
        t3 = time.perf_counter()
        if b > 0:   # boundary 0 is the counted cold full pack
            stream_ms.append((t2 - t1) * 1e3)
            rebuild_ms.append((t3 - t2) * 1e3)
        if not plans_equal(plan_s, plan_f):
            planes_identical = False
            log(f"[pack] cqs={n_cqs} boundary {b}: PLANES DIVERGED")
        if plan_s is not None:
            arrays = plan_s.arrays
            bytes_raw = sum(int(np.asarray(v).nbytes)
                            for v in arrays.values())
            bytes_tight = sum(
                int(np.asarray(v).nbytes)
                for v in tighten_arrays(arrays, tight).values())
            rows = sum(1 for row in plan_s.keys
                       for k in row if k is not None)
    # the row-backed control pack on the SAME final state: aggregate
    # compression off, everything else identical — the packed-row
    # shrink and the max_res_ts (clock-anchor) equality come from here
    with agg_planes_off():
        plan_row = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
    rows_row_backed = 0 if plan_row is None else sum(
        1 for row in plan_row.keys for k in row if k is not None)
    agg_max_ts_equal = (
        (plan_s is None) == (plan_row is None)
        and (plan_s is None or plan_s.max_res_ts == plan_row.max_res_ts))
    out = {
        "cqs": n_cqs,
        "rows": rows,
        "live_rows": len(d.workloads),
        "rows_row_backed": rows_row_backed,
        "agg_rows_compressed": int(stats.get("agg_rows_compressed", 0)),
        "agg_max_res_ts_equal": bool(agg_max_ts_equal),
        "boundaries": boundaries,
        "churn_cqs_per_boundary": n_churn,
        "pack_ms_stream": round(float(np.median(stream_ms)), 3),
        "pack_ms_rebuild": round(float(np.median(rebuild_ms)), 3),
        "pack_speedup": round(float(np.median(rebuild_ms))
                              / max(float(np.median(stream_ms)), 1e-9),
                              2),
        "planes_identical": planes_identical,
        "bytes_to_device_raw": bytes_raw,
        "bytes_to_device": bytes_tight,
        "tighten_ratio": round(bytes_raw / max(bytes_tight, 1), 2),
        "stream_packs": stats.get("stream_packs", 0),
        "stream_full_packs": stats.get("stream_full_packs", 0),
        "pack_rank_patches": stats.get("pack_rank_patches", 0),
        "arena_bytes": stats.get("pack_arena_bytes", 0),
        "rss_mb": rss_mb(),
    }
    log(f"[pack] cqs={n_cqs}: stream={out['pack_ms_stream']}ms "
        f"rebuild={out['pack_ms_rebuild']}ms "
        f"speedup={out['pack_speedup']}x "
        f"parity={'OK' if planes_identical else 'DIVERGED'}")
    del d
    gc.collect()
    return out


# ---------------------------------------------------------------------------
# Phase B: end-to-end decision A/B (streaming vs rebuild drivers)
# ---------------------------------------------------------------------------

_ARM_ENV = {
    "stream": {"KUEUE_TPU_STREAM_PACK": "1"},
    "rebuild": {"KUEUE_TPU_STREAM_PACK": "0",
                "KUEUE_BURST_DELTA_PACK": "0"},
    # the r18 bit-identity control: streaming pack on, every scale
    # optimization off — aggregate compression, lazy heap repair and
    # one-settle cycle bulk apply
    "classic": {"KUEUE_TPU_STREAM_PACK": "1",
                "KUEUE_TPU_AGG_PLANES": "0",
                "KUEUE_TPU_LAZY_HEAP": "0",
                "KUEUE_TPU_CYCLE_BULK_APPLY": "0"},
}

_ARM_KEYS = ("KUEUE_TPU_STREAM_PACK", "KUEUE_BURST_DELTA_PACK",
             "KUEUE_TPU_AGG_PLANES", "KUEUE_TPU_LAZY_HEAP",
             "KUEUE_TPU_CYCLE_BULK_APPLY")

#: span phases that are pack or device work — everything else inside
#: the timed wall is host decide+apply+heap+queue cost
_KERNEL_SPANS = ("burst.pack", "burst.dispatch", "burst.fetch")


def _span_totals(tracer) -> dict:
    return {n: tracer._hist_for(n).total for n in _KERNEL_SPANS}


def e2e_arm(arm: str, n_cqs: int, rounds: int, n_churn: int,
            seed: int) -> dict:
    old = {k: os.environ.get(k) for k in _ARM_KEYS}
    for k in _ARM_KEYS:
        os.environ.pop(k, None)
    os.environ.update(_ARM_ENV[arm])
    try:
        d, clock = build(n_cqs)
        preload(d, clock, n_cqs, seed)
        # span tracing is decision-neutral (OBS artifact contract) and
        # is enabled on every arm alike; the pack/dispatch/fetch span
        # sums subtracted from the timed wall leave the per-cycle HOST
        # apply+heap+queue cost the r18 bulk-apply stack targets
        tracer = d.obs.enable_tracing()
        rng = random.Random(seed + 2)
        decisions = []
        n_cycles = 0
        wall = 0.0
        base_spans = _span_totals(tracer)
        # round 0 is an untimed warmup: it absorbs the fused kernel's
        # JIT compiles (shape-dependent, cached process-wide) so the
        # timed rounds measure steady state — its DECISIONS still count
        # toward the parity check
        for r in range(rounds + 1):
            churn(d, clock, rng, n_cqs, n_churn, f"e2e{r}")
            t0 = time.perf_counter()
            recs = d.schedule_burst(
                3, runtime=2,
                on_cycle_start=lambda k: setattr(clock, "t",
                                                 clock.t + 1.0))
            if r > 0:
                wall += time.perf_counter() - t0
                n_cycles += len(recs)
            else:
                base_spans = _span_totals(tracer)
            decisions.extend(
                (sorted(s.admitted), sorted(s.skipped),
                 sorted(s.preempted_targets)) for s in recs)
        spans = _span_totals(tracer)
        kernel_s = sum(spans[n] - base_spans[n] for n in _KERNEL_SPANS)
        host_apply_ms = round(
            max(wall - kernel_s, 0.0) * 1e3 / max(n_cycles, 1), 3)
        bs = dict(d._burst_solver.stats) if d._burst_solver else {}
        pack_block = d.stats.get("pack", {})
    finally:
        _trace.clear()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    del d
    gc.collect()
    return {"arm": arm, "decisions": decisions,
            "cycle_wall_ms": round(wall * 1e3 / max(n_cycles, 1), 2),
            "host_apply_ms": host_apply_ms,
            "n_cycles": n_cycles,
            "bytes_h2d": int(bs.get("burst_launch_bytes_h2d", 0)),
            "pack": pack_block}


# ---------------------------------------------------------------------------
# Phase B2: the lifted row ceiling + the host apply/WAL microbenches
# ---------------------------------------------------------------------------

def ceiling_probe(n_cqs: int, seed: int) -> dict:
    """The lifted row cap, demonstrated on one state: a universe whose
    LIVE workload count (2 per CQ after preload) crosses the kernel's
    2^19 row budget while the aggregate-compressed pack stays under it
    — the row-backed pack of the SAME state does not.  One soak-style
    round (one arrival per CQ, fused cycles, retirement) measures the
    honest per-round wall at this size."""
    log(f"[ceiling] cqs={n_cqs}: building ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    preload(d, clock, n_cqs, seed)
    build_s = time.perf_counter() - t0
    live_rows = len(d.workloads)
    st = current_structure(d)
    t1 = time.perf_counter()
    plan = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
    pack_agg_s = time.perf_counter() - t1
    rows_packed = 0 if plan is None else sum(
        1 for row in plan.keys for k in row if k is not None)
    with agg_planes_off():
        t2 = time.perf_counter()
        plan_row = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
        pack_row_s = time.perf_counter() - t2
    rows_row_backed = 0 if plan_row is None else sum(
        1 for row in plan_row.keys for k in row if k is not None)
    del plan, plan_row
    # one soak-style round at the ceiling: the per-round wall that
    # sizes any longer soak at this universe
    clock.t += 1.0
    t3 = time.perf_counter()
    for i in range(n_cqs):
        d.create_workload(mk(f"ceil-{i}", f"lq-{i}", 2500,
                             prio=(i % 3) * 10, t=clock.t + i * 1e-4))
    recs = d.schedule_burst(
        4, runtime=2,
        on_cycle_start=lambda k: setattr(clock, "t", clock.t + 1.0))
    admitted = sum(len(s.admitted) for s in recs)
    done = [k for k, w in d.workloads.items() if w.is_finished]
    for k in done:
        d.delete_workload(k)
    round_s = time.perf_counter() - t3
    out = {
        "cqs": n_cqs,
        "row_budget": ROW_BUDGET,
        "live_rows": live_rows,
        "rows_packed": rows_packed,
        "rows_row_backed": rows_row_backed,
        "packed_under_budget": rows_packed < ROW_BUDGET,
        "row_backed_over_budget": rows_row_backed >= ROW_BUDGET,
        "pack_ms_agg": round(pack_agg_s * 1e3, 1),
        "pack_ms_row_backed": round(pack_row_s * 1e3, 1),
        "build_s": round(build_s, 1),
        "round": {"arrivals": n_cqs, "admitted": admitted,
                  "retired": len(done), "wall_s": round(round_s, 1)},
        "rss_mb": rss_mb(),
    }
    log(f"[ceiling] cqs={n_cqs}: live={live_rows} "
        f"packed={rows_packed} row_backed={rows_row_backed} "
        f"(budget {ROW_BUDGET}), round={out['round']['wall_s']}s, "
        f"rss={rss_mb()}MB")
    del d
    gc.collect()
    return out


class HeapItem:
    __slots__ = ("key", "prio", "ts")

    def __init__(self, key, prio, ts):
        self.key = key
        self.prio = prio
        self.ts = ts


def _heap_less(a, b):
    if a.prio != b.prio:
        return a.prio > b.prio
    if a.ts != b.ts:
        return a.ts < b.ts
    return a.key < b.key


def heap_bench(n_items: int, batch: int, cycles: int, seed: int) -> dict:
    """Per-cycle decision-apply cost on the CQ heap, lazy vs eager.

    One burst cycle's apply touches each decided key several times
    (requeue, backoff bump, priority/park update) and only the NEXT
    cycle's head read needs order — the access pattern lazy repair
    amortizes: eager pays a sift per touch, lazy pays a dict write per
    touch and one sift per KEY at the settle.  The same scripted storm
    replays on both arms; drain parity at the end re-proves order
    equality at this size."""
    points = []
    order_parity = True
    for touches in (1, 4, 8):
        rng = random.Random(seed * 7 + touches)
        storms = []
        for _ in range(cycles):
            ops = []
            for _ in range(batch):
                key = f"w{rng.randrange(n_items)}"
                for _ in range(touches):
                    ops.append((key, rng.choice((0, 10, 50)),
                                round(rng.random() * 1e3, 3)))
            storms.append(ops)
        walls = {}
        drains = {}
        for lazy in (False, True):
            h = Heap(key_fn=lambda it: it.key, less=_heap_less,
                     lazy=lazy)
            for i in range(n_items):
                h.push_or_update(HeapItem(f"w{i}", i % 50, float(i)))
            h.peek()   # settle the prefill outside the timed region
            t0 = time.perf_counter()
            for ops in storms:
                for key, prio, ts in ops:
                    h.push_or_update(HeapItem(key, prio, ts))
                # the next cycle's head read + requeue roundtrip
                top = h.pop()
                if top is not None:
                    h.push_or_update(top)
            walls[lazy] = (time.perf_counter() - t0) * 1e3 / cycles
            seq = []
            while (it := h.pop()) is not None:
                seq.append(it.key)
            drains[lazy] = seq
        if drains[False] != drains[True]:
            order_parity = False
        points.append({
            "touches_per_key": touches,
            "eager_ms_per_cycle": round(walls[False], 3),
            "lazy_ms_per_cycle": round(walls[True], 3),
            "speedup": round(walls[False] / max(walls[True], 1e-9), 2),
        })
        log(f"[heap] items={n_items} touches={touches}: "
            f"eager={points[-1]['eager_ms_per_cycle']}ms "
            f"lazy={points[-1]['lazy_ms_per_cycle']}ms "
            f"({points[-1]['speedup']}x)")
    return {"items": n_items, "batch": batch, "cycles": cycles,
            "order_parity": order_parity, "points": points}


def wal_shard_bench(prefix: str, n_ops: int, shards: int,
                    commit_every: int) -> dict:
    """Append + group-commit wall for one high-rate decision stream,
    single-file vs sharded, and replay parity: the sharded tail merged
    back into seq order must equal the unsharded tail op for op (seq
    stamps aside), live and after a file round-trip."""
    def drive(w):
        t0 = time.perf_counter()
        for i in range(n_ops):
            w.log({"op": "admit", "key": f"ns/w{i}",
                   "cq": f"cq-{i % 257}", "at": float(i)})
            if (i + 1) % 32 == 0:
                w.commit()
        for i in range(5):   # the open tail a crash would replay
            w.log({"op": "evict", "key": f"ns/w{i}", "at": float(i)})
        return (time.perf_counter() - t0) * 1e3

    p1, pk = prefix + ".one", prefix + ".striped"
    for p in glob.glob(p1 + "*") + glob.glob(pk + "*"):
        os.remove(p)
    w1 = CycleWAL(p1, commit_every=commit_every)
    ms1 = drive(w1)
    wk = ShardedCycleWAL(pk, shards=shards, commit_every=commit_every)
    msk = drive(wk)

    def strip(ops):
        return [{k: v for k, v in op.items() if k != "seq"}
                for op in ops]

    tails_equal = strip(wk.tail) == list(w1.tail)
    committed1 = sum(len(b) for b in w1.batches)
    committedk = sum(len(b) for sh in wk._shards for b in sh.batches)
    skew = wk.stats["wal_shard_skew"]
    w1.close()
    wk.close()
    l1, lk = load_cycle_wal(p1), load_cycle_wal(pk)
    roundtrip = (isinstance(lk, ShardedCycleWAL)
                 and strip(lk.tail) == list(l1.tail)
                 and strip(lk.tail) == strip(wk.tail))
    for p in glob.glob(p1 + "*") + glob.glob(pk + "*"):
        os.remove(p)
    out = {
        "ops": n_ops,
        "shards": shards,
        "commit_every": commit_every,
        "single_ms": round(ms1, 1),
        "sharded_ms": round(msk, 1),
        "single_ops_per_s": round(n_ops / max(ms1 / 1e3, 1e-9)),
        "sharded_ops_per_s": round(n_ops / max(msk / 1e3, 1e-9)),
        "commit_speedup": round(ms1 / max(msk, 1e-9), 2),
        "shard_skew": skew,
        "replay_parity": bool(tails_equal and roundtrip
                              and committed1 == committedk),
    }
    log(f"[wal] {n_ops} ops: single={out['single_ms']}ms "
        f"sharded({shards})={out['sharded_ms']}ms "
        f"parity={'OK' if out['replay_parity'] else 'DIVERGED'}")
    return out


# ---------------------------------------------------------------------------
# Phase C: the high-count workload soak
# ---------------------------------------------------------------------------

def soak(n_cqs: int, target: int, seed: int, wal_path: str,
         commit_every: int, wal_shards: int = 1) -> dict:
    log(f"[soak] cqs={n_cqs} target={target} workloads, "
        f"wal commit_every={commit_every} shards={wal_shards} ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    wal = make_cycle_wal(wal_path, commit_every=commit_every,
                         compact_every=64, shards=wal_shards)
    d.attach_wal(wal)
    rng = random.Random(seed + 3)
    created = finished = admitted = 0
    rounds = 0
    prios = [0, 10, 20]
    peak_rss = rss_mb()
    t_report = t0
    while created < target:
        batch = min(n_cqs, target - created)
        clock.t += 1.0
        for i in range(batch):
            q = i % n_cqs
            d.create_workload(mk(f"s{rounds}-{i}", f"lq-{q}", 2500,
                                 prio=prios[(rounds + i) % 3],
                                 t=clock.t + i * 1e-4))
        created += batch
        recs = d.schedule_burst(
            4, runtime=2,
            on_cycle_start=lambda k: setattr(clock, "t",
                                             clock.t + 1.0))
        for s in recs:
            admitted += len(s.admitted)
        # retire finished rows so the live store stays O(active)
        done = [k for k, w in d.workloads.items() if w.is_finished]
        for k in done:
            d.delete_workload(k)
        finished += len(done)
        rounds += 1
        peak_rss = max(peak_rss, rss_mb())
        now = time.perf_counter()
        if now - t_report > 30.0:
            t_report = now
            log(f"[soak] {created}/{target} created, "
                f"{admitted} admitted, {finished} retired, "
                f"round {rounds}, rss={rss_mb()}MB, "
                f"{now - t0:.0f}s")
    # drain the in-flight tail
    for _ in range(4):
        recs = d.schedule_burst(
            4, runtime=2,
            on_cycle_start=lambda k: setattr(clock, "t",
                                             clock.t + 1.0))
        for s in recs:
            admitted += len(s.admitted)
        done = [k for k, w in d.workloads.items() if w.is_finished]
        for k in done:
            d.delete_workload(k)
        finished += len(done)
    wal_stats = dict(wal.stats)
    wal.close()
    # single-file layout is wal_path itself; sharded is wal_path.sNN
    wal_size = sum(os.path.getsize(p)
                   for p in glob.glob(wal_path + "*"))
    pack_block = d.stats.get("pack", {})
    wall = time.perf_counter() - t0
    out = {
        "cqs": n_cqs,
        "target_workloads": target,
        "created": created,
        "admitted": admitted,
        "finished": finished,
        "rounds": rounds,
        "completed": created >= target,
        "wall_s": round(wall, 1),
        "workloads_per_s": round(created / max(wall, 1e-9), 1),
        "peak_rss_mb": peak_rss,
        "wal": {**wal_stats,
                "commit_every": commit_every,
                "compact_every": 64,
                "layout": "sharded" if wal_shards > 1 else "single",
                "final_file_bytes": wal_size},
        "pack_counters": pack_block,
    }
    log(f"[soak] done: {created} workloads in {out['wall_s']}s "
        f"({out['workloads_per_s']}/s), {admitted} admitted, "
        f"wal compactions={wal_stats.get('wal_compactions', 0)} "
        f"file={wal_size}B")
    del d
    gc.collect()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", default="",
                    help="comma-separated CQ universe sizes")
    ap.add_argument("--seed", type=int,
                    default=int(env_value("KUEUE_TPU_SCALE_SEED")))
    ap.add_argument("--boundaries", type=int, default=8,
                    help="measured pack boundaries per size")
    ap.add_argument("--rounds", type=int, default=3,
                    help="churn+burst rounds per end-to-end arm")
    ap.add_argument("--churn", type=int, default=64,
                    help="CQs churned per boundary (the 'activity')")
    ap.add_argument("--soak-workloads", type=int, default=0,
                    help="0 = 10M full / 100k quick")
    ap.add_argument("--soak-cqs", type=int, default=0,
                    help="soak universe size (0 = largest curve size)")
    ap.add_argument("--ceiling-cqs", type=int, default=0,
                    help="row-ceiling probe size (0 = 3x the largest "
                         "curve size full / 2x quick)")
    ap.add_argument("--wal-shards", type=int, default=4,
                    help="CycleWAL segments for the soak (1 = the "
                         "classic single file)")
    ap.add_argument("--quick", action="store_true",
                    help="4k-CQ ceiling + 100k-workload soak")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SCALE_r18.json"))
    args = ap.parse_args()

    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",") if s]
    elif args.quick:
        sizes = [1000, 4000]
    else:
        sizes = [1000, 4000, 10000, 30000, 100000]
    boundaries = 4 if args.quick else args.boundaries
    soak_target = args.soak_workloads or (100_000 if args.quick
                                          else 10_000_000)
    soak_cqs = args.soak_cqs or sizes[-1]
    ceiling_cqs = args.ceiling_cqs or (
        2 * sizes[-1] if args.quick else 3 * sizes[-1])
    commit_every = int(env_value("KUEUE_TPU_WAL_COMMIT_EVERY", "64"))
    t_start = time.perf_counter()
    log(f"scale soak: sizes={sizes} boundaries={boundaries} "
        f"churn={args.churn} soak={soak_target}@{soak_cqs}cqs "
        f"ceiling={ceiling_cqs}cqs wal_shards={args.wal_shards} "
        f"seed={args.seed}")

    curve = []
    for n in sizes:
        point = pack_curve_point(n, boundaries, args.churn, args.seed)
        # end-to-end A/B, rebuild and classic interleaved right after
        # streaming on the same box (the environment-drift control)
        e_s = e2e_arm("stream", n, args.rounds, args.churn, args.seed)
        e_r = e2e_arm("rebuild", n, args.rounds, args.churn, args.seed)
        e_c = e2e_arm("classic", n, args.rounds, args.churn, args.seed)
        point["decisions_identical"] = \
            e_s["decisions"] == e_r["decisions"]
        point["decisions_identical_classic"] = \
            e_s["decisions"] == e_c["decisions"]
        point["cycle_wall_ms"] = e_s["cycle_wall_ms"]
        point["cycle_wall_ms_rebuild"] = e_r["cycle_wall_ms"]
        point["cycle_wall_ms_classic"] = e_c["cycle_wall_ms"]
        point["host_apply_ms"] = e_s["host_apply_ms"]
        point["host_apply_ms_classic"] = e_c["host_apply_ms"]
        point["host_apply_speedup"] = round(
            e_c["host_apply_ms"] / max(e_s["host_apply_ms"], 1e-3), 2)
        point["bytes_h2d_e2e"] = e_s["bytes_h2d"]
        point["e2e_cycles"] = e_s["n_cycles"]
        point["pack_counters"] = e_s["pack"]
        point["pack_counters_rebuild"] = e_r["pack"]
        log(f"[e2e] cqs={n}: cycle={e_s['cycle_wall_ms']}ms "
            f"(rebuild {e_r['cycle_wall_ms']}ms, classic "
            f"{e_c['cycle_wall_ms']}ms) host apply "
            f"{e_s['host_apply_ms']}ms vs {e_c['host_apply_ms']}ms "
            f"classic, decisions "
            f"{'identical' if point['decisions_identical'] and point['decisions_identical_classic'] else 'DIVERGED'}")
        curve.append(point)

    ceiling = ceiling_probe(ceiling_cqs, args.seed)
    heap_micro = heap_bench(
        n_items=5_000 if args.quick else 100_000,
        batch=256 if args.quick else 4096,
        cycles=5 if args.quick else 10, seed=args.seed)
    wal_block = wal_shard_bench(
        args.out + ".walbench",
        n_ops=5_000 if args.quick else 200_000,
        shards=max(2, args.wal_shards), commit_every=commit_every)

    wal_path = os.path.join(os.path.dirname(args.out),
                            "scale_soak_wal.jsonl")
    soak_block = soak(soak_cqs, soak_target, args.seed, wal_path,
                      commit_every, wal_shards=args.wal_shards)
    for p in glob.glob(wal_path + "*"):
        try:
            os.remove(p)
        except OSError:
            pass

    top = curve[-1]
    parity = {
        "planes_identical_all": all(p["planes_identical"]
                                    for p in curve),
        "decisions_identical_all": all(p["decisions_identical"]
                                       for p in curve),
        "decisions_identical_classic_all": all(
            p["decisions_identical_classic"] for p in curve),
        "max_res_ts_equal_all": all(p["agg_max_res_ts_equal"]
                                    for p in curve),
    }
    drift = ab_block(
        treatment={"arm": "stream", "cqs": top["cqs"],
                   "pack_ms": top["pack_ms_stream"],
                   "cycle_wall_ms": top["cycle_wall_ms"],
                   "pack": top["pack_counters"]},
        control={"arm": "rebuild", "interleaved": True,
                 "cqs": top["cqs"],
                 "pack_ms": top["pack_ms_rebuild"],
                 "cycle_wall_ms": top["cycle_wall_ms_rebuild"],
                 "pack": top["pack_counters_rebuild"]})

    aggregate = {
        "flag": "KUEUE_TPU_AGG_PLANES",
        "row_budget": ROW_BUDGET,
        "points": [{"cqs": p["cqs"], "live_rows": p["live_rows"],
                    "rows_packed": p["rows"],
                    "rows_row_backed": p["rows_row_backed"],
                    "rows_compressed": p["agg_rows_compressed"],
                    "max_res_ts_equal": p["agg_max_res_ts_equal"]}
                   for p in curve],
        "max_res_ts_equal_all": parity["max_res_ts_equal_all"],
        "compression_at_max": round(
            top["rows_row_backed"] / max(top["rows"], 1), 2),
    }
    heap_block = {
        "flag": "KUEUE_TPU_LAZY_HEAP",
        "microbench": heap_micro,
        "driver_host_apply": {
            "cqs": top["cqs"],
            "optimized_ms_per_cycle": top["host_apply_ms"],
            "classic_ms_per_cycle": top["host_apply_ms_classic"],
            "speedup": top["host_apply_speedup"],
        },
    }
    heap_t8 = next(p["speedup"] for p in heap_micro["points"]
                   if p["touches_per_key"] == 8)
    soak_rate = soak_block["workloads_per_s"]
    residues = {
        "baseline": "SCALE_r13",
        "entries": [
            {"id": "live_row_cap",
             "residue": "every live workload held a packed row, so the "
                        "kernel's 2^19 composite-key row budget capped "
                        "LIVE WORKLOADS, not CQs",
             "status": "lifted",
             "flag": "KUEUE_TPU_AGG_PLANES",
             "mechanism": "cohort-forest aggregate planes: admitted "
                          "rows of non-preempting forests fold into "
                          "per-CQ aggregates at pack time; kernel rows "
                          "scale with pending heads + preempting "
                          "forests",
             "evidence": {"cqs": ceiling["cqs"],
                          "live_rows": ceiling["live_rows"],
                          "rows_packed": ceiling["rows_packed"],
                          "rows_row_backed": ceiling["rows_row_backed"],
                          "row_budget": ROW_BUDGET}},
            {"id": "host_apply_serial",
             "residue": "the host apply requeued and re-sifted per "
                        "decision; at 100k CQs the apply dominated the "
                        "burst cycle",
             "status": "reduced",
             "flag": "KUEUE_TPU_CYCLE_BULK_APPLY",
             "mechanism": "one-settle cycle bulk apply (one deduped "
                          "requeue pass + one deferred cache rebuild "
                          "per cycle) + lazy heap repair (one "
                          "amortized sift pass per ordered read)",
             "evidence": {
                 "host_apply_speedup_at_max":
                     top["host_apply_speedup"],
                 "heap_speedup_touches_8": heap_t8}},
            {"id": "wal_group_commit",
             "residue": "one journal stream serialized every decision "
                        "append behind a single group-commit flush",
             "status": "reduced",
             "flag": "KUEUE_TPU_WAL_SHARDS",
             "mechanism": "sharded CycleWAL: appends stripe across K "
                          "segments by workload-key hash; a global "
                          "monotone seq merges replay back into total "
                          "order",
             "evidence": {
                 "commit_speedup": wal_block["commit_speedup"],
                 "replay_parity": wal_block["replay_parity"],
                 "sharded_ops_per_s": wal_block["sharded_ops_per_s"],
                 "soak_workloads_per_s": soak_rate}},
        ],
        "walls": [
            {"id": "pending_heads",
             "wall": "pending heads stay row-backed (one packed row "
                     "per CQ with pending work), so the 2^19 row "
                     f"budget now caps ACTIVE CQs near {ROW_BUDGET}; "
                     f"probed at {ceiling['cqs']} CQs with "
                     f"{ceiling['live_rows']} live workloads"},
            {"id": "single_core_wall",
             "wall": f"one soak round at {ceiling['cqs']} CQs costs "
                     f"{ceiling['round']['wall_s']}s wall on this box; "
                     f"the soak sustained {soak_rate} workloads/s at "
                     f"{soak_block['cqs']} CQs — 50M workloads "
                     f"extrapolates to ~"
                     f"{round(50e6 / max(soak_rate, 1e-9) / 3600, 1)}h "
                     "and was not run in one sitting"},
        ],
    }

    tail = {
        "metric": "host_apply_speedup_at_max_cqs",
        "unit": "classic host apply+heap ms / optimized host "
                "apply+heap ms per cycle at the largest probed "
                "universe (every optimization bit-identical)",
        "value": top["host_apply_speedup"],
        "cqs": top["cqs"],
        "pack_speedup_at_max_cqs": top["pack_speedup"],
        "seed": args.seed,
        "quick": bool(args.quick),
        "mesh": mesh_info(),
        "sizes": sizes,
        "curve": curve,
        "parity": parity,
        "ceiling": ceiling,
        "aggregate": aggregate,
        "heap": heap_block,
        "wal_shard": wal_block,
        "soak": soak_block,
        "residues": residues,
        "control": drift["control"],
        "environment_drift": drift,
        "wall_s_total": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps({
        "metric": tail["metric"], "cqs": tail["cqs"],
        "value": tail["value"],
        "planes_identical_all": parity["planes_identical_all"],
        "decisions_identical_all": parity["decisions_identical_all"],
        "decisions_identical_classic_all":
            parity["decisions_identical_classic_all"],
        "soak_completed": soak_block["completed"]}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out} ({tail['wall_s_total']}s total)")
    ok = (parity["planes_identical_all"]
          and parity["decisions_identical_all"]
          and parity["decisions_identical_classic_all"]
          and parity["max_res_ts_equal_all"]
          and heap_micro["order_parity"]
          and wal_block["replay_parity"]
          and soak_block["completed"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
