"""Scale soak: the streaming delta-pack scaling law, 1k -> 100k CQs.

Publishes ``SCALE_r13.json``:

  curve   — per-universe-size (CQs 1k..100k) host pack cost for the
            streaming arena vs a from-scratch rebuild measured on the
            SAME live state at the SAME boundary (the rebuild therefore
            doubles as the interleaved same-box control), plane-parity
            verdicts (bytes-identical packed planes), bytes-to-device
            before/after dtype tightening, end-to-end burst cycle wall
            cost and decision A/B between the streaming and
            rebuild-every-boundary drivers, and RSS;
  soak    — a 10M-workload streaming run at the largest size with a
            group-committed, auto-compacting CycleWAL attached:
            workloads arrive, admit through the fused device path,
            finish, and are deleted in rounds until the target count
            has flowed through one box;
  parity  — every probed size must report bytes-identical planes AND
            bit-identical decisions between arms.

The claim under test (ISSUE 11): host pack cost is O(arrivals + dirty
rows), not O(universe) — the streaming arm's pack ms stays flat as CQs
grow 100x while the rebuild arm grows linearly, >= 5x apart at 100k.

Usage:
    python scripts/scale_soak.py [--sizes 1000,4000,...] [--seed N]
        [--boundaries N] [--rounds N] [--soak-workloads N]
        [--quick] [--out SCALE_r13.json]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
    PodSet,
)
from kueue_tpu.controller.driver import Driver
from kueue_tpu.features import env_value
from kueue_tpu.ops.burst import pack_burst, pack_burst_cached
from kueue_tpu.ops.packing import TightenState, tighten_arrays
from kueue_tpu.perf.harness import ab_block
from kueue_tpu.utils.journal import CycleWAL


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def mesh_info() -> dict:
    import jax
    devs = jax.devices()
    return {"n_devices": len(devs),
            "platform": devs[0].platform if devs else "none"}


def rss_mb() -> float:
    """Current resident set from /proc (no psutil dependency)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return -1.0


def build(n_cqs: int) -> tuple[Driver, VirtualClock]:
    """Cohorts of 4, 4000m cpu nominal, BEST_EFFORT_FIFO — the
    chaos/traffic soak cluster shape scaled out."""
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=True)
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    with d.bulk_apply():   # one O(N) settle instead of N rebuilds
        for q in range(n_cqs):
            name = f"cq-{q}"
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{q // 4}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                           cluster_queue=name))
    return d, clock


def mk(name: str, lq: str, cpu: int, prio: int, t: float) -> Workload:
    return Workload(name=name, queue_name=lq, priority=prio,
                    creation_time=t,
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": cpu})])


def preload(d, clock, n_cqs: int, seed: int) -> None:
    """Two 2500m workloads per CQ (one fits the 4000m nominal, one
    queues behind it), then one fused cycle to admit the first wave —
    every CQ ends with one admitted + one pending row."""
    rng = random.Random(seed)
    for q in range(n_cqs):
        for j in range(2):
            d.create_workload(mk(f"pre-{q}-{j}", f"lq-{q}", 2500,
                                 prio=rng.choice([0, 10, 20]),
                                 t=float(q * 2 + j)))
    clock.t += 1.0
    d.schedule_burst(1)


def current_structure(d):
    solver = d.scheduler.solver
    st = solver._structure
    if st is None or st.generation != d.cache.structure_generation:
        st = solver._structure_for(d.cache.snapshot(), [])
    return st


def plans_equal(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    for attr in ("C", "M", "L", "G", "n_levels", "KC", "seq_base",
                 "max_res_ts"):
        if getattr(a, attr) != getattr(b, attr):
            return False
    if set(a.arrays) != set(b.arrays):
        return False
    for name in a.arrays:
        x, y = np.asarray(a.arrays[name]), np.asarray(b.arrays[name])
        if x.dtype != y.dtype or x.shape != y.shape \
                or not np.array_equal(x, y):
            return False
    return a.keys == b.keys and a.row_of_key == b.row_of_key


def churn(d, clock, rng, n_cqs: int, n_churn: int, tag: str) -> None:
    """O(activity) mutation batch: ``n_churn`` CQs get one arrival,
    half of them also finish their admitted head (which is then
    deleted, the 10M-soak's row-retirement path)."""
    cqs = rng.sample(range(n_cqs), min(n_churn, n_cqs))
    clock.t += 1.0
    for i, q in enumerate(cqs):
        d.create_workload(mk(f"{tag}-{q}", f"lq-{q}", 2500,
                             prio=rng.choice([0, 10, 20]),
                             t=clock.t + i * 1e-3))
        if i % 2 == 0:
            key = f"default/pre-{q}-0"
            wl = d.workloads.get(key)
            if wl is not None and wl.has_quota_reservation \
                    and not wl.is_finished:
                d.finish_workload(key)
                d.delete_workload(key)


# ---------------------------------------------------------------------------
# Phase A: pack scaling law (streaming vs rebuild on the same state)
# ---------------------------------------------------------------------------

def pack_curve_point(n_cqs: int, boundaries: int, n_churn: int,
                     seed: int) -> dict:
    log(f"[pack] cqs={n_cqs}: building cluster ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    preload(d, clock, n_cqs, seed)
    log(f"[pack] cqs={n_cqs}: preloaded in "
        f"{time.perf_counter() - t0:.1f}s, rss={rss_mb()}MB")

    rng = random.Random(seed + 1)
    stats: dict = {}
    state = None
    tight = TightenState()
    stream_ms, rebuild_ms = [], []
    planes_identical = True
    bytes_raw = bytes_tight = rows = 0
    for b in range(boundaries):
        churn(d, clock, rng, n_cqs, n_churn, f"ch{b}")
        st = current_structure(d)
        t1 = time.perf_counter()
        plan_s, state, _ = pack_burst_cached(
            st, d.queues, d.cache, d.scheduler, clock,
            state=state, stats=stats)
        t2 = time.perf_counter()
        plan_f = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
        t3 = time.perf_counter()
        if b > 0:   # boundary 0 is the counted cold full pack
            stream_ms.append((t2 - t1) * 1e3)
            rebuild_ms.append((t3 - t2) * 1e3)
        if not plans_equal(plan_s, plan_f):
            planes_identical = False
            log(f"[pack] cqs={n_cqs} boundary {b}: PLANES DIVERGED")
        if plan_s is not None:
            arrays = plan_s.arrays
            bytes_raw = sum(int(np.asarray(v).nbytes)
                            for v in arrays.values())
            bytes_tight = sum(
                int(np.asarray(v).nbytes)
                for v in tighten_arrays(arrays, tight).values())
            rows = sum(1 for row in plan_s.keys
                       for k in row if k is not None)
    out = {
        "cqs": n_cqs,
        "rows": rows,
        "boundaries": boundaries,
        "churn_cqs_per_boundary": n_churn,
        "pack_ms_stream": round(float(np.median(stream_ms)), 3),
        "pack_ms_rebuild": round(float(np.median(rebuild_ms)), 3),
        "pack_speedup": round(float(np.median(rebuild_ms))
                              / max(float(np.median(stream_ms)), 1e-9),
                              2),
        "planes_identical": planes_identical,
        "bytes_to_device_raw": bytes_raw,
        "bytes_to_device": bytes_tight,
        "tighten_ratio": round(bytes_raw / max(bytes_tight, 1), 2),
        "stream_packs": stats.get("stream_packs", 0),
        "stream_full_packs": stats.get("stream_full_packs", 0),
        "pack_rank_patches": stats.get("pack_rank_patches", 0),
        "arena_bytes": stats.get("pack_arena_bytes", 0),
        "rss_mb": rss_mb(),
    }
    log(f"[pack] cqs={n_cqs}: stream={out['pack_ms_stream']}ms "
        f"rebuild={out['pack_ms_rebuild']}ms "
        f"speedup={out['pack_speedup']}x "
        f"parity={'OK' if planes_identical else 'DIVERGED'}")
    del d
    gc.collect()
    return out


# ---------------------------------------------------------------------------
# Phase B: end-to-end decision A/B (streaming vs rebuild drivers)
# ---------------------------------------------------------------------------

_ARM_ENV = {
    "stream": {"KUEUE_TPU_STREAM_PACK": "1"},
    "rebuild": {"KUEUE_TPU_STREAM_PACK": "0",
                "KUEUE_BURST_DELTA_PACK": "0"},
}


def e2e_arm(arm: str, n_cqs: int, rounds: int, n_churn: int,
            seed: int) -> dict:
    old = {k: os.environ.get(k) for k in
           ("KUEUE_TPU_STREAM_PACK", "KUEUE_BURST_DELTA_PACK")}
    os.environ.update(_ARM_ENV[arm])
    try:
        d, clock = build(n_cqs)
        preload(d, clock, n_cqs, seed)
        rng = random.Random(seed + 2)
        decisions = []
        n_cycles = 0
        wall = 0.0
        # round 0 is an untimed warmup: it absorbs the fused kernel's
        # JIT compiles (shape-dependent, cached process-wide) so the
        # timed rounds measure steady state — its DECISIONS still count
        # toward the parity check
        for r in range(rounds + 1):
            churn(d, clock, rng, n_cqs, n_churn, f"e2e{r}")
            t0 = time.perf_counter()
            recs = d.schedule_burst(
                3, runtime=2,
                on_cycle_start=lambda k: setattr(clock, "t",
                                                 clock.t + 1.0))
            if r > 0:
                wall += time.perf_counter() - t0
                n_cycles += len(recs)
            decisions.extend(
                (sorted(s.admitted), sorted(s.skipped),
                 sorted(s.preempted_targets)) for s in recs)
        bs = dict(d._burst_solver.stats) if d._burst_solver else {}
        pack_block = d.stats.get("pack", {})
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    del d
    gc.collect()
    return {"arm": arm, "decisions": decisions,
            "cycle_wall_ms": round(wall * 1e3 / max(n_cycles, 1), 2),
            "n_cycles": n_cycles,
            "bytes_h2d": int(bs.get("burst_launch_bytes_h2d", 0)),
            "pack": pack_block}


# ---------------------------------------------------------------------------
# Phase C: the 10M-workload soak
# ---------------------------------------------------------------------------

def soak(n_cqs: int, target: int, seed: int, wal_path: str,
         commit_every: int) -> dict:
    log(f"[soak] cqs={n_cqs} target={target} workloads, "
        f"wal commit_every={commit_every} ...")
    t0 = time.perf_counter()
    d, clock = build(n_cqs)
    wal = CycleWAL(wal_path, commit_every=commit_every,
                   compact_every=64)
    d.attach_wal(wal)
    rng = random.Random(seed + 3)
    created = finished = admitted = 0
    rounds = 0
    prios = [0, 10, 20]
    peak_rss = rss_mb()
    t_report = t0
    while created < target:
        batch = min(n_cqs, target - created)
        clock.t += 1.0
        for i in range(batch):
            q = i % n_cqs
            d.create_workload(mk(f"s{rounds}-{i}", f"lq-{q}", 2500,
                                 prio=prios[(rounds + i) % 3],
                                 t=clock.t + i * 1e-4))
        created += batch
        recs = d.schedule_burst(
            4, runtime=2,
            on_cycle_start=lambda k: setattr(clock, "t",
                                             clock.t + 1.0))
        for s in recs:
            admitted += len(s.admitted)
        # retire finished rows so the live store stays O(active)
        done = [k for k, w in d.workloads.items() if w.is_finished]
        for k in done:
            d.delete_workload(k)
        finished += len(done)
        rounds += 1
        peak_rss = max(peak_rss, rss_mb())
        now = time.perf_counter()
        if now - t_report > 30.0:
            t_report = now
            log(f"[soak] {created}/{target} created, "
                f"{admitted} admitted, {finished} retired, "
                f"round {rounds}, rss={rss_mb()}MB, "
                f"{now - t0:.0f}s")
    # drain the in-flight tail
    for _ in range(4):
        recs = d.schedule_burst(
            4, runtime=2,
            on_cycle_start=lambda k: setattr(clock, "t",
                                             clock.t + 1.0))
        for s in recs:
            admitted += len(s.admitted)
        done = [k for k, w in d.workloads.items() if w.is_finished]
        for k in done:
            d.delete_workload(k)
        finished += len(done)
    wal_stats = dict(wal.stats)
    wal.close()
    wal_size = os.path.getsize(wal_path) if os.path.exists(wal_path) \
        else 0
    pack_block = d.stats.get("pack", {})
    wall = time.perf_counter() - t0
    out = {
        "cqs": n_cqs,
        "target_workloads": target,
        "created": created,
        "admitted": admitted,
        "finished": finished,
        "rounds": rounds,
        "completed": created >= target,
        "wall_s": round(wall, 1),
        "workloads_per_s": round(created / max(wall, 1e-9), 1),
        "peak_rss_mb": peak_rss,
        "wal": {**wal_stats,
                "commit_every": commit_every,
                "compact_every": 64,
                "final_file_bytes": wal_size},
        "pack_counters": pack_block,
    }
    log(f"[soak] done: {created} workloads in {out['wall_s']}s "
        f"({out['workloads_per_s']}/s), {admitted} admitted, "
        f"wal compactions={wal_stats.get('wal_compactions', 0)} "
        f"file={wal_size}B")
    del d
    gc.collect()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", default="",
                    help="comma-separated CQ universe sizes")
    ap.add_argument("--seed", type=int,
                    default=int(env_value("KUEUE_TPU_SCALE_SEED")))
    ap.add_argument("--boundaries", type=int, default=8,
                    help="measured pack boundaries per size")
    ap.add_argument("--rounds", type=int, default=3,
                    help="churn+burst rounds per end-to-end arm")
    ap.add_argument("--churn", type=int, default=64,
                    help="CQs churned per boundary (the 'activity')")
    ap.add_argument("--soak-workloads", type=int, default=0,
                    help="0 = 10M full / 100k quick")
    ap.add_argument("--quick", action="store_true",
                    help="4k-CQ ceiling + 100k-workload soak")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SCALE_r13.json"))
    args = ap.parse_args()

    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",") if s]
    elif args.quick:
        sizes = [1000, 4000]
    else:
        sizes = [1000, 4000, 10000, 30000, 100000]
    boundaries = 4 if args.quick else args.boundaries
    soak_target = args.soak_workloads or (100_000 if args.quick
                                          else 10_000_000)
    soak_cqs = sizes[-1]
    commit_every = int(env_value("KUEUE_TPU_WAL_COMMIT_EVERY", "64"))
    t_start = time.perf_counter()
    log(f"scale soak: sizes={sizes} boundaries={boundaries} "
        f"churn={args.churn} soak={soak_target}@{soak_cqs}cqs "
        f"seed={args.seed}")

    curve = []
    for n in sizes:
        point = pack_curve_point(n, boundaries, args.churn, args.seed)
        # end-to-end A/B, rebuild interleaved right after streaming on
        # the same box (the environment-drift control)
        e_s = e2e_arm("stream", n, args.rounds, args.churn, args.seed)
        e_r = e2e_arm("rebuild", n, args.rounds, args.churn, args.seed)
        point["decisions_identical"] = \
            e_s["decisions"] == e_r["decisions"]
        point["cycle_wall_ms"] = e_s["cycle_wall_ms"]
        point["cycle_wall_ms_rebuild"] = e_r["cycle_wall_ms"]
        point["bytes_h2d_e2e"] = e_s["bytes_h2d"]
        point["e2e_cycles"] = e_s["n_cycles"]
        point["pack_counters"] = e_s["pack"]
        point["pack_counters_rebuild"] = e_r["pack"]
        log(f"[e2e] cqs={n}: cycle={e_s['cycle_wall_ms']}ms "
            f"(rebuild {e_r['cycle_wall_ms']}ms) decisions "
            f"{'identical' if point['decisions_identical'] else 'DIVERGED'}")
        curve.append(point)

    wal_path = os.path.join(os.path.dirname(args.out),
                            "scale_soak_wal.jsonl")
    soak_block = soak(soak_cqs, soak_target, args.seed, wal_path,
                      commit_every)
    try:
        os.remove(wal_path)
    except OSError:
        pass

    top = curve[-1]
    parity = {
        "planes_identical_all": all(p["planes_identical"]
                                    for p in curve),
        "decisions_identical_all": all(p["decisions_identical"]
                                       for p in curve),
    }
    drift = ab_block(
        treatment={"arm": "stream", "cqs": top["cqs"],
                   "pack_ms": top["pack_ms_stream"],
                   "cycle_wall_ms": top["cycle_wall_ms"],
                   "pack": top["pack_counters"]},
        control={"arm": "rebuild", "interleaved": True,
                 "cqs": top["cqs"],
                 "pack_ms": top["pack_ms_rebuild"],
                 "cycle_wall_ms": top["cycle_wall_ms_rebuild"],
                 "pack": top["pack_counters_rebuild"]})

    tail = {
        "metric": "streaming_pack_speedup_at_max_cqs",
        "unit": "rebuild pack ms / streaming pack ms at the largest "
                "probed universe",
        "value": top["pack_speedup"],
        "cqs": top["cqs"],
        "seed": args.seed,
        "quick": bool(args.quick),
        "mesh": mesh_info(),
        "sizes": sizes,
        "curve": curve,
        "parity": parity,
        "soak": soak_block,
        "control": drift["control"],
        "environment_drift": drift,
        "wall_s_total": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps({
        "metric": tail["metric"], "cqs": tail["cqs"],
        "value": tail["value"],
        "planes_identical_all": parity["planes_identical_all"],
        "decisions_identical_all": parity["decisions_identical_all"],
        "soak_completed": soak_block["completed"]}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out} ({tail['wall_s_total']}s total)")
    ok = (parity["planes_identical_all"]
          and parity["decisions_identical_all"]
          and soak_block["completed"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
