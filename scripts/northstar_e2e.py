"""North-star END-TO-END run: the real Driver at 100k pending workloads
across 1k ClusterQueues, device solver on — pack + classify + admit-scan +
unpack + store updates per cycle, nothing synthetic.

Role-matches the reference's integrated perf artifact
(/root/reference/test/performance/scheduler/minimalkueue/main.go): the
whole scheduling path is exercised, only job execution is faked (admitted
workloads finish a fixed number of cycles after admission).

Usage:
    python scripts/northstar_e2e.py [--cqs 1000] [--wl 100000]
        [--cycles 30] [--host]   (--host = scalar path for comparison)

Prints per-cycle latency percentiles and a one-line JSON tail.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    ReclaimWithinCohort,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    WithinClusterQueue,
    Workload,
)
from kueue_tpu.controller.driver import Driver


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def build(n_cqs: int, n_wl: int, use_device: bool, cqs_per_cohort: int = 5,
          n_flavors: int = 1, n_resources: int = 1):
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=use_device)
    flavors = ([f"flavor-{f}" for f in range(n_flavors)]
               if n_flavors > 1 else ["default"])
    for f in flavors:
        d.apply_resource_flavor(ResourceFlavor(name=f))
    resources = (["cpu"] + [f"res-{r}" for r in range(1, n_resources)]
                 if n_resources > 1 else ["cpu"])
    per_cq = max(1, n_wl // n_cqs)
    t_build = time.perf_counter()
    for i in range(n_cqs):
        cohort = f"cohort-{i // cqs_per_cohort}"
        # early flavors are deliberately tight so the host flavor walk
        # (flavorassigner.go:499) has to visit most of the list
        d.apply_cluster_queue(ClusterQueue(
            name=f"cq-{i}", cohort=cohort,
            preemption=PreemptionPolicy(
                reclaim_within_cohort=ReclaimWithinCohort.ANY,
                within_cluster_queue=WithinClusterQueue.LOWER_PRIORITY),
            resource_groups=[ResourceGroup(
                covered_resources=list(resources),
                flavors=[FlavorQuotas(name=f, resources={
                    r: ResourceQuota(
                        nominal=(500 if fi < len(flavors) - 1 else 20_000),
                        borrowing_limit=100_000)
                    for r in resources})
                    for fi, f in enumerate(flavors)])]))
        d.apply_local_queue(LocalQueue(name=f"lq-{i}",
                                       cluster_queue=f"cq-{i}"))
    # Ragged pod sets + a low/medium priority mix (the reference perf
    # generator's class structure, default_generator_config.yaml); the
    # high-priority preemptor wave is INJECTED mid-run by run_path so
    # preemption and skips actually fire at scale instead of the
    # priority order absorbing everything at t0.
    total = 0
    for i in range(n_cqs):
        for k in range(per_cq):
            total += 1
            if k % 3 == 2:        # medium: 2500/pod x 2 pods
                per_pod, count, prio = 2500, 1 + (k % 2), 100
            else:                 # small: 500/pod x 1..4 pods (ragged)
                per_pod, count, prio = 500, (1, 2, 4)[k % 3], 50
            d.create_workload(Workload(
                name=f"wl-{i}-{k}", queue_name=f"lq-{i}",
                priority=prio, creation_time=float(total),
                pod_sets=[PodSet(name="main", count=count,
                                 requests={r: per_pod
                                           for r in resources})]))

    def preemptor_wave(start_time: float) -> int:
        """One large high-priority gang per CQ: 5000/pod x 4 pods fills
        the whole nominal quota, forcing preemption of the running
        low-priority wave (reclaimWithinCohort + lowerPriority)."""
        n = 0
        for i in range(n_cqs):
            n += 1
            d.create_workload(Workload(
                name=f"pre-{i}", queue_name=f"lq-{i}", priority=200,
                creation_time=start_time + n,
                pod_sets=[PodSet(name="main", count=4,
                                 requests={r: 5000 for r in resources})]))
        return n

    print(f"built {n_cqs} CQs x {len(flavors)} flavors x "
          f"{len(resources)} resources / {total} workloads in "
          f"{time.perf_counter() - t_build:.1f}s", file=sys.stderr)
    return d, clock, total, preemptor_wave


def run_path(args, use_device: bool) -> dict:
    d, clock, total, preemptor_wave = build(
        args.cqs, args.wl, use_device=use_device,
        n_flavors=args.flavors, n_resources=args.resources)
    if d.scheduler.solver is not None:
        t_w = time.perf_counter()
        d.scheduler.solver.warmup(d.cache.snapshot(), args.cqs)
        print(f"solver warmup {time.perf_counter() - t_w:.1f}s",
              file=sys.stderr)

    inject_at = args.inject_at if args.inject_at >= 0 else args.cycles // 3
    cycle_times = []
    admitted_total = preempted_total = skipped_total = 0
    running = []
    for cycle in range(args.cycles):
        if cycle == inject_at:
            n = preemptor_wave(clock.t)
            total += n
            print(f"cycle {cycle}: injected {n} high-priority preemptors",
                  file=sys.stderr)
        clock.t += 1.0
        c0 = time.perf_counter()
        stats = d.schedule_once()
        dt = time.perf_counter() - c0
        cycle_times.append(dt)
        admitted_total += len(stats.admitted)
        preempted_total += len(stats.preempted_targets)
        skipped_total += len(stats.skipped)
        for key in stats.admitted:
            running.append((cycle + args.runtime, key))
        still = []
        for fin, key in running:
            wl = d.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                continue
            if fin <= cycle:
                d.finish_workload(key)
            else:
                still.append((fin, key))
        running = still
        print(f"cycle {cycle}: {dt*1e3:.1f}ms admitted={len(stats.admitted)} "
              f"preempting={len(stats.preempting)} "
              f"preempted={len(stats.preempted_targets)} "
              f"skipped={len(stats.skipped)} "
              f"inadmissible={len(stats.inadmissible)}", file=sys.stderr)

    cycle_times.sort()
    p50 = cycle_times[len(cycle_times) // 2]
    p99 = cycle_times[min(len(cycle_times) - 1,
                          int(len(cycle_times) * 0.99))]
    solver = d.scheduler.solver
    out = {
        "path": "device" if use_device else "host",
        "p50_ms": round(p50 * 1e3, 1),
        "p99_ms": round(p99 * 1e3, 1),
        "admitted": admitted_total,
        "preempted": preempted_total,
        "skipped": skipped_total,
        "workloads": total,
    }
    if solver is not None:
        out["solver_stats"] = dict(solver.stats)
        if solver.rtt_s is not None:
            out["accel_rtt_ms"] = round(solver.rtt_s * 1e3, 1)
        print(f"stats: {solver.stats}", file=sys.stderr)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cqs", type=int, default=1000)
    ap.add_argument("--wl", type=int, default=100_000)
    ap.add_argument("--cycles", type=int, default=30)
    ap.add_argument("--host", action="store_true",
                    help="run ONLY the host path")
    ap.add_argument("--device", action="store_true",
                    help="run ONLY the device path")
    ap.add_argument("--runtime", type=int, default=4)
    ap.add_argument("--flavors", type=int, default=1)
    ap.add_argument("--resources", type=int, default=1)
    ap.add_argument("--inject-at", type=int, default=-1,
                    help="cycle at which the preemptor wave arrives "
                         "(default cycles//3)")
    args = ap.parse_args()

    # default: BOTH paths in one invocation, side by side — the honest
    # artifact the round-2 verdict asked for
    results = []
    if not args.host:
        results.append(run_path(args, use_device=True))
    if not args.device:
        results.append(run_path(args, use_device=False))
    tail = {
        "metric": "northstar_e2e_cycle_p99",
        "unit": "ms",
        "cqs": args.cqs,
        "flavors": args.flavors, "resources": args.resources,
    }
    for r in results:
        tail[r["path"]] = {k: v for k, v in r.items() if k != "path"}
    if len(results) == 2:
        dev, host = results[0], results[1]
        tail["value"] = dev["p99_ms"]
        tail["device_beats_host_p50"] = dev["p50_ms"] < host["p50_ms"]
        tail["device_beats_host_p99"] = dev["p99_ms"] < host["p99_ms"]
    else:
        tail["value"] = results[0]["p99_ms"]
    # the artifact must prove the hard paths ran at scale
    tail["hard_paths_exercised"] = all(
        r["preempted"] > 0 and r["skipped"] > 0 for r in results)
    print(json.dumps(tail))


if __name__ == "__main__":
    main()
