"""North-star END-TO-END run: the real Driver at 100k pending workloads
across 1k ClusterQueues, device solver on — pack + classify + admit-scan +
unpack + store updates per cycle, nothing synthetic.

Role-matches the reference's integrated perf artifact
(/root/reference/test/performance/scheduler/minimalkueue/main.go): the
whole scheduling path is exercised, only job execution is faked (admitted
workloads finish a fixed number of cycles after admission).

Usage:
    python scripts/northstar_e2e.py [--cqs 1000] [--wl 100000]
        [--cycles 30] [--host]   (--host = scalar path for comparison)

Prints per-cycle latency percentiles and a one-line JSON tail.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _peek_int_flag(argv, flag: str) -> int:
    """Read an int flag from raw argv (both '--f N' and '--f=N' forms)."""
    n = 0
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            try:
                n = max(n, int(argv[i + 1]))
            except ValueError:
                pass
        elif a.startswith(flag + "="):
            try:
                n = max(n, int(a.split("=", 1)[1]))
            except ValueError:
                pass
    return n


def _peek_arm_list(argv, flag: str) -> int:
    """Max shard count named in a comma-list flag (e.g. --crossover
    1,2,4,8) from raw argv — same pre-jax constraint as _peek_int_flag."""
    n = 0
    for i, a in enumerate(argv):
        v = None
        if a == flag and i + 1 < len(argv):
            v = argv[i + 1]
        elif a.startswith(flag + "="):
            v = a.split("=", 1)[1]
        if v:
            for part in v.split(","):
                try:
                    n = max(n, int(part))
                except ValueError:
                    pass
    return n


# sharding must be configured BEFORE jax initializes its backend (the
# kueue_tpu import below pulls jax in): on a CPU host the only way to
# get a multi-device mesh is --xla_force_host_platform_device_count
_shards = _peek_int_flag(sys.argv[1:], "--shards")
_ab_shards = _peek_int_flag(sys.argv[1:], "--ab-shards")
_xover = _peek_arm_list(sys.argv[1:], "--crossover")
_n_dev = max(_shards, _ab_shards, _xover)
if _n_dev > 1:
    _xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _xf:
        os.environ["XLA_FLAGS"] = (
            _xf + f" --xla_force_host_platform_device_count={_n_dev}"
        ).strip()
if _shards > 1:
    # the env route is what production uses; setting it here also
    # exercises the Driver.__init__ KUEUE_TPU_SHARDS wiring
    os.environ.setdefault("KUEUE_TPU_SHARDS", str(_shards))

from kueue_tpu.api.types import (
    ClusterQueue,
    FairSharing,
    FlavorFungibility,
    FlavorFungibilityPolicy,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    ReclaimWithinCohort,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    WithinClusterQueue,
    Workload,
)
from kueue_tpu.controller.driver import Driver

# heterogeneous runs cycle the whenCanBorrow x whenCanPreempt matrix
# across CQs so the in-kernel fungibility walk sees every policy shape
FF_MIX = [
    FlavorFungibility(),                                  # Borrow/TryNext
    FlavorFungibility(
        when_can_borrow=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR),
    FlavorFungibility(
        when_can_preempt=FlavorFungibilityPolicy.PREEMPT),
    FlavorFungibility(
        when_can_borrow=FlavorFungibilityPolicy.TRY_NEXT_FLAVOR,
        when_can_preempt=FlavorFungibilityPolicy.PREEMPT),
]


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def build(n_cqs: int, n_wl: int, use_device: bool, cqs_per_cohort: int = 5,
          n_flavors: int = 1, n_resources: int = 1):
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=use_device)
    flavors = ([f"flavor-{f}" for f in range(n_flavors)]
               if n_flavors > 1 else ["default"])
    for f in flavors:
        d.apply_resource_flavor(ResourceFlavor(name=f))
    resources = (["cpu"] + [f"res-{r}" for r in range(1, n_resources)]
                 if n_resources > 1 else ["cpu"])
    per_cq = max(1, n_wl // n_cqs)
    t_build = time.perf_counter()
    for i in range(n_cqs):
        cohort = f"cohort-{i // cqs_per_cohort}"
        # early flavors are deliberately tight so the host flavor walk
        # (flavorassigner.go:499) has to visit most of the list
        d.apply_cluster_queue(ClusterQueue(
            name=f"cq-{i}", cohort=cohort,
            flavor_fungibility=(FF_MIX[i % len(FF_MIX)]
                                if n_flavors > 1 else FlavorFungibility()),
            preemption=PreemptionPolicy(
                reclaim_within_cohort=ReclaimWithinCohort.ANY,
                within_cluster_queue=WithinClusterQueue.LOWER_PRIORITY),
            resource_groups=[ResourceGroup(
                covered_resources=list(resources),
                flavors=[FlavorQuotas(name=f, resources={
                    r: ResourceQuota(
                        nominal=(500 if fi < len(flavors) - 1 else 20_000),
                        borrowing_limit=100_000)
                    for r in resources})
                    for fi, f in enumerate(flavors)])]))
        d.apply_local_queue(LocalQueue(name=f"lq-{i}",
                                       cluster_queue=f"cq-{i}"))
    # Ragged pod sets + a low/medium priority mix (the reference perf
    # generator's class structure, default_generator_config.yaml); the
    # high-priority preemptor wave is INJECTED mid-run by run_path so
    # preemption and skips actually fire at scale instead of the
    # priority order absorbing everything at t0.
    total = 0
    for i in range(n_cqs):
        for k in range(per_cq):
            total += 1
            if k % 3 == 2:        # medium: 2500/pod x 2 pods
                per_pod, count, prio = 2500, 1 + (k % 2), 100
            else:                 # small: 500/pod x 1..4 pods (ragged)
                per_pod, count, prio = 500, (1, 2, 4)[k % 3], 50
            d.create_workload(Workload(
                name=f"wl-{i}-{k}", queue_name=f"lq-{i}",
                priority=prio, creation_time=float(total),
                pod_sets=[PodSet(name="main", count=count,
                                 requests={r: per_pod
                                           for r in resources})]))

    def preemptor_wave(start_time: float) -> int:
        """One large high-priority gang per CQ: 5000/pod x 4 pods fills
        the whole nominal quota, forcing preemption of the running
        low-priority wave (reclaimWithinCohort + lowerPriority)."""
        n = 0
        for i in range(n_cqs):
            n += 1
            d.create_workload(Workload(
                name=f"pre-{i}", queue_name=f"lq-{i}", priority=200,
                creation_time=start_time + n,
                pod_sets=[PodSet(name="main", count=4,
                                 requests={r: 5000 for r in resources})]))
        return n

    print(f"built {n_cqs} CQs x {len(flavors)} flavors x "
          f"{len(resources)} resources / {total} workloads in "
          f"{time.perf_counter() - t_build:.1f}s", file=sys.stderr)
    # The 100k Workload/Info object graph is immortal for the run's
    # lifetime; without freezing it, gen-2 collections walk all of it
    # and inject ~0.8s pauses into random cycles (measured r5: the
    # 'every ~11th cycle' spikes of VERDICT r4 weak #1 were exactly
    # these).  Freeze moves it out of GC's sight; scheduling itself
    # allocates only short-lived objects.
    gc.collect()
    gc.freeze()
    return d, clock, total, preemptor_wave


def summarize_trials(runs) -> dict:
    """Median trial (by p99) with min/max spread — the reference
    rangespec's ±band discipline (default_rangespec.yaml:1-6);
    single-trial numbers from this 1-core box swing 2-3x (VERDICT r4
    weak #2)."""
    cold_warmup_s = runs[0].get("warmup_s", 0.0)
    runs = sorted(runs, key=lambda r: r["p99_ms"])
    out = dict(runs[len(runs) // 2])
    out["trials"] = len(runs)
    out["p50_ms_range"] = [min(r["p50_ms"] for r in runs),
                           max(r["p50_ms"] for r in runs)]
    out["p99_ms_range"] = [min(r["p99_ms"] for r in runs),
                           max(r["p99_ms"] for r in runs)]
    out["warmup_s"] = cold_warmup_s   # chronologically-first (cold) trial
    out["decisions_stable"] = all(
        (r["admitted"], r["preempted"], r["skipped"]) ==
        (runs[0]["admitted"], runs[0]["preempted"], runs[0]["skipped"])
        for r in runs)
    return out


def with_trials(trial_fn, args) -> dict:
    runs = []
    for _ in range(max(1, args.trials)):
        runs.append(trial_fn())
        # un-freeze so the finished trial's (cyclic) driver graph is
        # collectable before the next build freezes its own
        gc.unfreeze()
        gc.collect()
    return summarize_trials(runs)


def run_burst_path(args, backend: str) -> dict:
    """The fused-burst path (kueue_tpu.ops.burst): runs of clean cycles
    are decided in single device dispatches on ``backend``; preemption
    waves fall back to the normal per-cycle path automatically.  Per-
    cycle wall times are measured between applied-cycle boundaries, so
    pack + dispatch costs land in the first cycle of each burst (honest
    p99: the amortization is visible, not hidden)."""
    os.environ["KUEUE_BURST_DELTA_PACK"] = (
        "0" if getattr(args, "no_delta_pack", False) else "1")
    d, clock, total, preemptor_wave = build(
        args.cqs, args.wl, use_device=True,
        n_flavors=args.flavors, n_resources=args.resources)
    t_w = time.perf_counter()
    d.scheduler.solver.warmup(d.cache.snapshot(), args.cqs)
    # pre-compile the burst kernel rungs this run can hit (one XLA
    # compile per (M, K) shape; the persistent compilation cache makes
    # this one-time per machine)
    from kueue_tpu.ops.burst import pack_burst, BurstSolver, K_BURST_LADDER
    import numpy as np
    st = d.scheduler.solver._structure_for(d.cache.snapshot(), [])
    plan = pack_burst(st, d.queues, d.cache, d.scheduler, clock)
    bs = BurstSolver(backend=backend)
    shards = getattr(args, "shards", 0)
    if shards > 1:
        bs.set_shards(shards)
    if plan is not None:
        F = max(1, len(st.fr_index))
        for K in K_BURST_LADDER:
            extr = np.zeros((K, plan.C, F), np.int32)
            extu = np.zeros((K, plan.G), bool)
            h = bs.dispatch(plan, K, args.runtime, extr, extu)
            bs.fetch_flags(h)
            # chain one speculative window so the pipeline's
            # carry-rebase path is compiled here, not at the first
            # measured boundary that speculates
            h2 = bs.dispatch_next(h, extr, extu)
            bs.fetch(h)
            if h2 is not None:
                bs.fetch(h2)
        bs.stats = {k: ([0.0] * len(v) if isinstance(v, list)
                        else 0 if isinstance(v, int) else 0.0)
                    for k, v in bs.stats.items()}
        bs._resident = None
        d._burst_m = plan.M
    d._burst_solver = bs
    warmup_s = time.perf_counter() - t_w
    print(f"solver+burst warmup {warmup_s:.1f}s", file=sys.stderr)

    # The frozen object graph keeps gen-2 sweeps off the immortal build
    # (see build()), but the run itself RETAINS per-cycle stats — the
    # unfrozen heap grows all run and periodic gen-2 pauses grow with
    # it (~0.5s at cycle 5 to ~2s at cycle 92 at 1000 CQs), drowning
    # the boundary costs the crossover compares.  Collection is paused
    # for the measured phase on every arm equally; refcounting still
    # frees the per-cycle churn, and the cyclic leftovers are bounded
    # by the run length (collected by with_trials between trials).
    gc.disable()

    inject_at = args.inject_at if args.inject_at >= 0 else args.cycles // 3
    budget_s = float(getattr(args, "budget_s", 0.0) or 0.0)
    completed = True
    t_run0 = time.perf_counter()
    all_stats = []
    cycle_times = []
    last_t = time.perf_counter()

    def on_cycle_start(_k):
        clock.t += 1.0

    def on_cycle(_k, stats):
        nonlocal last_t
        now = time.perf_counter()
        # finish application is workload-controller work, excluded from
        # scheduler-cycle latency exactly as the per-cycle harness loop
        # excludes it (finishes run outside its timed section)
        cycle_times.append(max(0.0, now - last_t - stats.finish_s))
        last_t = now
        print(f"cycle {len(cycle_times) - 1}: "
              f"{cycle_times[-1]*1e3:.1f}ms "
              f"admitted={len(stats.admitted)} "
              f"preempted={len(stats.preempted_targets)} "
              f"skipped={len(stats.skipped)} "
              f"inadmissible={len(stats.inadmissible)}", file=sys.stderr)

    injected = False
    while len(all_stats) < args.cycles:
        if budget_s and time.perf_counter() - t_run0 > budget_s:
            completed = False
            print(f"budget {budget_s:.0f}s exhausted after "
                  f"{len(all_stats)}/{args.cycles} cycles",
                  file=sys.stderr)
            break
        if not injected and len(all_stats) >= inject_at:
            n = preemptor_wave(clock.t)
            total += n
            injected = True
            print(f"cycle {len(all_stats)}: injected {n} preemptors",
                  file=sys.stderr)
        target = args.cycles if injected else inject_at
        if budget_s:
            # budgeted runs chunk the window stream so the wall check
            # fires between dispatches instead of after a whole phase
            target = min(target, len(all_stats) + 8)
        base = len(all_stats)
        ext: dict = {}
        for j, s in enumerate(all_stats):
            fin = j + args.runtime
            if fin >= base:
                keys = [k for k in s.admitted
                        if (wl := d.workloads.get(k)) is not None
                        and wl.has_quota_reservation]
                if keys:
                    ext[fin - base] = keys
        last_t = time.perf_counter()
        stats = d.schedule_burst(
            target - base, runtime=args.runtime, external_finishes=ext,
            on_cycle=on_cycle, on_cycle_start=on_cycle_start,
            backend=backend, pipeline=not args.no_pipeline)
        all_stats.extend(stats)
        if not stats:
            if not injected:
                # drained before the wave: pad the quiet cycles (the
                # per-cycle path runs them as empty cycles) and inject
                from kueue_tpu.scheduler.scheduler import CycleStats
                while len(all_stats) < inject_at:
                    clock.t += 1.0
                    all_stats.append(CycleStats())
                    cycle_times.append(0.0)
                continue
            break

    # sparse-boundary phase: production steady state is a trickle of
    # arrivals touching a few queues between windows, not 1000 CQs of
    # uniform churn (those boundaries are full-repack territory and the
    # delta path deliberately falls back).  Each round dirties a
    # handful of CQs and runs one short window, so the boundary pack is
    # paid at O(dirty rows) — this is where the delta-vs-full claim is
    # measured.
    trickle = getattr(args, "trickle", 0)
    n_main_cycles = len(cycle_times)
    if trickle > 0:
        resources = (["cpu"] + [f"res-{r}"
                                for r in range(1, args.resources)]
                     if args.resources > 1 else ["cpu"])
        # first build the steady state the trickle measures against:
        # long-running services (no finish events) fill every CQ, the
        # leftover backlog parks as inadmissible — boundaries between
        # trickle rounds then see a full, QUIET cluster, which is the
        # production shape the delta pack optimizes (a backlog drain
        # dirties every CQ every window and correctly full-repacks)
        for i in range(args.cqs):
            for s in range(8):
                total += 1
                d.create_workload(Workload(
                    name=f"svc-{i}-{s}", queue_name=f"lq-{i}",
                    priority=300, creation_time=clock.t + i * 8 + s,
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={r: 2500
                                               for r in resources})]))
        for _ in range(8):   # fill to quiescence (svc admits + evictions
            last_t = time.perf_counter()   # of the preemptor wave settle)
            stats = d.schedule_burst(
                16, runtime=10_000, external_finishes={},
                on_cycle=on_cycle, on_cycle_start=on_cycle_start,
                backend=backend, pipeline=not args.no_pipeline)
            all_stats.extend(stats)
            if not any(s.admitted or s.preempted_targets for s in stats):
                break
        pre = dict(d._burst_solver.stats)
        n_touch = max(1, min(10, args.cqs))
        t_adm = 0
        rounds_run = 0
        for t in range(trickle):
            if budget_s and time.perf_counter() - t_run0 > budget_s:
                completed = False
                print(f"budget {budget_s:.0f}s exhausted after trickle "
                      f"round {t}/{trickle}", file=sys.stderr)
                break
            rounds_run += 1
            for i in range(n_touch):
                total += 1
                d.create_workload(Workload(
                    name=f"trk-{t}-{i}", queue_name=f"lq-{i}",
                    priority=200, creation_time=clock.t + i + 1,
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={r: 100
                                               for r in resources})]))
            last_t = time.perf_counter()
            stats = d.schedule_burst(
                2, runtime=args.runtime, external_finishes={},
                on_cycle=on_cycle, on_cycle_start=on_cycle_start,
                backend=backend, pipeline=not args.no_pipeline)
            all_stats.extend(stats)
            t_adm += sum(len(s.admitted) for s in stats)
        bs_now = d._burst_solver.stats
        trickle_stats = {
            k: (round(bs_now.get(k, 0) - pre.get(k, 0), 4)
                if isinstance(bs_now.get(k, 0), float)
                else bs_now.get(k, 0) - pre.get(k, 0))
            for k in ("burst_pack_s", "burst_packs", "burst_full_packs",
                      "burst_delta_packs", "delta_pack_s", "rows_reused",
                      "rows_repacked")}
        trickle_stats["rounds"] = rounds_run
        trickle_stats["rounds_requested"] = trickle
        trickle_stats["cqs_touched_per_round"] = n_touch
        trickle_stats["admitted"] = t_adm

    gc.enable()
    # headline percentiles cover the backlog-drain phase only (the
    # r06-comparable number); the fill/trickle phases report their own
    # boundary costs through the pack counters
    cycle_times = sorted(cycle_times[:n_main_cycles])
    p50 = cycle_times[len(cycle_times) // 2] if cycle_times else 0.0
    p99 = (cycle_times[min(len(cycle_times) - 1,
                           int(len(cycle_times) * 0.99))]
           if cycle_times else 0.0)
    from kueue_tpu.perf.harness import burst_boundary_report
    suffix = ("" if not args.no_pipeline else "-serial") + (
        "-fullpack" if getattr(args, "no_delta_pack", False) else "") + (
        f"-shard{bs.n_shards}" if bs.n_shards > 1 else "")
    out = {
        "path": f"burst-{backend}{suffix}",
        "p50_ms": round(p50 * 1e3, 1),
        "p99_ms": round(p99 * 1e3, 1),
        "admitted": sum(len(s.admitted) for s in all_stats),
        "preempted": sum(len(s.preempted_targets) for s in all_stats),
        "skipped": sum(len(s.skipped) for s in all_stats),
        "workloads": total,
        "cycles_run": len(all_stats),
        "completed": completed,
        "warmup_s": round(warmup_s, 1),
        "burst_stats": dict(d._burst_solver.stats),
        "boundary_pipeline": burst_boundary_report(d._burst_solver.stats),
        "solver_stats": dict(d.scheduler.solver.stats),
        "obs": d.obs.report(),
    }
    if budget_s:
        out["budget_s"] = budget_s
        out["elapsed_s"] = round(time.perf_counter() - t_run0, 1)
    if trickle > 0:
        out["trickle"] = trickle_stats
    print(f"burst[{backend}] stats: {d._burst_solver.stats}",
          file=sys.stderr)
    return out


def run_fs_path(args, use_device: bool) -> dict:
    """Fair sharing at north-star scale: cohorts with uneven weights and
    heavy borrowing contention, so FS FULL cycles (the ops/fs_scan.py
    in-scan tournament) run hot — fs_full_cycles was 0 in every prior
    perf artifact (VERDICT r4 weak #4).  FS preemption stays host-side;
    this variant measures the admission tournament."""
    clock = VirtualClock()
    d = Driver(clock=clock, fair_sharing=True,
               use_device_solver=use_device)
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    n_cqs = args.cqs
    per_cq = max(1, args.wl // n_cqs)
    weights = (1.0, 2.0, 4.0, 1.0, 0.5)
    for i in range(n_cqs):
        d.apply_cluster_queue(ClusterQueue(
            name=f"cq-{i}", cohort=f"cohort-{i // 5}",
            fair_sharing=FairSharing(weight=weights[i % 5]),
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(name="default", resources={
                    "cpu": ResourceQuota(nominal=4_000,
                                         borrowing_limit=80_000)})])]))
        d.apply_local_queue(LocalQueue(name=f"lq-{i}",
                                       cluster_queue=f"cq-{i}"))
    total = 0
    for i in range(n_cqs):
        for k in range(per_cq):
            total += 1
            d.create_workload(Workload(
                name=f"wl-{i}-{k}", queue_name=f"lq-{i}", priority=50,
                creation_time=float(total),
                pod_sets=[PodSet(name="main", count=1,
                                 requests={"cpu": 2_000})]))
    # per-CQ demand (per_cq x 2000) >> nominal 4000: every admission
    # beyond the second borrows and the DRS tournament arbitrates
    gc.collect()
    gc.freeze()
    if d.scheduler.solver is not None:
        t_w = time.perf_counter()
        d.scheduler.solver.warmup(d.cache.snapshot(), args.cqs)
        print(f"solver warmup {time.perf_counter() - t_w:.1f}s",
              file=sys.stderr)

    cycle_times = []
    admitted_total = skipped_total = 0
    running = []
    for cycle in range(args.cycles):
        clock.t += 1.0
        c0 = time.perf_counter()
        stats = d.schedule_once()
        dt = time.perf_counter() - c0
        cycle_times.append(dt)
        admitted_total += len(stats.admitted)
        skipped_total += len(stats.skipped)
        for key in stats.admitted:
            running.append((cycle + args.runtime, key))
        still = []
        for fin, key in running:
            wl = d.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                continue
            if fin <= cycle:
                d.finish_workload(key)
            else:
                still.append((fin, key))
        running = still
        print(f"cycle {cycle}: {dt*1e3:.1f}ms "
              f"admitted={len(stats.admitted)} "
              f"skipped={len(stats.skipped)}", file=sys.stderr)

    cycle_times.sort()
    p50 = cycle_times[len(cycle_times) // 2]
    p99 = cycle_times[min(len(cycle_times) - 1,
                          int(len(cycle_times) * 0.99))]
    solver = d.scheduler.solver
    out = {
        "path": "fs-device" if use_device else "fs-host",
        "p50_ms": round(p50 * 1e3, 1),
        "p99_ms": round(p99 * 1e3, 1),
        "admitted": admitted_total,
        "preempted": 0,
        "skipped": skipped_total,
        "workloads": total,
        "fs_stats": dict(d.scheduler.fs_stats),
        "obs": d.obs.report(),
    }
    if solver is not None:
        out["solver_stats"] = dict(solver.stats)
        out["fs_full_cycles"] = solver.stats.get("fs_full_cycles", 0)
        print(f"fs stats: {solver.stats} {d.scheduler.fs_stats}",
              file=sys.stderr)
    return out


def run_path(args, use_device: bool) -> dict:
    d, clock, total, preemptor_wave = build(
        args.cqs, args.wl, use_device=use_device,
        n_flavors=args.flavors, n_resources=args.resources)
    if d.scheduler.solver is not None:
        t_w = time.perf_counter()
        d.scheduler.solver.warmup(d.cache.snapshot(), args.cqs)
        print(f"solver warmup {time.perf_counter() - t_w:.1f}s",
              file=sys.stderr)

    inject_at = args.inject_at if args.inject_at >= 0 else args.cycles // 3
    budget_s = float(getattr(args, "budget_s", 0.0) or 0.0)
    completed = True
    # same GC discipline as run_burst_path: collection paused for the
    # measured phase on every arm equally (period-3 gen collections
    # otherwise inject 0.5-1.1s pauses that grow with the run)
    gc.disable()
    t_run0 = time.perf_counter()
    cycle_times = []
    admitted_total = preempted_total = skipped_total = 0
    running = []
    for cycle in range(args.cycles):
        if budget_s and time.perf_counter() - t_run0 > budget_s:
            completed = False
            print(f"budget {budget_s:.0f}s exhausted after "
                  f"{cycle}/{args.cycles} cycles", file=sys.stderr)
            break
        if cycle == inject_at:
            n = preemptor_wave(clock.t)
            total += n
            print(f"cycle {cycle}: injected {n} high-priority preemptors",
                  file=sys.stderr)
        clock.t += 1.0
        c0 = time.perf_counter()
        stats = d.schedule_once()
        dt = time.perf_counter() - c0
        cycle_times.append(dt)
        admitted_total += len(stats.admitted)
        preempted_total += len(stats.preempted_targets)
        skipped_total += len(stats.skipped)
        for key in stats.admitted:
            running.append((cycle + args.runtime, key))
        still = []
        for fin, key in running:
            wl = d.workloads.get(key)
            if wl is None or not wl.has_quota_reservation:
                continue
            if fin <= cycle:
                d.finish_workload(key)
            else:
                still.append((fin, key))
        running = still
        print(f"cycle {cycle}: {dt*1e3:.1f}ms admitted={len(stats.admitted)} "
              f"preempting={len(stats.preempting)} "
              f"preempted={len(stats.preempted_targets)} "
              f"skipped={len(stats.skipped)} "
              f"inadmissible={len(stats.inadmissible)}", file=sys.stderr)

    gc.enable()
    cycle_times.sort()
    p50 = cycle_times[len(cycle_times) // 2]
    p99 = cycle_times[min(len(cycle_times) - 1,
                          int(len(cycle_times) * 0.99))]
    solver = d.scheduler.solver
    out = {
        "path": "device" if use_device else "host",
        "p50_ms": round(p50 * 1e3, 1),
        "p99_ms": round(p99 * 1e3, 1),
        "admitted": admitted_total,
        "preempted": preempted_total,
        "skipped": skipped_total,
        "workloads": total,
        "cycles_run": len(cycle_times),
        "completed": completed,
        "obs": d.obs.report(),
    }
    if budget_s:
        out["budget_s"] = budget_s
        out["elapsed_s"] = round(time.perf_counter() - t_run0, 1)
    if solver is not None:
        out["solver_stats"] = dict(solver.stats)
        if solver.rtt_s is not None:
            out["accel_rtt_ms"] = round(solver.rtt_s * 1e3, 1)
        print(f"stats: {solver.stats}", file=sys.stderr)
    return out


def mesh_info(shards: int) -> dict:
    """Self-describing mesh/shard block for every artifact (VERDICT r5:
    dryrun-ambiguous MULTICHIP files)."""
    import jax
    devs = jax.devices()
    info = {
        "n_devices": len(devs),
        "platform": devs[0].platform if devs else "none",
        "shards": max(1, shards),
    }
    if shards > 1:
        try:
            from kueue_tpu.parallel.sharded import (make_burst_mesh,
                                                    make_mesh)
            m = make_mesh(shards)
            if m is not None:
                info["cycle_mesh_axes"] = {
                    k: int(v) for k, v in m.shape.items()}
            bm = make_burst_mesh(shards)
            if bm is not None:
                info["burst_mesh_axes"] = {
                    k: int(v) for k, v in bm.shape.items()}
        except Exception:
            pass
    return info


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cqs", type=int, default=1000)
    ap.add_argument("--wl", type=int, default=100_000)
    ap.add_argument("--cycles", type=int, default=30)
    ap.add_argument("--host", action="store_true",
                    help="run ONLY the host path")
    ap.add_argument("--device", action="store_true",
                    help="run ONLY the device path")
    ap.add_argument("--runtime", type=int, default=4)
    ap.add_argument("--flavors", type=int, default=1)
    ap.add_argument("--resources", type=int, default=1)
    ap.add_argument("--inject-at", type=int, default=-1,
                    help="cycle at which the preemptor wave arrives "
                         "(default cycles//3)")
    ap.add_argument("--burst", action="store_true",
                    help="run the fused multi-cycle burst path in place "
                         "of the per-cycle device path")
    ap.add_argument("--burst-backend", default="both",
                    choices=["both", "cpu", "accel"])
    ap.add_argument("--trials", type=int, default=3,
                    help="trials per path; the median (by p99) is "
                         "reported with min/max spread")
    ap.add_argument("--fair-sharing", action="store_true",
                    help="run the fair-sharing tournament variant "
                         "(uneven weights, borrowing contention) in "
                         "place of the preemption scenario")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="disable the burst boundary pipeline (serial "
                         "pack+dispatch+apply) for A/B comparison")
    ap.add_argument("--ab-pipeline", action="store_true",
                    help="run pipelined and serial burst trials "
                         "INTERLEAVED in one process (drift-fair A/B) "
                         "and report both paths plus a boundary-cost "
                         "comparison")
    ap.add_argument("--no-delta-pack", action="store_true",
                    help="disable the incremental delta pack "
                         "(KUEUE_BURST_DELTA_PACK=0): every window "
                         "boundary re-walks all queues")
    ap.add_argument("--ab-pack", action="store_true",
                    help="run delta-pack and full-repack burst trials "
                         "INTERLEAVED in one process (drift-fair A/B) "
                         "and report both paths plus a pack-cost "
                         "comparison; forces --no-pipeline on both arms "
                         "so every window boundary pays a host pack")
    ap.add_argument("--trickle", type=int, default=0,
                    help="after the main cycles, run N sparse-boundary "
                         "rounds (arrivals to ~10 CQs, one short window "
                         "each) — the steady-state shape the delta pack "
                         "optimizes; --ab-pack defaults this to 6")
    ap.add_argument("--shards", type=int, default=0,
                    help="shard the burst window + FS/admit scans "
                         "across N devices (same as KUEUE_TPU_SHARDS=N; "
                         "on a CPU host this also forces "
                         "--xla_force_host_platform_device_count=N)")
    ap.add_argument("--ab-hetero", action="store_true",
                    help="heterogeneous A/B: the in-kernel fungibility "
                         "per-cycle arm, the fused burst arm (plus an "
                         "--ab-shards arm when set) INTERLEAVED with "
                         "the host-walk oracle; emits a 'hetero' block "
                         "with fallback counters and cross-arm "
                         "decision identity")
    ap.add_argument("--ab-shards", type=int, default=0,
                    help="run serial and N-shard burst trials "
                         "INTERLEAVED in one process (drift-fair A/B) "
                         "and report both arms plus a shard_compare "
                         "block with cross-arm decision identity")
    ap.add_argument("--crossover", default=None,
                    help="comma list of shard counts (e.g. 1,2,4,8; "
                         "1 = the single-device serial control) run "
                         "INTERLEAVED per trial block (drift-fair) "
                         "with a per-arm crossover curve in the JSON "
                         "tail")
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="per-trial wall budget in seconds; a run that "
                         "exhausts it stops at the next window "
                         "boundary and is recorded completed=false")
    ap.add_argument("--require-accel", action="store_true",
                    help="abort (exit 1) if no accelerator platform is "
                         "reachable instead of producing CPU-only "
                         "numbers; also makes the accel smoke test "
                         "FAIL rather than skip")
    ap.add_argument("--quick", action="store_true",
                    help="seconds-level smoke sizing (CI wiring check, "
                         "not a perf number): caps cqs/wl/cycles and "
                         "runs one trial per arm")
    ap.add_argument("--out", default=None,
                    help="also write the JSON tail to this file")
    args = ap.parse_args()
    if args.quick:
        args.cqs = min(args.cqs, 12)
        args.wl = min(args.wl, 240)
        args.cycles = min(args.cycles, 12)
        args.trials = 1

    if args.require_accel:
        from kueue_tpu.perf.harness import require_accel_or_die
        require_accel_or_die()

    # default: BOTH paths in one invocation, side by side — the honest
    # artifact the round-2 verdict asked for
    results = []
    shard_compare = None
    crossover = None
    hetero = None
    if args.burst and args.ab_hetero:
        # drift-fair heterogeneous A/B: the in-kernel fungibility arms
        # (per-cycle device solver — the headline p99 treatment, since
        # its cycle boundaries attribute cost exactly like the host
        # control's — plus the fused serial burst and an optional
        # --ab-shards arm) interleaved with the host-walk oracle in one
        # process; decisions must be bit-identical across every
        # completed arm
        from kueue_tpu.perf.harness import ab_block
        backend = ("cpu" if args.burst_backend == "both"
                   else args.burst_backend)
        shard_n = args.ab_shards if args.ab_shards > 1 else 0
        runs = {"in_kernel": [], "burst": [], "host": []}
        if shard_n:
            runs["sharded"] = []
        for _ in range(max(1, args.trials)):
            args.shards = 0
            runs["in_kernel"].append(run_path(args, use_device=True))
            gc.unfreeze()
            gc.collect()
            runs["burst"].append(run_burst_path(args, backend=backend))
            gc.unfreeze()
            gc.collect()
            if shard_n:
                args.shards = shard_n
                runs["sharded"].append(run_burst_path(args,
                                                      backend=backend))
                args.shards = 0
                gc.unfreeze()
                gc.collect()
            runs["host"].append(run_path(args, use_device=False))
            gc.unfreeze()
            gc.collect()
        sums = {k: summarize_trials(v) for k, v in runs.items()}
        results.append(sums["in_kernel"])
        results.append(sums["burst"])
        if shard_n:
            results.append(sums["sharded"])
        results.append(sums["host"])
        ik, bu, ho = sums["in_kernel"], sums["burst"], sums["host"]
        # fallback counters are merged across every device-resident
        # arm — the zero-host-fallback claim covers all of them
        device_arms = [ik, bu] + ([sums["sharded"]] if shard_n else [])
        sstats = [a.get("solver_stats", {}) for a in device_arms]
        bs_ = bu.get("burst_stats", {})
        reasons = {}
        for ss in sstats:
            for k, v in ss.get("scalar_reasons", {}).items():
                reasons[k] = reasons.get(k, 0) + v
        done = [r for arm in runs.values() for r in arm
                if r.get("completed", True)]
        identical = bool(done) and all(
            (r["admitted"], r["preempted"], r["skipped"]) ==
            (done[0]["admitted"], done[0]["preempted"],
             done[0]["skipped"]) for r in done)
        fallbacks = {
            "host_cycles": sum(s.get("host_cycles", 0) for s in sstats),
            "scalar_heads": sum(s.get("scalar_heads", 0)
                                for s in sstats),
            "scalar_reasons": reasons,
            "native_ff_fallbacks": sum(s.get("native_ff_fallbacks", 0)
                                       for s in sstats),
            "burst_dirty_cycles": bs_.get("burst_dirty_cycles", 0),
            "burst_dirty_preempt": bs_.get("burst_dirty_preempt", 0),
            "burst_dirty_scalar": bs_.get("burst_dirty_scalar", 0),
            "burst_dirty_resume": bs_.get("burst_dirty_resume", 0),
        }
        ss = ik.get("solver_stats", {})
        hetero = {
            "flavors": args.flavors,
            "resources": args.resources,
            "fungibility_mix": "whenCanBorrow x whenCanPreempt matrix "
                               "cycled across CQs (4 combos)",
            "fallbacks": fallbacks,
            "zero_host_fallbacks": (fallbacks["host_cycles"] == 0
                                    and fallbacks["scalar_heads"] == 0),
            "resume_heads": sum(s.get("resume_heads", 0)
                                for s in sstats),
            "walk_stop_heads": sum(s.get("walk_stop_heads", 0)
                                   for s in sstats),
            "p50_ms_in_kernel": ik["p50_ms"],
            "p50_ms_host": ho["p50_ms"],
            "p99_ms_in_kernel": ik["p99_ms"],
            "p99_ms_host": ho["p99_ms"],
            "in_kernel_beats_host_p99": ik["p99_ms"] < ho["p99_ms"],
            "decisions_identical_across_arms": identical,
            "burst_arm": {
                "p50_ms": bu["p50_ms"], "p99_ms": bu["p99_ms"],
                "completed": bu.get("completed", True),
                "burst_dirty_cycles": bs_.get("burst_dirty_cycles", 0),
                "burst_suppressed_cycles": bs_.get(
                    "burst_suppressed_cycles", 0)},
            "drift": ab_block(
                treatment={"arm": ik["path"], "p99_ms": ik["p99_ms"],
                           "solver_stats": {
                               k: v for k, v in ss.items()
                               if not isinstance(v, dict)},
                           "burst_stats": {
                               k: bs_.get(k, 0)
                               for k in ("burst_dirty_cycles",
                                         "burst_dirty_preempt",
                                         "burst_dirty_scalar",
                                         "burst_dirty_resume",
                                         "burst_suppressed_cycles")}},
                control={"arm": "host", "interleaved": True,
                         "p99_ms": ho["p99_ms"],
                         "cycles_run": ho.get("cycles_run", 0)},
                treatment_label="in_kernel",
                control_label="host_fallback"),
        }
        if shard_n:
            sh = sums["sharded"]
            hetero["shard_arm"] = {
                "shards": shard_n, "p99_ms": sh["p99_ms"],
                "completed": sh.get("completed", True)}
    elif args.burst and args.crossover:
        # the shard crossover curve: every arm (single-device serial
        # control included) runs back to back inside each trial block,
        # so machine drift lands on all arms equally; each arm's p99
        # is the median trial, and cross-arm decision identity is
        # required over every run that completed the full cycle count
        from kueue_tpu.perf.harness import shard_imbalance_report
        backend = ("cpu" if args.burst_backend == "both"
                   else args.burst_backend)
        arms = sorted({max(1, int(x))
                       for x in args.crossover.split(",") if x.strip()})
        runs = {n: [] for n in arms}
        for _ in range(max(1, args.trials)):
            for n_sh in arms:
                args.shards = 0 if n_sh == 1 else n_sh
                runs[n_sh].append(run_burst_path(args, backend=backend))
                gc.unfreeze()
                gc.collect()
        args.shards = 0
        sums = {n: summarize_trials(runs[n]) for n in arms}
        results.extend(sums[n] for n in arms)
        curve = []
        for n in arms:
            s = sums[n]
            entry = {
                "shards": n,
                "p50_ms": s["p50_ms"],
                "p99_ms": s["p99_ms"],
                "p99_ms_range": s["p99_ms_range"],
                "decisions_stable": s["decisions_stable"],
                "completed": s.get("completed", True),
                "cycles_run": s.get("cycles_run", 0),
            }
            if "elapsed_s" in s:
                entry["elapsed_s"] = s["elapsed_s"]
            if "trickle" in s:
                entry["trickle_rounds"] = s["trickle"]["rounds"]
                entry["trickle_rounds_requested"] = \
                    s["trickle"]["rounds_requested"]
            bsh = s.get("burst_stats", {})
            if n > 1:
                entry["imbalance"] = shard_imbalance_report(bsh)
                entry["boundary_bytes_h2d"] = bsh.get(
                    "burst_boundary_bytes_h2d", 0)
                entry["boundary_bytes_equiv"] = bsh.get(
                    "burst_boundary_bytes_equiv", 0)
            curve.append(entry)
        # budget-cut runs stop at different cycles and are excluded
        # from the identity check, not from the curve
        done = [r for n in arms for r in runs[n]
                if r.get("completed", True)]
        identical = bool(done) and all(
            (r["admitted"], r["preempted"], r["skipped"]) ==
            (done[0]["admitted"], done[0]["preempted"],
             done[0]["skipped"]) for r in done)
        crossover = {
            "arms": arms,
            "trials_per_arm": len(runs[arms[0]]),
            "curve": curve,
            "decisions_identical_across_arms": identical,
        }
        if args.budget_s:
            crossover["budget_s"] = args.budget_s
        sharded_sums = [sums[n] for n in arms if n > 1]
        if sharded_sums:
            crossover["sharded_completed_within_budget"] = all(
                s.get("completed", True) for s in sharded_sums)
        ctrl = sums.get(1)
        if ctrl is not None:
            crossover["control_p99_ms"] = ctrl["p99_ms"]
            crossover["control_completed"] = ctrl.get("completed", True)
            done_sharded = [s for s in sharded_sums
                            if s.get("completed", True)]
            if done_sharded:
                best = min(done_sharded, key=lambda s: s["p99_ms"])
                crossover["best_sharded_shards"] = next(
                    n for n in arms if n > 1 and sums[n] is best)
                crossover["best_sharded_p99_ms"] = best["p99_ms"]
                crossover["sharded_beats_serial_p99"] = (
                    ctrl.get("completed", True)
                    and best["p99_ms"] < ctrl["p99_ms"])
    elif args.burst and args.ab_shards > 1:
        # drift-fair shard A/B: alternate N-shard/serial burst trials
        # in one process (same rationale as --ab-pipeline) and require
        # cross-arm decision identity — the tentpole's bit-identical
        # claim measured at artifact scale, not just in unit tests
        backend = ("cpu" if args.burst_backend == "both"
                   else args.burst_backend)
        runs = {0: [], args.ab_shards: []}
        for _ in range(max(1, args.trials)):
            for n_sh in (args.ab_shards, 0):
                args.shards = n_sh
                runs[n_sh].append(run_burst_path(args, backend=backend))
                gc.unfreeze()
                gc.collect()
        args.shards = 0
        sh_sum = summarize_trials(runs[args.ab_shards])
        se_sum = summarize_trials(runs[0])
        results.append(sh_sum)
        results.append(se_sum)
        ref = runs[0][0]
        stable = all(
            (r["admitted"], r["preempted"], r["skipped"]) ==
            (ref["admitted"], ref["preempted"], ref["skipped"])
            for arm in runs.values() for r in arm)
        bsh = sh_sum["burst_stats"]
        shard_compare = {
            "shards": args.ab_shards,
            "decisions_stable": stable,   # across BOTH arms, all trials
            "trials_per_arm": len(runs[0]),
            "sharded_dispatches": bsh.get("burst_sharded_dispatches", 0),
            # per-shard permute cost at pack time, and per-shard fetch
            # completion deltas (the dispatch-skew proxy); median trial
            "shard_pack_s": [round(t, 4) for t in
                             bsh.get("burst_shard_pack_s", [])],
            "shard_fetch_s": [round(t, 4) for t in
                              bsh.get("burst_shard_fetch_s", [])],
            "p50_ms_sharded": sh_sum["p50_ms"],
            "p50_ms_serial": se_sum["p50_ms"],
            "p99_ms_sharded": sh_sum["p99_ms"],
            "p99_ms_serial": se_sum["p99_ms"],
        }
    elif args.fair_sharing:
        results.append(with_trials(
            lambda: run_fs_path(args, use_device=True), args))
        if not args.device:
            results.append(with_trials(
                lambda: run_fs_path(args, use_device=False), args))
    elif args.burst and args.ab_pack:
        # drift-fair pack A/B: alternate delta-pack/full-repack trials
        # (same rationale as --ab-pipeline); the boundary pipeline is
        # disabled on both arms so every window pays a measurable host
        # pack instead of hiding it behind the previous apply loop
        backend = ("cpu" if args.burst_backend == "both"
                   else args.burst_backend)
        args.no_pipeline = True
        if args.trickle == 0:
            args.trickle = 6
        runs = {False: [], True: []}
        piped = []
        for _ in range(max(1, args.trials)):
            for no_delta in (False, True):
                args.no_delta_pack = no_delta
                runs[no_delta].append(run_burst_path(args, backend=backend))
                gc.unfreeze()
                gc.collect()
            # the shipping configuration (boundary pipeline + delta
            # pack) rides along for the headline p99 — the serial arms
            # exist to expose the pack cost, not to represent it
            args.no_delta_pack = False
            args.no_pipeline = False
            piped.append(run_burst_path(args, backend=backend))
            args.no_pipeline = True
            gc.unfreeze()
            gc.collect()
        args.no_delta_pack = False
        args.no_pipeline = False
        results.append(summarize_trials(piped))
        results.append(summarize_trials(runs[False]))
        results.append(summarize_trials(runs[True]))
    elif args.burst and args.ab_pipeline:
        # drift-fair A/B: alternate pipelined/serial trials so slow
        # machine windows hit both modes equally (a sequential pair of
        # 3-trial runs on this box once showed a 2.3x whole-process
        # skew that had nothing to do with the code under test)
        backend = ("cpu" if args.burst_backend == "both"
                   else args.burst_backend)
        runs = {False: [], True: []}
        for _ in range(max(1, args.trials)):
            for no_pipe in (False, True):
                args.no_pipeline = no_pipe
                runs[no_pipe].append(run_burst_path(args, backend=backend))
                gc.unfreeze()
                gc.collect()
        args.no_pipeline = False
        results.append(summarize_trials(runs[False]))
        results.append(summarize_trials(runs[True]))
    elif args.burst:
        backends = (["cpu", "accel"] if args.burst_backend == "both"
                    else [args.burst_backend])
        for b in backends:
            results.append(with_trials(
                lambda b=b: run_burst_path(args, backend=b), args))
    if not args.host and not args.burst and not args.fair_sharing:
        results.append(with_trials(
            lambda: run_path(args, use_device=True), args))
    if not args.device and not args.fair_sharing and not args.ab_hetero:
        results.append(with_trials(
            lambda: run_path(args, use_device=False), args))
    mesh_shards = max(args.shards, args.ab_shards,
                      (crossover or {}).get("arms", [0])[-1])
    tail = {
        "metric": "northstar_e2e_cycle_p99",
        "unit": "ms",
        "cqs": args.cqs,
        "flavors": args.flavors, "resources": args.resources,
        "mesh": mesh_info(mesh_shards),
    }
    if args.quick:
        tail["quick"] = True
    if shard_compare is not None:
        tail["shard_compare"] = shard_compare
    if hetero is not None:
        tail["hetero"] = hetero
    if crossover is not None:
        tail["crossover"] = crossover
        # the mesh block is the self-describing home for shard-health
        # counters; surface the widest sharded arm's imbalance there
        for e in reversed(crossover["curve"]):
            if e.get("imbalance"):
                tail["mesh"]["shard_imbalance"] = e["imbalance"]
                break
    for r in results:
        tail[r["path"]] = {k: v for k, v in r.items()
                           if k not in ("path", "obs")}
    piped_r = next((r for r in results
                    if r["path"].startswith("burst-")
                    and "-serial" not in r["path"]
                    and "-fullpack" not in r["path"]), None)
    serial_r = next((r for r in results
                     if r["path"].endswith("-serial")), None)
    if piped_r is not None and serial_r is not None:
        # the tentpole claim, stated from the counters: a serially
        # packed window pays pack + blocking fetch at its boundary; an
        # overlapped window pays only the residual speculative-fetch
        # wait not hidden behind the previous window's apply loop
        bs_on, bs_off = piped_r["burst_stats"], serial_r["burst_stats"]
        per_w = lambda bs: ((bs["burst_pack_s"] + bs["burst_dispatch_s"])
                            / max(1, bs["burst_serial_windows"]))
        overlapped = max(1, bs_on["burst_overlapped_packs"])
        tail["boundary_compare"] = {
            "serial_boundary_s_per_window": round(per_w(bs_off), 4),
            "pipelined_serial_boundary_s_per_window":
                round(per_w(bs_on), 4),
            "overlapped_windows": bs_on["burst_overlapped_packs"],
            "overlapped_boundary_s_per_window": round(
                bs_on["burst_spec_fetch_wait_s"] / overlapped, 4),
            "spec_cancelled": bs_on["burst_spec_cancelled"],
            "p50_ms_pipelined": piped_r["p50_ms"],
            "p50_ms_serial": serial_r["p50_ms"],
            "p99_ms_pipelined": piped_r["p99_ms"],
            "p99_ms_serial": serial_r["p99_ms"],
        }
    # the pack A/B pairs the two serial arms (drift-fair); the
    # pipelined arm, when present, is the shipping-config headline
    delta_r = (next((r for r in results
                     if r["path"].endswith("-serial")), None)
               or next((r for r in results
                        if r["path"].startswith("burst-")
                        and not r["path"].endswith("-fullpack")), None))
    fullpack_r = next((r for r in results
                       if r["path"].endswith("-fullpack")), None)
    if delta_r is not None and fullpack_r is not None:
        # the delta-pack claim, stated from the counters: a full-repack
        # boundary re-walks every queue (burst_pack_s / packs); a delta
        # boundary re-walks only journal-dirty CQs (delta_pack_s per
        # delta window) — decisions must be identical either way
        bs_on = delta_r["burst_stats"]
        bs_off = fullpack_r["burst_stats"]
        # prefer the sparse-boundary (trickle) windows when both arms
        # ran them: uniform-churn boundaries are full-repack territory
        # on BOTH arms (the delta path falls back above 50% dirty), so
        # the delta claim is about the sparse windows
        tr_on = delta_r.get("trickle")
        tr_off = fullpack_r.get("trickle")
        if (tr_on and tr_off and tr_on.get("burst_delta_packs")
                and tr_off.get("burst_packs")):
            full_per = (tr_off["burst_pack_s"]
                        / max(1, tr_off["burst_packs"]))
            delta_per = (tr_on["delta_pack_s"]
                         / max(1, tr_on["burst_delta_packs"]))
            scope = "trickle-windows"
        else:
            full_per = (bs_off["burst_pack_s"]
                        / max(1, bs_off["burst_packs"]))
            delta_per = (bs_on["delta_pack_s"]
                         / max(1, bs_on["burst_delta_packs"]))
            scope = "whole-run"
        tail["pack_compare"] = {
            "windows_scope": scope,
            "full_pack_s_per_window": round(full_per, 4),
            "delta_pack_s_per_window": round(delta_per, 4),
            "pack_cost_reduction_x": round(
                full_per / max(delta_per, 1e-9), 1),
            "delta_windows": bs_on["burst_delta_packs"],
            "full_fallbacks": bs_on["burst_full_packs"],
            "rows_reused": bs_on["rows_reused"],
            "rows_repacked": bs_on["rows_repacked"],
            "decisions_identical": (
                (delta_r["admitted"], delta_r["preempted"],
                 delta_r["skipped"]) ==
                (fullpack_r["admitted"], fullpack_r["preempted"],
                 fullpack_r["skipped"])),
            "p99_ms_delta": delta_r["p99_ms"],
            "p99_ms_fullpack": fullpack_r["p99_ms"],
        }
    host_r = next((r for r in results
                   if r["path"] in ("host", "fs-host")), None)
    solver_rs = [r for r in results
                 if r["path"] not in ("host", "fs-host")]
    if solver_rs:
        # a budget-cut run's partial-phase p99 is not comparable to a
        # full run's; only promote it to the headline when nothing
        # finished
        done_rs = [r for r in solver_rs if r.get("completed", True)]
        best = min(done_rs or solver_rs, key=lambda r: r["p99_ms"])
        tail["value"] = best["p99_ms"]
        tail["best_solver_path"] = best["path"]
        if host_r is not None:
            for r in solver_rs:
                tail[f"{r['path']}_beats_host_p50"] = (
                    r["p50_ms"] < host_r["p50_ms"])
                tail[f"{r['path']}_beats_host_p99"] = (
                    r["p99_ms"] < host_r["p99_ms"])
    else:
        tail["value"] = results[0]["p99_ms"]
    # the artifact must prove the hard paths ran at scale (the FS
    # variant's hard path is the tournament, counted separately)
    if args.fair_sharing:
        tail["hard_paths_exercised"] = all(
            r.get("fs_full_cycles", 1) > 0 or r["path"] == "fs-host"
            for r in results)
    else:
        # a budget-cut run may stop before the preemptor wave; only
        # completed runs owe the hard-path proof
        tail["hard_paths_exercised"] = all(
            r["preempted"] > 0 and r["skipped"] > 0 for r in results
            if r.get("completed", True))
    # r16+: the telemetry plane rides every soak — stamp the headline
    # arm's obs block (validate_artifacts requires it from r16 on)
    obs_by_path = {r["path"]: r["obs"] for r in results if r.get("obs")}
    if obs_by_path:
        tail["obs"] = obs_by_path.get(tail.get("best_solver_path"),
                                      next(iter(obs_by_path.values())))
    print(json.dumps(tail))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(tail, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
