"""Federation soak: N-cluster MultiKueue under fire at 1000 CQs.

Every scenario runs two arms of the seeded federation sim
(``kueue_tpu.federation``) from identical specs and identical traffic:

  control — fault-free;
  faulted — the same federation with a seeded ChaosInjector armed.

A scenario passes only if the faulted arm converges back to the
control arm after the fault clears (``decisions_stable``):

  strict parity   — the post-recovery global state (manager + every
                    worker, conditions and timestamps included) is
                    bit-identical to the control arm's
                    (partition/rejoin, duplicate storms, worker crash);
  outcome parity  — the same workloads finish with zero invariant
                    violations (permanent cluster loss: the ejection
                    timing is the fault, so timestamps shift by
                    design, but nothing may be lost or run twice).

Both arms also carry the sim's per-step invariant sampling: no key is
ever quota-reserved on two ACTIVE clusters (double admission) and no
key ever finishes on two workers (double execution).

Scenarios: a partition severing two clusters between nomination and
winner selection (rejoined through the half-open circuit + rejoin
reconciliation), an at-least-once watch storm (resume tokens held
back, mutations doubled), a worker killed between its WAL append and
the admit mutation (recovered from the journal the same virtual
second), and a cluster destroyed outright (assignments ejected and
re-dispatched).

Usage:
    python scripts/federation_soak.py [--cqs 1000] [--workers 4]
        [--seed N] [--quick] [--only a,b] [--out FED_r15.json]

The base seed comes from --seed or KUEUE_TPU_FED_SEED (default 1511);
scenario i uses seed+i, so any single scenario replays in isolation.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_tpu.chaos import injector as chaos
from kueue_tpu.chaos.injector import ChaosInjector
from kueue_tpu.features import env_value
from kueue_tpu.federation.sim import (
    FederationSim,
    FedSpec,
    global_digest,
    outcome,
    schedule_traffic,
)
from kueue_tpu.perf.harness import chaos_report
from kueue_tpu.traffic.arrivals import (
    ArrivalStream,
    PoissonProcess,
    TrafficSpec,
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_traffic(cfg, seed):
    """The shared mixed local/remote stream, quantized onto sim steps
    (both arms of every scenario ingest the identical schedule)."""
    spec = TrafficSpec(n_cqs=cfg["cqs"], remote_fraction=0.5,
                       cancel_fraction=0.0, churn_fraction=0.0,
                       runtime_choices_s=(2.0,))
    events = ArrivalStream(PoissonProcess(cfg["rate"], seed=seed),
                           spec, seed=seed).take(cfg["events"])
    return schedule_traffic(events, n_cqs=cfg["cqs"],
                            remote_cqs=cfg["remote_cqs"])


def run_arm(cfg, seed, wal_dir, arm=None, **spec_kw):
    """One sim arm.  Chaos is armed only after construction + traffic
    load, so site hit counts line up with ``step()``'s consult points
    regardless of build-time work."""
    chaos.clear()
    spec = FedSpec(n_workers=cfg["workers"], n_cqs=cfg["cqs"],
                   remote_cqs=cfg["remote_cqs"], seed=seed, **spec_kw)
    sim = FederationSim(spec, wal_dir=wal_dir)
    by_step, n_remote = make_traffic(cfg, seed)
    sim.load_traffic(by_step)
    inj = None
    if arm is not None:
        inj = chaos.install(ChaosInjector(seed=seed))
        arm(inj)
    settled = sim.run(cfg["steps"], drain_max=cfg["drain_max"])
    chaos.clear()
    return sim, settled, inj, n_remote


class Checker:
    def __init__(self):
        self.failures: list[str] = []

    def check(self, ok, msg):
        if not ok:
            self.failures.append(msg)
        return ok


def _parity(ck, control, faulted, mode):
    """The convergence verdict both parity levels share."""
    ck.check(faulted.violations == [],
             f"invariant violations: {faulted.violations[:2]}")
    ck.check(control.violations == [], "control arm violated invariants")
    if mode == "strict":
        ck.check(global_digest(faulted) == global_digest(control),
                 "post-recovery global state diverged from the "
                 "fault-free control")
    else:
        ck.check(outcome(faulted) == outcome(control),
                 "finish set diverged from the fault-free control")
        ck.check(all(outcome(faulted).values()),
                 "workloads left unfinished after failover")


def _result(ck, control, faulted, inj, mode, extra=None):
    out = {
        "decisions_stable": not ck.failures,
        "failures": ck.failures,
        "parity": mode,
        "double_admissions": sum(
            1 for v in faulted.violations
            if v.get("kind") == "double_admission"),
        "ingested": faulted.ingested,
        "finished": sum(1 for v in outcome(faulted).values() if v),
        "spread": faulted.assignment_spread(),
        "counters": dict(faulted.counters),
        "state_digest": {"control": global_digest(control),
                         "faulted": global_digest(faulted)},
        "chaos": chaos_report(injector=inj),
        "obs": faulted.manager.obs.report(),
    }
    out.update(extra or {})
    return out


def scenario_partition_during_nominate(cfg, seed, td):
    """Sever two clusters between nomination/admission and winner
    selection (the mid-step consult), heal after 3 steps: the rejoin
    reconciliation must delete exactly the stale mirrors the control
    deleted at winner time, bit-identically."""
    control, ok_c, _i, _r = run_arm(cfg, seed, os.path.join(td, "c"))
    victims = tuple(control.worker_names[-2:])
    at_step = max(2, cfg["steps"] // 3)
    faulted, ok_f, inj, _r = run_arm(
        cfg, seed, os.path.join(td, "f"),
        arm=lambda i: i.arm("fed.partition", at=2 * at_step,
                            action="partition",
                            payload=(victims, 3)))
    ck = Checker()
    ck.check(ok_c and ok_f, f"arm did not settle "
             f"(control={ok_c}, faulted={ok_f})")
    ck.check(faulted.counters["partitions"] >= 1, "partition never fired")
    ck.check(faulted.counters["heals"] >= 1, "partition never healed")
    ck.check(all(c.active for c in faulted.clusters.values()),
             "a cluster never rejoined")
    _parity(ck, control, faulted, "strict")
    return _result(ck, control, faulted, inj, "strict",
                   {"victims": list(victims), "partition_step": at_step})


def scenario_duplicate_watch_storm(cfg, seed, td):
    """At-least-once delivery storm: watch resume tokens held back so
    whole batches re-deliver, plus doubled mutations on the transport.
    Every replay must be absorbed — strict parity against a control
    running the same (quiet) transport wrapper."""
    control, ok_c, _i, _r = run_arm(cfg, seed, os.path.join(td, "c"),
                                    chaos_transport=True, drift_every=4)
    faulted, ok_f, inj, _r = run_arm(
        cfg, seed, os.path.join(td, "f"),
        chaos_transport=True, drift_every=4,
        arm=lambda i: (
            i.arm("remote.duplicate_event", prob=0.25,
                  times=cfg["storm_times"], action="duplicate"),
            i.arm("remote.duplicate", prob=0.05,
                  times=cfg["storm_times"], action="duplicate")))
    ck = Checker()
    ck.check(ok_c and ok_f, f"arm did not settle "
             f"(control={ok_c}, faulted={ok_f})")
    _parity(ck, control, faulted, "strict")
    return _result(ck, control, faulted, inj, "strict",
                   {"storm_times": cfg["storm_times"]})


def scenario_worker_crash_mid_sync(cfg, seed, td):
    """Kill a worker between its WAL append and the admit mutation,
    rebuild it from store + journal tail the same virtual second, and
    re-run the interrupted cycle: the watch epoch change forces a
    resync and the recovered federation must match control exactly."""
    control, ok_c, _i, _r = run_arm(cfg, seed, os.path.join(td, "c"))
    at_step = max(2, cfg["steps"] // 3)
    faulted, ok_f, inj, _r = run_arm(
        cfg, seed, os.path.join(td, "f"),
        arm=lambda i: i.arm("fed.worker_crash", at=at_step,
                            payload=control.worker_names[0]))
    ck = Checker()
    ck.check(ok_c and ok_f, f"arm did not settle "
             f"(control={ok_c}, faulted={ok_f})")
    ck.check(faulted.counters["worker_crashes"] == 1,
             "worker crash never fired")
    ck.check(faulted.counters["mid_admit_crashes"] >= 1,
             "the crash missed the journaled-but-unapplied window")
    ck.check(faulted.counters["wal_tail_replayed"] >= 1,
             "recovery never replayed the WAL tail")
    _parity(ck, control, faulted, "strict")
    return _result(ck, control, faulted, inj, "strict",
                   {"crash_step": at_step})


def scenario_cluster_loss_permanent(cfg, seed, td):
    """Destroy a cluster outright: everything it held must be ejected
    (pending deletes queued, checks back to Retry) and re-dispatched to
    the survivors exactly once.  Outcome parity: the ejection timing is
    the fault, so timestamps shift, but the same workloads finish and
    nothing runs twice."""
    control, ok_c, _i, _r = run_arm(cfg, seed, os.path.join(td, "c"),
                                    worker_lost_timeout=2.0)
    at_step = max(2, cfg["steps"] // 3)
    faulted, ok_f, inj, _r = run_arm(
        cfg, seed, os.path.join(td, "f"), worker_lost_timeout=2.0,
        arm=lambda i: i.arm("fed.cluster_loss", at=at_step,
                            payload=control.worker_names[0]))
    ck = Checker()
    ck.check(ok_c and ok_f, f"arm did not settle "
             f"(control={ok_c}, faulted={ok_f})")
    ck.check(faulted.counters["losses"] == 1, "cluster loss never fired")
    ck.check(faulted.counters["ejections"] >= 1,
             "nothing was ejected off the dead cluster")
    lost = control.worker_names[0]
    ck.check(not faulted.clusters[lost].active,
             "the destroyed cluster came back")
    ck.check(all(len(ws) == 1 for ws in faulted._finished_on.values()),
             "a workload executed on two workers")
    _parity(ck, control, faulted, "outcome")
    return _result(ck, control, faulted, inj, "outcome",
                   {"lost_cluster": lost, "loss_step": at_step})


SCENARIOS = [
    ("partition_during_nominate", scenario_partition_during_nominate),
    ("duplicate_watch_storm", scenario_duplicate_watch_storm),
    ("worker_crash_mid_sync", scenario_worker_crash_mid_sync),
    ("cluster_loss_permanent", scenario_cluster_loss_permanent),
]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cqs", type=int, default=1000)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--seed", type=int,
                    default=int(env_value("KUEUE_TPU_FED_SEED", "1511")))
    ap.add_argument("--quick", action="store_true",
                    help="tiny federation for a fast functional pass")
    ap.add_argument("--only", default=None,
                    help="comma-separated scenario names")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "FED_r15.json"))
    args = ap.parse_args()
    if args.workers < 2:
        ap.error("--workers must be >= 2 (failover needs a survivor)")

    cqs = 16 if args.quick else args.cqs
    cfg = {
        "cqs": cqs,
        "remote_cqs": max(2, cqs // 4),
        "workers": args.workers,
        "events": 5 * cqs,
        "rate": max(4.0, cqs / 2.0),   # ~10 virtual seconds of arrivals
        "steps": 12,
        "drain_max": 400,
        "storm_times": 30 * args.workers if cqs <= 16 else 400,
    }
    only = set(args.only.split(",")) if args.only else None

    gc.collect()
    scenarios: dict[str, dict] = {}
    t_start = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="federation_soak_") as td:
        for i, (name, fn) in enumerate(SCENARIOS):
            if only and name not in only:
                continue
            chaos.clear()
            log(f"[{i + 1}/{len(SCENARIOS)}] {name} "
                f"(cqs={cqs}, workers={cfg['workers']}, "
                f"seed={args.seed + i}) ...")
            t0 = time.perf_counter()
            try:
                res = fn(cfg, args.seed + i, os.path.join(td, name))
            except Exception as e:   # a scenario bug is a failed scenario
                res = {"decisions_stable": False, "parity": "strict",
                       "double_admissions": 0,
                       "failures": [f"{type(e).__name__}: {e}"]}
            finally:
                chaos.clear()
            res["wall_s"] = round(time.perf_counter() - t0, 2)
            res["seed"] = args.seed + i
            scenarios[name] = res
            ok = res["decisions_stable"]
            log(f"    {'converged' if ok else 'DIVERGED'} "
                f"({res['wall_s']}s)"
                + ("" if ok else f" — {res['failures'][:3]}"))
            gc.collect()

    stable = sum(1 for v in scenarios.values() if v["decisions_stable"])
    tail = {
        "metric": "federation_soak_recovery_parity",
        "unit": "fault arms converged to the fault-free control",
        "cqs": cqs,
        "remote_cqs": cfg["remote_cqs"],
        "workers": cfg["workers"],
        "events": cfg["events"],
        "seed": args.seed,
        "scenarios": scenarios,
        "scenarios_total": len(scenarios),
        "scenarios_stable": stable,
        "all_stable": stable == len(scenarios) and len(scenarios) > 0,
        "double_admissions_total": sum(
            v.get("double_admissions", 0) for v in scenarios.values()),
        "value": stable,
        "elapsed_s": round(time.perf_counter() - t_start, 2),
        # r16+: the telemetry plane rides every soak — the first
        # scenario's manager-side obs block stands for the run
        "obs": next((v["obs"] for v in scenarios.values()
                     if "obs" in v), None),
        "hard_paths_exercised": [
            "fed.partition between nomination and winner selection",
            "half-open try_reconnect + reconcile_rejoined stale-mirror GC",
            "remote.duplicate_event resume-token holdback",
            "remote.duplicate doubled mutations",
            "fed.worker_crash wal.admit tail replay + watch epoch resync",
            "fed.cluster_loss ejection + exactly-once re-dispatch",
        ],
    }
    print(json.dumps({k: tail[k] for k in
                      ("metric", "cqs", "workers", "scenarios_total",
                       "scenarios_stable", "all_stable")}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out}")
    return 0 if tail["all_stable"] else 1


if __name__ == "__main__":
    sys.exit(main())
