#!/usr/bin/env python
"""Serving soak: the long-lived admission service under fire.

Four arms, one artifact (SERVE):

- **wall** — a real wall-clock service loop (no virtual time): concurrent
  submitter threads replay a pre-generated diurnal arrival schedule
  against ``AdmissionService.serve`` while the adaptive burst window K
  tracks the load swing online; evidence is per-window p99 admission
  latency against the SLO plus the K values actually chosen.
- **kill_restart** — deterministic virtual-time arms: SIGKILL-equivalent
  chaos crashes (``svc.cycle`` at a step boundary, ``svc.ingest`` inside
  the submit path) mid-load, then recovery from the durable store + the
  CycleWAL tail + the ingest journal.  The recovered run must match an
  unkilled control bit-for-bit in per-cycle decisions and final state
  digest, lose zero accepted submissions, and duplicate zero admissions
  (idempotent tokens are exercised by resubmitting the interrupted
  batch).
- **drain** — SIGTERM to a serving process: graceful drain must stop
  accepting (reject with ``draining``), finish in-flight cycles, flush
  the WAL, and exit clean.
- **parity** — the same submit-only traffic through the service loop
  (K pinned to 1) and through ``traffic.runner.run_open_loop`` on a
  fresh batch driver: per-cycle decisions must be bit-identical.

Artifact: SERVE_r17.json (see README "Serving").
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
)
from kueue_tpu.chaos import injector as chaos
from kueue_tpu.chaos.injector import ChaosInjector, InjectedCrash
from kueue_tpu.controller.driver import Driver
from kueue_tpu.features import env_int
from kueue_tpu.serving import AdmissionService, ServiceConfig, recover_service
from kueue_tpu.traffic import (
    ArrivalStream,
    DiurnalProcess,
    OpenLoopConfig,
    PoissonProcess,
    ReplayStream,
    TrafficSpec,
    run_open_loop,
)
from kueue_tpu.utils.journal import CycleWAL


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pctile(xs, q):
    if not xs:
        return 0.0
    s = sorted(xs)
    import math
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


# ---------------------------------------------------------------------------
# Cluster builders (the chaos-soak shape: cohorts of 4, 4000m each,
# BEST_EFFORT_FIFO so parked re-wakes cannot change admission order)
# ---------------------------------------------------------------------------

def cluster_spec(n_cqs):
    def fn(d):
        d.apply_resource_flavor(ResourceFlavor(name="default"))
        for q in range(n_cqs):
            name = f"cq-{q}"
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{q // 4}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                           cluster_queue=name))
    return fn


def build_virtual(n_cqs):
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=True)
    cluster_spec(n_cqs)(d)
    return d, clock


def build_wall(n_cqs):
    d = Driver(clock=time.time, use_device_solver=True)
    cluster_spec(n_cqs)(d)
    return d


def full_state(d):
    out = {}
    for key, w in d.workloads.items():
        out[key] = (
            w.is_finished, w.is_active, w.has_quota_reservation,
            None if w.admission is None else (
                w.admission.cluster_queue,
                tuple((a.name, tuple(sorted(a.flavors.items())),
                       tuple(sorted(a.resource_usage.items())), a.count)
                      for a in w.admission.pod_set_assignments)),
            tuple(sorted((c.type, c.status.value, c.reason, c.message,
                          c.last_transition_time)
                         for c in w.conditions.values())),
            tuple(sorted((s.name, s.state.value)
                         for s in w.admission_check_states.values())),
            None if w.requeue_state is None else
            (w.requeue_state.count, w.requeue_state.requeue_at),
        )
    return out


def state_digest(d) -> str:
    blob = repr(sorted(full_state(d).items())).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def mesh_info() -> dict:
    import jax
    devs = jax.devices()
    return {"n_devices": len(devs),
            "platform": devs[0].platform if devs else "none"}


# ---------------------------------------------------------------------------
# Arm: wall-clock soak with diurnal swing + online K adaptation
# ---------------------------------------------------------------------------

def gen_wall_schedule(cfg, seed):
    """Pre-generate the diurnal submission schedule so the submitter
    threads replay it at wall pace: (t_rel, name, lq, prio, runtime)."""
    proc = DiurnalProcess(cfg["wall_trough_rate"], cfg["wall_peak_rate"],
                          period_s=cfg["wall_duration_s"], seed=seed)
    marks = random.Random(seed + 1)
    events, t, i = [], 0.0, 0
    while True:
        t += proc.next_gap(t)
        if t >= cfg["wall_duration_s"]:
            return events
        i += 1
        events.append((t, f"s{i}", f"lq-{marks.randrange(cfg['cqs'])}",
                       marks.choice((0, 10, 20)), cfg["wall_runtime_s"]))


def arm_wall(cfg, seed, td):
    d = build_wall(cfg["cqs"])
    wal = CycleWAL(path=os.path.join(td, "wall.wal"))
    d.attach_wal(wal)
    svc = AdmissionService(d, config=ServiceConfig(
        dt_s=cfg["wall_dt_s"], high_water=cfg["high_water"],
        slo_p99_s=cfg["slo_p99_s"], drain_timeout_s=30.0,
        journal_path=os.path.join(td, "wall.ing"),
        k_max=cfg["k_max"], ewma_halflife_s=2.0), wal=wal)
    events = gen_wall_schedule(cfg, seed)
    stop = threading.Event()
    server = threading.Thread(target=svc.serve, args=(stop,), daemon=True)
    server.start()
    t_start = time.perf_counter()
    n_threads = cfg["wall_submitters"]

    def submitter(lane):
        for (t_rel, name, lq, prio, rt) in events[lane::n_threads]:
            lag = t_start + t_rel - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            svc.submit(name=name, queue_name=lq, requests={"cpu": 1500},
                       priority=prio, runtime_s=rt)

    subs = [threading.Thread(target=submitter, args=(i,), daemon=True)
            for i in range(n_threads)]
    for s in subs:
        s.start()
    for s in subs:
        s.join()
    # let the tail admit, then drain and stop
    time.sleep(4 * cfg["wall_dt_s"])
    stop.set()
    server.join(timeout=svc.cfg.drain_timeout_s + 10.0)
    duration = cfg["wall_duration_s"]
    n_windows = cfg["wall_windows"]
    w_len = duration / n_windows
    windows = []
    for w in range(n_windows):
        lo, hi = w * w_len, (w + 1) * w_len
        lats = [lat for (t, lat) in svc.latency_log if lo <= t < hi]
        ks = [s["k"] for s in svc.telemetry if lo <= s["t_wall"] < hi]
        rates = [s["ewma_rate"] for s in svc.telemetry
                 if lo <= s["t_wall"] < hi]
        windows.append({
            "t0_s": lo, "samples": len(lats),
            "p99_s": _pctile(lats, 0.99),
            "rate_per_s": (sum(rates) / len(rates)) if rates else 0.0,
            "k_max": max(ks) if ks else 0,
        })
    active = [w for w in windows if w["samples"] > 0]
    held = bool(active) and all(w["p99_s"] <= cfg["slo_p99_s"]
                                for w in active)
    k_values = sorted({s["k"] for s in svc.telemetry})
    stats = svc.stats()
    return {
        "wall_clock": True,
        "duration_s": duration,
        "submitted": stats["accepted"],
        "admitted": stats["admitted"],
        "admissions_per_s": stats["admitted"] / duration,
        "drained_clean": stats["drained_clean"],
        "slo": {
            "p99_target_s": cfg["slo_p99_s"],
            "held": held,
            "windows": windows,
            "k_values": k_values,
            "k_adapted": len(k_values) > 1,
        },
        "backpressure": {
            "high_water": cfg["high_water"],
            "rejected": stats["rejected"],
            "shed": stats["shed"],
        },
        "arrivals": {"process": "diurnal",
                     "trough_rate_per_s": cfg["wall_trough_rate"],
                     "peak_rate_per_s": cfg["wall_peak_rate"],
                     "events": len(events)},
    }


# ---------------------------------------------------------------------------
# Arm: kill mid-load + restart vs unkilled control (virtual time)
# ---------------------------------------------------------------------------

def gen_kill_schedule(cfg, seed):
    """Per-step submission batches, deterministic: heavier even steps
    keep a backlog alive across the kill point."""
    rng = random.Random(seed)
    out, n = [], 0
    for s in range(cfg["kill_steps"]):
        batch = []
        for _ in range(3 if s % 2 == 0 else 1):
            n += 1
            batch.append((f"w{n}", f"lq-{rng.randrange(cfg['cqs'])}",
                          rng.choice((0, 10, 20)),
                          float(rng.choice((2, 3)))))
        out.append(batch)
    return out


def run_killable(cfg, sched, kill_site, kill_at, td, tag):
    """One serving run over ``sched``; when ``kill_site`` is armed the
    run crashes, recovers from store + WAL + ingest journal, resubmits
    the interrupted batch (idempotent tokens), and continues."""
    d, clock = build_virtual(cfg["cqs"])
    wal = CycleWAL(path=os.path.join(td, f"{tag}.wal"))
    d.attach_wal(wal)
    jpath = os.path.join(td, f"{tag}.ing")
    svc_cfg = ServiceConfig(dt_s=1.0, k_max=1, journal_path=jpath,
                            high_water=1 << 30, epoch_t=clock.t)
    svc = AdmissionService(d, config=svc_cfg, wal=wal)
    if kill_site is not None:
        inj = chaos.install(ChaosInjector(seed=1000 + kill_at))
        inj.arm(kill_site, at=kill_at)
    decisions, crashed, s = [], None, 0
    while s < len(sched):
        try:
            for (name, lq, prio, rt) in sched[s]:
                svc.submit(name=name, queue_name=lq,
                           requests={"cpu": 1500}, priority=prio,
                           runtime_s=rt)
            out = svc.step()
            decisions.extend(out["decisions"])
            s += 1
        except InjectedCrash as e:
            crashed = str(e)
            chaos.clear()
            d2 = Driver(clock=clock, use_device_solver=True)
            cluster_spec(cfg["cqs"])(d2)
            # a fresh process: same durable store + WAL + ingest journal
            svc = recover_service(
                d2, d.workloads.values(), wal,
                config=ServiceConfig(dt_s=1.0, k_max=1,
                                     journal_path=jpath,
                                     high_water=1 << 30,
                                     epoch_t=svc_cfg.epoch_t))
            d = d2
    return d, svc, decisions, crashed


def arm_kill_restart(cfg, seed, td):
    sched = gen_kill_schedule(cfg, seed)
    d_c, svc_c, dec_c, _ = run_killable(cfg, sched, None, 0, td, "ctl")
    digest_c = state_digest(d_c)
    accepted_keys = [f"default/{name}" for batch in sched
                     for (name, _, _, _) in batch]
    scenarios = {}
    lost_total = dup_total = 0
    all_identical = all_digests = True
    arms = [("cycle_kill", "svc.cycle", cfg["kill_steps"] // 2 + 1),
            ("ingest_kill", "svc.ingest",
             max(2, len(accepted_keys) // 2))]
    for tag, site, at in arms:
        d_k, svc_k, dec_k, crashed = run_killable(
            cfg, sched, site, at, td, tag)
        digest_k = state_digest(d_k)
        flat = [k for cyc in dec_k for k in cyc]
        dup = sum(1 for k in set(flat) if flat.count(k) > 1)
        lost = sum(1 for k in accepted_keys
                   if k not in d_k.workloads)
        identical = dec_k == dec_c
        digests = digest_k == digest_c
        scenarios[tag] = {
            "site": site, "crashed": crashed,
            "cycles": len(dec_k),
            "decisions_identical": identical,
            "digest": digest_k,
            "digests_match": digests,
            "lost_accepted_submissions": lost,
            "duplicated_admissions": dup,
            "duplicate_tokens_resubmitted": svc_k.duplicate_total,
            "sheds": len(svc_k.journal.shed_seqs),
        }
        lost_total += lost
        dup_total += dup
        all_identical = all_identical and identical
        all_digests = all_digests and digests
        log(f"  kill[{tag}]: crashed={crashed} identical={identical} "
            f"digests={digests} lost={lost} dup={dup}")
    return {
        "control_digest": digest_c,
        "control_cycles": len(dec_c),
        "scenarios": scenarios,
        "lost_accepted_submissions": lost_total,
        "duplicated_admissions": dup_total,
        "decisions_identical": all_identical,
        "digests_match": all_digests,
    }


# ---------------------------------------------------------------------------
# Arm: SIGTERM graceful drain (wall clock, real signal)
# ---------------------------------------------------------------------------

def arm_drain(cfg, seed, td):
    d = build_wall(cfg["cqs"])
    wal = CycleWAL(path=os.path.join(td, "drain.wal"))
    d.attach_wal(wal)
    svc = AdmissionService(d, config=ServiceConfig(
        dt_s=cfg["wall_dt_s"], high_water=cfg["high_water"],
        drain_timeout_s=20.0,
        journal_path=os.path.join(td, "drain.ing"), k_max=cfg["k_max"]),
        wal=wal)
    svc.install_signal_handlers()
    server = threading.Thread(target=svc.serve, daemon=True)
    server.start()
    n_subs = cfg["drain_submissions"]

    def submitter(lane):
        for i in range(lane, n_subs, 2):
            svc.submit(name=f"d{i}", queue_name=f"lq-{i % cfg['cqs']}",
                       requests={"cpu": 1500}, priority=0,
                       runtime_s=cfg["wall_runtime_s"])
    subs = [threading.Thread(target=submitter, args=(i,), daemon=True)
            for i in range(2)]
    t0 = time.perf_counter()
    for s in subs:
        s.start()
    for s in subs:
        s.join()
    os.kill(os.getpid(), signal.SIGTERM)   # graceful drain, not death
    server.join(timeout=30.0)
    drain_wall = time.perf_counter() - t0
    post = svc.submit(name="late", queue_name="lq-0",
                      requests={"cpu": 1500})
    stats = svc.stats()
    applied = sum(1 for i in range(n_subs)
                  if f"default/d{i}" in d.workloads)
    wal_flushed = (wal.stats.get("wal_flushes", 0) > 0
                   and len(wal.tail) == 0)
    clean = (not server.is_alive() and svc.stopped
             and svc.drained_clean and stats["ingest_depth"] == 0)
    return {
        "clean": clean,
        "wal_flushed": wal_flushed,
        "accepted": stats["accepted"],
        "applied_in_store": applied,
        "zero_lost": applied == stats["accepted"] - stats["shed"],
        "rejected_after_drain": post.status == "draining",
        "drain_wall_s": drain_wall,
        "journal": stats["journal"],
    }


# ---------------------------------------------------------------------------
# Arm: decision parity service loop vs batch open-loop runner
# ---------------------------------------------------------------------------

def arm_parity(cfg, seed):
    n, dt, cycles = cfg["cqs"], 1.0, cfg["parity_cycles"]
    spec = TrafficSpec(n_cqs=n, cancel_fraction=0.0, churn_fraction=0.0,
                       runtime_choices_s=(2.0, 4.0))
    stream = ArrivalStream(PoissonProcess(cfg["parity_rate"], seed=seed),
                           spec, seed=seed)
    events = []
    for ev in stream:
        if ev.t > cycles * dt:
            break
        events.append(ev)
    # batch arm: the open-loop runner
    d1, c1 = build_virtual(n)
    res = run_open_loop(d1, c1, ReplayStream(events),
                        OpenLoopConfig(duration_s=cycles * dt, dt_s=dt))
    # service arm: same events through submit/step, K pinned to 1
    d2, c2 = build_virtual(n)
    svc = AdmissionService(d2, config=ServiceConfig(
        dt_s=dt, k_max=1, journal_path="", high_water=1 << 30,
        epoch_t=c2.t))
    decisions, i = [], 0
    for k in range(cycles):
        t_k = (k + 1) * dt
        while i < len(events) and events[i].t <= t_k:
            ev = events[i]
            i += 1
            ns, name = ev.key.split("/", 1)
            svc.submit(name=name, namespace=ns,
                       queue_name=f"lq-{ev.cq}",
                       requests={"cpu": ev.cpu_m}, priority=ev.priority,
                       creation_time=svc.epoch + ev.t,
                       runtime_s=ev.runtime_s)
        out = svc.step()
        decisions.extend(out["decisions"])
    identical = decisions == res.decisions
    digests = state_digest(d1) == state_digest(d2)
    return {
        "cycles": cycles,
        "events": len(events),
        "service_admitted": sum(len(c) for c in decisions),
        "batch_admitted": sum(len(c) for c in res.decisions),
        "decisions_identical": identical,
        "state_digests_match": digests,
    }


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cqs", type=int, default=16)
    ap.add_argument("--seed", type=int,
                    default=env_int("KUEUE_TPU_SVC_SEED"))
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized: 8 CQs, ~6s wall arm")
    ap.add_argument("--out", default="SERVE_r17.json")
    args = ap.parse_args()

    cfg = {
        "cqs": 8 if args.quick else args.cqs,
        "wall_dt_s": 0.25,
        "wall_duration_s": 6.0 if args.quick else 24.0,
        "wall_trough_rate": 4.0 if args.quick else 8.0,
        "wall_peak_rate": 48.0 if args.quick else 96.0,
        "wall_runtime_s": 0.3,
        "wall_submitters": 4,
        "wall_windows": 6 if args.quick else 8,
        "slo_p99_s": 2.0,
        "high_water": env_int("KUEUE_TPU_SVC_HIGH_WATER"),
        "k_max": 8,
        "kill_steps": 14 if args.quick else 28,
        "drain_submissions": 40 if args.quick else 160,
        "parity_cycles": 20 if args.quick else 48,
        "parity_rate": 4.0,
    }
    if args.quick:
        cfg["cqs"] = 8
    seed = args.seed
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        log(f"serve soak: cqs={cfg['cqs']} seed={seed} "
            f"quick={args.quick}")
        log("arm: parity")
        parity = arm_parity(cfg, seed)
        log(f"  parity: identical={parity['decisions_identical']} "
            f"admitted={parity['service_admitted']}")
        log("arm: kill_restart")
        kill = arm_kill_restart(cfg, seed + 1, td)
        log("arm: drain")
        drain = arm_drain(cfg, seed + 2, td)
        log(f"  drain: clean={drain['clean']} "
            f"wal_flushed={drain['wal_flushed']}")
        log("arm: wall")
        wall = arm_wall(cfg, seed + 3, td)
        log(f"  wall: adm/s={wall['admissions_per_s']:.1f} "
            f"held={wall['slo']['held']} k={wall['slo']['k_values']}")

    all_ok = (parity["decisions_identical"]
              and kill["decisions_identical"] and kill["digests_match"]
              and kill["lost_accepted_submissions"] == 0
              and kill["duplicated_admissions"] == 0
              and drain["clean"] and drain["wal_flushed"]
              and wall["slo"]["held"])
    art = {
        "metric": "serve_soak_wall_admissions_per_s",
        "unit": "admissions/s",
        "value": wall["admissions_per_s"],
        "cqs": cfg["cqs"],
        "seed": seed,
        "quick": bool(args.quick),
        "mesh": mesh_info(),
        "config": cfg,
        "wall": wall,
        "kill_restart": kill,
        "drain": drain,
        "parity": parity,
        "all_ok": all_ok,
        "elapsed_s": time.perf_counter() - t0,
    }
    with open(args.out, "w") as fh:
        json.dump(art, fh, indent=1, sort_keys=True)
    log(f"wrote {args.out} (all_ok={all_ok}, "
        f"{art['elapsed_s']:.1f}s)")
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
