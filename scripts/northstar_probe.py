"""North-star scale probe (BASELINE.json): classify 100k pending
workloads against 1k ClusterQueues in one device cycle, and run the
sequential admit scan over the 1k cycle heads.

Run on TPU: ``python scripts/northstar_probe.py [W] [C]``.
Prints phase timings; the target is <1 s p99 per cycle on v5e.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax  # noqa: E402

from kueue_tpu.ops.cycle import solve_cycle, solve_cycle_forests  # noqa: E402


def synth(W=100_000, C=1_000, S=4, R=3, cohorts=64, seed=0):
    """A synthetic packed cycle at north-star scale (no host objects —
    this probes the device plane, not the packer)."""
    rng = np.random.default_rng(seed)
    N = C + cohorts
    parent = np.full(N, -1, dtype=np.int32)
    parent[:C] = C + rng.integers(0, cohorts, C)      # CQ → cohort
    F = S * R
    nominal = rng.integers(8, 64, (C, F)).astype(np.int32) * 1000
    subtree = np.zeros((N, F), dtype=np.int32)
    subtree[:C] = nominal
    for c in range(C):                                # cohort subtree sums
        subtree[parent[c]] += nominal[c]
    guaranteed = subtree.copy()
    usage0 = (nominal * rng.random((C, F)) * 0.8).astype(np.int32)
    usage0 = np.concatenate([usage0, np.zeros((cohorts, F), np.int32)])
    for c in range(C):
        usage0[parent[c]] += usage0[c]
    borrow_cap = np.full((N, F), 2**31 // 64, dtype=np.int32)
    has_blim = np.zeros((N, F), dtype=bool)
    slot_fr = np.zeros((C, S, R), dtype=np.int32)
    for s in range(S):
        for r in range(R):
            slot_fr[:, s, r] = s * R + r
    slot_valid = np.ones((C, S), dtype=bool)
    can_preempt = np.zeros(C, dtype=bool)
    wl_cq = rng.integers(0, C, W).astype(np.int32)
    wl_requests = rng.integers(1, 16, (W, R)).astype(np.int32) * 500
    wl_priority = rng.integers(0, 100, W).astype(np.int32)
    wl_timestamp = rng.random(W).astype(np.float64)
    depth = 2      # chain node count: CQ -> cohort
    return (usage0, subtree, guaranteed, borrow_cap, has_blim, parent,
            nominal, slot_fr, slot_valid, can_preempt,
            wl_cq, wl_requests, wl_priority, wl_timestamp), depth


def bench_fn(fn, *args, reps=20, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2], times[-1], out


def main():
    W = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    C = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000
    print(f"devices: {jax.devices()}")
    args, depth = synth(W=W, C=C)
    print(f"W={W} C={C} — compiling…")

    p50, worst, out = bench_fn(solve_cycle, *args, depth=depth,
                               run_scan=False)
    fit = int(np.asarray(out[4] >= 0).sum())
    print(f"phase-1 classify {W}x{C}: p50={p50 * 1e3:.1f}ms "
          f"worst={worst * 1e3:.1f}ms  ({fit} fits)")

    # the sequential admit scan runs over cycle heads (one per CQ)
    heads_args, _ = synth(W=C, C=C, seed=1)
    p50s, worsts, _ = bench_fn(solve_cycle, *heads_args, depth=depth,
                               run_scan=True)
    print(f"flat {C}-head admit scan: p50={p50s * 1e3:.1f}ms "
          f"worst={worsts * 1e3:.1f}ms")

    # forest-parallel scan: cohort forests admit in lockstep
    cohorts = 64
    forest_of_node = np.concatenate([
        np.asarray(heads_args[5][:C]) - C,     # CQ → its cohort index
        np.arange(cohorts, dtype=np.int32)])   # cohorts are the roots
    max_group = int(np.bincount(
        forest_of_node[np.maximum(np.asarray(heads_args[10]), 0)],
        minlength=cohorts).max())
    p50f, worstf, _ = bench_fn(
        solve_cycle_forests, *heads_args,
        forest_of_node.astype(np.int32), depth=depth,
        n_forests=cohorts, max_forest_wl=max_group + 1)
    print(f"forest-parallel admit scan ({cohorts} forests, "
          f"{max_group + 1} steps): p50={p50f * 1e3:.1f}ms "
          f"worst={worstf * 1e3:.1f}ms")
    total = p50 + p50f
    print(f"north-star cycle (classify backlog + admit heads): "
          f"{total * 1e3:.1f}ms  (target <1000ms)")


if __name__ == "__main__":
    main()
