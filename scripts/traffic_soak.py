"""Traffic soak: open-loop saturation search at 1000 CQs.

Feeds seeded arrival streams (Poisson for the curve, MMPP for the
storm probe) into ``Driver.schedule_once`` through the open-loop
runner (kueue_tpu/traffic/) and publishes:

  curve      — per-arm latency-vs-offered-rate ladder, serial and
               ``--shards 8``, probes interleaved (serial, sharded,
               serial, …) so the serial arm doubles as the same-box
               environment-drift control;
  saturation — per-arm binary-searched sustainable admissions/s at the
               fixed p99 submit→admit SLO (virtual seconds, so the
               number is deterministic and replayable);
  replay     — the sustainable-rate run's recorded event stream re-run
               through a ReplayStream on an identically-built driver
               must reproduce the per-cycle decisions bit-for-bit, and
               serial vs sharded decisions at that rate must match;
  host cost  — measured incremental-snapshot counters at a low and a
               high rate plus a full-rebuild control arm
               (KUEUE_TPU_SNAP_INCREMENTAL=0): steady-state per-cycle
               host cost tracks the arrival rate, not the CQ universe;
  storms     — an MMPP burst probe's requeue-storm counters, plus a
               MultiKueue probe routing a slice of submissions through
               the remote.py worker client.

Usage:
    python scripts/traffic_soak.py [--cqs 1000] [--shards 8]
        [--seed N] [--quick] [--out TRAFFIC_r11.json]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _peek_int_flag(argv, flag: str) -> int:
    """Read an int flag from raw argv (both '--f N' and '--f=N' forms)."""
    n = 0
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            try:
                n = max(n, int(argv[i + 1]))
            except ValueError:
                pass
        elif a.startswith(flag + "="):
            try:
                n = max(n, int(a.split("=", 1)[1]))
            except ValueError:
                pass
    return n


# the sharded arm needs an N-device mesh, which on a CPU host only
# exists if the XLA flag lands BEFORE jax initializes its backend (the
# kueue_tpu import below pulls jax in)
_n_dev = _peek_int_flag(sys.argv[1:], "--shards") or 8
if _n_dev > 1:
    _xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _xf:
        os.environ["XLA_FLAGS"] = (
            _xf + f" --xla_force_host_platform_device_count={_n_dev}"
        ).strip()

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
)
from kueue_tpu.controller.driver import Driver
from kueue_tpu.features import env_value
from kueue_tpu.perf.harness import ab_block
from kueue_tpu.remote import LocalWorkerClient
from kueue_tpu.traffic import (
    ArrivalStream,
    MMPPProcess,
    OpenLoopConfig,
    PoissonProcess,
    ReplayStream,
    TrafficSpec,
    find_sustainable_rate,
    run_open_loop,
)


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def mesh_info() -> dict:
    import jax
    devs = jax.devices()
    return {"n_devices": len(devs),
            "platform": devs[0].platform if devs else "none"}


def build(n_cqs: int, shards: int) -> tuple[Driver, VirtualClock]:
    """Fresh driver per probe: cohorts of 4, 4000m cpu nominal,
    BEST_EFFORT_FIFO (chaos_soak's cluster shape).  ``shards`` is
    applied through the same KUEUE_TPU_SHARDS env the production path
    reads; 0 leaves the serial solver."""
    old = os.environ.pop("KUEUE_TPU_SHARDS", None)
    if shards > 1:
        os.environ["KUEUE_TPU_SHARDS"] = str(shards)
    try:
        clock = VirtualClock()
        d = Driver(clock=clock, use_device_solver=True)
    finally:
        if shards > 1:
            os.environ.pop("KUEUE_TPU_SHARDS", None)
        if old is not None:
            os.environ["KUEUE_TPU_SHARDS"] = old
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    for q in range(n_cqs):
        name = f"cq-{q}"
        d.apply_cluster_queue(ClusterQueue(
            name=name, cohort=f"co-{q // 4}",
            queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
            preemption=PreemptionPolicy(),
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(name="default", resources={
                    "cpu": ResourceQuota(nominal=4000)})])]))
        d.apply_local_queue(LocalQueue(name=f"lq-{q}",
                                       cluster_queue=name))
    return d, clock


RUNTIMES_S = (2.0, 4.0)   # mean 3s; 2 concurrent 1500m slots per CQ


def capacity_estimate(n_cqs: int) -> float:
    """Quota ceiling in admissions/s: slots / mean service time."""
    return n_cqs * 2 / (sum(RUNTIMES_S) / len(RUNTIMES_S))


def spec_for(n_cqs: int, remote_fraction: float = 0.0) -> TrafficSpec:
    return TrafficSpec(n_cqs=n_cqs, cpu_choices=(1500,),
                       priorities=(0, 10, 20),
                       runtime_choices_s=RUNTIMES_S,
                       cancel_fraction=0.02, churn_fraction=0.02,
                       remote_fraction=remote_fraction)


def rate_seed(base: int, rate: float) -> int:
    # same rate → same stream in every arm, so serial vs sharded
    # probes (and the replay rerun) see identical events
    return base + int(round(rate * 8))


def probe(cfg: dict, rate: float, shards: int, *, seed: int,
          process=None, remote: bool = False, snap_incremental=None):
    """One fresh-driver open-loop run at ``rate``; returns the
    OpenLoopResult (events retained for replay)."""
    if snap_incremental is not None:
        os.environ["KUEUE_TPU_SNAP_INCREMENTAL"] = \
            "1" if snap_incremental else "0"
    try:
        d, clock = build(cfg["cqs"], shards)
    finally:
        os.environ.pop("KUEUE_TPU_SNAP_INCREMENTAL", None)
    sp = spec_for(cfg["cqs"], remote_fraction=0.25 if remote else 0.0)
    proc = process or PoissonProcess(rate, seed=seed)
    stream = ArrivalStream(proc, sp, seed=seed)
    oc = OpenLoopConfig(duration_s=cfg["duration_s"], dt_s=1.0,
                        slo_p99_s=cfg["slo_p99_s"],
                        wall_budget_s=cfg["wall_budget_s"])
    rc = LocalWorkerClient(d) if remote else None
    r = run_open_loop(d, clock, stream, oc, remote_client=rc)
    r.rate_per_s = rate
    r.obs = d.obs.report()
    gc.collect()
    return r


def curve_entry(r) -> dict:
    return {"rate_per_s": round(r.rate_per_s, 1),
            "submitted": r.submitted,
            "admitted": r.admitted,
            "p50_latency_s": round(r.p50_latency_s, 3),
            "p99_latency_s": round(r.p99_latency_s, 3),
            "mean_latency_s": round(r.mean_latency_s, 3),
            "end_depth": r.end_depth,
            "max_depth": r.max_depth,
            "admissions_per_s": round(r.admissions_per_wall_s, 1),
            "cycle_wall_p50_ms": round(r.cycle_wall_p50_ms, 2),
            "cycle_wall_p99_ms": round(r.cycle_wall_p99_ms, 2),
            "latency_hist": r.latency_hist,
            "meets_slo": r.meets_slo,
            "truncated": r.truncated}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cqs", type=int, default=1000)
    ap.add_argument("--shards", type=int, default=8,
                    help="sharded-arm mesh size (consumed pre-import)")
    ap.add_argument("--seed", type=int,
                    default=int(env_value("KUEUE_TPU_TRAFFIC_SEED")))
    ap.add_argument("--duration", type=float, default=30.0,
                    help="virtual seconds per probe")
    ap.add_argument("--slo", type=float, default=8.0,
                    help="p99 submit->admit SLO, virtual seconds")
    ap.add_argument("--iters", type=int, default=4,
                    help="binary-search refinement steps per arm")
    ap.add_argument("--quick", action="store_true",
                    help="tiny cluster for a seconds-level pass")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "TRAFFIC_r11.json"))
    args = ap.parse_args()

    cqs = 16 if args.quick else args.cqs
    cfg = {
        "cqs": cqs,
        "duration_s": 10.0 if args.quick else args.duration,
        "slo_p99_s": args.slo,
        "wall_budget_s": 20.0 if args.quick else 120.0,
    }
    iters = 2 if args.quick else args.iters
    cap = capacity_estimate(cqs)
    # offered-rate ladder as fractions of the quota ceiling; the
    # >= 1.0 rungs are the past-saturation measurements
    ladder = ([0.5, 1.0, 1.5] if args.quick
              else [0.25, 0.5, 0.75, 0.9, 1.0, 1.2, 1.5])
    arms = {"serial": 0, f"shards_{args.shards}": args.shards}
    t_start = time.perf_counter()

    log(f"traffic soak: cqs={cqs} capacity_estimate={cap:.0f}/s "
        f"slo_p99={cfg['slo_p99_s']}s duration={cfg['duration_s']}s "
        f"seed={args.seed}")

    # --- saturation curve, probes interleaved across arms ------------
    curves: dict[str, list] = {name: [] for name in arms}
    results: dict[str, dict[float, object]] = {name: {} for name in arms}
    for frac in ladder:
        rate = round(cap * frac, 1)
        for name, shards in arms.items():
            r = probe(cfg, rate, shards, seed=rate_seed(args.seed, rate))
            curves[name].append(curve_entry(r))
            results[name][rate] = r
            log(f"  [{name}] rate={rate}/s ({frac:.2f}x cap) "
                f"p99={r.p99_latency_s:.2f}s depth_end={r.end_depth} "
                f"wall={r.wall_s:.1f}s "
                f"{'OK' if r.meets_slo else 'over SLO'}")

    # --- binary search: sustainable admissions/s per arm -------------
    saturation: dict[str, dict] = {}
    for name, shards in arms.items():
        ok_rates = [r for r in sorted(results[name])
                    if results[name][r].meets_slo]
        bad_rates = [r for r in sorted(results[name])
                     if not results[name][r].meets_slo]
        lo = ok_rates[-1] if ok_rates else cap * ladder[0] / 2
        hi = bad_rates[0] if bad_rates else cap * ladder[-1] * 2
        best, probes = find_sustainable_rate(
            lambda rate: probe(cfg, rate, shards,
                               seed=rate_seed(args.seed, rate)),
            lo, hi, iters=iters)
        for r in probes:
            curves[name].append(curve_entry(r))
            results[name][r.rate_per_s] = r
            log(f"  [{name}] search rate={r.rate_per_s:.1f}/s "
                f"p99={r.p99_latency_s:.2f}s "
                f"{'OK' if r.meets_slo else 'over SLO'}")
        at_best = results[name].get(best) or max(
            (results[name][r] for r in results[name]
             if results[name][r].meets_slo),
            key=lambda r: r.rate_per_s, default=None)
        saturation[name] = {
            "sustainable_rate_per_s": round(best, 1),
            "bracket": [round(lo, 1), round(hi, 1)],
            "search_iters": iters,
            "p99_latency_s_at_rate": (
                round(at_best.p99_latency_s, 3) if at_best else None),
            "admissions_per_wall_s_at_rate": (
                round(at_best.admissions_per_wall_s, 1)
                if at_best else None),
        }
        log(f"[{name}] sustainable ~= {best:.1f}/s at p99<="
            f"{cfg['slo_p99_s']}s")
        curves[name].sort(key=lambda e: e["rate_per_s"])

    # --- replay bit-identity at the serial sustainable rate ----------
    replay_rate = saturation["serial"]["sustainable_rate_per_s"]
    seed_r = rate_seed(args.seed, replay_rate)
    log(f"replay check @ {replay_rate}/s ...")
    live = probe(cfg, replay_rate, 0, seed=seed_r)

    def rerun(shards):
        d, clock = build(cfg["cqs"], shards)
        oc = OpenLoopConfig(duration_s=cfg["duration_s"], dt_s=1.0,
                            slo_p99_s=cfg["slo_p99_s"],
                            wall_budget_s=cfg["wall_budget_s"])
        return run_open_loop(d, clock, ReplayStream(live.events), oc)

    replayed = rerun(0)
    sharded = rerun(args.shards)
    replay_identical = replayed.decisions == live.decisions
    serial_shard_match = sharded.decisions == live.decisions
    gc.collect()
    log(f"  replay {'bit-identical' if replay_identical else 'DIVERGED'}"
        f"; serial-vs-sharded decisions "
        f"{'match' if serial_shard_match else 'DIVERGED'}")

    # --- host-cost scaling: O(arrivals + dirty rows), not O(universe) -
    lo_rate, hi_rate = round(cap * 0.05, 1), round(cap * 0.75, 1)
    snap_probes = {}
    for tag, rate, inc in (("low_rate", lo_rate, True),
                           ("high_rate", hi_rate, True),
                           ("low_rate_full_rebuild", lo_rate, False)):
        r = probe(cfg, rate, 0, seed=rate_seed(args.seed, rate),
                  snap_incremental=inc)
        snap_probes[tag] = {
            "rate_per_s": rate,
            "incremental": inc,
            "snap_cqs_recloned_per_cycle": round(
                r.snap_cqs_recloned_per_cycle, 1),
            "snap_trees_reused_per_cycle": round(
                r.snap_trees_reused_per_cycle, 1),
            "snap_full_rebuilds": r.snap_full_rebuilds,
            "cycle_wall_p50_ms": round(r.cycle_wall_p50_ms, 2),
            "cycle_wall_p99_ms": round(r.cycle_wall_p99_ms, 2),
        }
        log(f"  snapshot[{tag}] rate={rate}/s recloned/cyc="
            f"{snap_probes[tag]['snap_cqs_recloned_per_cycle']} "
            f"cyc_p50={snap_probes[tag]['cycle_wall_p50_ms']}ms")
    snapshot_counters = {
        "cq_universe": cqs,
        "probes": snap_probes,
        # the scaling claim, from measured counters: per-cycle reclone
        # work tracks the offered rate (low ≪ high) and sits far below
        # the universe, while the full-rebuild control re-clones every
        # CQ every cycle
        "recloned_per_cycle_low_over_universe": round(
            snap_probes["low_rate"]["snap_cqs_recloned_per_cycle"] / cqs,
            3),
        "recloned_per_cycle_full_rebuild_over_universe": round(
            snap_probes["low_rate_full_rebuild"]
            ["snap_cqs_recloned_per_cycle"] / cqs, 3),
    }

    # --- MMPP storm probe + MultiKueue remote-path probe -------------
    burst_rate = round(cap * 0.6, 1)
    mmpp = probe(cfg, burst_rate, 0, seed=args.seed + 17,
                 process=MMPPProcess(quiet_rate_per_s=burst_rate * 0.2,
                                     burst_rate_per_s=burst_rate * 2.5,
                                     mean_dwell_s=5.0,
                                     seed=args.seed + 17))
    mmpp.rate_per_s = burst_rate
    storm_block = {
        "process": "mmpp",
        "mean_rate_per_s": burst_rate,
        "p99_latency_s": round(mmpp.p99_latency_s, 3),
        "max_depth": mmpp.max_depth,
        "requeue_unparked": mmpp.requeue_unparked,
        "requeue_storm_peak": mmpp.requeue_storm_peak,
    }
    log(f"  mmpp storm probe: p99={storm_block['p99_latency_s']}s "
        f"max_depth={storm_block['max_depth']} "
        f"storm_peak={storm_block['requeue_storm_peak']}")
    remote_rate = round(cap * 0.4, 1)
    rem = probe(cfg, remote_rate, 0, seed=args.seed + 29, remote=True)
    remote_block = {
        "rate_per_s": remote_rate,
        "remote_fraction": 0.25,
        "remote_submitted": rem.remote_submitted,
        "submitted": rem.submitted,
        "p99_latency_s": round(rem.p99_latency_s, 3),
        "meets_slo": rem.meets_slo,
    }
    log(f"  remote probe: {rem.remote_submitted}/{rem.submitted} via "
        f"worker client, p99={remote_block['p99_latency_s']}s")

    # --- environment-drift bookkeeping: the interleaved serial arm is
    # the same-box control for the sharded treatment; harness.ab_block
    # refuses to build this without it ---------------------------------
    shard_name = f"shards_{args.shards}"
    drift = ab_block(
        treatment={"arm": shard_name,
                   "sustainable_rate_per_s":
                       saturation[shard_name]["sustainable_rate_per_s"],
                   "cycle_wall_p50_ms_at_cap": next(
                       (e["cycle_wall_p50_ms"] for e in curves[shard_name]
                        if e["rate_per_s"] >= cap), None)},
        control={"arm": "serial", "interleaved": True,
                 "sustainable_rate_per_s":
                     saturation["serial"]["sustainable_rate_per_s"],
                 "cycle_wall_p50_ms_at_cap": next(
                     (e["cycle_wall_p50_ms"] for e in curves["serial"]
                      if e["rate_per_s"] >= cap), None)})

    arrival = {"process": "poisson", "seed": args.seed,
               "cpu_m_choices": [1500],
               "runtime_choices_s": list(RUNTIMES_S),
               "cancel_fraction": 0.02, "churn_fraction": 0.02,
               "capacity_estimate_per_s": round(cap, 1)}

    tail = {
        "metric": "open_loop_sustainable_admissions_per_s",
        "unit": "admissions/s at p99 submit->admit <= SLO (virtual s)",
        "cqs": cqs,
        "seed": args.seed,
        "quick": bool(args.quick),
        "mesh": mesh_info(),
        "slo": {"p99_latency_s": cfg["slo_p99_s"]},
        "arrival": arrival,
        "open_loop": {"duration_s": cfg["duration_s"], "dt_s": 1.0,
                      "wall_budget_s": cfg["wall_budget_s"],
                      "iters": iters},
        "arms": {name: {**saturation[name], "curve": curves[name]}
                 for name in arms},
        "control": drift["control"],
        "environment_drift": drift,
        "replay_identical": replay_identical,
        "serial_shard_decisions_match": serial_shard_match,
        "snapshot_counters": snapshot_counters,
        "storm_probe": storm_block,
        "remote_probe": remote_block,
        # r16+: the telemetry plane rides every soak — the replay-rate
        # serial probe's obs block stands for the headline arm
        "obs": live.obs,
        "value": saturation["serial"]["sustainable_rate_per_s"],
        "wall_s_total": round(time.perf_counter() - t_start, 1),
    }
    print(json.dumps({k: tail[k] for k in
                      ("metric", "cqs", "value", "replay_identical",
                       "serial_shard_decisions_match")}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out} ({tail['wall_s_total']}s total)")
    return 0 if (replay_identical and serial_shard_match) else 1


if __name__ == "__main__":
    sys.exit(main())
