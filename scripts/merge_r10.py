"""Merge the two crossover tails into MULTICHIP_r10.json.

One-shot helper for the r10 artifact: takes the 1000-CQ crossover tail
and the budgeted 10k-CQ tail (both produced by ``northstar_e2e.py
--burst --crossover ...``) and wraps them as::

    { metric, unit, value, best_solver_path, mesh, cqs,
      runs: { cqs_1000: <tail>, cqs_10000_budgeted: <tail> } }

The top-level value/mesh come from the 1000-CQ run (the north-star
scale); the wrapper deliberately avoids the ``scenarios`` key, which
the artifact validator reserves for chaos tables.

Usage:
    python scripts/merge_r10.py <tail_1000.json> <tail_10k.json> <out>
"""

from __future__ import annotations

import json
import sys


def main() -> int:
    if len(sys.argv) != 4:
        print(__doc__, file=sys.stderr)
        return 2
    with open(sys.argv[1]) as f:
        t1k = json.load(f)
    with open(sys.argv[2]) as f:
        t10k = json.load(f)
    out = {
        "metric": t1k.get("metric", "northstar_e2e_cycle_p99"),
        "unit": t1k.get("unit", "ms"),
        "value": t1k.get("value"),
        "best_solver_path": t1k.get("best_solver_path"),
        "cqs": t1k.get("cqs"),
        "mesh": t1k.get("mesh"),
        "runs": {"cqs_1000": t1k, "cqs_10000_budgeted": t10k},
    }
    with open(sys.argv[3], "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(f"wrote {sys.argv[3]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
