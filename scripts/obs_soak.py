"""OBS soak: prove the telemetry plane is free, honest, and dumpable.

Runs the host-path drain scenario (oversubscribed cohorts, WAL
attached) through two arms on identically-built drivers, interleaved
at *cycle* granularity — cycle k runs on the untraced driver and the
traced driver back to back (order alternating per cycle), so every
traced sample has a time-adjacent untraced partner and machine drift
(frequency scaling, noisy neighbors) cancels out of the A/B — and
publishes:

  decisions  — per-cycle decision digests and the final admitted set
               must be bit-identical between the arms and across every
               rep (tracing may not change a single decision);
  overhead   — traced vs untraced per-cycle wall p50 over the
               min-across-reps per cycle index (interference only ever
               adds time); the ratio must hold the <= 5% guarantee
               validate_artifacts enforces;
  spans      — the traced arm's per-phase roster must cover every
               host hot-path phase (cycle, cycle.snapshot,
               cycle.nominate, cycle.admit, wal.append, wal.commit);
  dumps      — a programmatic flight-recorder dump whose digests match
               the recorded cycles, a SIGUSR2 state dump carrying the
               obs sections, and a non-empty Chrome trace
               (/debug/spans food, opens in Perfetto).

Usage:
    python scripts/obs_soak.py [--cycles 16] [--reps 5] [--quick]
        [--out OBS_r16.json]
"""

from __future__ import annotations

import argparse
import gc
import io
import json
import os
import signal
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    QueueingStrategy,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from kueue_tpu.controller.driver import Driver
from kueue_tpu.debugger import Dumper
from kueue_tpu.obs import trace as obs_trace
from kueue_tpu.obs.flight import decision_digest
from kueue_tpu.utils.journal import CycleWAL


class VirtualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build(n_cohorts: int, cqs: int, per_lq: int) -> tuple[Driver, VirtualClock]:
    """Fresh driver per arm: oversubscribed drain (quota-bound
    admissions against a deep backlog), runtime-driven finishes,
    BEST_EFFORT_FIFO — the chaos-soak shape, host path so every
    classical phase appears in the roster."""
    clock = VirtualClock()
    d = Driver(clock=clock, use_device_solver=False)
    d.attach_wal(CycleWAL())
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    n = 0
    for c in range(n_cohorts):
        for q in range(cqs):
            name = f"cq-{c}-{q}"
            d.apply_cluster_queue(ClusterQueue(
                name=name, cohort=f"co-{c}",
                queueing_strategy=QueueingStrategy.BEST_EFFORT_FIFO,
                preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources={
                        "cpu": ResourceQuota(nominal=4000)})])]))
            d.apply_local_queue(LocalQueue(name=f"lq-{c}-{q}",
                                           cluster_queue=name))
            for i in range(per_lq):
                n += 1
                d.create_workload(Workload(
                    name=f"w-{c}-{q}-{i}",
                    queue_name=f"lq-{c}-{q}", priority=(i % 3) * 10,
                    creation_time=float(n),
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": 1500})]))
    return d, clock


def _drive(d, clock, out, c: int, runtime: int) -> float:
    """One harness cycle on one driver: tick, schedule (timed), finish
    admissions whose modeled runtime elapsed (untimed)."""
    clock.t += 1.0
    t0 = time.perf_counter()
    stats = d.schedule_once()
    wall = time.perf_counter() - t0
    out.append(stats)
    if runtime > 0 and c - runtime >= 0:
        for key in out[c - runtime].admitted:
            wl = d.workloads.get(key)
            if wl is not None and wl.has_quota_reservation:
                d.finish_workload(key)
    return wall


def run_pair(cycles: int, runtime: int, shape: tuple[int, int, int]):
    """One rep: an untraced and a traced driver advanced in lockstep,
    cycle k on both back to back (order alternating per cycle).  The
    process-global tracer is installed around the traced driver's
    cycle only — its finishes included — and cleared for the untraced
    one, so the untraced arm never pays a single span."""
    obs_trace.clear()
    du, cu = build(*shape)
    dt, ct = build(*shape)
    tracer = dt.obs.enable_tracing()
    obs_trace.clear()
    outs = {"untraced": [], "traced": []}
    walls = {"untraced": [], "traced": []}
    arms = {"untraced": (du, cu, None), "traced": (dt, ct, tracer)}
    order = ("untraced", "traced")
    gc.collect()
    gc.disable()   # collector pauses land on whichever arm is running
    try:
        for c in range(cycles):
            for name in (order if c % 2 == 0 else order[::-1]):
                d, clock, tr = arms[name]
                obs_trace.install(tr)   # None = off for the untraced arm
                walls[name].append(_drive(d, clock, outs[name], c,
                                          runtime))
            obs_trace.clear()
    finally:
        gc.enable()
    return {
        "digests": {n: [decision_digest(s) for s in outs[n]]
                    for n in outs},
        "walls": walls,
        "admitted": {n: sorted(arms[n][0].admitted_keys()) for n in outs},
        "traced_driver": dt,
        "tracer": tracer,
    }


def sigusr2_dump(d) -> bool:
    """Fire a real SIGUSR2 at ourselves through debugger.Dumper and
    check the dump carries the obs sections."""
    buf = io.StringIO()
    old = signal.getsignal(signal.SIGUSR2)
    try:
        Dumper(d, out=buf).listen_for_signal()
        os.kill(os.getpid(), signal.SIGUSR2)
    finally:
        signal.signal(signal.SIGUSR2, old)
    text = buf.getvalue()
    return bool(text) and "flight" in text


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cycles", type=int, default=16)
    ap.add_argument("--runtime", type=int, default=2,
                    help="modeled runtime (cycles) before finish")
    ap.add_argument("--reps", type=int, default=12,
                    help="lockstep untraced+traced rep pairs")
    ap.add_argument("--cohorts", type=int, default=4)
    ap.add_argument("--cqs-per-cohort", type=int, default=4)
    ap.add_argument("--per-lq", type=int, default=24)
    ap.add_argument("--quick", action="store_true",
                    help="fewer reps for a seconds-level pass")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "OBS_r16.json"))
    args = ap.parse_args()

    reps = 8 if args.quick else args.reps
    shape = (args.cohorts, args.cqs_per_cohort, args.per_lq)
    t_start = time.perf_counter()
    log(f"obs soak: cycles={args.cycles} reps={reps} shape={shape} "
        f"(cycle-interleaved untraced/traced)")

    # warmup rep, discarded: first-touch costs (imports, caches,
    # allocator) must not land on either side of the A/B
    run_pair(args.cycles, args.runtime, shape)
    gc.collect()

    pairs = []
    for rep in range(reps):
        p = run_pair(args.cycles, args.runtime, shape)
        pairs.append(p)
        log(f"  rep {rep} admitted={len(p['admitted']['traced'])} "
            f"untraced_p50="
            f"{statistics.median(p['walls']['untraced']) * 1e3:.3f}ms "
            f"traced_p50="
            f"{statistics.median(p['walls']['traced']) * 1e3:.3f}ms")
        gc.collect()

    # --- bit-identity between arms and across every rep --------------
    ref_digests = pairs[0]["digests"]["untraced"]
    ref_admitted = pairs[0]["admitted"]["untraced"]
    decisions_identical = all(
        p["digests"][arm] == ref_digests
        and p["admitted"][arm] == ref_admitted
        for p in pairs for arm in ("untraced", "traced"))
    log(f"decisions {'bit-identical' if decisions_identical else 'DIVERGED'}"
        f" across {2 * reps} runs")

    # --- overhead: per-cycle wall p50 over min-across-reps -----------
    # cycle k is the same work in every rep; the min across reps is
    # the interference-free estimate of that cycle (noise only ever
    # adds time), and the cycle-interleaved arms see the same drift
    pool = {arm: [min(p["walls"][arm][k] for p in pairs)
                  for k in range(args.cycles)]
            for arm in ("untraced", "traced")}
    traced_p50_ms = statistics.median(pool["traced"]) * 1e3
    untraced_p50_ms = statistics.median(pool["untraced"]) * 1e3
    ratio = traced_p50_ms / untraced_p50_ms
    log(f"overhead: traced_p50={traced_p50_ms:.4f}ms "
        f"untraced_p50={untraced_p50_ms:.4f}ms ratio={ratio:.4f}")

    # --- roster + dumps from the last rep's traced driver ------------
    last = pairs[-1]
    d = last["traced_driver"]
    obs_trace.install(last["tracer"])   # dumps read the live tracer
    roster = last["tracer"].roster()
    missing = [p for p in obs_trace.HOT_PATH_PHASES
               if p in ("cycle", "cycle.snapshot", "cycle.nominate",
                        "cycle.order", "cycle.admit", "wal.append",
                        "wal.commit") and p not in roster]

    dump = d.obs.flight.dump()
    traced_digests = last["digests"]["traced"]
    flight_ok = (dump["buffered"] == len(dump["cycles"])
                 and [c["digest"] for c in dump["cycles"]]
                 == traced_digests[-dump["buffered"]:]
                 # empty-head cycles open no spans; every deciding
                 # cycle must carry its span trail
                 and all(c["spans"] for c in dump["cycles"]
                         if c["admitted"] or c["preempting"]))
    sig_ok = sigusr2_dump(d)
    chrome = d.obs.spans_chrome_trace()
    obs_block = d.obs.report()
    spans_out = {p: {"count": row["count"],
                     "p50_ms": round(row["p50_ms"], 4),
                     "p99_ms": round(row["p99_ms"], 4),
                     "total_s": round(row["total_s"], 6)}
                 for p, row in roster.items()}
    obs_trace.clear()
    log(f"roster: {sorted(roster)}; flight_ok={flight_ok} "
        f"sigusr2_ok={sig_ok} chrome_events={len(chrome['traceEvents'])}")

    tail = {
        "metric": "obs_tracing_overhead_ratio",
        "unit": "traced / untraced per-cycle wall p50 (drift-fair A/B)",
        "cqs": args.cohorts * args.cqs_per_cohort,
        "cycles": args.cycles,
        "reps": reps,
        "quick": bool(args.quick),
        "control": {"arm": "untraced", "interleaved": True,
                    "reps": reps,
                    "cycle_wall_p50_ms": untraced_p50_ms},
        "decisions_identical": decisions_identical,
        "admitted_total": len(ref_admitted),
        "overhead": {"traced_p50_ms": traced_p50_ms,
                     "untraced_p50_ms": untraced_p50_ms,
                     "ratio": ratio},
        "spans": spans_out,
        "spans_missing_host_phases": missing,
        "dumps": {"flightrecorder_ok": flight_ok,
                  "sigusr2_ok": sig_ok,
                  "chrome_trace_events": len(chrome["traceEvents"])},
        "obs": obs_block,
        "value": ratio,
        "elapsed_s": round(time.perf_counter() - t_start, 2),
    }
    ok = (decisions_identical and ratio <= 1.05 and not missing
          and flight_ok and sig_ok and chrome["traceEvents"])
    print(json.dumps({k: tail[k] for k in
                      ("metric", "value", "decisions_identical")}))
    with open(args.out, "w") as f:
        json.dump(tail, f, indent=1)
        f.write("\n")
    log(f"wrote {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
