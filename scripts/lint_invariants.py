"""Run the AST invariant lint (``kueue_tpu/analysis``) over the repo.

Five passes, all stdlib-``ast``, no jax/numpy import on the lint path:

  purity       no host effects reachable from jit/shard_map entries
  dtype        plane creations match the declared PLANE_SCHEMA
  wal-order    journal append dominates the store mutation
  chaos-sites  doc / code / scenario site sets agree exactly
  env-flags    KUEUE_TPU_* reads go through features.ENV_FLAGS and
               match the README flag table

Findings not grandfathered in ``kueue_tpu/analysis/baseline.json``
fail the lint (exit 1), as do *stale* baseline entries — the baseline
may only shrink.

Usage:
    python scripts/lint_invariants.py [paths ...]        # human output
    python scripts/lint_invariants.py --json             # machine output
    python scripts/lint_invariants.py --write-baseline   # grandfather
    python scripts/lint_invariants.py --artifact LINT_r14.json

Default paths: kueue_tpu/ scripts/ bench.py (relative to the repo
root).  ``--artifact`` stamps a ``LINT_*`` artifact in the shape
``scripts/validate_artifacts.py`` checks.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from kueue_tpu.analysis import (  # noqa: E402
    BASELINE_PATH,
    all_passes,
    apply_baseline,
    load_baseline,
    run_all,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="AST invariant lint for the kueue-tpu stack")
    ap.add_argument("paths", nargs="*",
                    default=["kueue_tpu", "scripts", "bench.py"],
                    help="files/dirs to scan, relative to the repo root")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="grandfathered-findings file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--artifact", default=None,
                    help="also write a LINT_* artifact JSON here")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    passes = all_passes()
    findings = run_all(_ROOT, args.paths, passes=passes)
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        prior = load_baseline(args.baseline)
        first = prior.get("first_full_run_findings") or len(findings)
        payload = {
            "first_full_run_findings": first,
            "entries": [{"key": f.key, "line": f.line,
                         "message": f.message}
                        for f in findings],
        }
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        print(f"baseline: {len(findings)} entries -> {args.baseline}")
        return 0

    baseline = load_baseline(args.baseline)
    unsuppressed, suppressed, stale = apply_baseline(findings, baseline)
    ok = not unsuppressed and not stale

    counts: dict[str, int] = {}
    for f in unsuppressed:
        counts[f.rule] = counts.get(f.rule, 0) + 1

    report = {
        "passes": [{"name": p.name, "doc": p.doc} for p in passes],
        "paths": list(args.paths),
        "findings": [f.to_json() for f in unsuppressed],
        "suppressed": [f.to_json() for f in suppressed],
        "stale_baseline": stale,
        "counts": counts,
        "total_findings": len(findings),
        "baseline_entries": len(baseline.get("entries", [])),
        "first_full_run_findings":
            baseline.get("first_full_run_findings", 0),
        "elapsed_s": round(elapsed, 3),
        "ok": ok,
    }

    if args.artifact:
        artifact = dict(report)
        artifact.update(metric="lint_unsuppressed_findings",
                        value=len(unsuppressed), unit="findings")
        with open(args.artifact, "w", encoding="utf-8") as fh:
            json.dump(artifact, fh, indent=2)
            fh.write("\n")

    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for f in unsuppressed:
            print(f.render())
        for key in stale:
            print(f"stale baseline entry (violation is gone — delete "
                  f"it): {key}")
        n_pass = len(passes)
        print(f"lint: {n_pass} passes, {len(findings)} findings "
              f"({len(suppressed)} grandfathered, "
              f"{len(unsuppressed)} new, {len(stale)} stale baseline) "
              f"in {elapsed:.2f}s")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
