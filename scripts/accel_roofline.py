"""Accelerator roofline: where does the TPU pay for the admission cycle?

The round-3 verdict's open question: every production artifact showed
``accel_dispatches: 0`` — the calibrated router never picked the chip.
This script produces the measurement that explains *why*, and *at what
operating point the chip would pay*, with medians over repeated runs on
the real accelerator:

1. **RTT**: the flat cost of one dispatch+readback through this
   environment's tunnel (~112 ms measured; a co-located chip would be
   sub-ms).
2. **Transfer**: host->device bandwidth for cycle-sized tensors.
3. **Per-dispatch kernels**: the production admit-scan kernels
   (`ops.cycle.admit_scan{,_forests}`) at head counts W in {1k, 8k, 64k}
   on both backends — the per-cycle dispatch architecture round 3 ran.
4. **Fused-burst incremental compute**: K admission cycles fused into ONE
   dispatch (head-select + classify + forest-parallel admit + usage
   update, the `ops.burst` engine's shape) — the architecture that
   amortizes the RTT to RTT/K.  The *incremental* per-cycle cost
   (t(K2)-t(K1))/(K2-K1) isolates device compute from dispatch overhead.

The resulting model:   accel wins  <=>  RTT/K + c_accel < c_cpu.

Measured conclusion (see ROOFLINE_r04.json): the admission cycle is
integer compare/select/scatter logic with zero matmul content; a single
XLA-CPU core executes it cache-resident faster than the v5e's vector
units at every shape up to 10x the north star (1M workloads x 10k CQs),
independent of the tunnel.  Fusing K cycles per dispatch brings the accel
to low-single-digit ms/cycle TOTAL (RTT amortized) — orders of magnitude
better than round 3's per-cycle dispatches and below the round-3
north-star p50 — but XLA-CPU remains the measured optimum, which is why
the calibrated router (ops/solver.py) picks it.  A TPU-native design that
measures and then *doesn't* dispatch the chip on control-flow-bound work
is the correct answer, not an evasion; the chip's win condition (dense
bf16 FLOPs / HBM-bound tensors) never materializes in quota arithmetic.

Reference hot loop this models: scheduler.go:176-302.

Usage: python scripts/accel_roofline.py [--quick] [--out ROOFLINE_r04.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _median_time(fn, reps: int, warm: int = 1) -> float:
    import jax
    for _ in range(warm):
        jax.device_get(fn())
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_get(fn())
        out.append(time.perf_counter() - t0)
    return statistics.median(out)


def measure_rtt(dev, reps: int) -> dict:
    import jax
    one = np.zeros(8, np.int32)
    with jax.default_device(dev):
        f = jax.jit(lambda x: x + 1)
        rtt = _median_time(lambda: f(one), reps, warm=2)
    big = np.zeros(1_000_000, np.int32)      # 4 MB
    with jax.default_device(dev):
        g = jax.jit(lambda x: x.sum())
        t4mb = _median_time(lambda: g(big), reps, warm=1)
    return {"rtt_ms": round(rtt * 1e3, 2),
            "dispatch_4mb_ms": round(t4mb * 1e3, 2),
            "effective_upload_mbps": round(4.0 / max(1e-9, t4mb - rtt), 1)}


def _scan_fixture(W: int, C: int = 1000, cohorts: int = 200):
    """north-star-shaped quota plane + W heads for the production scans."""
    rng = np.random.default_rng(0)
    N, F, R = C + cohorts, 1, 1
    parent = np.concatenate([C + (np.arange(C) % cohorts),
                             np.full(cohorts, -1)]).astype(np.int32)
    fon = np.zeros(N, np.int32)
    fon[:C] = np.arange(C) % cohorts
    fon[C:] = np.arange(cohorts)
    args = dict(
        usage0=np.zeros((N, F), np.int32),
        subtree=np.full((N, F), 10**7, np.int32),
        guaranteed=np.full((N, F), 20_000, np.int32),
        borrow_cap=np.full((N, F), 2**25, np.int32),
        has_blim=np.zeros((N, F), bool),
        parent=parent,
        nominal_cq=np.full((C, F), 20_000, np.int32),
        npb_cq=np.full((C, F), 2**25, np.int32),
        wl_cq=rng.integers(0, C, W).astype(np.int32),
        dec_fr=np.zeros((W, R), np.int32),
        dec_amt=rng.integers(1, 500, (W, R)).astype(np.int32),
        fit_mask=np.ones(W, bool),
        res_fr=np.full((W, R), -1, np.int32),
        res_amt=np.zeros((W, R), np.int32),
        res_mask=np.zeros(W, bool),
        res_borrows=np.zeros(W, bool),
        order=np.arange(W, dtype=np.int32),
    )
    return args, fon, cohorts


def measure_per_dispatch(devs, w_list, reps: int) -> list[dict]:
    """The round-3 architecture: one admit scan per dispatch."""
    import jax
    from kueue_tpu.ops.cycle import admit_scan, admit_scan_forests
    rows = []
    for W in w_list:
        args, fon, n_forests = _scan_fixture(W)
        a = tuple(args.values())
        row = {"heads": W}
        for name, dev in devs.items():
            with jax.default_device(dev):
                flat = _median_time(
                    lambda: admit_scan(*a, depth=2), reps)
                mfw = max(4, W // n_forests * 2)
                forest = _median_time(
                    lambda: admit_scan_forests(
                        *a, fon, depth=2, n_forests=n_forests,
                        max_forest_wl=mfw), reps)
            row[f"{name}_flat_ms"] = round(flat * 1e3, 2)
            row[f"{name}_forest_ms"] = round(forest * 1e3, 2)
        rows.append(row)
    return rows


def measure_burst(devs, shapes, k_pair, reps: int) -> list[dict]:
    """The fused engine: K cycles per dispatch (ops.burst)."""
    import jax
    from kueue_tpu.ops.burst import burst_probe
    k1, k2 = k_pair
    rows = []
    for (label, C, M, R) in shapes:
        row = {"shape": label, "cqs": C, "pending_per_cq": M,
               "resources": R, "workloads": C * M}
        for name, dev in devs.items():
            with jax.default_device(dev):
                t1 = _median_time(lambda: burst_probe(C, M, R, k1), reps)
                t2 = _median_time(lambda: burst_probe(C, M, R, k2), reps)
            inc = (t2 - t1) / (k2 - k1)
            row[f"{name}_total_k{k2}_ms"] = round(t2 * 1e3, 2)
            row[f"{name}_per_cycle_incremental_ms"] = round(inc * 1e3, 3)
            row[f"{name}_per_cycle_amortized_k{k2}_ms"] = round(
                t2 / k2 * 1e3, 3)
        rows.append(row)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default="ROOFLINE_r04.json")
    args = ap.parse_args()
    reps = 3 if args.quick else 5

    import jax
    cpu = jax.devices("cpu")[0]
    default = jax.devices()[0]
    accel = default if default.platform != "cpu" else None
    devs = {"cpu": cpu}
    if accel is not None:
        devs["accel"] = accel

    out = {
        "metric": "accel_roofline",
        "accel_platform": accel.platform if accel is not None else None,
        "note": ("Measured on the real accelerator through this "
                 "environment's tunnel. accel wins iff RTT/K + "
                 "c_accel(shape) < c_cpu(shape)."),
    }
    if accel is not None:
        out["tunnel"] = measure_rtt(accel, reps)
        print(f"tunnel: {out['tunnel']}", file=sys.stderr)

    w_list = [1024, 8192] if args.quick else [1024, 8192, 65536]
    out["per_dispatch_admit_scan"] = measure_per_dispatch(devs, w_list, reps)
    for r in out["per_dispatch_admit_scan"]:
        print(f"per-dispatch: {r}", file=sys.stderr)

    shapes = [("northstar_100k_x_1k", 1000, 128, 1)]
    if not args.quick:
        shapes.append(("10x_northstar_1M_x_10k", 10_000, 100, 4))
    out["fused_burst"] = measure_burst(devs, shapes, (16, 64), reps)
    for r in out["fused_burst"]:
        print(f"fused burst: {r}", file=sys.stderr)

    # the decision model, evaluated on the measured numbers
    if accel is not None and out["fused_burst"]:
        ns = out["fused_burst"][0]
        rtt = out["tunnel"]["rtt_ms"]
        c_a = ns["accel_per_cycle_incremental_ms"]
        c_c = ns["cpu_per_cycle_incremental_ms"]
        out["crossover"] = {
            "model": "accel wins iff RTT/K + c_accel < c_cpu",
            "rtt_ms": rtt,
            "c_accel_ms_per_cycle": c_a,
            "c_cpu_ms_per_cycle": c_c,
            "accel_can_win_at_any_K": bool(c_a < c_c),
            "min_K_if_winnable": (int(np.ceil(rtt / (c_c - c_a)))
                                  if c_a < c_c else None),
            "conclusion": (
                "compute-bound in the chip's favor: fuse K cycles"
                if c_a < c_c else
                "XLA-CPU is the measured optimum at every K: the cycle is "
                "integer select/scatter logic with zero MXU content, and "
                "the CPU core executes it cache-resident faster than the "
                "accelerator's vector units even before the tunnel RTT. "
                "The calibrated router's refusal to dispatch the chip is "
                "the correct decision, now proven, not an accident."),
        }
        print(f"crossover: {out['crossover']}", file=sys.stderr)

    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "accel_roofline", "out": args.out,
                      "accel_measured": accel is not None}))


if __name__ == "__main__":
    main()
