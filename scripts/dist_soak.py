#!/usr/bin/env python
"""Distributed control-plane soak: real processes, real sockets, kills.

The control plane splits across OS processes — N submitter processes
hammer front-end shard processes through ``POST
/apis/serving/v1/submit``, federation workers run as ``WorkerServer``
processes behind ``HttpWorkerClient`` (optionally through a
``SocketFaultProxy``) — and a seeded :class:`ProcessSupervisor`
SIGKILLs them on a deterministic ``dist.kill`` schedule.

Arms, one artifact (DIST):

- **saturation** — wall-clock throughput search: every submitter
  process blasts uniquely-named submissions as fast as the wire
  allows, the shards drain the backlog through real ``/admin/step``
  cycles, and the round size doubles until the measured admissions/s
  stops improving; the ceiling is the best sustained rate.
- **kills** — four process-death arms, each recovering with zero lost
  and zero duplicated admissions and decisions bit-identical to a
  single-process control fed the same deterministic schedule:
  ``submitter`` (killed mid-run; replays its schedule from zero and
  every replay dedupes), ``front_end_shard`` (killed at a barrier;
  rebuilt from its IngestJournal + CycleWAL on the same port),
  ``service_mid_cycle`` (dies at an armed ``svc.cycle`` crashpoint
  inside ``/admin/step``, exit 17, no cleanup), and
  ``federation_worker`` (SIGKILLed behind a fault-injecting proxy;
  journal rebuild + fresh-watch-epoch resync over the wire keep every
  digest bit-identical to the in-process FederationSim control).
- **socket_faults** — the proxy's wire faults against the client's
  retry classification: connect-refused retries within the deadline,
  truncated responses count as mid-body and probe the watch epoch,
  blackholes end at the socket timeout.

Artifact: DIST_r20.json (see README "Distributed control plane").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_tpu.chaos import injector as chaos
from kueue_tpu.chaos.injector import ChaosInjector
from kueue_tpu.dist.proxy import FaultPlan, SocketFaultProxy
from kueue_tpu.dist.serving import ShardClient, build_shard_service, step_payloads
from kueue_tpu.dist.supervisor import ProcessSupervisor, child_argv
from kueue_tpu.features import env_int


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Harness pieces
# ---------------------------------------------------------------------------

def _shard_argv(tmp, shard_id, n_cqs, recover=False, resume_cycle=0,
                port=0, crash_site="", crash_at=0):
    pf = f"{tmp}/shard{shard_id}.port"
    kw = dict(shard_id=shard_id, n_cqs=n_cqs, state_dir=str(tmp),
              port_file=pf, recover=recover, resume_cycle=resume_cycle,
              port=port)
    if crash_site:
        kw.update(crash_site=crash_site, crash_at=crash_at)
    return child_argv("shard", **kw), pf


def _spawn_shards(sup, tmp, n_shards, n_cqs):
    shards = []
    for s in range(n_shards):
        argv, pf = _shard_argv(tmp, s, n_cqs)
        shards.append(sup.spawn(f"shard{s}", "shard", argv, port_file=pf))
    for mp in shards:
        sup.wait_ready(mp)
    return shards


def _spawn_submitter(sup, j, n_sub, per_step, n_cqs, ports):
    mp = sup.spawn(
        f"sub{j}", "submitter",
        child_argv("submitter", submitter_id=j, n_submitters=n_sub,
                   per_step=per_step, n_cqs=n_cqs,
                   shard_ports=",".join(map(str, ports))),
        pipe_stdio=True)
    assert mp.proc.stdout.readline().strip() == "ready"
    return mp


def _spawn_submitters(sup, tmp, n_sub, per_step, n_cqs, ports):
    return [_spawn_submitter(sup, j, n_sub, per_step, n_cqs, ports)
            for j in range(n_sub)]


def _cmd(mp, line: str) -> str:
    mp.proc.stdin.write(line + "\n")
    mp.proc.stdin.flush()
    return mp.proc.stdout.readline().strip()


def _cmd_all(subs, line: str) -> list[str]:
    for mp in subs:
        mp.proc.stdin.write(line + "\n")
        mp.proc.stdin.flush()
    return [mp.proc.stdout.readline().strip() for mp in subs]


def _control(tmp, n_cqs):
    os.makedirs(f"{tmp}/ctl", exist_ok=True)
    svc, _clock = build_shard_service(0, n_cqs, f"{tmp}/ctl")
    return svc


def _ctl_submit(svc, step, n_sub, per_step, n_cqs):
    for j in range(n_sub):
        for b in step_payloads(step, j, n_sub, per_step, n_cqs):
            svc.submit(name=b["name"], queue_name=b["queue_name"],
                       requests=b["requests"], priority=b["priority"],
                       namespace=b["namespace"], runtime_s=b["runtime_s"],
                       count=b["count"], token=b["token"])


def _lockstep(subs, clients, ctl_svc, step, cfg):
    """One barrier: submit, step every shard, replay into the control;
    returns (dist keys, ctl keys) for this step, each sorted."""
    _cmd_all(subs, f"step {step}")
    _ctl_submit(ctl_svc, step, len(subs), cfg["per_step"], cfg["cqs"])
    got = []
    for c in clients:
        st = c.step(retry_deadline_s=15.0)
        for dec in st["decisions"]:
            got.extend(dec)
    ctl = ctl_svc.step()
    want = [k for dec in ctl["decisions"] for k in dec]
    return sorted(got), sorted(want)


def _loss_dup(dist_keys: list, ctl_keys: list) -> tuple[int, int]:
    """Multiset compare of every admission key across the arm: keys
    the control admitted but the dist run lost, and keys the dist run
    admitted more often than the control (a double admission)."""
    d, c = Counter(dist_keys), Counter(ctl_keys)
    lost = sum((c - d).values())
    duplicated = sum((d - c).values())
    return lost, duplicated


def _merge_reports(reports: dict) -> dict:
    """Sum the per-arm supervisor reports into the artifact's dist
    block (spawns/kills/restarts by role, kill log tagged by arm)."""
    by_role: dict[str, dict[str, int]] = {}
    kill_log = []
    for arm, rep in reports.items():
        for role, st in rep["by_role"].items():
            per = by_role.setdefault(
                role, {"spawns": 0, "kills": 0, "restarts": 0})
            for k, v in st.items():
                per[k] += v
        kill_log.extend(f"{arm}:{name}" for name in rep["kill_log"])
    return {"by_role": by_role, "kill_log": kill_log,
            "per_arm": reports}


# ---------------------------------------------------------------------------
# saturation
# ---------------------------------------------------------------------------

def arm_saturation(cfg, seed, td):
    """Wall-clock admissions/s ceiling: blast rounds double until the
    measured end-to-end rate (accept over HTTP + drain through real
    step cycles) stops improving by >5%."""
    tmp = f"{td}/sat"
    os.makedirs(tmp, exist_ok=True)
    sup = ProcessSupervisor(seed=seed)
    rounds = []
    try:
        shards = _spawn_shards(sup, tmp, cfg["shards"], cfg["cqs"])
        ports = [mp.port for mp in shards]
        clients = [ShardClient(p) for p in ports]
        subs = _spawn_submitters(sup, tmp, cfg["submitters"],
                                 cfg["per_step"], cfg["cqs"], ports)
        n = cfg["sat_base"]
        best = 0.0
        for r in range(cfg["sat_max_rounds"]):
            t0 = time.monotonic()
            replies = _cmd_all(subs, f"blast {n}")
            accepted = sum(int(rep.split()[2]) for rep in replies)
            # drain: real step cycles until every accept is admitted
            steps = 0
            while steps < cfg["sat_drain_cap"]:
                stats = [c.svc_stats() for c in clients]
                if (sum(s["admitted"] for s in stats)
                        >= sum(s["accepted"] for s in stats)):
                    break
                for c in clients:
                    c.step(retry_deadline_s=15.0)
                steps += 1
            elapsed = time.monotonic() - t0
            stats = [c.svc_stats() for c in clients]
            drained = (sum(s["admitted"] for s in stats)
                       == sum(s["accepted"] for s in stats))
            rate = accepted / elapsed if elapsed > 0 else 0.0
            rounds.append({"n_per_submitter": n, "accepted": accepted,
                           "drain_steps": steps, "elapsed_s": elapsed,
                           "admissions_per_s": rate, "drained": drained})
            log(f"  saturation round {r}: n={n} adm/s={rate:.1f} "
                f"drain_steps={steps}")
            if not drained or (best > 0 and rate < best * 1.05):
                break
            best = max(best, rate)
            n *= 2
        depths = {str(i): c.svc_stats()["ingest_depth"]
                  for i, c in enumerate(clients)}
        rep = sup.report()
    finally:
        sup.terminate_all()
    ceiling = max((r["admissions_per_s"] for r in rounds), default=0.0)
    return {
        "wall_clock": True,
        "rounds": rounds,
        "ceiling_admissions_per_s": ceiling,
        "submitter_procs": cfg["submitters"],
        "shard_procs": cfg["shards"],
        "shard_depths": depths,
        "ok": ceiling > 0 and all(r["drained"] for r in rounds),
    }, rep


# ---------------------------------------------------------------------------
# kill arms
# ---------------------------------------------------------------------------

def arm_kill_front_end_shard(cfg, seed, td):
    """SIGKILL shard0 at a lockstep barrier via the armed ``dist.kill``
    site; rebuild it from IngestJournal + CycleWAL on the same port,
    resync the submitters, keep stepping — decisions bit-identical."""
    tmp = f"{td}/kshard"
    os.makedirs(tmp, exist_ok=True)
    sup = ProcessSupervisor(seed=seed)
    dist_keys, ctl_keys, per_step_ok = [], [], []
    try:
        shards = _spawn_shards(sup, tmp, cfg["shards"], cfg["cqs"])
        ports = [mp.port for mp in shards]
        clients = [ShardClient(p) for p in ports]
        subs = _spawn_submitters(sup, tmp, cfg["submitters"],
                                 cfg["per_step"], cfg["cqs"], ports)
        ctl_svc = _control(tmp, cfg["cqs"])
        half = cfg["kill_steps"] // 2
        for s in range(half):
            got, want = _lockstep(subs, clients, ctl_svc, s, cfg)
            dist_keys += got
            ctl_keys += want
            per_step_ok.append(got == want)

        inj = ChaosInjector(seed=seed)
        inj.arm("dist.kill", at=1, payload="shard0")
        chaos.install(inj)
        killed = sup.maybe_kill("shard0")
        chaos.clear()

        argv, _ = _shard_argv(tmp, 0, cfg["cqs"], recover=True,
                              resume_cycle=half, port=ports[0])
        sup.restart("shard0", argv=argv)
        same_port = shards[0].port == ports[0]
        # replay the whole delivered schedule; every replay dedupes
        replies = _cmd_all(subs, f"resync {half}")
        deduped = sum(int(r.split()[2]) for r in replies)
        expected_dedupes = len(subs) * half * cfg["per_step"]

        for s in range(half, cfg["kill_steps"]):
            got, want = _lockstep(subs, clients, ctl_svc, s, cfg)
            dist_keys += got
            ctl_keys += want
            per_step_ok.append(got == want)
        rep = sup.report()
    finally:
        sup.terminate_all()
    lost, duplicated = _loss_dup(dist_keys, ctl_keys)
    identical = all(per_step_ok)
    return {
        "killed": bool(killed), "same_port": same_port,
        "steps": cfg["kill_steps"], "admissions": len(ctl_keys),
        "decisions_identical": identical, "parity": identical,
        "lost": lost, "duplicated": duplicated,
        "dedupe": {"replayed": deduped, "expected": expected_dedupes},
        "restarts": rep["by_role"]["shard"]["restarts"],
        "ok": (killed and same_port and identical and lost == 0
               and duplicated == 0 and deduped == expected_dedupes),
    }, rep


def arm_kill_submitter(cfg, seed, td):
    """SIGKILL one submitter process mid-run; the respawn replays its
    deterministic schedule from zero and every delivered submission
    dedupes — the shards admit nothing twice."""
    tmp = f"{td}/ksub"
    os.makedirs(tmp, exist_ok=True)
    sup = ProcessSupervisor(seed=seed)
    dist_keys, ctl_keys, per_step_ok = [], [], []
    try:
        shards = _spawn_shards(sup, tmp, cfg["shards"], cfg["cqs"])
        ports = [mp.port for mp in shards]
        clients = [ShardClient(p) for p in ports]
        subs = _spawn_submitters(sup, tmp, cfg["submitters"],
                                 cfg["per_step"], cfg["cqs"], ports)
        ctl_svc = _control(tmp, cfg["cqs"])
        half = cfg["kill_steps"] // 2
        for s in range(half):
            got, want = _lockstep(subs, clients, ctl_svc, s, cfg)
            dist_keys += got
            ctl_keys += want
            per_step_ok.append(got == want)

        inj = ChaosInjector(seed=seed)
        inj.arm("dist.kill", at=1, payload="sub0")
        chaos.install(inj)
        killed = sup.maybe_kill("sub0")
        chaos.clear()

        # respawn with the SAME identity (submitter_id 0 of N): the
        # deterministic schedule it replays must be the one it owned
        sub0 = _spawn_submitter(sup, 0, cfg["submitters"],
                                cfg["per_step"], cfg["cqs"], ports)
        subs[0] = sub0
        deduped = int(_cmd(sub0, f"resync {half}").split()[2])
        expected_dedupes = half * cfg["per_step"]

        for s in range(half, cfg["kill_steps"]):
            got, want = _lockstep(subs, clients, ctl_svc, s, cfg)
            dist_keys += got
            ctl_keys += want
            per_step_ok.append(got == want)
        rep = sup.report()
    finally:
        sup.terminate_all()
    lost, duplicated = _loss_dup(dist_keys, ctl_keys)
    identical = all(per_step_ok)
    return {
        "killed": bool(killed), "steps": cfg["kill_steps"],
        "admissions": len(ctl_keys),
        "decisions_identical": identical, "parity": identical,
        "lost": lost, "duplicated": duplicated,
        "dedupe": {"replayed": deduped, "expected": expected_dedupes},
        "restarts": rep["by_role"]["submitter"]["kills"],
        "ok": (killed and identical and lost == 0 and duplicated == 0
               and deduped == expected_dedupes),
    }, rep


def arm_kill_service_mid_cycle(cfg, seed, td):
    """The service process dies *inside* ``/admin/step`` at its own
    armed ``svc.cycle`` crashpoint (exit 17, no cleanup); recovery
    from the journals plus a re-issued step lands on the control's
    exact decisions."""
    tmp = f"{td}/ksvc"
    os.makedirs(tmp, exist_ok=True)
    sup = ProcessSupervisor(seed=seed)
    dist_keys, ctl_keys, per_step_ok = [], [], []
    crashes = 0
    crash_exit = None
    try:
        argv, pf = _shard_argv(tmp, 0, cfg["cqs"],
                               crash_site="svc.cycle", crash_at=2)
        mp = sup.spawn("shard0", "shard", argv, port_file=pf)
        sup.wait_ready(mp)
        port = mp.port
        ctl_svc = _control(tmp, cfg["cqs"])
        client = ShardClient(port)
        for s in range(cfg["kill_steps"]):
            for b in step_payloads(s, 0, 1, cfg["per_step"], cfg["cqs"]):
                client.submit(b, retry_deadline_s=5.0)
            _ctl_submit(ctl_svc, s, 1, cfg["per_step"], cfg["cqs"])
            try:
                st = client.step()
            except Exception:
                mp.proc.wait(timeout=10)
                crash_exit = mp.proc.returncode
                crashes += 1
                argv, _ = _shard_argv(tmp, 0, cfg["cqs"], recover=True,
                                      resume_cycle=s, port=port)
                sup.restart("shard0", argv=argv)
                st = client.step(retry_deadline_s=10.0)
            got = sorted(k for dec in st["decisions"] for k in dec)
            ctl = ctl_svc.step()
            want = sorted(k for dec in ctl["decisions"] for k in dec)
            dist_keys += got
            ctl_keys += want
            per_step_ok.append(got == want)
        rep = sup.report()
    finally:
        sup.terminate_all()
    lost, duplicated = _loss_dup(dist_keys, ctl_keys)
    identical = all(per_step_ok)
    return {
        "crashes": crashes, "crash_exit": crash_exit,
        "steps": cfg["kill_steps"], "admissions": len(ctl_keys),
        "decisions_identical": identical, "parity": identical,
        "lost": lost, "duplicated": duplicated,
        "restarts": rep["by_role"]["shard"]["restarts"],
        "ok": (crashes == 1 and crash_exit == 17 and identical
               and lost == 0 and duplicated == 0),
    }, rep


def arm_kill_federation_worker(cfg, seed, td):
    """SIGKILL a federation worker process behind a fault-injecting
    socket proxy; its journal rebuild + fresh-watch-epoch resync over
    the real wire keep every digest bit-identical to the in-process
    FederationSim control — while the proxy's seeded resets, latency,
    and an armed truncate chew on the manager's RPCs."""
    from kueue_tpu.federation.procs import ProcFederation, fed_traffic
    from kueue_tpu.federation.sim import FederationSim, FedSpec
    from kueue_tpu.remote import state_digest
    tmp = f"{td}/kfed"
    os.makedirs(tmp, exist_ok=True)
    n_cqs, remote_cqs = cfg["fed_cqs"], cfg["fed_remote_cqs"]
    sup = ProcessSupervisor(seed=seed)
    proxies = []
    try:
        def worker_argv(name, recover=False, resume_t=None, port=0):
            pf = f"{tmp}/{name}.port"
            return child_argv(
                "worker", name=name, remote_cqs=remote_cqs,
                state_dir=tmp, port_file=pf, recover=recover,
                resume_t=resume_t, port=port), pf

        names = [f"w{i}" for i in range(cfg["workers"])]
        workers = {}
        for name in names:
            argv, pf = worker_argv(name)
            workers[name] = sup.spawn(name, "worker", argv, port_file=pf)
        for mp in workers.values():
            sup.wait_ready(mp)

        # wire faults: a seeded probability plan plus one armed
        # truncate — retries and the epoch probe must absorb them all
        inj = ChaosInjector(seed=seed)
        inj.arm("dist.proxy_fault", at=3, action="truncate", payload=16)
        inj.arm("dist.proxy_fault", at=9, action="reset")
        chaos.install(inj)
        plan = FaultPlan.resolved(reset=cfg["proxy_reset"],
                                  latency=cfg["proxy_latency"],
                                  latency_s=0.02)
        urls = {}
        for name, mp in workers.items():
            px = SocketFaultProxy(mp.port, seed=seed, plan=plan)
            px.start()
            proxies.append(px)
            urls[name] = px.base_url

        traffic = fed_traffic(steps=cfg["fed_traffic_steps"],
                              per_step=2, n_cqs=n_cqs)
        fed = ProcFederation(urls, n_cqs=n_cqs, remote_cqs=remote_cqs,
                             client_timeout=2.0, client_retries=4)
        fed.load_traffic(traffic)
        spec = FedSpec(n_workers=cfg["workers"], n_cqs=n_cqs,
                       remote_cqs=remote_cqs, manager_quota_m=8000,
                       worker_quota_m=4000, runtime_steps=2,
                       worker_lost_timeout=3.0, reconnect_budget=0)
        ctl = FederationSim(spec, wal_dir=f"{tmp}/ctl")
        ctl.load_traffic(dict(traffic))

        pre = cfg["fed_pre_kill_steps"]
        for _ in range(pre):
            fed.step()
            ctl.step()

        port0 = workers["w0"].port
        inj.arm("dist.kill", at=1, payload="w0")
        killed = sup.maybe_kill("w0")
        argv, _ = worker_argv("w0", recover=True, resume_t=fed.clock.t,
                              port=port0)
        sup.restart("w0", argv=argv)

        for _ in range(cfg["fed_post_kill_steps"]):
            fed.step()
            ctl.step()

        dg = fed.digests()
        worker_parity = all(
            dg["workers"][n] == state_digest(ctl.workers[n])
            for n in urls)
        manager_parity = dg["manager"] == state_digest(ctl.manager)
        settled = fed.settled() and ctl.settled()
        cl_stats = fed.client_stats()
        resyncs = cl_stats["w0"]["epoch_resyncs"]
        proxy_stats = Counter()
        for px in proxies:
            proxy_stats.update(px.stats)

        # feed the distributed counters through Driver.stats so the
        # kueue_dist_* / kueue_rpc_* series sample from a live run
        fed.manager.rpc_clients = list(fed.clients.values())
        fed.manager.dist_stats = {
            "by_role": sup.stats, "proxy": dict(proxy_stats),
            "shard_depths": {}}
        mstats = fed.manager.stats
        rep = sup.report()
        unfinished = sum(1 for wl in fed.manager.workloads.values()
                         if not wl.is_finished)
        duplicated = len(fed.violations) + len(ctl.violations)
    finally:
        chaos.clear()
        for px in proxies:
            px.stop()
        sup.terminate_all()
    parity = manager_parity and worker_parity
    return {
        "killed": bool(killed),
        "steps": pre + cfg["fed_post_kill_steps"],
        "manager_parity": manager_parity,
        "worker_parity": worker_parity,
        "decisions_identical": parity, "parity": parity,
        "settled": settled,
        "lost": 0 if settled else unfinished,
        "duplicated": duplicated,
        "epoch_resyncs": resyncs,
        "client_stats": cl_stats,
        "proxy": dict(proxy_stats),
        "restarts": rep["by_role"]["worker"]["restarts"],
        "metrics": {"rpc": mstats.get("rpc"), "dist": mstats.get("dist")},
        "ok": (killed and parity and settled and duplicated == 0
               and resyncs >= 1),
    }, rep


# ---------------------------------------------------------------------------
# socket faults
# ---------------------------------------------------------------------------

def arm_socket_faults(cfg, seed, td):
    """Classification checks against a live worker process: refused vs
    mid-body vs timeout, counted separately, epoch probed behind the
    truncate."""
    import socket as _socket

    from kueue_tpu.remote import ConnectionLost, HttpWorkerClient
    tmp = f"{td}/sock"
    os.makedirs(tmp, exist_ok=True)
    sup = ProcessSupervisor(seed=seed)
    px = None
    try:
        pf = f"{tmp}/w0.port"
        argv = child_argv("worker", name="w0", remote_cqs=2,
                          state_dir=tmp, port_file=pf)
        mp = sup.spawn("w0", "worker", argv, port_file=pf)
        sup.wait_ready(mp)

        inj = ChaosInjector(seed=seed)
        inj.arm("dist.proxy_fault", at=2, action="reset")
        inj.arm("dist.proxy_fault", at=4, action="truncate", payload=16)
        inj.arm("dist.proxy_fault", at=6, action="latency", payload=0.2)
        inj.arm("dist.proxy_fault", at=8, action="blackhole")
        chaos.install(inj)
        px = SocketFaultProxy(mp.port, seed=seed)
        px.start()
        cl = HttpWorkerClient(px.base_url, timeout=1.0, retries=4,
                              backoff_base=0.01, backoff_max=0.05,
                              deadline_s=10.0)
        for _ in range(10):
            cl.admin_status()   # retries absorb every armed fault
        survived = True
        chaos.clear()

        # nothing listening: pure connect-refused classification
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        cl2 = HttpWorkerClient(f"http://127.0.0.1:{dead_port}",
                               timeout=1.0, retries=2, backoff_base=0.01,
                               backoff_max=0.02, deadline_s=5.0)
        refused_kind = None
        try:
            cl2.admin_status()
        except ConnectionLost as e:
            refused_kind = e.kind
        rep = sup.report()
    finally:
        chaos.clear()
        if px is not None:
            px.stop()
        sup.terminate_all()
    ok = (survived and px.stats["resets"] == 1
          and px.stats["truncations"] == 1
          and px.stats["latencies"] == 1
          and px.stats["blackholes"] == 1
          and cl.stats["midbody_retries"] >= 1
          and cl.stats["retries"] >= 3
          and refused_kind == "refused"
          and cl2.stats["refused_retries"] == 2)
    return {
        "proxy": dict(px.stats),
        "client": dict(cl.stats),
        "refused_kind": refused_kind,
        "refused_retries": cl2.stats["refused_retries"],
        "ok": ok,
    }, rep


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int,
                    default=env_int("KUEUE_TPU_DIST_SEED"))
    ap.add_argument("--shards", type=int,
                    default=env_int("KUEUE_TPU_DIST_SHARDS"))
    ap.add_argument("--submitters", type=int,
                    default=env_int("KUEUE_TPU_DIST_SUBMITTERS"))
    ap.add_argument("--workers", type=int,
                    default=env_int("KUEUE_TPU_DIST_WORKERS"))
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized: small blasts, short lockstep arms")
    ap.add_argument("--out", default="DIST_r20.json")
    args = ap.parse_args()

    cfg = {
        "cqs": 8,
        "shards": max(2, args.shards),
        "submitters": max(2, args.submitters),
        "workers": max(2, args.workers),
        "per_step": 3 if args.quick else 4,
        "kill_steps": 4 if args.quick else 8,
        "sat_base": 16 if args.quick else 48,
        "sat_max_rounds": 2 if args.quick else 5,
        "sat_drain_cap": 400,
        "fed_cqs": 6,
        "fed_remote_cqs": 4,
        "fed_traffic_steps": 3 if args.quick else 5,
        "fed_pre_kill_steps": 3,
        "fed_post_kill_steps": 4 if args.quick else 7,
        "proxy_reset": 0.03,
        "proxy_latency": 0.05,
    }
    seed = args.seed
    t0 = time.perf_counter()
    reports = {}
    with tempfile.TemporaryDirectory() as td:
        log(f"dist soak: seed={seed} shards={cfg['shards']} "
            f"submitters={cfg['submitters']} workers={cfg['workers']} "
            f"quick={args.quick}")
        log("arm: saturation")
        saturation, reports["saturation"] = arm_saturation(cfg, seed, td)
        log(f"  ceiling={saturation['ceiling_admissions_per_s']:.1f}/s "
            f"ok={saturation['ok']}")
        log("arm: kill front_end_shard")
        k_shard, reports["front_end_shard"] = arm_kill_front_end_shard(
            cfg, seed + 1, td)
        log(f"  parity={k_shard['parity']} lost={k_shard['lost']} "
            f"dup={k_shard['duplicated']}")
        log("arm: kill submitter")
        k_sub, reports["submitter"] = arm_kill_submitter(cfg, seed + 2, td)
        log(f"  parity={k_sub['parity']} lost={k_sub['lost']} "
            f"dup={k_sub['duplicated']}")
        log("arm: kill service_mid_cycle")
        k_svc, reports["service_mid_cycle"] = arm_kill_service_mid_cycle(
            cfg, seed + 3, td)
        log(f"  parity={k_svc['parity']} crashes={k_svc['crashes']} "
            f"exit={k_svc['crash_exit']}")
        log("arm: kill federation_worker")
        k_fed, reports["federation_worker"] = arm_kill_federation_worker(
            cfg, seed + 4, td)
        log(f"  parity={k_fed['parity']} settled={k_fed['settled']} "
            f"epoch_resyncs={k_fed['epoch_resyncs']}")
        log("arm: socket_faults")
        sock, reports["socket_faults"] = arm_socket_faults(
            cfg, seed + 5, td)
        log(f"  ok={sock['ok']} client={sock['client']}")

    kills = {"submitter": k_sub, "front_end_shard": k_shard,
             "service_mid_cycle": k_svc, "federation_worker": k_fed}
    all_ok = (saturation["ok"] and sock["ok"]
              and all(arm["ok"] for arm in kills.values()))
    art = {
        "metric": "dist_soak_saturation_admissions_per_s",
        "unit": "admissions/s",
        "value": saturation["ceiling_admissions_per_s"],
        "seed": seed,
        "quick": bool(args.quick),
        "config": cfg,
        "saturation": saturation,
        "kills": kills,
        "socket_faults": sock,
        "dist": _merge_reports(reports),
        "metrics": k_fed.pop("metrics"),
        "all_ok": all_ok,
        "elapsed_s": time.perf_counter() - t0,
    }
    with open(args.out, "w") as fh:
        json.dump(art, fh, indent=1, sort_keys=True)
    log(f"wrote {args.out} (all_ok={all_ok}, {art['elapsed_s']:.1f}s)")
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
