"""Schema check for the repo's run artifacts (``*_r*.json``).

The artifact files are the repo's durable experimental record; a
truncated write, a hand edit, or a schema drift in a generator script
should fail fast in CI instead of surfacing months later as an
unreadable number.  Checks are tiered:

  every artifact   — parses as JSON, top level is a non-empty object,
                     and any of the common optional fields that ARE
                     present have the right shape (``metric`` str,
                     ``value`` number/null, ``unit`` str, ``cqs`` int,
                     ``mesh`` a dict with int ``n_devices`` and str
                     ``platform``).
  CHAOS_*          — additionally: a non-empty ``scenarios`` object
                     whose entries each carry ``decisions_stable``
                     bool + list ``failures`` (or ``skipped`` true
                     with a ``reason``), plus ``all_stable`` /
                     ``scenarios_total`` / ``scenarios_stable``
                     consistent with the per-scenario verdicts.
  TRAFFIC_*        — additionally: the SLO + arrival-process params,
                     per-arm ``sustainable_rate_per_s`` with a
                     per-rate latency ``curve`` (histograms included),
                     an ``interleaved`` control arm, a bool
                     ``replay_identical``, and the
                     ``snapshot_counters`` host-cost block.
  NORTHSTAR_* /
  MULTICHIP_r08+   — additionally: ``metric`` + numeric ``value``.
  LINT_*           — additionally: the named analysis passes (a prefix
                     of the canonical roster; metrics-doc joined at
                     r16), a
                     ``findings`` list whose length equals ``value``,
                     ``ok`` consistent with findings/stale entries, a
                     strictly-shrinking baseline
                     (``baseline_entries`` < ``first_full_run_findings``),
                     and a sub-10s ``elapsed_s`` (the lint is tier-1).
  OBS_*            — additionally: an interleaved untraced ``control``
                     arm, ``decisions_identical`` true, an ``overhead``
                     block whose ratio stays <= 1.05, a ``spans``
                     roster covering every host hot-path phase, working
                     ``dumps`` surfaces, and the ``obs`` block itself.
                     NORTHSTAR/TRAFFIC/FED artifacts from r16 on must
                     also carry an ``obs`` block.
  MULTICHIP_r10+   — additionally: at least one ``crossover`` block
                     (top level or per-``runs`` entry) whose ``curve``
                     lists one entry per shard arm with int ``shards``,
                     numeric ``p99_ms``, bool ``decisions_stable`` and
                     bool ``completed``, plus a bool
                     ``decisions_identical_across_arms``; sharded
                     arms (shards > 1) also carry an ``imbalance``
                     object and the ``boundary_bytes_h2d`` /
                     ``boundary_bytes_equiv`` pair.

Usage:
    python scripts/validate_artifacts.py [paths...]

With no paths, scans the repo root for ``*_r*.json``.  Exits non-zero
on any violation, listing every one.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys


def _err(out, path, msg):
    out.append(f"{os.path.basename(path)}: {msg}")


def _check_common(d, path, out):
    if not isinstance(d, dict) or not d:
        _err(out, path, "top level must be a non-empty JSON object")
        return False
    if "metric" in d and not isinstance(d["metric"], str):
        _err(out, path, "'metric' must be a string")
    if "value" in d and d["value"] is not None \
            and not isinstance(d["value"], (int, float)):
        _err(out, path, "'value' must be a number or null")
    if "unit" in d and not isinstance(d["unit"], str):
        _err(out, path, "'unit' must be a string")
    if "cqs" in d and not isinstance(d["cqs"], int):
        _err(out, path, "'cqs' must be an int")
    mesh = d.get("mesh")
    if mesh is not None:
        if not isinstance(mesh, dict):
            _err(out, path, "'mesh' must be an object")
        else:
            if not isinstance(mesh.get("n_devices"), int):
                _err(out, path, "'mesh.n_devices' must be an int")
            if not isinstance(mesh.get("platform"), str):
                _err(out, path, "'mesh.platform' must be a string")
    return True


def _check_chaos(d, path, out):
    scenarios = d.get("scenarios")
    if not isinstance(scenarios, dict) or not scenarios:
        _err(out, path, "'scenarios' must be a non-empty object")
        return
    n_ran = n_stable = 0
    for name, s in scenarios.items():
        if not isinstance(s, dict):
            _err(out, path, f"scenario '{name}' must be an object")
            continue
        if s.get("skipped"):
            if not isinstance(s.get("reason"), str):
                _err(out, path, f"skipped scenario '{name}' needs a "
                     "'reason' string")
            continue
        n_ran += 1
        if not isinstance(s.get("decisions_stable"), bool):
            _err(out, path, f"scenario '{name}' missing bool "
                 "'decisions_stable'")
            continue
        if not isinstance(s.get("failures"), list):
            _err(out, path, f"scenario '{name}' missing 'failures' list")
        if s["decisions_stable"]:
            n_stable += 1
            if s.get("failures"):
                _err(out, path, f"scenario '{name}' claims stable but "
                     f"lists failures: {s['failures'][:2]}")
    if not isinstance(d.get("all_stable"), bool):
        _err(out, path, "missing bool 'all_stable'")
    elif d["all_stable"] != (n_ran > 0 and n_stable == n_ran):
        _err(out, path, f"'all_stable'={d['all_stable']} inconsistent "
             f"with {n_stable}/{n_ran} stable scenarios")
    if d.get("scenarios_total") != n_ran:
        _err(out, path, f"'scenarios_total'={d.get('scenarios_total')} "
             f"but {n_ran} scenarios ran")
    if d.get("scenarios_stable") != n_stable:
        _err(out, path, f"'scenarios_stable'={d.get('scenarios_stable')} "
             f"but {n_stable} verdicts are stable")


def _check_metric_value(d, path, out):
    if not isinstance(d.get("metric"), str):
        _err(out, path, "missing string 'metric'")
    if not isinstance(d.get("value"), (int, float)):
        _err(out, path, "missing numeric 'value'")


def _crossover_blocks(d):
    """Every SHARD-crossover block in an artifact: top level, or one
    per entry of a multi-scenario ``runs`` wrapper.  Keyed on the
    ``curve``/``arms`` shape — ROOFLINE_* reuses the 'crossover' name
    for the accel break-even model, which is not this schema."""
    blocks = []
    c = d.get("crossover")
    if isinstance(c, dict) and ("curve" in c or "arms" in c):
        blocks.append(("crossover", c))
    runs = d.get("runs")
    if isinstance(runs, dict):
        for name, r in runs.items():
            if not isinstance(r, dict):
                continue
            c = r.get("crossover")
            if isinstance(c, dict) and ("curve" in c or "arms" in c):
                blocks.append((f"runs.{name}.crossover", c))
    return blocks


def _check_crossover(label, c, path, out):
    curve = c.get("curve")
    if not isinstance(curve, list) or len(curve) < 2:
        _err(out, path, f"'{label}.curve' must list >= 2 shard arms")
        return
    for e in curve:
        if not isinstance(e, dict):
            _err(out, path, f"'{label}.curve' entries must be objects")
            continue
        n = e.get("shards")
        if not isinstance(n, int) or n < 1:
            _err(out, path, f"'{label}' arm missing int 'shards' >= 1")
            continue
        if not isinstance(e.get("p99_ms"), (int, float)):
            _err(out, path, f"'{label}' arm {n}: missing numeric "
                 "'p99_ms'")
        for k in ("decisions_stable", "completed"):
            if not isinstance(e.get(k), bool):
                _err(out, path, f"'{label}' arm {n}: missing bool "
                     f"'{k}'")
        if n > 1:
            if not isinstance(e.get("imbalance"), dict):
                _err(out, path, f"'{label}' arm {n}: missing "
                     "'imbalance' object")
            for k in ("boundary_bytes_h2d", "boundary_bytes_equiv"):
                if not isinstance(e.get(k), int):
                    _err(out, path, f"'{label}' arm {n}: missing int "
                         f"'{k}'")
    if not isinstance(c.get("decisions_identical_across_arms"), bool):
        _err(out, path, f"'{label}' missing bool "
             "'decisions_identical_across_arms'")


def _check_hetero(d, path, out):
    """NORTHSTAR heterogeneous artifacts (scripts/northstar_e2e.py
    --ab-hetero): the in-kernel fungibility arm's fallback counters, the
    zero-host-fallback verdict consistent with them, cross-arm decision
    identity, the p99 comparison against the interleaved host oracle,
    and the environment-drift block with its fallback-counter record."""
    h = d.get("hetero")
    if not isinstance(h, dict):
        _err(out, path, "'hetero' must be an object")
        return
    for k in ("flavors", "resources"):
        if not isinstance(h.get(k), int) or h[k] < 1:
            _err(out, path, f"'hetero.{k}' must be an int >= 1")
    if isinstance(h.get("flavors"), int) and h["flavors"] < 2:
        _err(out, path, "'hetero.flavors' must be >= 2 (a single-flavor "
             "run has no fungibility walk to measure)")
    fb = h.get("fallbacks")
    if not isinstance(fb, dict):
        _err(out, path, "'hetero.fallbacks' must be an object")
        fb = {}
    else:
        for k in ("host_cycles", "scalar_heads", "native_ff_fallbacks",
                  "burst_dirty_cycles", "burst_dirty_preempt",
                  "burst_dirty_scalar", "burst_dirty_resume"):
            if not isinstance(fb.get(k), int):
                _err(out, path, f"'hetero.fallbacks.{k}' must be an int")
    zero = h.get("zero_host_fallbacks")
    if not isinstance(zero, bool):
        _err(out, path, "'hetero' missing bool 'zero_host_fallbacks'")
    elif isinstance(fb.get("host_cycles"), int) \
            and isinstance(fb.get("scalar_heads"), int) \
            and zero != (fb["host_cycles"] == 0
                         and fb["scalar_heads"] == 0):
        _err(out, path, f"'hetero.zero_host_fallbacks'={zero} "
             "inconsistent with the fallback counters")
    for k in ("decisions_identical_across_arms",
              "in_kernel_beats_host_p99"):
        if not isinstance(h.get(k), bool):
            _err(out, path, f"'hetero' missing bool '{k}'")
    for k in ("p99_ms_in_kernel", "p99_ms_host"):
        if not isinstance(h.get(k), (int, float)):
            _err(out, path, f"'hetero' missing numeric '{k}'")
    drift = h.get("drift")
    if not isinstance(drift, dict):
        _err(out, path, "'hetero.drift' must be an object (see "
             "perf/harness.ab_block)")
    else:
        env = drift.get("environment_drift")
        if not isinstance(env, dict) or env.get("interleaved") is not True \
                or not isinstance(env.get("fallback_counters"), dict):
            _err(out, path, "'hetero.drift.environment_drift' must carry "
                 "interleaved=true and a 'fallback_counters' object")


def _check_scale(d, path, out):
    """SCALE_* scaling-law artifacts (scripts/scale_soak.py): the
    per-universe-size curve (streaming vs rebuild host pack ms measured
    on the same state, end-to-end cycle cost, bytes-to-device, RSS,
    per-size parity verdicts), the all-sizes parity booleans, the
    completed high-count workload soak, and the interleaved same-box
    control arm."""
    curve = d.get("curve")
    if not isinstance(curve, list) or not curve:
        _err(out, path, "'curve' must be a non-empty list of sizes")
        curve = []
    for e in curve:
        if not isinstance(e, dict):
            _err(out, path, "'curve' entries must be objects")
            continue
        n = e.get("cqs")
        if not isinstance(n, int) or n < 1:
            _err(out, path, "'curve' entry missing int 'cqs' >= 1")
            continue
        for k in ("pack_ms_stream", "pack_ms_rebuild",
                  "cycle_wall_ms", "rss_mb"):
            if not isinstance(e.get(k), (int, float)):
                _err(out, path, f"'curve' size {n}: missing numeric "
                     f"'{k}'")
        for k in ("bytes_to_device", "bytes_to_device_raw"):
            if not isinstance(e.get(k), int):
                _err(out, path, f"'curve' size {n}: missing int '{k}'")
        for k in ("planes_identical", "decisions_identical"):
            if not isinstance(e.get(k), bool):
                _err(out, path, f"'curve' size {n}: missing bool '{k}'")
    parity = d.get("parity")
    if not isinstance(parity, dict):
        _err(out, path, "'parity' must be an object")
    else:
        for k in ("planes_identical_all", "decisions_identical_all"):
            v = parity.get(k)
            if not isinstance(v, bool):
                _err(out, path, f"'parity.{k}' must be a bool")
            elif curve and all(isinstance(e, dict) for e in curve):
                per = k.rsplit("_", 1)[0]
                got = all(e.get(per) is True for e in curve)
                if v != got:
                    _err(out, path, f"'parity.{k}'={v} inconsistent "
                         "with the per-size verdicts")
    soak = d.get("soak")
    if not isinstance(soak, dict):
        _err(out, path, "'soak' must be an object")
    else:
        for k in ("target_workloads", "created", "admitted", "rounds"):
            if not isinstance(soak.get(k), int):
                _err(out, path, f"'soak.{k}' must be an int")
        done = soak.get("completed")
        if not isinstance(done, bool):
            _err(out, path, "'soak.completed' must be a bool")
        elif isinstance(soak.get("created"), int) \
                and isinstance(soak.get("target_workloads"), int) \
                and done != (soak["created"] >= soak["target_workloads"]):
            _err(out, path, f"'soak.completed'={done} inconsistent with "
                 f"created={soak['created']} vs "
                 f"target={soak['target_workloads']}")
    control = d.get("control")
    if not isinstance(control, dict) \
            or control.get("interleaved") is not True:
        _err(out, path, "'control' must be an object with "
             "interleaved=true (same-box environment-drift arm)")
    rnd = re.match(r"SCALE_R(\d+)", os.path.basename(path).upper())
    if rnd and int(rnd.group(1)) >= 18:
        _check_scale_r18(d, path, out, curve)
    if rnd and int(rnd.group(1)) >= 19:
        _check_scale_r19(d, path, out)


def _check_scale_r18(d, path, out, curve):
    """SCALE_r18+ (scripts/scale_soak.py, ISSUE 16): the classic
    (all-scale-optimizations-off) bit-identity arm per size, the lifted
    row ceiling, the aggregate/heap/wal_shard measurement blocks, and
    the machine-readable residue ledger with named walls."""
    for e in curve:
        if not isinstance(e, dict) or not isinstance(e.get("cqs"), int):
            continue
        n = e["cqs"]
        if not isinstance(e.get("decisions_identical_classic"), bool):
            _err(out, path, f"'curve' size {n}: missing bool "
                 "'decisions_identical_classic' (r18 classic arm)")
        for k in ("host_apply_ms", "host_apply_ms_classic"):
            if not isinstance(e.get(k), (int, float)):
                _err(out, path, f"'curve' size {n}: missing numeric "
                     f"'{k}'")
        for k in ("live_rows", "rows_row_backed"):
            if not isinstance(e.get(k), int):
                _err(out, path, f"'curve' size {n}: missing int '{k}'")
    parity = d.get("parity") if isinstance(d.get("parity"), dict) else {}
    for k in ("decisions_identical_classic_all", "max_res_ts_equal_all"):
        if not isinstance(parity.get(k), bool):
            _err(out, path, f"'parity.{k}' must be a bool (r18)")
    if isinstance(parity.get("decisions_identical_classic_all"), bool) \
            and curve and all(isinstance(e, dict) for e in curve):
        got = all(e.get("decisions_identical_classic") is True
                  for e in curve)
        if parity["decisions_identical_classic_all"] != got:
            _err(out, path, "'parity.decisions_identical_classic_all' "
                 "inconsistent with the per-size verdicts")
    ceiling = d.get("ceiling")
    if not isinstance(ceiling, dict):
        _err(out, path, "r18 artifacts must carry a 'ceiling' block")
        ceiling = {}
    for k in ("cqs", "row_budget", "live_rows", "rows_packed",
              "rows_row_backed"):
        if not isinstance(ceiling.get(k), int):
            _err(out, path, f"'ceiling.{k}' must be an int")
    for k in ("packed_under_budget", "row_backed_over_budget"):
        if not isinstance(ceiling.get(k), bool):
            _err(out, path, f"'ceiling.{k}' must be a bool")
    if isinstance(ceiling.get("rows_packed"), int) \
            and isinstance(ceiling.get("row_budget"), int) \
            and isinstance(ceiling.get("packed_under_budget"), bool) \
            and ceiling["packed_under_budget"] != (
                ceiling["rows_packed"] < ceiling["row_budget"]):
        _err(out, path, "'ceiling.packed_under_budget' inconsistent "
             "with rows_packed vs row_budget")
    rnd = ceiling.get("round")
    if not isinstance(rnd, dict) \
            or not isinstance(rnd.get("wall_s"), (int, float)):
        _err(out, path, "'ceiling.round' must carry numeric 'wall_s' "
             "(the honest per-round wall at the ceiling size)")
    agg = d.get("aggregate")
    if not isinstance(agg, dict):
        _err(out, path, "r18 artifacts must carry an 'aggregate' block")
        agg = {}
    if agg.get("max_res_ts_equal_all") is not True:
        _err(out, path, "'aggregate.max_res_ts_equal_all' must be "
             "true: compression must not move the clock anchor")
    pts = agg.get("points")
    if not isinstance(pts, list) or not pts:
        _err(out, path, "'aggregate.points' must be a non-empty list")
    else:
        for p in pts:
            if not isinstance(p, dict):
                _err(out, path, "'aggregate.points' entries must be "
                     "objects")
                continue
            for k in ("cqs", "live_rows", "rows_packed",
                      "rows_row_backed"):
                if not isinstance(p.get(k), int):
                    _err(out, path, f"'aggregate.points[].{k}' must "
                         "be an int")
            if isinstance(p.get("rows_packed"), int) \
                    and isinstance(p.get("rows_row_backed"), int) \
                    and p["rows_packed"] > p["rows_row_backed"]:
                _err(out, path, "'aggregate.points[]': rows_packed "
                     "must not exceed rows_row_backed")
    heap = d.get("heap")
    if not isinstance(heap, dict):
        _err(out, path, "r18 artifacts must carry a 'heap' block")
        heap = {}
    micro = heap.get("microbench")
    if not isinstance(micro, dict):
        _err(out, path, "'heap.microbench' must be an object")
        micro = {}
    if micro.get("order_parity") is not True:
        _err(out, path, "'heap.microbench.order_parity' must be true: "
             "lazy repair must pop the identical sequence")
    mpts = micro.get("points")
    if not isinstance(mpts, list) or not mpts:
        _err(out, path, "'heap.microbench.points' must be a non-empty "
             "list")
    else:
        for p in mpts:
            for k in ("eager_ms_per_cycle", "lazy_ms_per_cycle",
                      "speedup"):
                if not isinstance(p, dict) \
                        or not isinstance(p.get(k), (int, float)):
                    _err(out, path, "'heap.microbench.points[]' must "
                         f"carry numeric '{k}'")
                    break
    dha = heap.get("driver_host_apply")
    if not isinstance(dha, dict):
        _err(out, path, "'heap.driver_host_apply' must be an object")
    else:
        for k in ("optimized_ms_per_cycle", "classic_ms_per_cycle",
                  "speedup"):
            if not isinstance(dha.get(k), (int, float)):
                _err(out, path, "'heap.driver_host_apply' must carry "
                     f"numeric '{k}'")
    ws = d.get("wal_shard")
    if not isinstance(ws, dict):
        _err(out, path, "r18 artifacts must carry a 'wal_shard' block")
        ws = {}
    if not isinstance(ws.get("shards"), int) or ws.get("shards", 0) < 2:
        _err(out, path, "'wal_shard.shards' must be an int >= 2")
    if ws.get("replay_parity") is not True:
        _err(out, path, "'wal_shard.replay_parity' must be true: the "
             "seq-merged sharded replay must equal the single-file "
             "replay")
    for k in ("single_ms", "sharded_ms"):
        if not isinstance(ws.get(k), (int, float)):
            _err(out, path, f"'wal_shard.{k}' must be numeric")
    res = d.get("residues")
    if not isinstance(res, dict):
        _err(out, path, "r18 artifacts must carry a 'residues' block "
             "(the machine-readable r13-residue ledger)")
        res = {}
    entries = res.get("entries")
    if not isinstance(entries, list) or len(entries) < 3:
        _err(out, path, "'residues.entries' needs >= 3 entries (row "
             "cap, host apply, WAL group commit)")
    else:
        for e in entries:
            if not isinstance(e, dict):
                _err(out, path, "'residues.entries' must be objects")
                continue
            for k in ("id", "residue", "status", "mechanism"):
                if not isinstance(e.get(k), str) or not e[k]:
                    _err(out, path, "'residues.entries[]' must carry "
                         f"non-empty str '{k}'")
            if not isinstance(e.get("evidence"), dict):
                _err(out, path, "'residues.entries[]' must carry an "
                     "'evidence' object of measured values")
    walls = res.get("walls")
    if not isinstance(walls, list) or not walls:
        _err(out, path, "'residues.walls' must be a non-empty list of "
             "named remaining walls")
    else:
        for w in walls:
            if not isinstance(w, dict) \
                    or not isinstance(w.get("id"), str) \
                    or not isinstance(w.get("wall"), str):
                _err(out, path, "'residues.walls[]' must carry str "
                     "'id' and 'wall'")


def _check_scale_r19(d, path, out):
    """SCALE_r19+ (scripts/scale_soak.py, ISSUE 17): the head-only
    packing ceiling probe (>= 1M active CQs with pending work under the
    2^19 row budget on a full run), the parallel host apply/pack plane
    block with its cores-vs-throughput curve and the single-core
    honesty gate, the collapsed-vs-striped WAL arms, and a residue
    ledger that carries all four r18 residues."""
    quick = bool(d.get("quick"))
    ceiling = d.get("ceiling") if isinstance(d.get("ceiling"), dict) \
        else {}
    active = ceiling.get("active_cqs_pending")
    if not isinstance(active, int) or active < 1:
        _err(out, path, "'ceiling.active_cqs_pending' must be an int "
             ">= 1 (the census of CQs with pending work at the probe)")
    elif not quick and active < 1_000_000:
        _err(out, path, f"'ceiling.active_cqs_pending'={active}: a "
             "full r19 run must probe >= 1,000,000 active CQs")
    for k in ("rows_grid", "rows_budget_row_backed", "preempt_cohorts"):
        if not isinstance(ceiling.get(k), int):
            _err(out, path, f"'ceiling.{k}' must be an int (r19)")
    if isinstance(ceiling.get("rows_packed"), int) \
            and isinstance(ceiling.get("row_budget"), int) \
            and ceiling["rows_packed"] > ceiling["row_budget"]:
        _err(out, path, "'ceiling.rows_packed' (the budget-charged "
             "rows) must fit the row budget")
    hp = d.get("head_pack")
    if not isinstance(hp, dict):
        _err(out, path, "r19 artifacts must carry a 'head_pack' block")
        hp = {}
    for k in ("row_budget", "ceiling_cqs", "active_cqs_pending",
              "budget_rows", "grid_rows", "live_rows"):
        if not isinstance(hp.get(k), int):
            _err(out, path, f"'head_pack.{k}' must be an int")
    if not isinstance(hp.get("flag"), str):
        _err(out, path, "'head_pack.flag' must name the env flag")
    pool = d.get("host_pool")
    if not isinstance(pool, dict):
        _err(out, path, "r19 artifacts must carry a 'host_pool' block")
        pool = {}
    for k in ("cqs", "workers", "cores_available"):
        if not isinstance(pool.get(k), int):
            _err(out, path, f"'host_pool.{k}' must be an int")
    for k in ("apply_pack_ms_serial", "apply_pack_ms_pooled",
              "apply_pack_speedup"):
        if not isinstance(pool.get(k), (int, float)):
            _err(out, path, f"'host_pool.{k}' must be numeric")
    if pool.get("decisions_identical") is not True:
        _err(out, path, "'host_pool.decisions_identical' must be "
             "true: the pooled plane may never change a decision")
    curve = pool.get("cores_curve")
    if not isinstance(curve, list) or not curve:
        _err(out, path, "'host_pool.cores_curve' must be a non-empty "
             "list (pooled WAL-commit plane, per worker count)")
    else:
        for p in curve:
            if not isinstance(p, dict) \
                    or not isinstance(p.get("workers"), int) \
                    or not isinstance(p.get("ops_per_s"), (int, float)):
                _err(out, path, "'host_pool.cores_curve[]' must carry "
                     "int 'workers' and numeric 'ops_per_s'")
            elif p.get("seq_order_ok") is not True:
                _err(out, path, "'host_pool.cores_curve[]': pooled "
                     "commits must preserve total seq order")
    # honesty gate: >= 2x apply+pack overlap is only demandable when
    # the box has the cores; a 1-core host records the measured number
    # and the 'cores_available' evidence instead of a fabricated win
    if isinstance(pool.get("apply_pack_speedup"), (int, float)) \
            and isinstance(pool.get("cores_available"), int) \
            and isinstance(pool.get("workers"), int) \
            and pool["apply_pack_speedup"] < 2.0 \
            and pool["cores_available"] >= pool["workers"] \
            and pool["workers"] >= 4:
        _err(out, path, f"'host_pool.apply_pack_speedup'="
             f"{pool['apply_pack_speedup']}: >= 2x required at >= 4 "
             "workers when the box has that many cores")
    ws = d.get("wal_shard") if isinstance(d.get("wal_shard"), dict) \
        else {}
    for k in ("striped_ms",):
        if not isinstance(ws.get(k), (int, float)):
            _err(out, path, f"'wal_shard.{k}' must be numeric (r19 "
                 "striping-engaged arm)")
    if ws.get("collapsed_segments") != 1:
        _err(out, path, "'wal_shard.collapsed_segments' must be 1: a "
             "single appender must auto-collapse to one hot segment")
    if not isinstance(ws.get("striped_segments"), int) \
            or ws.get("striped_segments", 0) < 2:
        _err(out, path, "'wal_shard.striped_segments' must be >= 2: "
             "registered appenders must engage striping")
    # the e2e bulk-apply A/B is single-flag (stream vs the same arm
    # with KUEUE_TPU_CYCLE_BULK_APPLY=0) so the measured speedup is
    # the bulk-apply win alone, not confounded with the aggregate
    # fold tax the classic arm also drops
    heap = d.get("heap") if isinstance(d.get("heap"), dict) else {}
    dha = heap.get("driver_host_apply") \
        if isinstance(heap.get("driver_host_apply"), dict) else {}
    for k in ("bulk_off_ms_per_cycle", "speedup_vs_classic"):
        if not isinstance(dha.get(k), (int, float)):
            _err(out, path, f"'heap.driver_host_apply.{k}' must be "
                 "numeric (r19 single-flag bulk-apply A/B)")
    # The single-flag A/B measures ~1.0x by design, not by accident:
    # r13's incremental settles and the batched finish API already
    # removed the per-call redundancy bulk apply would dedupe, and the
    # e2e apply wall is per-admission-dominated (profiled: ~135us per
    # admission across prepare/assume/slot-assignment vs ~66us per
    # deduped requeue storm).  The gate is therefore "bulk apply never
    # costs" — a materially regressed speedup means the dedupe itself
    # became overhead; the measured ~1.0x is ledgered as a residues
    # wall, not asserted away.
    if not quick and isinstance(dha.get("speedup"), (int, float)) \
            and dha["speedup"] < 0.8:
        _err(out, path, f"'heap.driver_host_apply.speedup'="
             f"{dha['speedup']}: the e2e bulk-apply A/B regressed "
             "below 0.8x — cycle dedupe must never cost more than it "
             "saves in the apply-dominated regime")
    par = d.get("parity") if isinstance(d.get("parity"), dict) else {}
    if par.get("decisions_identical_nobulk_all") is not True:
        _err(out, path, "'parity.decisions_identical_nobulk_all' must "
             "be true: bulk apply may never change a decision")
    res = d.get("residues") if isinstance(d.get("residues"), dict) \
        else {}
    entries = res.get("entries")
    if isinstance(entries, list) and len(entries) < 4:
        _err(out, path, "r19 'residues.entries' needs >= 4 entries "
             "(row cap, host apply, WAL single-appender, lazy heap)")


def _check_traffic(d, path, out):
    """TRAFFIC_* open-loop artifacts (scripts/traffic_soak.py): the
    arrival-process parameters, the SLO, per-arm sustainable-rate
    results with per-rate latency curves (histograms included), the
    interleaved same-box control arm, the replay verdict, and the
    incremental-snapshot host-cost counters."""
    slo = d.get("slo")
    if not isinstance(slo, dict) \
            or not isinstance(slo.get("p99_latency_s"), (int, float)):
        _err(out, path, "'slo' must carry numeric 'p99_latency_s'")
    arrival = d.get("arrival")
    if not isinstance(arrival, dict):
        _err(out, path, "'arrival' must be an object")
    else:
        if not isinstance(arrival.get("process"), str):
            _err(out, path, "'arrival.process' must be a string")
        if not isinstance(arrival.get("seed"), int):
            _err(out, path, "'arrival.seed' must be an int")
    arms = d.get("arms")
    if not isinstance(arms, dict) or not arms:
        _err(out, path, "'arms' must be a non-empty object")
    else:
        for name, a in arms.items():
            if not isinstance(a, dict):
                _err(out, path, f"arm '{name}' must be an object")
                continue
            if not isinstance(a.get("sustainable_rate_per_s"),
                              (int, float)):
                _err(out, path, f"arm '{name}' missing numeric "
                     "'sustainable_rate_per_s'")
            curve = a.get("curve")
            if not isinstance(curve, list) or len(curve) < 2:
                _err(out, path, f"arm '{name}' needs a 'curve' list "
                     "with >= 2 rates")
                continue
            for e in curve:
                if not isinstance(e, dict):
                    _err(out, path, f"arm '{name}' curve entries must "
                         "be objects")
                    break
                for k in ("rate_per_s", "p50_latency_s",
                          "p99_latency_s", "admissions_per_s"):
                    if not isinstance(e.get(k), (int, float)):
                        _err(out, path, f"arm '{name}' curve entry "
                             f"missing numeric '{k}'")
                if not isinstance(e.get("latency_hist"), list):
                    _err(out, path, f"arm '{name}' curve entry missing "
                         "'latency_hist' list")
    control = d.get("control")
    if not isinstance(control, dict) \
            or control.get("interleaved") is not True:
        _err(out, path, "'control' must be an object with "
             "interleaved=true (same-box environment-drift arm)")
    if not isinstance(d.get("replay_identical"), bool):
        _err(out, path, "missing bool 'replay_identical'")
    if not isinstance(d.get("snapshot_counters"), dict):
        _err(out, path, "missing 'snapshot_counters' object")


_LINT_PASSES = ("purity", "dtype", "wal-order", "chaos-sites",
                "env-flags", "metrics-doc")
#: Passes that must appear in every LINT_* artifact regardless of age.
#: Later rounds append passes (r16 added metrics-doc), so the check is
#: "a prefix of the canonical order" rather than exact equality —
#: LINT_r14 stays valid while new artifacts must carry the full roster.
_LINT_PASSES_REQUIRED = _LINT_PASSES[:5]


def _check_lint(d, path, out):
    """LINT_* invariant-lint artifacts (scripts/lint_invariants.py
    --artifact): the named passes ran in canonical order, the finding
    count matches the headline 'value', the ok verdict matches the
    findings/stale state, the baseline only ever shrinks, and the run
    stayed tier-1 fast."""
    passes = d.get("passes")
    names = [p.get("name") for p in passes] \
        if isinstance(passes, list) \
        and all(isinstance(p, dict) for p in passes) else None
    if names is None or tuple(names) != _LINT_PASSES[:len(names)] \
            or len(names) < len(_LINT_PASSES_REQUIRED):
        _err(out, path, f"'passes' must be a prefix of {_LINT_PASSES} "
             f"covering at least {_LINT_PASSES_REQUIRED} "
             f"(got {names})")
    findings = d.get("findings")
    if not isinstance(findings, list):
        _err(out, path, "'findings' must be a list")
        findings = []
    if isinstance(d.get("value"), (int, float)) \
            and d["value"] != len(findings):
        _err(out, path, f"'value'={d['value']} but {len(findings)} "
             "findings listed")
    stale = d.get("stale_baseline")
    if not isinstance(stale, list):
        _err(out, path, "'stale_baseline' must be a list")
        stale = []
    ok = d.get("ok")
    if not isinstance(ok, bool):
        _err(out, path, "missing bool 'ok'")
    elif ok != (not findings and not stale):
        _err(out, path, f"'ok'={ok} inconsistent with "
             f"{len(findings)} findings / {len(stale)} stale entries")
    n_base = d.get("baseline_entries")
    first = d.get("first_full_run_findings")
    if not isinstance(n_base, int) or not isinstance(first, int):
        _err(out, path, "missing int 'baseline_entries' / "
             "'first_full_run_findings'")
    elif not n_base < first:
        _err(out, path, f"baseline must shrink: "
             f"baseline_entries={n_base} vs first full run={first}")
    el = d.get("elapsed_s")
    if not isinstance(el, (int, float)):
        _err(out, path, "missing numeric 'elapsed_s'")
    elif el >= 10.0:
        _err(out, path, f"'elapsed_s'={el} breaks the <10s tier-1 "
             "budget")


#: Hot-path phases the OBS artifact's span roster must cover — kept in
#: sync with kueue_tpu/obs/trace.py HOT_PATH_PHASES by tests/test_obs.py
#: (test_validator_phases_are_a_subset_of_hot_path).
_OBS_HOST_PHASES = ("cycle", "cycle.snapshot", "cycle.nominate",
                    "cycle.admit", "wal.append", "wal.commit")


def _check_obs_block(obs, path, out, where="obs"):
    """The ``obs`` block every r16+ soak artifact carries: event-stream
    counts, flight-recorder totals, and the tracing flag."""
    if not isinstance(obs, dict):
        _err(out, path, f"'{where}' must be an object")
        return
    ev = obs.get("events")
    if not isinstance(ev, dict) or not isinstance(ev.get("counts"), dict) \
            or not isinstance(ev.get("total"), int) \
            or not isinstance(ev.get("dropped"), int):
        _err(out, path, f"'{where}.events' needs counts/total/dropped")
    elif sum(ev["counts"].values()) != ev["total"]:
        _err(out, path, f"'{where}.events': counts sum "
             f"{sum(ev['counts'].values())} != total {ev['total']}")
    fl = obs.get("flight")
    if not isinstance(fl, dict) \
            or not isinstance(fl.get("recorded_total"), int) \
            or not isinstance(fl.get("buffered"), int):
        _err(out, path, f"'{where}.flight' needs recorded_total/buffered")
    elif fl["buffered"] > fl["recorded_total"]:
        _err(out, path, f"'{where}.flight': buffered exceeds "
             "recorded_total")
    if not isinstance(obs.get("tracing"), bool):
        _err(out, path, f"'{where}' missing bool 'tracing'")


def _check_obs(d, path, out):
    """OBS_* telemetry artifacts (scripts/obs_soak.py): a traced and an
    interleaved untraced arm over the same scenario, bit-identical
    decision digests, <= 5% traced p50 overhead, a span roster covering
    every host hot-path phase, and working dump surfaces."""
    control = d.get("control")
    if not isinstance(control, dict) \
            or control.get("interleaved") is not True:
        _err(out, path, "'control' must be an object with "
             "interleaved=true (same-box drift-fair untraced arm)")
    if d.get("decisions_identical") is not True:
        _err(out, path, "'decisions_identical' must be true: tracing "
             "may not change a single decision")
    ov = d.get("overhead")
    if not isinstance(ov, dict) \
            or not isinstance(ov.get("traced_p50_ms"), (int, float)) \
            or not isinstance(ov.get("untraced_p50_ms"), (int, float)) \
            or not isinstance(ov.get("ratio"), (int, float)):
        _err(out, path, "'overhead' needs traced_p50_ms / "
             "untraced_p50_ms / ratio")
    else:
        if ov["untraced_p50_ms"] > 0 and abs(
                ov["ratio"] - ov["traced_p50_ms"] / ov["untraced_p50_ms"]
        ) > 1e-6:
            _err(out, path, "'overhead.ratio' does not equal "
                 "traced_p50_ms / untraced_p50_ms")
        if ov["ratio"] > 1.05:
            _err(out, path, f"'overhead.ratio'={ov['ratio']:.4f} breaks "
                 "the <=5% tracing-overhead guarantee")
    spans = d.get("spans")
    if not isinstance(spans, dict):
        _err(out, path, "missing 'spans' roster object")
    else:
        missing = [p for p in _OBS_HOST_PHASES if p not in spans]
        if missing:
            _err(out, path, f"span roster missing hot-path phases "
                 f"{missing}")
        for phase, row in spans.items():
            if not isinstance(row, dict) \
                    or not isinstance(row.get("count"), int) \
                    or not isinstance(row.get("p50_ms"), (int, float)) \
                    or not isinstance(row.get("p99_ms"), (int, float)):
                _err(out, path, f"span roster row '{phase}' needs "
                     "count/p50_ms/p99_ms")
    dumps = d.get("dumps")
    if not isinstance(dumps, dict) \
            or dumps.get("flightrecorder_ok") is not True \
            or dumps.get("sigusr2_ok") is not True \
            or dumps.get("chrome_trace_events", 0) <= 0:
        _err(out, path, "'dumps' must prove flightrecorder_ok, "
             "sigusr2_ok, and a non-empty chrome trace")
    _check_obs_block(d.get("obs"), path, out)
    if not isinstance(d.get("elapsed_s"), (int, float)):
        _err(out, path, "missing numeric 'elapsed_s'")


def _check_fed(d, path, out):
    """FED_* federation-soak artifacts (scripts/federation_soak.py):
    a real federation (>= 4 worker clusters), every fault scenario
    carrying a parity verdict against its fault-free control, zero
    double-admissions anywhere, and strict scenarios proving
    bit-identical post-recovery state via matching digests.  The
    generic scenario-table consistency (all_stable vs the per-scenario
    verdicts) is _check_chaos's job — the 'scenarios' key routes every
    FED artifact through it as well."""
    workers = d.get("workers")
    if not isinstance(workers, int) or workers < 4:
        _err(out, path, f"'workers'={workers}: the federation soak "
             "needs >= 4 worker clusters")
    scenarios = d.get("scenarios")
    if not isinstance(scenarios, dict) or len(scenarios) < 4:
        _err(out, path, "needs >= 4 fault scenarios")
        scenarios = {}
    dbl_total = 0
    for name, s in scenarios.items():
        if not isinstance(s, dict):
            continue
        parity = s.get("parity")
        if parity not in ("strict", "outcome"):
            _err(out, path, f"scenario '{name}': 'parity' must be "
                 f"'strict' or 'outcome' (got {parity!r})")
        dbl = s.get("double_admissions")
        if not isinstance(dbl, int):
            _err(out, path, f"scenario '{name}' missing int "
                 "'double_admissions'")
        else:
            dbl_total += dbl
            if dbl != 0:
                _err(out, path, f"scenario '{name}': "
                     f"{dbl} double-admissions")
        digest = s.get("state_digest")
        if not isinstance(digest, dict) \
                or not isinstance(digest.get("control"), str) \
                or not isinstance(digest.get("faulted"), str):
            _err(out, path, f"scenario '{name}' missing "
                 "'state_digest' {control, faulted}")
        elif (parity == "strict" and s.get("decisions_stable")
                and digest["control"] != digest["faulted"]):
            _err(out, path, f"scenario '{name}': claims strict parity "
                 "but the control/faulted digests differ")
    if d.get("double_admissions_total") != dbl_total:
        _err(out, path, "'double_admissions_total'="
             f"{d.get('double_admissions_total')} but scenarios sum "
             f"to {dbl_total}")
    if not isinstance(d.get("elapsed_s"), (int, float)):
        _err(out, path, "missing numeric 'elapsed_s'")


def _check_serve(d, path, out):
    """SERVE_* serving-soak artifacts (scripts/serve_soak.py): a
    wall-clock soak holding the p99 admission-latency SLO across a
    diurnal swing with the burst window K adapted online, kill/restart
    arms converging bit-identically to an unkilled control with zero
    accepted submissions lost and zero admissions duplicated, a clean
    SIGTERM drain with the WAL flushed, and decision parity between the
    service path and the batch open-loop runner."""
    wall = d.get("wall")
    if not isinstance(wall, dict):
        _err(out, path, "missing 'wall' block")
        wall = {}
    if wall.get("wall_clock") is not True:
        _err(out, path, "'wall.wall_clock' must be true (the serving "
             "soak is a real wall-clock run)")
    for k in ("duration_s", "admissions_per_s"):
        if not isinstance(wall.get(k), (int, float)):
            _err(out, path, f"missing numeric 'wall.{k}'")
    slo = wall.get("slo")
    if not isinstance(slo, dict):
        _err(out, path, "missing 'wall.slo' block")
        slo = {}
    if not isinstance(slo.get("p99_target_s"), (int, float)):
        _err(out, path, "missing numeric 'wall.slo.p99_target_s'")
    if slo.get("held") is not True:
        _err(out, path, "'wall.slo.held' must be true: the service "
             "must hold the p99 SLO across the load swing")
    windows = slo.get("windows")
    if not isinstance(windows, list) or len(windows) < 2:
        _err(out, path, "'wall.slo.windows' needs >= 2 windows "
             "(the SLO must hold across a swing, not one average)")
    else:
        for i, w in enumerate(windows):
            if not isinstance(w, dict) \
                    or not isinstance(w.get("p99_s"), (int, float)):
                _err(out, path, f"window {i} missing numeric 'p99_s'")
    if slo.get("k_adapted") is not True:
        _err(out, path, "'wall.slo.k_adapted' must be true: the burst "
             "window K must actually move with the load swing")
    kill = d.get("kill_restart")
    if not isinstance(kill, dict):
        _err(out, path, "missing 'kill_restart' block")
        kill = {}
    if kill.get("lost_accepted_submissions") != 0:
        _err(out, path, "'kill_restart.lost_accepted_submissions'="
             f"{kill.get('lost_accepted_submissions')}: restart must "
             "lose zero accepted submissions")
    if kill.get("duplicated_admissions") != 0:
        _err(out, path, "'kill_restart.duplicated_admissions'="
             f"{kill.get('duplicated_admissions')}: restart must "
             "duplicate zero admissions")
    if kill.get("decisions_identical") is not True:
        _err(out, path, "'kill_restart.decisions_identical' must be "
             "true against the unkilled control")
    if kill.get("digests_match") is not True:
        _err(out, path, "'kill_restart.digests_match' must be true "
             "against the unkilled control")
    scen = kill.get("scenarios")
    if not isinstance(scen, dict) or len(scen) < 2:
        _err(out, path, "'kill_restart.scenarios' needs >= 2 kill "
             "sites (cycle boundary and ingest path)")
    drain = d.get("drain")
    if not isinstance(drain, dict):
        _err(out, path, "missing 'drain' block")
        drain = {}
    if drain.get("clean") is not True:
        _err(out, path, "'drain.clean' must be true: SIGTERM must "
             "drain and exit clean")
    if not isinstance(drain.get("wal_flushed"), bool):
        _err(out, path, "missing bool 'drain.wal_flushed'")
    elif not drain["wal_flushed"]:
        _err(out, path, "'drain.wal_flushed' must be true")
    parity = d.get("parity")
    if not isinstance(parity, dict):
        _err(out, path, "missing 'parity' block")
        parity = {}
    if parity.get("decisions_identical") is not True:
        _err(out, path, "'parity.decisions_identical' must be true: "
             "service-path decisions must be bit-identical to the "
             "batch open-loop runner")
    if not isinstance(d.get("elapsed_s"), (int, float)):
        _err(out, path, "missing numeric 'elapsed_s'")


def _check_dist(d, path, out):
    """DIST_* distributed-soak artifacts (scripts/dist_soak.py): a
    wall-clock saturation search across >= 2 submitter and >= 2 shard
    processes, four process-kill arms (submitter, front-end shard,
    service mid-cycle, federation worker) each recovering with zero
    lost and zero duplicated admissions and decisions bit-identical
    to a single-process control, plus socket-fault classification."""
    sat = d.get("saturation")
    if not isinstance(sat, dict):
        _err(out, path, "missing 'saturation' block")
        sat = {}
    if sat.get("wall_clock") is not True:
        _err(out, path, "'saturation.wall_clock' must be true (the "
             "ceiling is a measured wall-clock rate)")
    ceiling = sat.get("ceiling_admissions_per_s")
    if not isinstance(ceiling, (int, float)) or ceiling <= 0:
        _err(out, path, "missing positive numeric "
             "'saturation.ceiling_admissions_per_s'")
    for k in ("submitter_procs", "shard_procs"):
        v = sat.get(k)
        if not isinstance(v, int) or v < 2:
            _err(out, path, f"'saturation.{k}'={v}: the distributed "
                 "soak needs >= 2 real processes per role")
    if not isinstance(sat.get("rounds"), list) or not sat["rounds"]:
        _err(out, path, "'saturation.rounds' must be a non-empty list "
             "(the search must show its measurements)")
    kills = d.get("kills")
    if not isinstance(kills, dict):
        _err(out, path, "missing 'kills' block")
        kills = {}
    for arm in ("submitter", "front_end_shard", "service_mid_cycle",
                "federation_worker"):
        k = kills.get(arm)
        if not isinstance(k, dict):
            _err(out, path, f"missing 'kills.{arm}' arm")
            continue
        if k.get("parity") is not True:
            _err(out, path, f"'kills.{arm}.parity' must be true "
                 "against the single-process control")
        if k.get("decisions_identical") is not True:
            _err(out, path, f"'kills.{arm}.decisions_identical' must "
                 "be true: recovery must be bit-identical")
        if k.get("lost") != 0:
            _err(out, path, f"'kills.{arm}.lost'={k.get('lost')}: "
                 "a killed process must lose zero admissions")
        if k.get("duplicated") != 0:
            _err(out, path, f"'kills.{arm}.duplicated'="
                 f"{k.get('duplicated')}: a killed process must "
                 "duplicate zero admissions")
    sock = d.get("socket_faults")
    if not isinstance(sock, dict):
        _err(out, path, "missing 'socket_faults' block")
    elif sock.get("ok") is not True:
        _err(out, path, "'socket_faults.ok' must be true: the client "
             "must classify and survive every wire fault")
    dist = d.get("dist")
    if not isinstance(dist, dict):
        _err(out, path, "missing 'dist' block (supervisor report)")
    elif not dist.get("kill_log"):
        _err(out, path, "'dist.kill_log' is empty: the kill arms must "
             "record real SIGKILLs")
    if not isinstance(d.get("elapsed_s"), (int, float)):
        _err(out, path, "missing numeric 'elapsed_s'")


# generator scripts that postdate the schema convention (metric+value
# at top level); older BENCH_/MULTICHIP_r01-05 wrappers predate it and
# only get the common checks
_STRICT_PREFIXES = ("NORTHSTAR_", "CHAOS_", "TRAFFIC_", "SCALE_",
                    "LINT_", "FED_", "OBS_", "SERVE_", "DIST_")


def validate(path: str) -> list[str]:
    out: list[str] = []
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{os.path.basename(path)}: unreadable ({e})"]
    if not _check_common(d, path, out):
        return out
    base = os.path.basename(path).upper()
    # by name or by shape: a scenarios table is a chaos artifact even
    # if the file was renamed
    if base.startswith("CHAOS_") or "scenarios" in d:
        _check_chaos(d, path, out)
    # by name or by shape: a per-arm saturation table is a traffic
    # artifact even if the file was renamed
    if base.startswith("TRAFFIC_") or "arms" in d:
        _check_traffic(d, path, out)
    # by name or by shape: a per-size soak+parity record is a scale
    # artifact even if the file was renamed
    if base.startswith("SCALE_") or ("soak" in d and "parity" in d):
        _check_scale(d, path, out)
    # by name or by shape: a stale_baseline key marks an invariant-lint
    # record even if the file was renamed
    if base.startswith("LINT_") or "stale_baseline" in d:
        _check_lint(d, path, out)
    # by name or by shape: a per-cluster parity table marks a
    # federation-soak record even if the file was renamed
    if base.startswith("FED_") or "double_admissions_total" in d:
        _check_fed(d, path, out)
    # by name or by shape: an overhead A/B block marks a telemetry
    # artifact even if the file was renamed
    if base.startswith("OBS_") or "overhead" in d:
        _check_obs(d, path, out)
    # by name or by shape: a kill_restart+wall pair marks a serving-soak
    # record even if the file was renamed
    if base.startswith("SERVE_") or ("kill_restart" in d and "wall" in d):
        _check_serve(d, path, out)
    # by name or by shape: a kills+saturation pair marks a distributed
    # soak record even if the file was renamed
    if base.startswith("DIST_") or ("kills" in d and "saturation" in d):
        _check_dist(d, path, out)
    # from r16 on, every NORTHSTAR/TRAFFIC/FED soak artifact must carry
    # the obs block (the telemetry plane rides every soak)
    rnd = re.match(r"(?:NORTHSTAR|TRAFFIC|FED)_R(\d+)", base)
    if rnd and int(rnd.group(1)) >= 16:
        if "obs" not in d:
            _err(out, path, f"{base.split('_')[0]}_r16+ artifacts must "
                 "carry an 'obs' block")
        else:
            _check_obs_block(d["obs"], path, out)
    m = re.match(r"MULTICHIP_R(\d+)", base)
    if base.startswith(_STRICT_PREFIXES) or (m and int(m.group(1)) >= 8):
        _check_metric_value(d, path, out)
    # by name or by shape: the heterogeneous fast-path tier applies to
    # any artifact carrying a 'hetero' block, and NORTHSTAR_r12+ must
    # carry one (the mixed-fleet scenario is the north star from r12 on)
    ns = re.match(r"NORTHSTAR_R(\d+)", base)
    if "hetero" in d:
        _check_hetero(d, path, out)
    elif ns and int(ns.group(1)) >= 12:
        _err(out, path, "NORTHSTAR_r12+ artifacts must carry a "
             "'hetero' block")
    blocks = _crossover_blocks(d)
    for label, c in blocks:
        _check_crossover(label, c, path, out)
    if m and int(m.group(1)) >= 10 and not blocks:
        _err(out, path, "MULTICHIP_r10+ artifacts must carry a "
             "'crossover' block")
    return out


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sys.argv[1:] or sorted(glob.glob(os.path.join(root,
                                                          "*_r*.json")))
    if not paths:
        print("validate_artifacts: no artifacts found", file=sys.stderr)
        return 1
    failures: list[str] = []
    for p in paths:
        failures.extend(validate(p))
    for msg in failures:
        print(f"FAIL {msg}", file=sys.stderr)
    print(f"validate_artifacts: {len(paths)} artifact(s), "
          f"{len(failures)} violation(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
