"""Chaos smoke: seeded fault injection, WAL crash recovery, degradation.

The fast tier-1 slice of the chaos harness (the full soak lives in
scripts/chaos_soak.py): every injected failure — a crash between
cycles, a crash with the admit op journaled but unapplied, a mid-burst
crash, a forced speculation divergence, an 8→4→1 device-loss cascade,
a partitioned MultiKueue transport — must leave a recovered driver
whose decisions match a fault-free control arm, plus unit coverage for
the satellites (restore_workload rebuild parity, PackJournal soft-key
pruning, requeue-backoff clamp + jitter).
"""

from __future__ import annotations

import pytest

from kueue_tpu.api.types import LocalQueue, RequeueState
from kueue_tpu.chaos import injector as chaos
from kueue_tpu.chaos.injector import ChaosInjector, InjectedCrash
from kueue_tpu.controller.driver import Driver, WaitForPodsReadyConfig
from kueue_tpu.ops.burst import BurstSolver
from kueue_tpu.remote import (
    ChaosWorkerClient,
    ConnectionLost,
    LocalWorkerClient,
)
from kueue_tpu.utils.journal import (
    CycleWAL,
    PackJournal,
    evict_op,
    replay_op,
    requeue_op,
)
from kueue_tpu.workload import _jitter_fraction, update_requeue_state

from tests.conftest import FakeClock
from test_burst import (
    Clock,
    add_workloads,
    build,
    mk,
    run_host,
    simple_cluster,
)
from test_burst_pipeline import run_burst_mode, sustained_spec
from test_multichip_parity import needs_8_devices


@pytest.fixture(autouse=True)
def _chaos_off():
    """Chaos must never leak into the rest of the suite."""
    chaos.clear()
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# Scenario plumbing
# ---------------------------------------------------------------------------

def drain_spec():
    """The simple-drain scenario: more pending than quota, runtime-
    driven finishes, BEST_EFFORT_FIFO (skips don't block, so a crash
    that re-wakes parked workloads cannot change admissions)."""
    wls = []
    n = 0
    for c in range(2):
        for q in range(2):
            for i in range(6):
                n += 1
                wls.append(mk(f"w-{c}-{q}-{i}", f"lq-{c}-{q}", 1500,
                              prio=(i % 3) * 10, t=float(n)))
    return add_workloads(simple_cluster(), wls)


def resume_host(d, clock, cycles, runtime, out, tick_first=True):
    """Continue the per-cycle harness loop from ``len(out)`` completed
    cycles.  ``tick_first=False`` re-runs a cycle whose clock tick was
    already consumed before the crash (schedule_once crashes after the
    caller's tick)."""
    while len(out) < cycles:
        c = len(out)
        if tick_first:
            clock.t += 1.0
        tick_first = True
        stats = d.schedule_once()
        out.append(stats)
        if runtime > 0 and c - runtime >= 0:
            for key in out[c - runtime].admitted:
                w = d.workloads.get(key)
                if w is not None and w.has_quota_reservation:
                    d.finish_workload(key)
    return out


def run_host_until_crash(d, clock, cycles, runtime):
    """run_host that surfaces an injected crash: returns the records of
    the cycles that fully completed before the driver 'died'."""
    out = []
    try:
        resume_host(d, clock, cycles, runtime, out)
    except InjectedCrash:
        return out, True
    return out, False


def run_burst_until_crash(d, clock, cycles, runtime):
    """schedule_burst that surfaces an injected crash, collecting each
    applied cycle's record through on_cycle (the burst's own return
    value is lost when the exception unwinds)."""
    recs = []

    def on_cycle_start(_k):
        clock.t += 1.0

    def on_cycle(_k, stats):
        recs.append(stats)

    try:
        d.schedule_burst(cycles, runtime=runtime,
                         on_cycle_start=on_cycle_start, on_cycle=on_cycle)
    except InjectedCrash:
        return recs, True
    return recs, False


def full_state(d):
    """Every workload's durable status, timestamps included — the
    bit-identical recovery bar."""
    out = {}
    for key, w in d.workloads.items():
        out[key] = (
            w.is_finished, w.is_active, w.has_quota_reservation,
            None if w.admission is None else (
                w.admission.cluster_queue,
                tuple((a.name, tuple(sorted(a.flavors.items())),
                       tuple(sorted(a.resource_usage.items())), a.count)
                      for a in w.admission.pod_set_assignments)),
            tuple(sorted((c.type, c.status.value, c.reason, c.message,
                          c.last_transition_time)
                         for c in w.conditions.values())),
            tuple(sorted((s.name, s.state.value)
                         for s in w.admission_check_states.values())),
            None if w.requeue_state is None else
            (w.requeue_state.count, w.requeue_state.requeue_at),
        )
    return out


def assert_admitted_prefix(crashed, control, label):
    for k, (x, y) in enumerate(zip(crashed, control)):
        assert sorted(x.admitted) == sorted(y.admitted), \
            f"{label} cycle {k}: {sorted(x.admitted)} vs {sorted(y.admitted)}"


def recover(spec, crashed, wal):
    """Discard the crashed driver, rebuild from its durable store + WAL
    tail — same clock object so time stays aligned with the control."""
    d2 = Driver(clock=crashed.clock, use_device_solver=True)
    spec(d2)
    d2.recover_from(crashed.workloads.values(), wal)
    return d2


# ---------------------------------------------------------------------------
# Crash/recover parity: host path
# ---------------------------------------------------------------------------

def test_crash_at_cycle_start_recovers_bit_identical(tmp_path):
    """Boundary crash: the driver dies entering a cycle (tick consumed,
    nothing decided, WAL tail empty).  The recovered driver re-runs the
    cycle and every decision from there on matches the control arm —
    final state bit-identical, timestamps included."""
    spec, cluster = drain_spec(), simple_cluster()
    dc, cc = build(spec)
    control = run_host(dc, cc, 12, 2)

    d1, c1 = build(spec)
    wal = CycleWAL(str(tmp_path / "wal.jsonl"))
    d1.attach_wal(wal)
    chaos.install(ChaosInjector(seed=3)).arm("cycle.start", at=4)
    out, crashed = run_host_until_crash(d1, c1, 12, 2)
    assert crashed and len(out) == 3
    assert wal.tail == [], "boundary crash must leave no uncommitted ops"
    chaos.clear()

    d2 = recover(cluster, d1, wal)
    resume_host(d2, c1, 12, 2, out, tick_first=False)
    assert_admitted_prefix(out, control, "boundary-crash")
    assert d2.admitted_keys() == dc.admitted_keys()
    assert full_state(d2) == full_state(dc)


def test_crash_mid_admit_replays_wal_tail(tmp_path):
    """The hard case: the admit op is journaled, the store write never
    lands.  Recovery must roll the tail forward (with the journaled
    timestamps) and converge on the control arm's exact state."""
    spec, cluster = drain_spec(), simple_cluster()
    dc, cc = build(spec)
    control = run_host(dc, cc, 12, 2)

    d1, c1 = build(spec)
    wal = CycleWAL(str(tmp_path / "wal.jsonl"))
    d1.attach_wal(wal)
    chaos.install(ChaosInjector(seed=3)).arm("wal.admit", at=5)
    out, crashed = run_host_until_crash(d1, c1, 12, 2)
    assert crashed
    tail_admits = {op["key"] for op in wal.tail if op["op"] == "admit"}
    assert tail_admits, "crash site must leave journaled-but-unapplied ops"
    chaos.clear()

    d2 = recover(cluster, d1, wal)
    k = len(out)   # the cycle being re-run after recovery
    resume_host(d2, c1, k + 1, 2, out, tick_first=False)
    # the replayed ops belong to control's cycle k; the re-run makes
    # exactly the decisions of that cycle the crash cut off
    assert tail_admits <= set(control[k].admitted)
    assert set(out[k].admitted) == set(control[k].admitted) - tail_admits
    # the cycle's full decision batch is WAL-recovered + re-run: fold the
    # replayed admits back into its record so the modeled-runtime
    # finisher sees the same obligations as the uncrashed harness
    out[k].admitted.extend(sorted(tail_admits))
    resume_host(d2, c1, 12, 2, out)
    assert_admitted_prefix(out, control, "crash-recovery")
    assert d2.admitted_keys() == dc.admitted_keys()
    assert full_state(d2) == full_state(dc)
    # and the on-disk journal round-trips: recovery committed the tail
    wal.close()
    loaded = CycleWAL.load(str(tmp_path / "wal.jsonl"))
    assert loaded.batches == wal.batches and loaded.tail == []


def test_crash_mid_evict_replays_requeue_and_eviction():
    """Crash between the evict op's journal write and the status
    mutations: replay must land the eviction AND the requeue backoff
    exactly once, matching an uncrashed control driver."""
    def mk_driver(clock):
        d = Driver(clock=clock, wait_for_pods_ready=WaitForPodsReadyConfig(
            enable=True, timeout_seconds=30.0,
            requeuing_backoff_base_seconds=10,
            requeuing_backoff_max_seconds=100))
        simple_cluster(n_cohorts=1, cqs=1)(d)
        d.create_workload(mk("slow", "lq-0-0", 1000, t=1.0))
        return d

    clock_c, clock_x = FakeClock(), FakeClock()
    dc = mk_driver(clock_c)
    dc.run_until_settled()
    clock_c.tick(31.0)
    dc.evict_for_pods_ready_timeout("default/slow")

    d1 = mk_driver(clock_x)
    wal = CycleWAL()
    d1.attach_wal(wal)
    d1.run_until_settled()
    clock_x.tick(31.0)
    chaos.install(ChaosInjector(seed=1)).arm("wal.evict", at=1)
    with pytest.raises(InjectedCrash):
        d1.evict_for_pods_ready_timeout("default/slow")
    chaos.clear()
    kinds = [op["op"] for op in wal.tail]
    assert "requeue" in kinds and "evict" in kinds

    d2 = Driver(clock=clock_x, wait_for_pods_ready=WaitForPodsReadyConfig(
        enable=True, timeout_seconds=30.0,
        requeuing_backoff_base_seconds=10,
        requeuing_backoff_max_seconds=100))
    simple_cluster(n_cohorts=1, cqs=1)(d2)
    replayed = d2.recover_from(d1.workloads.values(), wal)
    assert replayed >= 1
    assert full_state(d2) == full_state(dc)
    w = d2.workloads["default/slow"]
    assert w.requeue_state.count == 1   # replay count guard: exactly once

    # both arms: backoff still gates, then expires and re-admits
    for d in (dc, d2):
        d.run_until_settled()
        assert "default/slow" not in d.admitted_keys()
    clock_c.tick(70.0)
    clock_x.t = clock_c.t
    for d in (dc, d2):
        d.queues.queue_inadmissible_workloads(["cq-0-0"])
        d.run_until_settled()
        assert "default/slow" in d.admitted_keys()
    assert full_state(d2) == full_state(dc)


def test_crash_mid_finish_replays_wal_tail():
    """Crash between the finish op's journal write and the condition
    flips: replay must finish the workload exactly once and release its
    quota, matching an uncrashed control driver."""
    def mk_driver(clock):
        d = Driver(clock=clock)
        simple_cluster(n_cohorts=1, cqs=1)(d)
        d.create_workload(mk("job", "lq-0-0", 1000, t=1.0))
        return d

    clock_c, clock_x = FakeClock(), FakeClock()
    dc = mk_driver(clock_c)
    dc.run_until_settled()
    assert "default/job" in dc.admitted_keys()
    clock_c.tick(5.0)
    dc.finish_workloads(["default/job"], message="done")

    d1 = mk_driver(clock_x)
    wal = CycleWAL()
    d1.attach_wal(wal)
    d1.run_until_settled()
    clock_x.tick(5.0)
    chaos.install(ChaosInjector(seed=2)).arm("wal.finish", at=1)
    with pytest.raises(InjectedCrash):
        d1.finish_workloads(["default/job"], message="done")
    chaos.clear()
    assert [op["op"] for op in wal.tail] == ["finish"]
    assert not d1.workloads["default/job"].is_finished, \
        "the crash must land between journal append and mutation"

    d2 = Driver(clock=clock_x)
    simple_cluster(n_cohorts=1, cqs=1)(d2)
    replayed = d2.recover_from(d1.workloads.values(), wal)
    assert replayed >= 1
    assert d2.workloads["default/job"].is_finished
    assert full_state(d2) == full_state(dc)
    # the freed quota is actually reusable after recovery
    for d in (dc, d2):
        d.create_workload(mk("next", "lq-0-0", 1000, t=10.0))
        d.run_until_settled()
        assert "default/next" in d.admitted_keys()
    assert full_state(d2) == full_state(dc)


# ---------------------------------------------------------------------------
# Crash/recover parity: fused burst path
# ---------------------------------------------------------------------------

def test_crash_at_burst_window_boundary_recovers(tmp_path):
    """Driver dies between fused windows; recovery resumes per-cycle
    and matches the fault-free host control arm end to end."""
    spec, cluster = sustained_spec(), simple_cluster(n_cohorts=1, cqs=2)
    dc, cc = build(spec)
    control = run_host(dc, cc, 60, 2)

    d1, c1 = build(spec)
    wal = CycleWAL(str(tmp_path / "wal.jsonl"))
    d1.attach_wal(wal)
    chaos.install(ChaosInjector(seed=9)).arm("burst.window_boundary", at=2)
    out, crashed = run_burst_until_crash(d1, c1, 60, 2)
    assert crashed and 0 < len(out) < 60
    assert wal.tail == []
    chaos.clear()

    d2 = recover(cluster, d1, wal)
    resume_host(d2, c1, 60, 2, out, tick_first=True)
    assert_admitted_prefix(out, control, "window-boundary-crash")
    assert d2.admitted_keys() == dc.admitted_keys()
    assert full_state(d2) == full_state(dc)


def test_crash_mid_burst_window_recovers(tmp_path):
    """Driver dies between applied cycles INSIDE a fused window — the
    acceptance criterion's mid-burst crash.  The WAL commit at each
    applied cycle bounds the loss to zero full cycles; per-cycle
    decisions and final state match the control."""
    spec, cluster = sustained_spec(), simple_cluster(n_cohorts=1, cqs=2)
    dc, cc = build(spec)
    control = run_host(dc, cc, 60, 2)

    d1, c1 = build(spec)
    wal = CycleWAL(str(tmp_path / "wal.jsonl"))
    d1.attach_wal(wal)
    chaos.install(ChaosInjector(seed=9)).arm("burst.mid_window", at=7)
    out, crashed = run_burst_until_crash(d1, c1, 60, 2)
    assert crashed and 0 < len(out) < 60
    chaos.clear()

    d2 = recover(cluster, d1, wal)
    resume_host(d2, c1, 60, 2, out, tick_first=True)
    assert_admitted_prefix(out, control, "mid-window-crash")
    assert d2.admitted_keys() == dc.admitted_keys()
    assert full_state(d2) == full_state(dc)


def test_forced_speculation_divergence_keeps_parity():
    """Chaos discards speculative windows unconsumed; the serial
    fallback must decide identically to the fault-free pipeline."""
    spec = sustained_spec()
    dc, cc = build(spec)
    control = run_host(dc, cc, 60, 2)

    d1, c1 = build(spec)
    chaos.install(ChaosInjector(seed=5)).arm(
        "burst.force_spec_divergence", at=1, times=3, action="cancel")
    out = run_burst_mode(d1, c1, 60, 2, pipeline=True)
    chaos.clear()

    assert d1._burst_solver.stats["burst_chaos_divergences"] >= 1
    assert_admitted_prefix(out, control, "forced-divergence")
    assert d1.admitted_keys() == dc.admitted_keys()


# ---------------------------------------------------------------------------
# Graceful shard degradation
# ---------------------------------------------------------------------------

@needs_8_devices
def test_shard_loss_cascade_8_4_1_keeps_parity():
    """The 8→4→1 cascade: chaos kills 4 devices at the first fresh
    window and 3 more at the second; the solver re-partitions over the
    survivors, then falls back to the serial path — decisions stay
    identical to an undegraded control arm throughout."""
    spec = sustained_spec()
    dc, cc = build(spec)
    control = run_host(dc, cc, 80, 2)

    d1, c1 = build(spec)
    bs = BurstSolver(backend="cpu")
    bs.set_shards(8)
    d1._burst_solver = bs
    inj = chaos.install(ChaosInjector(seed=11))
    inj.arm("shard.device_loss", at=1, action="degrade", payload=4)
    inj.arm("shard.device_loss", at=2, action="degrade", payload=3)
    out = run_burst_mode(d1, c1, 80, 2, pipeline=False)
    chaos.clear()

    assert bs.stats["burst_shard_degradations"] == 2, bs.stats
    assert bs.stats["burst_shard_serial_fallbacks"] == 1, bs.stats
    assert bs.n_shards == 1, "cascade must end on the serial path"
    assert_admitted_prefix(out, control, "shard-cascade")
    assert d1.admitted_keys() == dc.admitted_keys()
    assert full_state(d1) == full_state(dc)


# ---------------------------------------------------------------------------
# restore_workload rebuild parity (satellite)
# ---------------------------------------------------------------------------

def test_restore_after_admissions_matches_store():
    """Rebuild-from-store after a few admitted cycles: cache usage,
    queues, and subsequent decisions all match the original driver.
    The store is deep-copied so the two arms can keep scheduling side
    by side without sharing workload objects."""
    import copy

    spec, cluster = drain_spec(), simple_cluster()
    da, ca = build(spec)
    run_host(da, ca, 4, 0)
    assert da.admitted_keys()

    cb = Clock(t=ca.t)
    db = Driver(clock=cb, use_device_solver=True)
    cluster(db)
    db.recover_from(copy.deepcopy(list(da.workloads.values())))
    assert db.admitted_keys() == da.admitted_keys()
    assert full_state(db) == full_state(da)
    a = resume_host(da, ca, 10, 0, [None] * 4)
    b = resume_host(db, cb, 10, 0, [None] * 4)
    for x, y in zip(a[4:], b[4:]):
        assert sorted(x.admitted) == sorted(y.admitted)
    assert db.admitted_keys() == da.admitted_keys()
    assert full_state(db) == full_state(da)


def test_restore_after_evict_and_backoff_gates_requeue():
    """An evicted workload under requeue backoff must come back gated:
    the rebuilt driver honors requeue_at from the store and re-admits
    only after it expires — same trajectory as the original."""
    clock = FakeClock()
    d = Driver(clock=clock, wait_for_pods_ready=WaitForPodsReadyConfig(
        enable=True, timeout_seconds=30.0,
        requeuing_backoff_base_seconds=10,
        requeuing_backoff_max_seconds=100))
    simple_cluster(n_cohorts=1, cqs=1)(d)
    d.create_workload(mk("slow", "lq-0-0", 1000, t=1.0))
    d.run_until_settled()
    clock.tick(31.0)
    d.evict_for_pods_ready_timeout("default/slow")
    w = d.workloads["default/slow"]
    assert w.requeue_state.count == 1 and w.requeue_state.requeue_at

    d2 = Driver(clock=clock, wait_for_pods_ready=WaitForPodsReadyConfig(
        enable=True, timeout_seconds=30.0,
        requeuing_backoff_base_seconds=10,
        requeuing_backoff_max_seconds=100))
    simple_cluster(n_cohorts=1, cqs=1)(d2)
    d2.recover_from(d.workloads.values())
    assert full_state(d2) == full_state(d)
    d2.run_until_settled()
    assert "default/slow" not in d2.admitted_keys(), \
        "restored driver ignored the requeue backoff"
    clock.t = w.requeue_state.requeue_at + 1.0
    d2.queues.queue_inadmissible_workloads(["cq-0-0"])
    d2.run_until_settled()
    assert "default/slow" in d2.admitted_keys()


# ---------------------------------------------------------------------------
# CycleWAL unit coverage
# ---------------------------------------------------------------------------

def test_wal_log_commit_tail_and_file_roundtrip(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    wal = CycleWAL(path)
    wal.log({"op": "requeue", "key": "ns/a", "count": 1, "at": 7.0})
    wal.log({"op": "deactivate", "key": "ns/b"})
    assert len(wal.tail) == 2 and wal.batches == []
    wal.commit()
    assert wal.tail == [] and len(wal.batches) == 1
    wal.commit()   # empty commit is a no-op
    assert len(wal.batches) == 1
    wal.log({"op": "deactivate", "key": "ns/c"})   # uncommitted tail
    wal.close()

    loaded = CycleWAL.load(path)
    assert loaded.batches == wal.batches
    assert loaded.tail == [{"op": "deactivate", "key": "ns/c"}]


def test_wal_replay_ops_are_idempotent():
    wl = mk("a", "lq", 1000, t=1.0)
    store = {wl.key: wl}
    op = evict_op(wl.key, "PodsReadyTimeout", "timed out", None, 50.0)
    assert replay_op(store, op) is True
    state_once = full_state(type("D", (), {"workloads": store}))
    assert replay_op(store, op) is False, "second replay must be a no-op"
    assert full_state(type("D", (), {"workloads": store})) == state_once

    wl.requeue_state = RequeueState(count=2, requeue_at=60.0)
    assert replay_op(store, requeue_op(wl.key, 2, 99.0)) is False, \
        "count guard: an already-applied requeue must not re-land"
    assert wl.requeue_state.requeue_at == 60.0
    assert replay_op(store, requeue_op(wl.key, 3, 99.0)) is True
    assert replay_op(store, {"op": "deactivate", "key": "missing"}) is False


# ---------------------------------------------------------------------------
# PackJournal satellites + corruption sites
# ---------------------------------------------------------------------------

def test_drain_into_drops_soft_keys_for_dirty_cqs():
    j = PackJournal()
    j.drain_into(set(), {})           # clear the fresh journal's dirty-all
    j.touch("cq-a")
    j.note_roundtrip("cq-a", "k1")    # journal-dirty CQ: pruned
    j.note_roundtrip("cq-b", "k2")
    j.note_roundtrip("cq-c", "k3")    # caller-dirty CQ: pruned too
    dirty, soft = {"cq-c"}, {"cq-c": {"k0"}}
    was_all = j.drain_into(dirty, soft)
    assert was_all is False
    assert dirty == {"cq-a", "cq-c"}
    assert soft == {"cq-b": {"k2"}}, soft
    assert not j.dirty and not j.soft and not j.dirty_all


def test_journal_corruption_sites_force_full_walk():
    inj = chaos.install(ChaosInjector(seed=2))
    inj.arm("journal.drop_touch", at=1)
    j = PackJournal()
    j.drain_into(set(), {})
    j.touch("cq-a")                   # eaten: the lost update
    assert j.tainted and "cq-a" not in j.dirty
    dirty = set()
    assert j.drain_into(dirty, {}) is True, \
        "a tainted journal must fall back to a full walk"
    assert not j.tainted

    inj.arm("journal.spurious_dirty_all", at=2)
    j.touch("cq-b")                   # hit 1: armed at 2, passes through
    j.touch("cq-c")                   # hit 2: fires
    assert j.dirty_all and {"cq-b", "cq-c"} <= j.dirty
    assert j.drain_into(set(), {}) is True


# ---------------------------------------------------------------------------
# Requeue backoff clamp + jitter (satellite)
# ---------------------------------------------------------------------------

def test_update_requeue_state_clamps_exponent():
    base, cap = 60, 3600
    expect = [60, 120, 240, 480, 960, 1920, 3600, 3600]
    wl = mk("a", "lq", 1000)
    for want in expect:
        update_requeue_state(wl, base, cap, now=0.0)
        assert wl.requeue_state.requeue_at == want, \
            (wl.requeue_state.count, wl.requeue_state.requeue_at)
    # a mass-evicted stray with a huge count must not materialize 2^n
    wl.requeue_state = RequeueState(count=10_000_000)
    update_requeue_state(wl, base, cap, now=0.0)
    assert wl.requeue_state.requeue_at == cap
    wl2 = mk("b", "lq", 1000)
    update_requeue_state(wl2, 0, cap, now=5.0)   # base 0: immediate
    assert wl2.requeue_state.requeue_at == 5.0


def test_update_requeue_state_jitter_fans_out_deterministically():
    deadlines = {}
    for i in range(16):
        wl = mk(f"w{i}", "lq", 1000)
        update_requeue_state(wl, 60, 3600, now=0.0, jitter=0.5)
        deadlines[wl.key] = wl.requeue_state.requeue_at
        assert 60 <= wl.requeue_state.requeue_at <= 90   # wait·(1+0.5)
    assert len(set(deadlines.values())) > 1, "jitter did not spread"
    # deterministic: the same (key, attempt) always lands the same spot
    again = mk("w3", "lq", 1000)
    update_requeue_state(again, 60, 3600, now=0.0, jitter=0.5)
    assert again.requeue_state.requeue_at == deadlines["default/w3"]
    assert _jitter_fraction("k", 1) == _jitter_fraction("k", 1)
    assert _jitter_fraction("k", 1) != _jitter_fraction("k", 2)


# ---------------------------------------------------------------------------
# MultiKueue transport faults
# ---------------------------------------------------------------------------

def _worker():
    d = Driver(clock=FakeClock())
    simple_cluster(n_cohorts=1, cqs=1)(d)
    return d


def test_chaos_worker_client_partition_heals_by_retry():
    client = ChaosWorkerClient(LocalWorkerClient(_worker()),
                               injector=ChaosInjector(seed=4),
                               backoff_base=0.0, backoff_max=0.0)
    client._inj().arm("remote.partition", at=1, times=2, action="partition")
    client.create_workload(mk("a", "lq-0-0", 1000, t=1.0))
    assert client.get_workload("default/a") is not None
    assert client.stats["partitioned"] == 2
    assert client.stats["retries"] == 2


def test_chaos_worker_client_partition_exhausts_retries():
    client = ChaosWorkerClient(LocalWorkerClient(_worker()),
                               injector=ChaosInjector(seed=4),
                               max_retries=2, backoff_base=0.0,
                               backoff_max=0.0)
    client._inj().arm("remote.partition", at=1, times=99,
                      action="partition")
    with pytest.raises(ConnectionLost):
        client.create_workload(mk("a", "lq-0-0", 1000, t=1.0))
    assert not client.healthy()


def test_chaos_worker_client_duplicate_and_delay_are_absorbed():
    client = ChaosWorkerClient(LocalWorkerClient(_worker()),
                               injector=ChaosInjector(seed=4))
    inj = client._inj()
    inj.arm("remote.duplicate", at=1, action="duplicate")
    inj.arm("remote.delay", at=1, action="delay", payload=0.0)
    client.create_workload(mk("a", "lq-0-0", 1000, t=1.0))
    assert client.stats["duplicates"] == 1 and client.stats["delays"] == 1
    assert client.list_workload_keys() == ["default/a"]


def test_chaos_worker_client_watch_partition_is_raw():
    """WatchLoop owns watch backoff: a partitioned watch must surface
    ConnectionLost directly, not be absorbed by the retry loop."""
    client = ChaosWorkerClient(LocalWorkerClient(_worker()),
                               injector=ChaosInjector(seed=4))
    client._inj().arm("remote.partition", at=1, action="partition")
    with pytest.raises(ConnectionLost):
        client.watch_events(0)
    batch, since, _ = client.watch_events(0)   # healed next call
    assert batch == [] and since == 0


# ---------------------------------------------------------------------------
# Injector determinism
# ---------------------------------------------------------------------------

def test_injector_is_deterministic_under_seed():
    # armed at a real site: the chaos-sites lint rejects names no
    # injection point answers to (a typo'd arm would test nothing)
    def run(seed):
        inj = ChaosInjector(seed=seed)
        inj.arm("cycle.start", prob=0.3, times=50, action="tick")
        return [inj.hit("cycle.start") is not None for _ in range(200)]

    a, b = run(7), run(7)
    assert a == b and any(a)
    assert run(8) != a   # a different seed lands a different trace
