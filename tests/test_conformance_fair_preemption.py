"""Conformance replay of the reference's TestFairPreemptions tables
(/root/reference/pkg/scheduler/preemption/preemption_test.go:1891-2200),
end to end through the fair-sharing scheduler on both paths.

Fixture: CQs a/b/c (nominal 3 cpu each) + preemptible (nominal 0) in one
cohort "all" (total 9), borrowWithinCohort LowerPriority threshold -3,
withinClusterQueue LowerPriority, reclaimWithinCohort Any — the `want`
sets are the reference's own expectations, transliterated."""

import pytest

from kueue_tpu.api.types import (
    BorrowWithinCohort,
    BorrowWithinCohortPolicy,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PreemptionPolicy,
    ReclaimWithinCohort,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    WithinClusterQueue,
)
from kueue_tpu.controller.driver import Driver
from tests.conftest import FakeClock
from tests.test_conformance_preemption import admit, cycle, incoming, preempted

K = 1000


def make_driver(use_device):
    clock = FakeClock()
    d = Driver(clock=clock, use_device_solver=use_device, fair_sharing=True,
               solver_backend="cpu" if use_device else "auto")
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    policy = PreemptionPolicy(
        within_cluster_queue=WithinClusterQueue.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohort.ANY,
        borrow_within_cohort=BorrowWithinCohort(
            policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
            max_priority_threshold=-3))
    for name in ("a", "b", "c"):
        d.apply_cluster_queue(ClusterQueue(
            name=name, cohort="all", preemption=policy,
            resource_groups=[ResourceGroup(covered_resources=["cpu"], flavors=[
                FlavorQuotas(name="default", resources={
                    "cpu": ResourceQuota(nominal=3 * K)})])]))
        d.apply_local_queue(LocalQueue(name=f"lq-{name}", cluster_queue=name))
    d.apply_cluster_queue(ClusterQueue(
        name="preemptible", cohort="all",
        resource_groups=[ResourceGroup(covered_resources=["cpu"], flavors=[
            FlavorQuotas(name="default", resources={
                "cpu": ResourceQuota(nominal=0)})])]))
    d.apply_local_queue(LocalQueue(name="lq-preemptible",
                                   cluster_queue="preemptible"))
    return d, clock


def units(d, cq_name, names, cpu=1 * K, priority=0):
    for n in names:
        admit(d, n, cq_name, {"cpu": ("default", cpu)}, priority=priority)


@pytest.fixture(params=[False, True], ids=["host", "device"])
def use_device(request):
    return request.param


# --- :1952 "reclaim nominal from user using the most" -------------------

def test_reclaim_nominal_from_biggest_user(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- :1969 "can reclaim from queue using less, if taking the latest
#            workload from the biggest user isn't enough" ----------------

def test_reclaim_from_queue_using_less(use_device):
    d, clock = make_driver(use_device)
    admit(d, "a1", "a", {"cpu": ("default", 3 * K)})
    admit(d, "a2", "a", {"cpu": ("default", 1 * K)})
    admit(d, "b1", "b", {"cpu": ("default", 2 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 3 * K)})
    incoming(d, "c-incoming", "c", {"cpu": 3 * K})
    assert preempted(cycle(d, clock)) == {"a1"}


# --- :1981 "reclaim borrowable quota from user using the most" ----------

def test_reclaim_borrowable_from_biggest_user(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- :1998 "preempt one from each CQ borrowing" -------------------------

def test_preempt_one_from_each_borrowing_cq(use_device):
    d, clock = make_driver(use_device)
    admit(d, "a1", "a", {"cpu": ("default", 500)})
    admit(d, "a2", "a", {"cpu": ("default", 500)})
    admit(d, "a3", "a", {"cpu": ("default", 3 * K)})
    admit(d, "b1", "b", {"cpu": ("default", 500)})
    admit(d, "b2", "b", {"cpu": ("default", 500)})
    admit(d, "b3", "b", {"cpu": ("default", 3 * K)})
    incoming(d, "c-incoming", "c", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"a1", "b1"}


# --- :2015 "can't preempt when everyone under nominal" ------------------

def test_no_preemption_when_everyone_under_nominal(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "c", ["c1", "c2", "c3"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- :2031 "can't preempt when it would switch the imbalance" -----------

def test_no_preemption_when_it_switches_imbalance(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- :2046 "can preempt lower priority workloads from same CQ" ----------

def test_preempt_lower_priority_same_cq(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1-low", "a2-low"], priority=-1)
    units(d, "a", ["a3", "a4"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"a1-low", "a2-low"}


# --- :2066 "can preempt a combination of same CQ and highest user" ------

def test_preempt_combination_same_cq_and_biggest_user(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a-low"], priority=-1)
    units(d, "a", ["a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5", "b6"])
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"a-low", "b1"}


# --- :2086 "preempt huge workload if there is no other option" ----------

def test_preempt_huge_workload_when_only_option(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b1", "b", {"cpu": ("default", 9 * K)})
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- :2095 "can't preempt huge workload if the incoming is also huge" ---

def test_no_preempt_huge_for_huge_incoming(use_device):
    d, clock = make_driver(use_device)
    admit(d, "a1", "a", {"cpu": ("default", 2 * K)})
    admit(d, "b1", "b", {"cpu": ("default", 7 * K)})
    incoming(d, "a-incoming", "a", {"cpu": 5 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- :2104 "can't preempt 2 smaller workloads if the incoming is huge" --

def test_no_preempt_two_smaller_for_huge_incoming(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b1", "b", {"cpu": ("default", 2 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 2 * K)})
    admit(d, "b3", "b", {"cpu": ("default", 3 * K)})
    incoming(d, "a-incoming", "a", {"cpu": 6 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- :2113 "preempt from target and others even if over nominal" --------

def test_preempt_target_and_others_over_nominal(use_device):
    d, clock = make_driver(use_device)
    admit(d, "a1-low", "a", {"cpu": ("default", 2 * K)}, priority=-1)
    admit(d, "a2-low", "a", {"cpu": ("default", 1 * K)}, priority=-1)
    admit(d, "b1", "b", {"cpu": ("default", 3 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 3 * K)})
    incoming(d, "a-incoming", "a", {"cpu": 4 * K})
    assert preempted(cycle(d, clock)) == {"a1-low", "b1"}


# --- :2129 "prefer to preempt workloads that don't make the target CQ
#            have the biggest share" -------------------------------------

def test_prefer_not_making_target_biggest_share(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b1", "b", {"cpu": ("default", 2 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 1 * K)})
    admit(d, "b3", "b", {"cpu": ("default", 2 * K)})
    admit(d, "c1", "c", {"cpu": ("default", 1 * K)})
    incoming(d, "a-incoming", "a", {"cpu": 3500})
    assert preempted(cycle(d, clock)) == {"b2"}


# --- :2144 "preempt from different cluster queues if the end result has
#            a smaller max share" ----------------------------------------

def test_preempt_from_different_cqs_smaller_max_share(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b1", "b", {"cpu": ("default", 2 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 2500)})
    admit(d, "c1", "c", {"cpu": ("default", 2 * K)})
    admit(d, "c2", "c", {"cpu": ("default", 2500)})
    incoming(d, "a-incoming", "a", {"cpu": 3500})
    assert preempted(cycle(d, clock)) == {"b1", "c1"}


# --- :2159 "scenario above does not flap" -------------------------------

def test_no_flapping(use_device):
    d, clock = make_driver(use_device)
    admit(d, "a1", "a", {"cpu": ("default", 3500)})
    admit(d, "b2", "b", {"cpu": ("default", 2500)})
    admit(d, "c2", "c", {"cpu": ("default", 2500)})
    incoming(d, "b-incoming", "b", {"cpu": 2 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- :2171 "cannot preempt if it would make the candidate CQ go under
#            nominal after preempting one element" -----------------------

def test_no_preempt_below_nominal_candidate(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b1", "b", {"cpu": ("default", 3 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 3 * K)})
    admit(d, "c1", "c", {"cpu": ("default", 3 * K)})
    incoming(d, "a-incoming", "a", {"cpu": 4 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- :2186 "workloads under priority threshold not capriciously
#            preempted" --------------------------------------------------

def test_priority_threshold_not_capricious(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "preemptible", ["p1", "p2", "p3"], priority=-3)
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    stats = cycle(d, clock)
    assert not preempted(stats)


# ========================================================================
# Second TestFairPreemptions table: strategy-specific rows (S2-a vs S2-b
# applied alone), threshold-boundary borrowing rows, tournament-ordering
# rows, and multi-cycle stability rows — same fixture, transliterated
# from the upstream table's second half.
# ========================================================================


def make_driver_strategies(use_device, strategies):
    """Same fixture as make_driver but with an explicit fair-sharing
    preemption-strategy list (reference parseStrategies)."""
    clock = FakeClock()
    d = Driver(clock=clock, use_device_solver=use_device, fair_sharing=True,
               fs_preemption_strategies=list(strategies),
               solver_backend="cpu" if use_device else "auto")
    d.apply_resource_flavor(ResourceFlavor(name="default"))
    policy = PreemptionPolicy(
        within_cluster_queue=WithinClusterQueue.LOWER_PRIORITY,
        reclaim_within_cohort=ReclaimWithinCohort.ANY,
        borrow_within_cohort=BorrowWithinCohort(
            policy=BorrowWithinCohortPolicy.LOWER_PRIORITY,
            max_priority_threshold=-3))
    for name in ("a", "b", "c"):
        d.apply_cluster_queue(ClusterQueue(
            name=name, cohort="all", preemption=policy,
            resource_groups=[ResourceGroup(covered_resources=["cpu"], flavors=[
                FlavorQuotas(name="default", resources={
                    "cpu": ResourceQuota(nominal=3 * K)})])]))
        d.apply_local_queue(LocalQueue(name=f"lq-{name}", cluster_queue=name))
    d.apply_cluster_queue(ClusterQueue(
        name="preemptible", cohort="all",
        resource_groups=[ResourceGroup(covered_resources=["cpu"], flavors=[
            FlavorQuotas(name="default", resources={
                "cpu": ResourceQuota(nominal=0)})])]))
    d.apply_local_queue(LocalQueue(name="lq-preemptible",
                                   cluster_queue="preemptible"))
    return d, clock


# --- "reclaim two units in one cycle" -----------------------------------

def test_reclaim_two_units_one_cycle(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"b1", "b2"}


# --- "candidate ordering prefers lower priority within the chosen CQ" ---

def test_reclaim_prefers_lower_priority_candidate(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1"], priority=5)
    units(d, "b", ["b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b2"}


# --- "cross-CQ reclaim ignores candidate priority entirely" -------------

def test_cross_cq_reclaim_ignores_candidate_priority(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"], priority=9)
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "preemptible CQ (nominal 0) pays first when over-borrowed" ---------

def test_preemptible_borrower_reclaimed_for_nominal_incoming(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "c", ["c1"])
    units(d, "preemptible", ["p1", "p2"], priority=-4)
    incoming(d, "c-incoming", "c", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"p1", "p2"}


# --- "borrowing incoming may preempt a sub-threshold borrower" ----------

def test_borrowing_incoming_preempts_below_threshold(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "c", ["c1"])
    units(d, "preemptible", ["p1", "p2"], priority=-4)
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"p1"}


# --- "threshold boundary: priority exactly at maxPriorityThreshold" -----

def test_borrowing_incoming_preempts_at_threshold_boundary(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "c", ["c1"])
    units(d, "preemptible", ["p1", "p2"], priority=-3)
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"p1"}


# --- "within-CQ candidates: lower priority first, then newest" ----------

def test_within_cq_prefers_newest_among_equal_priority(use_device):
    d, clock = make_driver(use_device)
    admit(d, "a1", "a", {"cpu": ("default", 1 * K)}, priority=-1,
          reserved_at=0.2)
    admit(d, "a2", "a", {"cpu": ("default", 1 * K)}, priority=-1,
          reserved_at=0.9)
    admit(d, "a3", "a", {"cpu": ("default", 1 * K)})
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "c", ["c1", "c2", "c3"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"a2"}


# --- "no preemption when free quota suffices" ---------------------------

def test_no_preemption_when_free_quota_suffices(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    stats = cycle(d, clock)
    assert not preempted(stats)
    assert stats.admitted == ["default/c-incoming"]


# --- "tournament descends into the highest-share CQ first" --------------

def test_tournament_picks_highest_share_cq_first(use_device):
    d, clock = make_driver(use_device)
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1", "c2", "c3", "c4"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "tournament equalizes across borrowers" ----------------------------

def test_tournament_equalizes_across_borrowing_cqs(use_device):
    d, clock = make_driver(use_device)
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1", "c2", "c3", "c4"])
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"b1", "c1"}


# --- "sole big borrower: S2-a fails, S2-b retry preempts it" ------------

def test_default_strategies_preempt_sole_big_borrower(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b-big", "b", {"cpu": ("default", 5 * K)})
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "c", ["c1"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b-big"}


def test_final_share_only_blocks_sole_big_borrower(use_device):
    d, clock = make_driver_strategies(
        use_device, ["LessThanOrEqualToFinalShare"])
    admit(d, "b-big", "b", {"cpu": ("default", 5 * K)})
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "c", ["c1"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


def test_initial_share_only_preempts_sole_big_borrower(use_device):
    d, clock = make_driver_strategies(use_device, ["LessThanInitialShare"])
    admit(d, "b-big", "b", {"cpu": ("default", 5 * K)})
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "c", ["c1"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b-big"}


# --- "S2-b needs STRICT inequality: equal shares don't preempt" ---------

def test_initial_share_strict_inequality_blocks_equal_shares(use_device):
    d, clock = make_driver_strategies(use_device, ["LessThanInitialShare"])
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4"])
    units(d, "c", ["c1", "c2"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


def test_default_strategies_block_equal_share_borrower(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4"])
    units(d, "c", ["c1", "c2"])
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- "S2-a alone still reclaims from the biggest user" ------------------

def test_final_share_only_reclaims_biggest_user(use_device):
    d, clock = make_driver_strategies(
        use_device, ["LessThanOrEqualToFinalShare"])
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


def test_initial_share_only_reclaims_biggest_user(use_device):
    d, clock = make_driver_strategies(use_device, ["LessThanInitialShare"])
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "a borrow that only equalizes shares is blocked" -------------------
# a at 6/9 would reach DRS 333 == b's current 333: S2-a fails after the
# removal drops b to 222, S2-b fails on the strict inequality, and the
# within-CQ eviction of a-low alone cannot free 3 units — so nothing
# is preempted at all.

def test_three_unit_borrow_blocked_at_equal_share(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a-low"], priority=-1)
    units(d, "a", ["a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5", "b6"])
    incoming(d, "a-incoming", "a", {"cpu": 3 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- "preempted workloads requeue; the system does not flap" ------------

def test_reclaim_converges_without_flapping(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    s1 = cycle(d, clock)
    assert preempted(s1) == {"b1"}
    admitted = set()
    for _ in range(4):
        s = cycle(d, clock)
        admitted.update(s.admitted)
        assert not preempted(s)   # no second round of evictions
    assert "default/c-incoming" in admitted


# --- "freed quota is re-lent after the reclaimer finishes" --------------
# The b units are admitted through the real scheduling path (one head
# per cycle) so they carry distinct admission timestamps and a queue
# route: the reclaim then targets the most recently admitted unit, the
# victim requeues, and once the reclaimer finishes it borrows again.

def test_requeued_victim_readmits_after_finish(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "c", ["c1"])
    for i in range(1, 6):
        incoming(d, f"b{i}", "b", {"cpu": 1 * K}, created=float(i))
    admitted = []
    for _ in range(5):
        admitted += cycle(d, clock).admitted
    assert admitted == [f"default/b{i}" for i in range(1, 6)]
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    # newest admitted unit pays (candidate ordering: priority, then
    # most recently admitted first)
    assert preempted(cycle(d, clock)) == {"b5"}
    readmitted = []
    for _ in range(3):
        s = cycle(d, clock)
        readmitted += s.admitted
        assert not preempted(s)
    assert "default/c-incoming" in readmitted
    d.finish_workload("default/c-incoming")
    got = []
    for _ in range(12):   # ride out the requeue backoff
        clock.t += 10.0
        got += d.schedule_once().admitted
        if got:
            break
    assert got == ["default/b5"]


# --- "reclaim within nominal ignores incoming priority" -----------------

def test_reclaim_ignores_incoming_priority(use_device):
    d, clock = make_driver(use_device)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K}, priority=-2)
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "single larger candidate preferred when one eviction suffices" -----

def test_single_larger_candidate_for_two_unit_incoming(use_device):
    d, clock = make_driver(use_device)
    admit(d, "b-big", "b", {"cpu": ("default", 2 * K)})
    admit(d, "b2", "b", {"cpu": ("default", 1 * K)})
    admit(d, "b3", "b", {"cpu": ("default", 1 * K)})
    admit(d, "b4", "b", {"cpu": ("default", 1 * K)})
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"b-big"}


# ========================================================================
# Third table: cohort-borrowing × FS-preemption × sharded-dispatch grid.
# Every row below runs in three modes — host, device, and device with
# the solver routed through an 8-way (wl, cq) mesh on the conftest's
# virtual CPU devices — and the `want` sets must hold in all three:
# sharded dispatch is a deployment choice, never a semantics change.
# ========================================================================


@pytest.fixture(params=["host", "device", "sharded"])
def fs_mode(request):
    return request.param


def make_driver_mode(mode):
    d, clock = make_driver(use_device=(mode != "host"))
    if mode == "sharded":
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices (conftest XLA flag)")
        from kueue_tpu.parallel.sharded import make_mesh
        d.scheduler.solver.set_mesh(make_mesh(8))
    return d, clock


# --- "reclaim one unit from the biggest borrower, deeper imbalance" -----

def test_sharded_reclaim_from_deeper_borrower(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5", "b6"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "reclaim two units from the sole borrower" -------------------------

def test_sharded_reclaim_two_from_sole_borrower(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5", "b6"])
    units(d, "c", ["c1", "c2"])
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"b1", "b2"}


# --- "borrowing incoming preempts two from a deep sub-threshold
#      borrower (a's post-borrow share stays strictly under p's)" --------

def test_sharded_borrowing_preempts_two_below_threshold(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1"])
    units(d, "c", ["c1"])
    units(d, "preemptible", ["p1", "p2", "p3", "p4"], priority=-4)
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"p1", "p2"}


# --- "while borrowing, the FS share strategies arbitrate — the
#      borrowWithinCohort priority threshold does not shield a deeper
#      borrower above it" ------------------------------------------------

def test_sharded_fs_strategies_override_borrow_threshold(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3"])
    units(d, "c", ["c1"])
    units(d, "preemptible", ["p1", "p2"], priority=-2)
    incoming(d, "a-incoming", "a", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"p1"}


# --- "reclaim targets the only borrowing CQ even when small" ------------

def test_sharded_reclaim_targets_only_borrower(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4"])
    units(d, "c", ["c1", "c2"])
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "borrowing incoming with no sub-threshold candidates is blocked" ---

def test_sharded_borrowing_incoming_blocked_without_candidates(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "c", ["c1"])
    incoming(d, "c-incoming", "c", {"cpu": 3 * K})
    stats = cycle(d, clock)
    assert not stats.admitted and not preempted(stats)


# --- "reclaim picks the bigger borrower over the preemptible CQ" --------

def test_sharded_reclaim_prefers_bigger_borrower_over_preemptible(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5"])
    units(d, "preemptible", ["p1"], priority=-4)
    incoming(d, "c-incoming", "c", {"cpu": 1 * K})
    assert preempted(cycle(d, clock)) == {"b1"}


# --- "huge preemptible workload reclaimed when it is the only option" ---

def test_sharded_huge_preemptible_reclaimed(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    admit(d, "p-big", "preemptible", {"cpu": ("default", 6 * K)},
          priority=-4)
    incoming(d, "c-incoming", "c", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"p-big"}


# --- "two-unit reclaim equalizes across equal borrowers" ----------------

def test_sharded_two_unit_reclaim_equalizes_borrowers(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1"])
    units(d, "b", ["b1", "b2", "b3", "b4"])
    units(d, "c", ["c1", "c2", "c3", "c4"])
    incoming(d, "a-incoming", "a", {"cpu": 2 * K})
    assert preempted(cycle(d, clock)) == {"b1", "c1"}


# --- "reclaim converges and the incoming admits without flapping" -------

def test_sharded_reclaim_converges_without_flapping(fs_mode):
    d, clock = make_driver_mode(fs_mode)
    units(d, "a", ["a1", "a2", "a3"])
    units(d, "b", ["b1", "b2", "b3", "b4", "b5", "b6"])
    incoming(d, "c-incoming", "c", {"cpu": 2 * K})
    s1 = cycle(d, clock)
    assert preempted(s1) == {"b1", "b2"}
    admitted = set()
    for _ in range(4):
        s = cycle(d, clock)
        admitted.update(s.admitted)
        assert not preempted(s)
    assert "default/c-incoming" in admitted
