"""Distributed control plane: real OS processes, kill/restart parity.

Every test here spawns actual child processes (``python -m
kueue_tpu.dist.child``) under the seeded :class:`ProcessSupervisor`,
SIGKILLs them — at lockstep barriers via the ``dist.kill`` chaos site,
or mid-cycle via a child-armed ``svc.cycle`` crashpoint — and proves
the distributed run recovers with zero lost and zero duplicated
admissions, bit-identical to a single-process control fed the same
deterministic schedule."""

from __future__ import annotations

import os

import pytest

from kueue_tpu.chaos import injector as chaos
from kueue_tpu.dist.serving import (
    ShardClient,
    build_shard_service,
    shard_of,
    step_payloads,
)
from kueue_tpu.dist.supervisor import ProcessSupervisor, child_argv

pytestmark = pytest.mark.skipif(
    os.environ.get("KUEUE_TPU_SKIP_PROC_TESTS") == "1",
    reason="process spawning disabled")


@pytest.fixture(autouse=True)
def _chaos_off():
    chaos.clear()
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# Harness pieces
# ---------------------------------------------------------------------------

N_CQS = 8
N_SHARDS = 2
N_SUB = 2
PER_STEP = 3


def _shard_argv(tmp, shard_id, recover=False, resume_cycle=0,
                port=0, crash_site="", crash_at=0):
    pf = f"{tmp}/shard{shard_id}.port"
    kw = dict(shard_id=shard_id, n_cqs=N_CQS, state_dir=str(tmp),
              port_file=pf, recover=recover, resume_cycle=resume_cycle,
              port=port)
    if crash_site:
        kw.update(crash_site=crash_site, crash_at=crash_at)
    return child_argv("shard", **kw), pf


def _spawn_shard(sup, tmp, shard_id, **kw):
    argv, pf = _shard_argv(tmp, shard_id, **kw)
    mp = sup.spawn(f"shard{shard_id}", "shard", argv, port_file=pf)
    return mp, argv


def _spawn_submitter(sup, tmp, j, ports):
    mp = sup.spawn(
        f"sub{j}", "submitter",
        child_argv("submitter", submitter_id=j, n_submitters=N_SUB,
                   per_step=PER_STEP, n_cqs=N_CQS,
                   shard_ports=",".join(map(str, ports))),
        pipe_stdio=True)
    assert mp.proc.stdout.readline().strip() == "ready"
    return mp


def _control(tmp):
    os.makedirs(f"{tmp}/ctl", exist_ok=True)
    svc, _clock = build_shard_service(0, N_CQS, f"{tmp}/ctl")
    return svc


def _ctl_submit(svc, step, submitter_id):
    for b in step_payloads(step, submitter_id, N_SUB, PER_STEP, N_CQS):
        svc.submit(name=b["name"], queue_name=b["queue_name"],
                   requests=b["requests"], priority=b["priority"],
                   namespace=b["namespace"], runtime_s=b["runtime_s"],
                   count=b["count"], token=b["token"])


def _lockstep(subs, clients, ctl_svc, step):
    """One barrier: submitters submit, every shard steps, the control
    replays the same schedule; returns (dist decisions, ctl decisions)
    as union-sorted key lists."""
    for mp in subs:
        mp.proc.stdin.write(f"step {step}\n")
        mp.proc.stdin.flush()
    for mp in subs:
        assert mp.proc.stdout.readline().startswith("done")
    for j in range(N_SUB):
        _ctl_submit(ctl_svc, step, j)
    got = []
    for c in clients:
        st = c.step(retry_deadline_s=15.0)
        for dec in st["decisions"]:
            got.extend(dec)
    ctl = ctl_svc.step()
    want = [k for dec in ctl["decisions"] for k in dec]
    return sorted(got), sorted(want)


# ---------------------------------------------------------------------------
# Kill/restart parity per process role
# ---------------------------------------------------------------------------

def test_shard_kill_restart_parity(tmp_path):
    """SIGKILL one front-end shard at a barrier (via the armed
    ``dist.kill`` site), recover it from its IngestJournal + CycleWAL
    on the same port, resync the submitters through it — decisions
    stay bit-identical to the single-process control, with every
    resubmission deduped by idempotent token."""
    tmp = str(tmp_path)
    sup = ProcessSupervisor(seed=11)
    shards = [_spawn_shard(sup, tmp, s)[0] for s in range(N_SHARDS)]
    try:
        for mp in shards:
            sup.wait_ready(mp)
        ports = [mp.port for mp in shards]
        subs = [_spawn_submitter(sup, tmp, j, ports)
                for j in range(N_SUB)]
        ctl_svc = _control(tmp)
        clients = [ShardClient(p) for p in ports]

        for s in range(2):
            got, want = _lockstep(subs, clients, ctl_svc, s)
            assert got == want

        # the deterministic kill schedule: first barrier consult fires
        inj = chaos.ChaosInjector(seed=11)
        inj.arm("dist.kill", at=1, payload="shard0")
        chaos.install(inj)
        assert sup.maybe_kill("shard0")
        assert not shards[0].alive

        argv, _ = _shard_argv(tmp, 0, recover=True, resume_cycle=2,
                              port=ports[0])
        sup.restart("shard0", argv=argv)
        assert shards[0].port == ports[0]   # bound-port handoff

        for mp in subs:
            mp.proc.stdin.write("resync 2\n")
            mp.proc.stdin.flush()
        for mp in subs:
            line = mp.proc.stdout.readline().split()
            # every replayed submission deduped, none double-admitted
            assert int(line[2]) == 2 * PER_STEP

        for s in range(2, 4):
            got, want = _lockstep(subs, clients, ctl_svc, s)
            assert got == want

        # zero lost / zero duplicated admissions overall
        import json
        for mp in subs:
            mp.proc.stdin.write("stats\n")
            mp.proc.stdin.flush()
            st = json.loads(mp.proc.stdout.readline())
            assert st["accepted"] == 4 * PER_STEP
            assert st["duplicates"] == 2 * PER_STEP
        rep = sup.report()
        assert rep["by_role"]["shard"]["kills"] == 1
        assert rep["by_role"]["shard"]["restarts"] == 1
        assert rep["kill_log"] == ["shard0"]
    finally:
        sup.terminate_all()


def test_service_mid_cycle_crash_recovery(tmp_path):
    """The service process dies *mid-request* at an armed ``svc.cycle``
    crashpoint (exit 17, no cleanup); recovery from the journals plus a
    re-issued step lands on the control's exact decisions."""
    tmp = str(tmp_path)
    sup = ProcessSupervisor(seed=11)
    mp, _ = _spawn_shard(sup, tmp, 0, crash_site="svc.cycle",
                         crash_at=2)
    try:
        sup.wait_ready(mp)
        port = mp.port
        ctl_svc = _control(tmp)
        client = ShardClient(port)
        crashes = 0
        for s in range(3):
            for b in step_payloads(s, 0, 1, PER_STEP, N_CQS):
                client.submit(b, retry_deadline_s=5.0)
            _ctl_submit_single(ctl_svc, s)
            try:
                st = client.step()
            except Exception:
                mp.proc.wait(timeout=10)
                assert mp.proc.returncode == 17
                crashes += 1
                argv, _ = _shard_argv(tmp, 0, recover=True,
                                      resume_cycle=s, port=port)
                sup.restart("shard0", argv=argv)
                st = client.step(retry_deadline_s=10.0)
            got = sorted(k for dec in st["decisions"] for k in dec)
            ctl = ctl_svc.step()
            want = sorted(k for dec in ctl["decisions"] for k in dec)
            assert got == want
        assert crashes == 1
    finally:
        sup.terminate_all()


def _ctl_submit_single(svc, step):
    for b in step_payloads(step, 0, 1, PER_STEP, N_CQS):
        svc.submit(name=b["name"], queue_name=b["queue_name"],
                   requests=b["requests"], priority=b["priority"],
                   namespace=b["namespace"], runtime_s=b["runtime_s"],
                   count=b["count"], token=b["token"])


def test_submitter_kill_restart_dedupe(tmp_path):
    """SIGKILL a submitter process mid-run; the respawned submitter
    replays its deterministic schedule from zero and every already-
    delivered submission dedupes — the shard admits nothing twice."""
    tmp = str(tmp_path)
    sup = ProcessSupervisor(seed=11)
    shard, _ = _spawn_shard(sup, tmp, 0)
    try:
        sup.wait_ready(shard)
        ports = [shard.port]
        subs = [_spawn_submitter(sup, tmp, j, ports)
                for j in range(N_SUB)]
        ctl_svc = _control(tmp)
        clients = [ShardClient(ports[0])]
        for s in range(2):
            got, want = _lockstep(subs, clients, ctl_svc, s)
            assert got == want

        assert sup.kill("sub0")
        sub0 = _spawn_submitter(sup, tmp, 0, ports)
        subs[0] = sub0
        sub0.proc.stdin.write("resync 2\n")
        sub0.proc.stdin.flush()
        deduped = int(sub0.proc.stdout.readline().split()[2])
        assert deduped == 2 * PER_STEP   # all replays deduped

        for s in range(2, 4):
            got, want = _lockstep(subs, clients, ctl_svc, s)
            assert got == want

        # the shard saw every token exactly once as an accept
        st = clients[0].svc_stats()
        assert st["accepted"] == 4 * PER_STEP * N_SUB
        assert st["duplicate"] == 2 * PER_STEP
    finally:
        sup.terminate_all()


# ---------------------------------------------------------------------------
# Per-shard journal replay & routing
# ---------------------------------------------------------------------------

def test_shard_routing_keeps_cohorts_together():
    """Quota borrowing never crosses a shard: every ClusterQueue of a
    cohort routes to the same shard."""
    for n_shards in (1, 2, 3, 4):
        for q in range(64):
            cohort_shard = shard_of(f"lq-{(q // 4) * 4}", n_shards)
            assert shard_of(f"lq-{q}", n_shards) == cohort_shard
    # non-numeric names still route stably
    assert shard_of("lq-abc", 4) == shard_of("lq-abc", 4)


def test_federation_worker_kill_parity(tmp_path):
    """SIGKILL a federation worker process at a barrier; its journal
    rebuild + fresh-watch-epoch resync over the real socket keep every
    digest bit-identical to the in-process FederationSim control."""
    from kueue_tpu.federation.procs import ProcFederation, fed_traffic
    from kueue_tpu.federation.sim import FederationSim, FedSpec
    from kueue_tpu.remote import state_digest
    tmp = str(tmp_path)
    n_cqs, remote_cqs = 6, 4
    sup = ProcessSupervisor(seed=11)

    def worker_argv(name, recover=False, resume_t=None, port=0):
        pf = f"{tmp}/{name}.port"
        return child_argv("worker", name=name, remote_cqs=remote_cqs,
                          state_dir=tmp, port_file=pf, recover=recover,
                          resume_t=resume_t, port=port), pf

    def spawn_worker(name):
        argv, pf = worker_argv(name)
        return sup.spawn(name, "worker", argv, port_file=pf)

    workers = {n: spawn_worker(n) for n in ("w0", "w1")}
    try:
        for mp in workers.values():
            sup.wait_ready(mp)
        urls = {n: f"http://127.0.0.1:{mp.port}"
                for n, mp in workers.items()}
        traffic = fed_traffic(steps=4, per_step=2, n_cqs=n_cqs)
        fed = ProcFederation(urls, n_cqs=n_cqs, remote_cqs=remote_cqs)
        fed.load_traffic(traffic)
        spec = FedSpec(n_workers=2, n_cqs=n_cqs, remote_cqs=remote_cqs,
                       manager_quota_m=8000, worker_quota_m=4000,
                       runtime_steps=2, worker_lost_timeout=3.0,
                       reconnect_budget=0)
        ctl = FederationSim(spec, wal_dir=f"{tmp}/ctl")
        ctl.load_traffic(dict(traffic))

        for _ in range(3):
            fed.step()
            ctl.step()

        port0 = workers["w0"].port
        inj = chaos.ChaosInjector(seed=11)
        inj.arm("dist.kill", at=1, payload="w0")
        chaos.install(inj)
        assert sup.maybe_kill("w0")
        argv, _ = worker_argv("w0", recover=True, resume_t=fed.clock.t,
                              port=port0)
        sup.restart("w0", argv=argv)

        for _ in range(5):
            fed.step()
            ctl.step()

        dg = fed.digests()
        assert dg["manager"] == state_digest(ctl.manager)
        for n in urls:
            assert dg["workers"][n] == state_digest(ctl.workers[n])
        assert fed.violations == [] and ctl.violations == []
        assert fed.settled() and ctl.settled()
        # the restarted process's fresh epoch was noticed over the wire
        assert fed.client_stats()["w0"]["epoch_resyncs"] >= 1
    finally:
        sup.terminate_all()


def test_shard_journal_replay_offline(tmp_path):
    """A shard rebuilt from its on-disk journals alone (no process,
    no sockets) reaches the digest of the service that wrote them."""
    from kueue_tpu.dist.serving import recover_shard_service
    from kueue_tpu.remote import state_digest
    tmp = str(tmp_path)
    svc, _clock = build_shard_service(0, N_CQS, tmp)
    for s in range(3):
        for b in step_payloads(s, 0, 1, PER_STEP, N_CQS):
            svc.submit(name=b["name"], queue_name=b["queue_name"],
                       requests=b["requests"], priority=b["priority"],
                       namespace=b["namespace"],
                       runtime_s=b["runtime_s"], count=b["count"],
                       token=b["token"])
        svc.step()
    want = state_digest(svc.driver)
    # simulate the SIGKILL: no drain, no close — just reopen from disk
    rec, _clock2 = recover_shard_service(0, N_CQS, tmp, resume_cycle=3)
    assert state_digest(rec.driver) == want
    assert rec.cycle_index == svc.cycle_index
